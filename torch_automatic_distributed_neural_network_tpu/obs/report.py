"""`tadnn report`: join the event journal with MetricsLogger JSONL and
answer "where did the wall-clock go?" from artifacts the run produced.

Inputs: a run directory (containing ``journal.jsonl`` and optionally
``metrics.jsonl``) or explicit file paths.  Output: one dict (``--json``)
or a human summary — throughput, MFU, compile/recompile accounting,
expected comm bytes vs. XLA bytes-accessed, goodput breakdown, and any
bench probe/tunnel incidents recorded in the journal.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any

from .journal import Journal
from .schema import names_for

# merged-first: a multihost run's aggregate view (obs/aggregate.py)
# carries host tags the per-host files lack
JOURNAL_NAMES = ("journal.merged.jsonl", "journal.jsonl", "events.jsonl")
METRICS_NAMES = ("metrics.jsonl",)


def _find(directory: str, names: tuple[str, ...], suffix: str) -> str | None:
    for n in names:
        p = os.path.join(directory, n)
        if os.path.isfile(p):
            return p
    hits = sorted(
        f for f in os.listdir(directory) if f.endswith(suffix)
    )
    return os.path.join(directory, hits[0]) if hits else None


def resolve_paths(target: str,
                  metrics: str | None = None) -> tuple[str, str | None]:
    """(journal_path, metrics_path) from a dir / journal file + override."""
    if os.path.isdir(target):
        jp = _find(target, JOURNAL_NAMES, ".journal.jsonl")
        if jp is None:
            raise FileNotFoundError(
                f"no journal (journal.jsonl / *.journal.jsonl) in {target}"
            )
        mp = metrics or _find(target, METRICS_NAMES, ".metrics.jsonl")
        return jp, mp
    return target, metrics


def _read_metrics(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    return out


def _finite(vals) -> list[float]:
    return [v for v in vals
            if isinstance(v, (int, float)) and math.isfinite(v)]


def _mean(vals) -> float | None:
    vals = _finite(vals)
    return sum(vals) / len(vals) if vals else None


def generate(target: str, metrics_path: str | None = None) -> dict:
    """Build the run-summary dict from on-disk artifacts."""
    journal_path, metrics_path = resolve_paths(target, metrics_path)
    events = Journal.read(journal_path)
    report: dict[str, Any] = {
        "journal": journal_path,
        "metrics": metrics_path,
        "n_journal_records": len(events),
    }
    if events:
        ts = _finite([e.get("t") for e in events])
        report["journal_wall_s"] = (max(ts) - min(ts)) if ts else 0.0

    def last(name):
        for e in reversed(events):
            if e.get("name") == name:
                return e
        return None

    plan = last("plan")
    if plan:
        report["plan"] = {k: plan.get(k)
                          for k in ("strategy", "mesh", "remat", "precision",
                                    "zero1")
                          if plan.get(k) is not None}
    decision = last("tune.decision")
    hit = last("tune.cache_hit")
    fallback = last("tune.fallback")
    chosen = decision or hit or fallback
    if chosen:
        tuning: dict[str, Any] = {
            "source": ("cache" if chosen is hit else
                       "fallback" if chosen is fallback else
                       chosen.get("source", "cost_model")),
            "strategy": chosen.get("strategy"),
            "mesh": chosen.get("degrees") or chosen.get("mesh"),
            "grad_accum": chosen.get("grad_accum"),
            "step_time_ms": chosen.get("step_time_ms"),
            "reason": chosen.get("reason"),
            "n_candidates": chosen.get("n_candidates"),
            "breakdown": chosen.get("breakdown"),
        }
        cands = [e for e in events if e.get("name") == "tune.candidate"]
        if cands:
            tuning["candidates"] = [
                {k: e.get(k) for k in
                 ("rank", "strategy", "mesh", "grad_accum",
                  "step_time_ms", "fits")}
                for e in cands
            ]
        trials = [e for e in events
                  if e.get("name") == "tune.trial.result"]
        if trials:
            tuning["trials"] = [
                {k: e.get(k) for k in
                 ("candidate", "step_time_ms", "error") if e.get(k)}
                for e in trials
            ]
        report["tuning"] = {k: v for k, v in tuning.items()
                            if v is not None}
    compiles = [e for e in events if e.get("name") == "compile"]
    recompiles = [e for e in events if e.get("name") == "recompile"]
    report["compile"] = {
        "count": len(compiles),
        "total_s": sum(_finite(e.get("dur_s") for e in compiles)),
        "recompile_count": len(recompiles),
        "recompile_total_s": sum(_finite(e.get("dur_s") for e in recompiles)),
        "recompile_reasons": [
            {k: e.get(k) for k in ("fn", "signature", "dur_s")}
            for e in recompiles
        ],
    }
    exports = [e for e in events
               if (e.get("name") or "").startswith("export.")]
    if exports:
        hits = [e for e in exports if e["name"] == "export.hit"]
        stores = [e for e in exports if e["name"] == "export.store"]
        stales = [e for e in exports if e["name"] == "export.stale"]
        deser = sum(_finite(e.get("deserialize_s") for e in hits))
        compw = sum(_finite(e.get("compile_s") for e in stores))
        exp: dict[str, Any] = {
            "hits": len(hits),
            "misses": len([e for e in exports
                           if e["name"] == "export.miss"]),
            "stores": len(stores),
            "stale": len(stales),
            "fallbacks": len([e for e in exports
                              if e["name"] == "export.fallback"]),
            "errors": len([e for e in exports
                           if e["name"] == "export.error"]),
            "prewarms": len([e for e in exports
                             if e["name"] == "export.prewarm"]),
            "gc_dropped": sum(
                int(e.get("dropped") or 0) for e in exports
                if e["name"] == "export.gc") or None,
            "gc_payload_bytes_freed": sum(
                int(e.get("payload_bytes_freed") or 0) for e in exports
                if e["name"] == "export.gc") or None,
            "deserialize_total_s": deser or None,
            "mean_deserialize_s": _mean(e.get("deserialize_s")
                                        for e in hits),
            "compile_total_s": compw or None,
            "mean_compile_s": _mean(e.get("compile_s") for e in stores),
            # the cold-start win this run actually realized: compile
            # wall of the entries it wrote vs deserialize wall of the
            # entries it read (same-config runs make this the speedup)
            "compile_over_deserialize": (
                round(compw / deser, 1) if compw and deser else None),
            "stale_reasons": [
                {k: e.get(k) for k in ("kind", "reason")}
                for e in stales
            ] or None,
        }
        report["export"] = {k: v for k, v in exp.items()
                            if v is not None}
    good = last("goodput")
    if good:
        report["goodput"] = {k: good.get(k)
                             for k in ("total_wall_s", "seconds",
                                       "fractions", "goodput")}
    comms = last("comms.estimate")
    if comms:
        report["comms"] = {k: comms.get(k)
                           for k in ("strategy", "total_wire_bytes",
                                     "per_device", "model_dependent")}
    cross = last("comms.crosscheck")
    if cross:
        report["comms_crosscheck"] = {
            k: cross.get(k)
            for k in ("expected_wire_bytes", "xla_bytes_accessed",
                      "comm_fraction_of_bytes_accessed", "consistent")}
    tsteps = [e for e in events if e.get("name") == "trace.step"]
    if tsteps:
        coll = sum(_finite(e.get("collective_s") for e in tsteps))
        exp = sum(_finite(e.get("exposed_collective_s") for e in tsteps))
        wall = sum(_finite(e.get("wall_s") for e in tsteps))
        trace: dict[str, Any] = {
            "n_steps": len(tsteps),
            "mean_wall_s": _mean(e.get("wall_s") for e in tsteps),
            "mean_compute_s": _mean(e.get("compute_s") for e in tsteps),
            "mean_collective_s": _mean(e.get("collective_s")
                                       for e in tsteps),
            "mean_exposed_s": _mean(e.get("exposed_collective_s")
                                    for e in tsteps),
            "collective_fraction": (coll / wall) if wall else None,
            # of the collective time, how much the schedule failed to
            # hide — the ROADMAP's overlap-push observable
            "exposed_fraction": (exp / coll) if coll else None,
            "mean_measured_mfu": _mean(e.get("measured_mfu")
                                       for e in tsteps
                                       if e.get("measured_mfu")
                                       is not None),
            "mfu_series": [
                {"step": e.get("step"), "mfu": e["measured_mfu"]}
                for e in tsteps if e.get("measured_mfu") is not None
            ][-24:],
        }
        report["trace"] = {k: v for k, v in trace.items()
                          if v not in (None, [])}
    tcoll = [e for e in events if e.get("name") == "trace.collective"]
    if tcoll:
        latest: dict[str, dict] = {}
        for e in tcoll:  # keep the newest record per category
            latest[e.get("category", "?")] = e
        report["trace_collectives"] = [
            {k: e.get(k) for k in
             ("category", "hlo_op", "count", "measured_bytes",
              "modeled_bytes", "ratio", "within_2x")}
            for e in latest.values()
        ]
    from .aggregate import host_skew

    skew = host_skew(events)
    if skew:
        report["hosts"] = skew
    probes = [e for e in events
              if str(e.get("name", "")).startswith("bench.")]
    if probes:
        report["bench_incidents"] = [
            {k: v for k, v in e.items() if k not in ("kind", "depth")}
            for e in probes
            if e.get("name") in ("bench.probe", "bench.stale",
                                 "bench.unmeasurable")
            and (e.get("probe_error") or e.get("stale")
                 or e.get("ok") is False)
        ]
    stalls = [e for e in events if e.get("name") == "watchdog.stall"]
    restarts = [e for e in events if e.get("name") == "elastic.restart"]
    corrupt = [e for e in events if e.get("name") == "ckpt.corrupt"]
    rollbacks = [e for e in events
                 if e.get("name") == "resilience.rollback"]
    chaos = [e for e in events if e.get("name") == "resilience.chaos"]
    escalations = [e for e in events
                   if e.get("name") == "resilience.stall_escalation"]
    exhausted = [e for e in events if e.get("name") == "data_exhausted"]
    if (stalls or restarts or corrupt or rollbacks or chaos
            or escalations or exhausted):
        report["incidents"] = {
            "watchdog_stalls": len(stalls),
            "elastic_restarts": len(restarts),
            "corrupt_checkpoints": len(corrupt),
            "anomaly_rollbacks": len(rollbacks),
            "chaos_faults": len(chaos),
            "stall_escalations": len(escalations),
            "data_exhausted": len(exhausted),
        }
        detail = []
        for e in corrupt:
            detail.append({"what": "ckpt.corrupt", "step": e.get("step"),
                           "reason": e.get("reason")})
        for e in rollbacks:
            detail.append({"what": "rollback", "reason": e.get("reason"),
                           "at_step": e.get("at_step"),
                           "to_step": e.get("to_step"),
                           "skipped_batches": e.get("skipped_batches")})
        if detail:
            report["incident_detail"] = detail
        gave_up = [e for e in restarts if e.get("gave_up")]
        if restarts:
            report["incidents"]["restarts_gave_up"] = len(gave_up)
    rounds = [e for e in events if e.get("name") == "launch.round"]
    lrestarts = [e for e in events if e.get("name") == "launch.restart"]
    lchaos = [e for e in events if e.get("name") == "launch.chaos"]
    replans = [e for e in events if e.get("name") == "launch.replan"]
    async_saves = [e for e in events if e.get("name") == "ckpt.async_save"]
    done = [e for e in events if e.get("name") == "launch.done"]
    if rounds or lrestarts or done:
        launch: dict = {
            "rounds": len(rounds),
            "restarts": len(lrestarts),
            "chaos_faults": [{"kind": e.get("kind"), "step": e.get("step"),
                              "host": e.get("host")} for e in lchaos],
            "replans": [{"from": e.get("world_from"),
                         "to": e.get("world_to")} for e in replans],
            "worlds": [e.get("world") for e in rounds],
            "completed": bool(done),
        }
        if lrestarts:
            launch["broken_by"] = [
                {"host": e.get("host"), "step": e.get("step"),
                 "reason": e.get("reason")} for e in lrestarts]
            launch["gave_up"] = any(e.get("gave_up") for e in lrestarts)
        if done:
            launch["final_step"] = done[-1].get("final_step")
            launch["final_loss"] = done[-1].get("final_loss")
        if async_saves:
            durs = _finite(e.get("off_thread_s") for e in async_saves)
            launch["async_saves"] = {
                "n": len(async_saves),
                "max_queue_depth": max((e.get("queue_depth") or 0)
                                       for e in async_saves),
                "mean_off_thread_s": (sum(durs) / len(durs)
                                      if durs else None),
            }
        report["launch"] = launch
    # the registry's deprecation table supplies every name this event
    # was ever emitted under (the r06 rename); older journals render
    sreqs = [e for e in events
             if e.get("name") in names_for("serve.request_done")]
    ssteps = [e for e in events if e.get("name") == "serve.step"]
    spreempt = [e for e in events if e.get("name") == "serve.preempt"]
    sengine = last("serve.engine")
    schunks = [e for e in events if e.get("name") == "serve.prefill_chunk"]
    if sreqs or ssteps:
        totals = sorted(_finite(e.get("total_s") for e in sreqs))

        def pct(vals, q):
            if not vals:
                return None
            return vals[min(len(vals) - 1,
                            max(0, math.ceil(q * len(vals)) - 1))]

        new_tokens = sum(_finite(e.get("n_new") for e in sreqs))
        ts = _finite([e.get("t") for e in sreqs + ssteps])
        wall = (max(ts) - min(ts)) if len(ts) > 1 else None
        serving: dict[str, Any] = {
            "n_requests": len(sreqs),
            "n_steps": len(ssteps),
            "p50_latency_s": pct(totals, 0.50),
            "p99_latency_s": pct(totals, 0.99),
            "mean_queue_s": _mean(e.get("queue_s") for e in sreqs),
            "mean_tokens_per_s": _mean(e.get("tokens_per_s")
                                       for e in sreqs),
            "total_new_tokens": new_tokens,
            # aggregate goodput: generated tokens over the serving
            # window — the number batching discipline moves
            "goodput_tokens_per_s": (new_tokens / wall
                                     if wall else None),
            "mean_occupancy": _mean(e.get("occupancy") for e in ssteps),
            "preemptions": (len(spreempt)
                            or sum(int(e.get("preempted") or 0)
                                   for e in sreqs)),
            # per-step phase breakdown (engines that journal the r02
            # fields; absent keys drop out below)
            "mean_decode_step_s": _mean(e.get("decode_s")
                                        for e in ssteps),
            "mean_prefill_step_s": _mean(e.get("prefill_s")
                                         for e in ssteps),
            "n_prefill_chunks": len(schunks) or None,
            "mean_prefill_chunk_s": _mean(e.get("seconds")
                                          for e in schunks),
            "attention_impl": (sengine or {}).get("attention_impl"),
            "prefill_chunk": (sengine or {}).get("prefill_chunk"),
            # disaggregated / sharded serving (r04 fields)
            "mode": (ssteps[-1].get("mode") if ssteps else None),
            "tp": (sengine or {}).get("tp"),
            "overlapped_wall_s": (sum(_finite(
                e.get("overlap_s") for e in ssteps)) or None),
        }
        # request span timelines (r06 serve.request_done fields): TTFT
        # and inter-token latency percentiles plus the mean phase mix —
        # where a request's wall time went, attributed per phase
        ttfts = sorted(_finite(e.get("ttft_s") for e in sreqs))
        itls = sorted(_finite(
            v for e in sreqs for v in (e.get("itl_s") or ())))
        if ttfts:
            serving["ttft_p50_s"] = pct(ttfts, 0.50)
            serving["ttft_p99_s"] = pct(ttfts, 0.99)
        if itls:
            serving["itl_p50_s"] = pct(itls, 0.50)
            serving["itl_p99_s"] = pct(itls, 0.99)
        phase_means = {
            label: _mean(e.get(key) for e in sreqs)
            for label, key in (("queue", "queue_s"),
                               ("prefill", "prefill_s"),
                               ("decode", "decode_s"),
                               ("lost", "lost_s"))}
        if any(v is not None for v in phase_means.values()):
            serving["phase_mean_s"] = {
                k: v for k, v in phase_means.items() if v is not None}
        ships = [e for e in events if e.get("name") == "serve.kv_ship"]
        if ships:
            serving["kv_ships"] = len(ships)
            serving["shipped_blocks"] = int(sum(
                _finite(e.get("n_blocks") for e in ships)))
            serving["shipped_bytes"] = int(sum(
                _finite(e.get("bytes") for e in ships)))
        spec = [e for e in events if e.get("name") == "serve.speculate"]
        if spec:
            drafted = sum(_finite(e.get("drafted") for e in spec))
            accepted = sum(_finite(e.get("accepted") for e in spec))
            serving["spec_rounds"] = len(spec)
            serving["spec_k"] = spec[-1].get("k")
            serving["spec_drafted"] = int(drafted)
            serving["spec_accepted"] = int(accepted)
            serving["spec_accept_rate"] = (accepted / drafted
                                           if drafted else None)
        sadapt = [e for e in events if e.get("name") == "serve.adapter"]
        occ_res = _mean(e.get("adapters_resident") for e in ssteps
                        if e.get("adapters_resident") is not None)
        if sadapt or occ_res is not None:
            hits = [e for e in sadapt if e.get("kind") == "hit"]
            faults = [e for e in sadapt if e.get("kind") == "fault"]
            serving["adapter_hits"] = len(hits)
            serving["adapter_faults"] = len(faults)
            serving["adapter_evictions"] = sum(
                1 for e in faults if e.get("evicted"))
            serving["adapter_stalls"] = sum(
                1 for e in sadapt if e.get("kind") == "stall")
            binds = len(hits) + len(faults)
            serving["adapter_hit_rate"] = (len(hits) / binds
                                           if binds else None)
            serving["mean_adapters_resident"] = occ_res
            serving["mean_adapters_pinned"] = _mean(
                e.get("adapters_pinned") for e in ssteps
                if e.get("adapters_pinned") is not None)
        sprefix = [e for e in events if e.get("name") == "serve.prefix"]
        if sprefix or any(e.get("prefix_blocks") is not None
                          for e in ssteps):
            matches = [e for e in sprefix if e.get("kind") == "match"]
            cached = int(sum(_finite(
                e.get("cached_tokens") for e in matches)))
            prompt_tokens = sum(_finite(
                e.get("n_prompt") for e in sreqs))
            serving["prefix_queries"] = len(matches)
            serving["prefix_hit_requests"] = sum(
                1 for e in matches if e.get("hit"))
            serving["prefix_cached_tokens"] = cached
            serving["prefix_hit_rate"] = (
                cached / prompt_tokens if prompt_tokens else None)
            chunk = serving.get("prefill_chunk")
            # per-request floor, matching the engine: a cached span
            # shorter than one chunk skips nothing
            serving["prefix_saved_chunks"] = (
                int(sum(int(t) // chunk for t in _finite(
                    e.get("cached_tokens") for e in matches)))
                if chunk else None)
            serving["prefix_published_blocks"] = int(sum(_finite(
                e.get("n_blocks") for e in sprefix
                if e.get("kind") == "publish")))
            serving["cow_forks"] = sum(
                1 for e in sprefix if e.get("kind") == "cow")
            resident = [e.get("prefix_blocks") for e in ssteps
                        if e.get("prefix_blocks") is not None]
            serving["prefix_blocks"] = (resident[-1] if resident
                                        else None)
        report["serving"] = {k: v for k, v in serving.items()
                             if v is not None}
    # SLO incidents (obs/slo_monitor): breach/recover transitions the
    # monitor journaled while watching (or replaying) this run
    breaches = [e for e in events if e.get("name") == "slo.breach"]
    recovers = [e for e in events if e.get("name") == "slo.recover"]
    if breaches or recovers:
        report["slo_incidents"] = {
            "breaches": len(breaches),
            "recoveries": len(recovers),
            "incidents": sorted(
                ([{"kind": "breach",
                   "window_start_s": e.get("window_start_s"),
                   "window_end_s": e.get("window_end_s"),
                   "violations": e.get("violations") or []}
                  for e in breaches]
                 + [{"kind": "recover",
                     "window_start_s": e.get("window_start_s"),
                     "window_end_s": e.get("window_end_s"),
                     "ok_windows": e.get("ok_windows")}
                    for e in recovers]),
                key=lambda i: (i.get("window_start_s") or 0.0)),
        }
    # gateway fleet events (inference/gateway): ingress admission,
    # replan decisions, and elastic resizes from the closed-loop
    # autoscaler
    greqs = [e for e in events if e.get("name") == "gateway.request"]
    grejects = [e for e in events if e.get("name") == "gateway.reject"]
    greplans = [e for e in events if e.get("name") == "gateway.replan"]
    gscales = [e for e in events if e.get("name") == "gateway.scale"]
    gfails = [e for e in events
              if e.get("name") == "gateway.failover"
              and e.get("kind") != "parked"]
    ghedges = [e for e in events if e.get("name") == "gateway.hedge"]
    gbreaker = [e for e in events if e.get("name") == "gateway.breaker"]
    gdegrade = [e for e in events
                if e.get("name") in ("gateway.degrade",
                                     "gateway.restore")]
    if (greqs or grejects or greplans or gscales or gfails
            or ghedges or gbreaker or gdegrade):
        gw: dict[str, Any] = {
            "requests": len(greqs),
            "rejected": len(grejects),
            "rejected_rate_limit": sum(
                1 for e in grejects if e.get("kind") == "rate_limit"),
            "rejected_backpressure": sum(
                1 for e in grejects if e.get("kind") == "backpressure"),
            "rejected_degraded": sum(
                1 for e in grejects if e.get("kind") == "degraded"),
            "failovers": [
                {"t": e.get("t"), "replica": e.get("replica"),
                 "reason": e.get("reason"),
                 "n_requeued": e.get("n_requeued")}
                for e in gfails],
            "hedges_dispatched": sum(
                1 for e in ghedges if e.get("kind") == "dispatch"),
            "hedges_won": sum(
                1 for e in ghedges if e.get("kind") == "win"
                and e.get("winner") == "hedge"),
            "breaker_opens": sum(
                1 for e in gbreaker if e.get("to") == "open"),
            "degrade_history": [
                {"t": e.get("t"),
                 "kind": e.get("name", ".").split(".", 1)[1],
                 "level": e.get("level"), "reason": e.get("reason")}
                for e in gdegrade],
            "replans": [
                {"t": e.get("t"), "reason": e.get("reason"),
                 "current": e.get("current"), "chosen": e.get("chosen"),
                 "rate_per_s": e.get("rate_per_s")}
                for e in greplans],
            "scales": [
                {"t": e.get("t"), "kind": e.get("kind"),
                 "replica": e.get("replica"),
                 "reason": e.get("reason"),
                 "n_replicas": e.get("n_replicas"),
                 "requeued": e.get("requeued")}
                for e in gscales],
        }
        if gscales:
            final = [e.get("n_replicas") for e in gscales
                     if e.get("n_replicas") is not None]
            gw["final_replicas"] = final[-1] if final else None
        report["gateway"] = gw
    # planner drift (obs/slo_monitor.drift_check): measured throughput
    # left the simulate prediction's 2x band
    drifts = [e for e in events if e.get("name") == "simulate.drift"]
    if drifts:
        report["drift"] = [
            {"predicted_tok_s": e.get("predicted_tok_s"),
             "measured_tok_s": e.get("measured_tok_s"),
             "ratio": e.get("ratio"), "band": e.get("band")}
            for e in drifts]
    lint_findings = [e for e in events if e.get("name") == "lint.finding"]
    lint_summary = last("lint.summary")
    lint_skipped = last("lint.skipped")
    if lint_findings or lint_summary or lint_skipped:
        lint: dict[str, Any] = {
            "errors": (lint_summary or {}).get("errors",
                                               len([f for f in lint_findings
                                                    if f.get("severity")
                                                    == "error"])),
            "warnings": (lint_summary or {}).get("warnings",
                                                 len([f for f in lint_findings
                                                      if f.get("severity")
                                                      == "warn"])),
            "by_code": (lint_summary or {}).get("by_code"),
            "phase": (lint_summary or lint_skipped or {}).get("phase"),
            "findings": [
                {k: e.get(k) for k in ("code", "severity", "where", "msg")}
                for e in lint_findings
            ],
        }
        if lint_skipped:
            lint["skipped"] = lint_skipped.get("error")
        report["lint"] = {k: v for k, v in lint.items() if v is not None}
    protocol = [e for e in events if e.get("name") == "lint.protocol"]
    if protocol:
        report["protocol"] = [
            {k: e.get(k) for k in ("model", "scope", "states",
                                   "transitions", "depth", "frontier_peak",
                                   "wall_s", "complete", "violations")}
            for e in protocol]
    mem_est = last("lint.mem_estimate")
    if mem_est:
        keys = ("params_bytes", "optimizer_bytes", "model_state_bytes",
                "batch_bytes", "activation_bytes", "peak_bytes",
                "budget_bytes", "strategy", "degrees", "grad_accum",
                "remat", "phase", "static_over_compiled")
        me = {k: mem_est.get(k) for k in keys if mem_est.get(k) is not None}
        compiled = mem_est.get("compiled") or {}
        if compiled.get("per_device_peak_bytes"):
            me["compiled_peak_bytes"] = compiled["per_device_peak_bytes"]
        report["memory_estimate"] = me
    sest = last("lint.serve_estimate")
    if sest:
        report["serve_estimate"] = {
            k: sest.get(k)
            for k in ("max_streams", "requested_streams", "num_blocks",
                      "blocks_per_stream", "block_size", "max_len",
                      "quant_kv", "budget_bytes",
                      "block_bytes_per_device", "attention_impl",
                      "decode_workspace_bytes", "adapter_pool_bytes",
                      "n_adapters", "adapter_rank", "quant_adapters",
                      "prefix_cache", "prefix_index_bytes",
                      "expected_hit_rate", "effective_max_streams")
            if sest.get(k) is not None}
    ssweep = last("simulate.sweep")
    scands = [e for e in events if e.get("name") == "simulate.candidate"]
    sdec = last("simulate.decision")
    scross = last("simulate.crosscheck")
    if ssweep or scands or sdec or scross:
        sim: dict[str, Any] = {}
        if ssweep:
            sim.update({k: ssweep.get(k)
                        for k in ("n_topologies", "n_candidates",
                                  "n_replays", "n_slo_ok")
                        if ssweep.get(k) is not None})
        if scands:
            sim["ranked"] = [
                {k: e.get(k) for k in
                 ("rank", "topology", "plan", "admission", "mfu",
                  "step_time_s", "hbm_headroom_frac", "tok_s_per_chip",
                  "p99_s", "survival", "slo_ok", "slo_violations")}
                for e in scands]
        if sdec:
            sim["decision"] = {
                k: sdec.get(k) for k in
                ("topology", "plan", "admission", "slo_ok",
                 "slo_violations", "mfu", "tok_s_per_chip", "p99_s",
                 "hbm_headroom_frac", "survival")}
        if scross:
            sim["crosscheck"] = {
                k: scross.get(k) for k in
                ("record", "predicted_tok_s", "measured_tok_s",
                 "tok_s_ratio", "predicted_occupancy",
                 "measured_occupancy", "occupancy_ratio",
                 "predicted_preemptions", "measured_preemptions",
                 "within_2x")}
        report["simulate"] = sim
    if metrics_path and os.path.isfile(metrics_path):
        recs = _read_metrics(metrics_path)
        steps = [r for r in recs if "step_time_s" in r]
        per_chip = [v for r in steps for k, v in r.items()
                    if k.endswith("_per_sec_per_chip") and v]
        report["training"] = {
            "n_step_records": len(steps),
            "last_step": max((r.get("step", 0) for r in steps), default=None),
            "mean_step_time_s": _mean(r.get("step_time_s") for r in steps),
            "items_per_sec_per_chip": _mean(per_chip),
            "mean_mfu": _mean(r.get("mfu") for r in steps
                              if "mfu" in r),
            "final_loss": next(
                (r["loss"] for r in reversed(steps) if "loss" in r), None),
        }
    return report


def _fmt_bytes(n) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def format_report(report: dict) -> str:
    """Human-readable rendering of :func:`generate`'s dict."""
    lines = [f"run journal: {report['journal']} "
             f"({report['n_journal_records']} records, "
             f"{report.get('journal_wall_s', 0.0):.1f}s span)"]
    plan = report.get("plan")
    if plan:
        strat = str(plan.get("strategy"))
        if plan.get("zero1"):
            strat += "+zero1"
        lines.append(f"plan: strategy={strat} "
                     f"mesh={plan.get('mesh')}")
    tun = report.get("tuning")
    if tun:
        head = (f"tuner: strategy={tun.get('strategy')} "
                f"mesh={tun.get('mesh')} ({tun.get('source')}")
        if tun.get("n_candidates"):
            head += f", {tun['n_candidates']} candidates"
        if tun.get("step_time_ms") is not None:
            head += f", modeled {tun['step_time_ms']:.3f}ms/step"
        lines.append(head + ")")
        if tun.get("reason"):
            lines.append(f"  {tun['reason']}")
        b = tun.get("breakdown")
        if b:
            lines.append(
                "  breakdown: " + "  ".join(
                    f"{k.removesuffix('_ms')} {b[k]:.3f}ms"
                    for k in ("compute_ms", "comm_ms", "hbm_ms",
                              "latency_ms") if b.get(k) is not None))
        trials = tun.get("trials")
        if trials:
            ok = [t for t in trials if t.get("step_time_ms") is not None]
            msg = f"  measured trials: {len(trials)}"
            if ok:
                best = min(ok, key=lambda t: t["step_time_ms"])
                msg += (f", best {best.get('candidate')} "
                        f"{best['step_time_ms']:.3f}ms")
            lines.append(msg)
    c = report["compile"]
    lines.append(
        f"compiles: {c['count']} ({c['total_s']:.2f}s)   "
        f"recompiles: {c['recompile_count']} "
        f"({c['recompile_total_s']:.2f}s)"
        + ("  <- shape churn, check input pipeline"
           if c["recompile_count"] else "")
    )
    ex = report.get("export")
    if ex:
        parts = [f"export cache: {ex.get('hits', 0)} hit(s)"]
        if ex.get("mean_deserialize_s") is not None:
            parts.append(
                f"deserialize {ex['mean_deserialize_s'] * 1e3:.1f}ms mean")
        if ex.get("stores"):
            parts.append(f"{ex['stores']} store(s)")
        if ex.get("mean_compile_s") is not None:
            parts.append(f"compile {ex['mean_compile_s']:.2f}s mean")
        if ex.get("compile_over_deserialize"):
            parts.append(
                f"{ex['compile_over_deserialize']}x compile/deserialize")
        if ex.get("prewarms"):
            parts.append(f"{ex['prewarms']} prewarm(s)")
        if ex.get("gc_dropped"):
            parts.append(
                f"gc dropped {ex['gc_dropped']} "
                f"({_fmt_bytes(ex.get('gc_payload_bytes_freed') or 0)} "
                f"freed)")
        lines.append("  ".join(parts))
        if ex.get("stale"):
            reasons = ex.get("stale_reasons") or []
            first = reasons[0].get("reason") if reasons else None
            lines.append(
                f"  STALE entries skipped: {ex['stale']} (recompiled)"
                + (f" — {first}" if first else ""))
        if ex.get("fallbacks"):
            lines.append(
                f"  !! {ex['fallbacks']} exported executable(s) "
                f"rejected runtime args — fell back to jit")
        if ex.get("errors"):
            lines.append(f"  !! {ex['errors']} export error(s) "
                         f"(see export.error events)")
    tr = report.get("training")
    if tr:
        parts = [f"steps logged: {tr['n_step_records']}"]
        if tr.get("mean_step_time_s") is not None:
            parts.append(f"mean step {tr['mean_step_time_s'] * 1e3:.1f}ms")
        if tr.get("items_per_sec_per_chip"):
            parts.append(f"{tr['items_per_sec_per_chip']:,.0f} items/s/chip")
        if tr.get("mean_mfu") is not None:
            parts.append(f"MFU {tr['mean_mfu']:.1%}")
        if tr.get("final_loss") is not None:
            parts.append(f"final loss {tr['final_loss']:.4f}")
        lines.append("training: " + "  ".join(parts))
    good = report.get("goodput")
    if good and good.get("fractions"):
        fr = good["fractions"]
        lines.append(
            "goodput: {:.1%} of {:.1f}s wall".format(
                good.get("goodput", 0.0), good.get("total_wall_s", 0.0))
        )
        lines.append("  " + "  ".join(
            f"{b} {fr[b]:.1%}" for b in
            ("compile", "step", "checkpoint", "eval", "trace",
             "input_stall", "idle")
            if b in fr))
    comms = report.get("comms")
    if comms:
        per = comms.get("per_device") or {}
        lines.append(
            f"comms (per device/step, {comms.get('strategy')}): "
            f"wire { _fmt_bytes(comms.get('total_wire_bytes')) }   "
            + "  ".join(f"{k} {_fmt_bytes(v)}" for k, v in per.items() if v)
        )
        md = comms.get("model_dependent")
        if md:
            lines.append(f"  model-dependent (unquantified): {', '.join(md)}")
    cross = report.get("comms_crosscheck")
    if cross and cross.get("xla_bytes_accessed"):
        lines.append(
            f"  XLA bytes-accessed {_fmt_bytes(cross['xla_bytes_accessed'])}"
            f" -> comm fraction "
            f"{cross.get('comm_fraction_of_bytes_accessed') or 0:.1%}"
            + ("" if cross.get("consistent") else
               "  !! estimate exceeds measurement")
        )
    trc = report.get("trace")
    if trc:
        head = f"trace: {trc['n_steps']} instrumented step(s)"
        if trc.get("mean_wall_s") is not None:
            head += f", mean wall {trc['mean_wall_s'] * 1e3:.1f}ms"
        if trc.get("mean_measured_mfu") is not None:
            head += f", measured MFU {trc['mean_measured_mfu']:.1%}"
        lines.append(head)
        if trc.get("collective_fraction") is not None:
            exp = trc.get("exposed_fraction")
            lines.append(
                f"  collective {trc['collective_fraction']:.1%} of step "
                f"wall"
                + (f", exposed {exp:.1%} of collective time"
                   if exp is not None else "")
            )
        series = trc.get("mfu_series")
        if series and len(series) > 1:
            lines.append("  mfu over time: " + "  ".join(
                f"s{p['step']} {p['mfu']:.1%}" for p in series[-8:]))
    tc = report.get("trace_collectives")
    if tc:
        lines.append("exposed-comm crosscheck (measured HLO vs modeled "
                     "planner bytes, per device/step):")
        for e in tc:
            lines.append(
                f"  {e.get('category'):<20} x{e.get('count', 0)}  "
                f"measured {_fmt_bytes(e.get('measured_bytes'))}  "
                f"modeled {_fmt_bytes(e.get('modeled_bytes'))}  "
                f"ratio {e.get('ratio')}"
                + ("" if e.get("within_2x") else "  !! outside 2x band")
            )
    hosts = report.get("hosts")
    if hosts:
        sf = hosts.get("skew_fraction")
        lines.append(
            f"hosts: {hosts['n_hosts']}  {hosts.get('event')} "
            f"{hosts.get('field')} "
            f"{hosts['fastest'] * 1e3:.1f}..{hosts['slowest'] * 1e3:.1f}ms"
            + (f"  skew {sf:.1%}" if sf is not None else "")
            + ("  <- straggler gates every collective"
               if sf is not None and sf > 0.1 else "")
        )
    inc = report.get("incidents")
    if inc:
        parts = [f"{inc['watchdog_stalls']} watchdog stalls",
                 f"{inc['elastic_restarts']} elastic restarts"]
        for key, label in (("corrupt_checkpoints", "corrupt checkpoints"),
                           ("anomaly_rollbacks", "anomaly rollbacks"),
                           ("chaos_faults", "chaos faults"),
                           ("stall_escalations", "stall escalations"),
                           ("data_exhausted", "data exhaustions")):
            if inc.get(key):
                parts.append(f"{inc[key]} {label}")
        if inc.get("restarts_gave_up"):
            parts.append(f"{inc['restarts_gave_up']} gave up (budget)")
        lines.append("incidents: " + ", ".join(parts))
        for d in report.get("incident_detail", [])[-4:]:
            if d["what"] == "ckpt.corrupt":
                lines.append(f"  ckpt.corrupt step {d.get('step')}: "
                             f"{d.get('reason')}")
            else:
                lines.append(
                    f"  rollback ({d.get('reason')}): step "
                    f"{d.get('at_step')} -> {d.get('to_step')}, skipped "
                    f"{d.get('skipped_batches')} batch(es)")
    la = report.get("launch")
    if la:
        worlds = la.get("worlds") or []
        head = (f"launch: {la.get('rounds', 0)} round(s), "
                f"{la.get('restarts', 0)} cohort restart(s), worlds "
                + (" -> ".join(str(w) for w in worlds) if worlds else "?"))
        if la.get("completed"):
            head += (f"; completed at step {la.get('final_step')}"
                     + (f" loss {la['final_loss']:.6g}"
                        if la.get("final_loss") is not None else ""))
        elif la.get("gave_up"):
            head += "; GAVE UP (restart budget)"
        lines.append(head)
        for f in la.get("chaos_faults", [])[-4:]:
            lines.append(f"  chaos {f.get('kind')} -> host "
                         f"{f.get('host')} at step {f.get('step')}")
        for b in la.get("broken_by", [])[-3:]:
            lines.append(f"  cohort broken by host {b.get('host')} at "
                         f"step {b.get('step')}: {b.get('reason')}")
        for r in la.get("replans", []):
            lines.append(f"  replanned world {r.get('from')} -> "
                         f"{r.get('to')} (choose_strategy at new size)")
        asv = la.get("async_saves")
        if asv:
            mean = asv.get("mean_off_thread_s")
            lines.append(
                f"  async saves: {asv['n']}, max queue depth "
                f"{asv['max_queue_depth']}"
                + (f", mean off-thread {mean * 1e3:.1f}ms"
                   if mean is not None else ""))
    sv = report.get("serving")
    if sv:
        head = f"serving: {sv.get('n_requests', 0)} request(s)"
        if sv.get("p50_latency_s") is not None:
            head += (f", latency p50 {sv['p50_latency_s'] * 1e3:.0f}ms"
                     f" p99 {sv.get('p99_latency_s', 0) * 1e3:.0f}ms")
        if sv.get("goodput_tokens_per_s") is not None:
            head += f", goodput {sv['goodput_tokens_per_s']:.1f} tok/s"
        lines.append(head)
        parts = []
        if sv.get("ttft_p50_s") is not None:
            tl = (f"  timeline: ttft p50 {sv['ttft_p50_s'] * 1e3:.1f}ms"
                  f" p99 {sv.get('ttft_p99_s', 0) * 1e3:.1f}ms")
            if sv.get("itl_p50_s") is not None:
                tl += (f"  itl p50 {sv['itl_p50_s'] * 1e3:.2f}ms"
                       f" p99 {sv.get('itl_p99_s', 0) * 1e3:.2f}ms")
            pm = sv.get("phase_mean_s") or {}
            if pm:
                tl += ("  phase mix " + " ".join(
                    f"{k} {v * 1e3:.0f}ms" for k, v in pm.items()))
            lines.append(tl)
        if sv.get("mean_occupancy") is not None:
            parts.append(f"slot occupancy {sv['mean_occupancy']:.1%} "
                         f"over {sv.get('n_steps', 0)} step(s)")
        if sv.get("mean_queue_s") is not None:
            parts.append(f"mean queue {sv['mean_queue_s'] * 1e3:.0f}ms")
        if sv.get("mean_tokens_per_s") is not None:
            parts.append(
                f"per-request {sv['mean_tokens_per_s']:.1f} tok/s")
        parts.append(f"{sv.get('preemptions', 0)} preemption(s)")
        lines.append("  " + "  ".join(parts))
        bparts = []
        if sv.get("attention_impl"):
            bparts.append(f"decode impl {sv['attention_impl']}")
        if sv.get("mean_decode_step_s") is not None:
            bparts.append(
                f"decode step {sv['mean_decode_step_s'] * 1e3:.1f}ms")
        if sv.get("mean_prefill_chunk_s") is not None:
            bparts.append(
                f"prefill chunk {sv['mean_prefill_chunk_s'] * 1e3:.1f}ms"
                f" x{sv.get('n_prefill_chunks', 0)}"
                + (f" (C={sv['prefill_chunk']})"
                   if sv.get("prefill_chunk") else ""))
        if bparts:
            lines.append("  " + "  ".join(bparts))
        if sv.get("mode") == "disaggregated" or (sv.get("tp") or 1) > 1:
            dparts = [f"mode {sv.get('mode') or 'colocated'}"]
            if (sv.get("tp") or 1) > 1:
                dparts.append(f"tp {sv['tp']}")
            if sv.get("overlapped_wall_s") is not None:
                dparts.append(
                    f"overlapped wall {sv['overlapped_wall_s']:.2f}s")
            if sv.get("kv_ships"):
                dparts.append(
                    f"kv ships {sv['kv_ships']} "
                    f"({sv.get('shipped_blocks', 0)} block(s), "
                    f"{sv.get('shipped_bytes', 0) / 1024:.0f} KiB)")
            lines.append("  " + "  ".join(dparts))
        if sv.get("spec_rounds"):
            rate = sv.get("spec_accept_rate")
            lines.append(
                f"  speculative: k={sv.get('spec_k')}, "
                f"{sv.get('spec_accepted', 0)}/{sv.get('spec_drafted', 0)} "
                "drafts accepted"
                + (f" ({rate:.1%})" if rate is not None else "")
                + f" over {sv['spec_rounds']} round(s)")
        if ("adapter_hits" in sv or "adapter_faults" in sv
                or sv.get("mean_adapters_resident") is not None):
            aparts = [
                f"{sv.get('adapter_hits', 0)} hit(s) / "
                f"{sv.get('adapter_faults', 0)} fault(s)"]
            if sv.get("adapter_hit_rate") is not None:
                aparts.append(f"hit rate {sv['adapter_hit_rate']:.1%}")
            if sv.get("adapter_evictions"):
                aparts.append(f"{sv['adapter_evictions']} eviction(s)")
            if sv.get("adapter_stalls"):
                aparts.append(f"{sv['adapter_stalls']} pool stall(s)")
            if sv.get("mean_adapters_resident") is not None:
                aparts.append(
                    f"mean resident {sv['mean_adapters_resident']:.1f}"
                    + (f" (pinned {sv['mean_adapters_pinned']:.1f})"
                       if sv.get("mean_adapters_pinned") is not None
                       else ""))
            lines.append("  adapters: " + "  ".join(aparts))
        if "prefix_queries" in sv or sv.get("prefix_blocks") is not None:
            pparts = [
                f"{sv.get('prefix_hit_requests', 0)}/"
                f"{sv.get('prefix_queries', 0)} request(s) hit"]
            if sv.get("prefix_hit_rate") is not None:
                pparts.append(
                    f"hit rate {sv['prefix_hit_rate']:.1%} "
                    f"({sv.get('prefix_cached_tokens', 0)} cached "
                    f"token(s))")
            if sv.get("prefix_saved_chunks") is not None:
                pparts.append(
                    f"{sv['prefix_saved_chunks']} prefill chunk(s) "
                    f"saved")
            if sv.get("cow_forks"):
                pparts.append(f"{sv['cow_forks']} CoW fork(s)")
            if sv.get("prefix_blocks") is not None:
                pparts.append(f"{sv['prefix_blocks']} block(s) indexed")
            lines.append("  prefix cache: " + "  ".join(pparts))
    slo = report.get("slo_incidents")
    if slo:
        lines.append(f"slo incidents: {slo.get('breaches', 0)} "
                     f"breach(es), {slo.get('recoveries', 0)} "
                     f"recovery(ies)")
        for inc in slo.get("incidents", ()):
            where = (f"window [{inc.get('window_start_s')}s, "
                     f"{inc.get('window_end_s')}s)")
            if inc.get("kind") == "breach":
                lines.append("  BREACH " + where + ": "
                             + "; ".join(inc.get("violations") or ()))
            else:
                lines.append(
                    "  recovered " + where
                    + (f" after {inc['ok_windows']} clean window(s)"
                       if inc.get("ok_windows") is not None else ""))
    gw = report.get("gateway")
    if gw:
        rej = gw.get("rejected", 0)
        lines.append(
            f"gateway: {gw.get('requests', 0)} request(s) accepted, "
            f"{rej} rejected"
            + (f" ({gw.get('rejected_rate_limit', 0)} rate-limit, "
               f"{gw.get('rejected_backpressure', 0)} backpressure)"
               if rej else ""))
        for rp in gw.get("replans", ()):
            lines.append(
                f"  replan t={(rp.get('t') or 0.0):7.2f}s "
                f"[{rp.get('reason')}]: {rp.get('current')} -> "
                f"{rp.get('chosen')} replica(s) at "
                f"{(rp.get('rate_per_s') or 0):.0f} req/s")
        for sc in gw.get("scales", ()):
            what = (f"scale-{sc.get('kind')}" if sc.get("kind")
                    else "scale")
            extra = (f", {sc['requeued']} request(s) requeued"
                     if sc.get("requeued") is not None else "")
            lines.append(
                f"  {what} t={(sc.get('t') or 0.0):7.2f}s: "
                f"{sc.get('replica')} -> fleet of "
                f"{sc.get('n_replicas')}{extra}")
        for fo in gw.get("failovers", ()):
            lines.append(
                f"  failover t={(fo.get('t') or 0.0):7.2f}s: "
                f"{fo.get('replica')} ({fo.get('reason')}), "
                f"{fo.get('n_requeued')} request(s) salvaged")
        if gw.get("hedges_dispatched"):
            lines.append(
                f"  hedges: {gw['hedges_dispatched']} dispatched, "
                f"{gw.get('hedges_won', 0)} won")
        if gw.get("breaker_opens"):
            lines.append(
                f"  circuit breaker: opened "
                f"{gw['breaker_opens']} time(s)")
        for dg in gw.get("degrade_history", ()):
            lines.append(
                f"  {dg.get('kind')} t={(dg.get('t') or 0.0):7.2f}s: "
                f"level {dg.get('level')} ({dg.get('reason') or '?'})")
        if gw.get("final_replicas") is not None:
            lines.append(
                f"  final fleet: {gw['final_replicas']} replica(s)")
    drift = report.get("drift")
    if drift:
        for d in drift:
            lines.append(
                f"planner drift: measured "
                f"{(d.get('measured_tok_s') or 0):.1f} tok/s vs "
                f"predicted {(d.get('predicted_tok_s') or 0):.1f} "
                f"(x{(d.get('ratio') or 0):.2f}, outside "
                f"{(d.get('band') or 0):g}x band)")
    sest = report.get("serve_estimate")
    if sest:
        head = (f"serve estimate: {sest.get('max_streams')} stream(s) "
                f"of {sest.get('max_len')} tokens "
                f"({sest.get('num_blocks')} blocks x "
                f"bs {sest.get('block_size')}"
                f"{', int8 KV' if sest.get('quant_kv') else ''})")
        if sest.get("requested_streams") is not None:
            head += f", requested {sest['requested_streams']}"
        if sest.get("attention_impl"):
            head += f", {sest['attention_impl']} decode"
        if sest.get("decode_workspace_bytes"):
            head += (f" (+{sest['decode_workspace_bytes'] // 1024} KiB "
                     f"gather workspace)")
        if sest.get("n_adapters"):
            head += (f", adapter pool {sest['n_adapters']}x "
                     f"r{sest.get('adapter_rank')} "
                     f"{'int8' if sest.get('quant_adapters') else 'f32'} "
                     f"({_fmt_bytes(sest.get('adapter_pool_bytes'))})")
        if sest.get("prefix_cache"):
            head += (f", prefix index "
                     f"{_fmt_bytes(sest.get('prefix_index_bytes'))}")
            if sest.get("effective_max_streams") is not None:
                head += (f" (~{sest['effective_max_streams']} effective "
                         f"stream(s) at "
                         f"{sest.get('expected_hit_rate') or 0:.0%} hit "
                         f"rate)")
        lines.append(head)
    sim = report.get("simulate")
    if sim:
        head = "simulate:"
        if sim.get("n_candidates") is not None:
            head += (f" {sim['n_candidates']} candidate(s) over "
                     f"{sim.get('n_topologies', '?')} topology(ies)")
            if sim.get("n_replays") is not None:
                head += f", {sim['n_replays']} serve replay(s)"
            if sim.get("n_slo_ok") is not None:
                head += f", {sim['n_slo_ok']} meet the SLO"
        lines.append(head)
        for e in (sim.get("ranked") or [])[:8]:
            mfu = (f"mfu {e['mfu']:.1%}"
                   if e.get("mfu") is not None else "mfu -")
            step = (f"step {e['step_time_s'] * 1e3:.1f}ms"
                    if e.get("step_time_s") is not None else "step -")
            hd = (f"headroom {e['hbm_headroom_frac']:.0%}"
                  if e.get("hbm_headroom_frac") is not None
                  else "headroom -")
            tok = (f"{e['tok_s_per_chip']:.1f} tok/s/chip"
                   if e.get("tok_s_per_chip") is not None else "- tok/s")
            p99 = (f"p99 {e['p99_s'] * 1e3:.0f}ms"
                   if e.get("p99_s") is not None else "p99 -")
            surv = (f"surv {e['survival']:.3f}"
                    if e.get("survival") is not None else "surv -")
            tail = (" ok" if e.get("slo_ok")
                    else "  !! " + "; ".join(e.get("slo_violations")
                                             or ("no SLO result",)))
            lines.append(
                f"  #{e.get('rank')} {e.get('topology')} "
                f"{e.get('plan')} [{e.get('admission')}]  "
                f"{mfu}  {step}  {hd}  {tok}  {p99}  {surv} " + tail)
        cc = sim.get("crosscheck")
        if cc:
            lines.append(
                f"  crosscheck vs {cc.get('record')}: "
                f"tok/s {cc.get('predicted_tok_s')} predicted / "
                f"{cc.get('measured_tok_s')} measured "
                f"(ratio {cc.get('tok_s_ratio')}), "
                f"occupancy ratio {cc.get('occupancy_ratio')}"
                + ("" if cc.get("within_2x")
                   else "  !! outside 2x band"))
    lint = report.get("lint")
    if lint:
        head = (f"lint ({lint.get('phase', 'check')}): "
                f"{lint.get('errors', 0)} error(s), "
                f"{lint.get('warnings', 0)} warning(s)")
        by_code = lint.get("by_code")
        if by_code:
            head += "  [" + "  ".join(
                f"{c}×{n}" for c, n in sorted(by_code.items())) + "]"
        lines.append(head)
        for f in lint.get("findings", [])[-6:]:
            lines.append(f"  {f.get('code')} {f.get('severity')} "
                         f"{f.get('where')}: {f.get('msg')}")
        if lint.get("skipped"):
            lines.append(f"  preflight skipped: {lint['skipped']}")
    protocol = report.get("protocol")
    if protocol:
        lines.append("protocol model check:")
        for p in protocol:
            status = ("ok" if p.get("complete") and not p.get("violations")
                      else "TRUNCATED" if not p.get("complete")
                      else "VIOLATED")
            lines.append(
                f"  {p.get('model')}: {p.get('states')} states / "
                f"{p.get('transitions')} transitions, depth "
                f"{p.get('depth')}, frontier peak "
                f"{p.get('frontier_peak')}, {p.get('wall_s')}s — "
                f"{status}"
                + (f" ({p.get('violations')} counterexample(s))"
                   if p.get("violations") else ""))
    me = report.get("memory_estimate")
    if me:
        mesh = "x".join(f"{a}{n}" for a, n in
                        sorted((me.get("degrees") or {}).items()))
        head = (f"memory estimate (static, per device): peak "
                f"{_fmt_bytes(me.get('peak_bytes'))}")
        if me.get("budget_bytes"):
            head += f" / budget {_fmt_bytes(me['budget_bytes'])}"
        head += (f"  [{me.get('strategy')} mesh {mesh or '1'}"
                 f"{', remat' if me.get('remat') else ''}]")
        lines.append(head)
        lines.append(
            f"  params {_fmt_bytes(me.get('params_bytes'))}"
            f"  optimizer {_fmt_bytes(me.get('optimizer_bytes'))}"
            f"  activations {_fmt_bytes(me.get('activation_bytes'))}"
            f"  batch {_fmt_bytes(me.get('batch_bytes'))}")
        if me.get("compiled_peak_bytes"):
            lines.append(
                f"  xla compiled peak "
                f"{_fmt_bytes(me['compiled_peak_bytes'])} "
                f"(static/compiled {me.get('static_over_compiled')}x)")
    bi = report.get("bench_incidents")
    if bi:
        lines.append(f"bench incidents: {len(bi)}")
        for e in bi[-3:]:
            lines.append(f"  {e.get('name')}: mode={e.get('mode')} "
                         f"error={e.get('probe_error')} "
                         f"stale={e.get('stale')}")
    return "\n".join(lines)


# -- bench freshness guard (`tadnn report --check`) -------------------------

# how much a headline value may drop vs BENCH_LAST_GOOD before the
# check fails (the ISSUE's >10% regression gate)
REGRESSION_TOLERANCE = 0.10


def _load_bench_record(path: str) -> dict | None:
    """One bench record from either bench.py stdout JSON or the driver's
    round artifact (which wraps it under ``parsed``)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    return data if isinstance(data, dict) else None


def check_bench(target: str, *, bench_path: str | None = None,
                last_good_path: str | None = None) -> tuple[int, list[str]]:
    """The freshness guard behind ``tadnn report --check``.

    Exit-nonzero conditions (each with a message):

    - no bench record found (missing trajectory = the r03-r05 dark run);
    - the latest record is stale-marked (``status:
      "backend_unreachable"``, ``stale: true``, or an ``unmeasurable``
      metric) — the round measured nothing;
    - the headline value regressed more than
    ``REGRESSION_TOLERANCE`` vs the committed BENCH_LAST_GOOD entry
      for the same metric.

    ``target`` is a directory holding ``BENCH_r*.json`` +
    ``BENCH_LAST_GOOD.json`` (the repo root in CI); explicit paths
    override discovery.  Returns ``(exit_code, messages)``.

    The serving trajectory (``SERVE_BENCH_r*.json`` +
    ``SERVE_LAST_GOOD.json`` from bench_serve.py) is checked under the
    SAME rules whenever either artifact exists in ``target`` — once a
    serving round has been committed it can never silently go stale —
    and skipped entirely before that (a training-only checkout is not
    failed for a trajectory it never started).  Explicit ``bench_path``
    / ``last_good_path`` bypass the serve check (single-family mode).
    """
    import glob as _glob

    d = target if os.path.isdir(target) else os.path.dirname(
        os.path.abspath(target)) or "."
    if bench_path is None and last_good_path is None:
        code, msgs = _check_bench_family(
            d, "BENCH", bench_path=None, last_good_path=None)
        armed = (_glob.glob(os.path.join(d, "SERVE_BENCH_r*.json"))
                 or os.path.isfile(
                     os.path.join(d, "SERVE_LAST_GOOD.json")))
        if armed:
            scode, smsgs = _check_bench_family(
                d, "SERVE_BENCH", bench_path=None, last_good_path=None)
            code = max(code, scode)
            msgs = msgs + smsgs
        return code, msgs
    return _check_bench_family(d, "BENCH", bench_path=bench_path,
                               last_good_path=last_good_path)


def _check_bench_family(d: str, prefix: str, *,
                        bench_path: str | None,
                        last_good_path: str | None
                        ) -> tuple[int, list[str]]:
    """One trajectory's freshness check (``{prefix}_r*.json`` vs the
    family's LAST_GOOD)."""
    import glob as _glob

    lg_name = ("BENCH_LAST_GOOD.json" if prefix == "BENCH"
               else prefix.replace("_BENCH", "") + "_LAST_GOOD.json")
    msgs: list[str] = []
    if bench_path is None:
        rounds = sorted(_glob.glob(os.path.join(d, f"{prefix}_r*.json")))
        bench_path = rounds[-1] if rounds else None
    if bench_path is None or not os.path.isfile(bench_path):
        return 1, [f"no bench record ({prefix}_r*.json) found — the "
                   + ("serving" if prefix != "BENCH" else "bench")
                   + " trajectory is dark"]
    rec = _load_bench_record(bench_path)
    if rec is None:
        return 1, [f"{bench_path}: unreadable bench record"]
    name = os.path.basename(bench_path)
    metric = str(rec.get("metric", ""))
    if rec.get("status") == "backend_unreachable" or rec.get("stale"):
        msgs.append(
            f"{name}: stale ({rec.get('status') or 'stale-marked'}"
            + (f", stale_of {rec['stale_of']}" if rec.get("stale_of")
               else "")
            + ") — this round measured nothing")
    elif "unmeasurable" in metric:
        msgs.append(f"{name}: unmeasurable ({metric})")
    else:
        lg_path = last_good_path or os.path.join(d, lg_name)
        try:
            with open(lg_path) as f:
                last_good = json.load(f)
        except (OSError, ValueError):
            last_good = {}
        for mode, entry in last_good.items():
            res = (entry or {}).get("result") or {}
            if res.get("metric") != metric or not res.get("value"):
                continue
            value = rec.get("value") or 0.0
            floor = (1.0 - REGRESSION_TOLERANCE) * res["value"]
            if value < floor:
                msgs.append(
                    f"{name}: {metric} = {value:g} regressed "
                    f"{1.0 - value / res['value']:.1%} vs last good "
                    f"{res['value']:g} ({mode}, "
                    f"{entry.get('measured_utc', '?')})")
            break
    if not msgs:
        msgs.append(f"{name}: fresh ({metric or 'no metric'}, "
                    f"value {rec.get('value')})")
        return 0, msgs
    return 1, msgs


# -- simulator crosscheck (`tadnn report --check-simulate`) ------------------

# predicted/measured ratio band the replay must land in.  2x is loose on
# purpose: the replay models scheduling exactly but step timings only to
# a roofline, so it catches "the simulator lives in fantasy land", not
# single-digit-percent drift (that is the --check regression gate's job).
CROSSCHECK_BAND = 2.0


def check_simulate(target: str) -> tuple[int, list[str]]:
    """Falsify the what-if serve model against the newest real record.

    Behind ``tadnn report --check-simulate``: finds the latest
    ``SERVE_BENCH_r*.json`` in ``target``, replays its exact recorded
    config (streams / slots / block size / chunking / measured per-step
    timings) through the discrete-event scheduler replay, and compares
    predicted vs measured throughput and occupancy.  Journals the
    ratios as a ``simulate.crosscheck`` event (within-2x band, same
    style as ``trace.collective``).  Exit nonzero when no record exists
    (nothing to falsify against) or a ratio leaves the band — either
    way the simulator's predictions should not be trusted unaudited.
    """
    import glob as _glob

    d = target if os.path.isdir(target) else os.path.dirname(
        os.path.abspath(target)) or "."
    rounds = sorted(_glob.glob(os.path.join(d, "SERVE_BENCH_r*.json")))
    if not rounds:
        return 1, ["no serve bench record (SERVE_BENCH_r*.json) found — "
                   "nothing to crosscheck the simulator against"]
    path = rounds[-1]
    rec = _load_bench_record(path)
    if rec is None or not isinstance(rec.get("extra"), dict):
        return 1, [f"{os.path.basename(path)}: unreadable serve bench "
                   "record (no extra config to replay)"]
    name = os.path.basename(path)
    extra = rec["extra"]
    # lazy: the replay pulls in the tune package (and with it jax);
    # everything else in this module stays importable without it.
    from ..tune.simulate import replay_bench_record

    from . import journal

    try:
        sim = replay_bench_record(extra)
    except (KeyError, TypeError, ValueError) as e:
        return 1, [f"{name}: replay failed on recorded config: {e}"]
    msgs: list[str] = []
    within = True
    measured_tok = rec.get("value") or 0.0
    measured_occ = extra.get("mean_occupancy")
    ratios: dict[str, float | None] = {"tok/s": None, "occupancy": None}
    for label, predicted, measured in (
            ("tok/s", sim.get("tokens_per_s"), measured_tok),
            ("occupancy", sim.get("mean_occupancy"), measured_occ)):
        if not measured or predicted is None:
            msgs.append(f"{name}: {label} not comparable "
                        f"(measured {measured!r})")
            continue
        ratio = predicted / measured
        ratios[label] = round(ratio, 4)
        ok = (1.0 / CROSSCHECK_BAND) <= ratio <= CROSSCHECK_BAND
        within = within and ok
        msgs.append(
            f"{name}: {label} predicted {predicted:g} vs measured "
            f"{measured:g}, ratio {ratio:.2f} "
            + ("within 2x" if ok else "OUTSIDE 2x BAND"))
    pred_pre = sim.get("preemptions", 0)
    meas_pre = extra.get("preemptions")
    if meas_pre is not None:
        # count, not a rate: "within 2x" here means the replay predicts
        # the same preemption regime (quiet pool vs thrashing pool).
        ok = pred_pre <= 2 * max(meas_pre, 1) and \
            meas_pre <= 2 * max(pred_pre, 1)
        within = within and ok
        msgs.append(
            f"{name}: preemptions predicted {pred_pre} vs measured "
            f"{meas_pre} " + ("within 2x" if ok else "OUTSIDE 2x BAND"))
    journal.event(
        "simulate.crosscheck",
        record=name,
        predicted_tok_s=sim.get("tokens_per_s"),
        measured_tok_s=measured_tok or None,
        tok_s_ratio=ratios["tok/s"],
        predicted_occupancy=sim.get("mean_occupancy"),
        measured_occupancy=measured_occ,
        occupancy_ratio=ratios["occupancy"],
        predicted_preemptions=pred_pre,
        measured_preemptions=meas_pre,
        within_2x=within,
    )
    return (0 if within else 1), msgs
