"""Span/event journal — the run-wide observability spine (SURVEY.md §5).

Every phase of a run (compile, step, checkpoint, eval, elastic events,
bench probe status) lands here as one JSON line with BOTH clocks:

- ``t``: seconds on the process monotonic clock relative to journal
  creation — durations and ordering survive wall-clock jumps;
- ``wall``: unix time — joinable against MetricsLogger records and logs.

Zero-dep (json/time/os only; jax is touched lazily and optionally, for
host-0 gating).  Usable three ways::

    j = Journal("run/journal.jsonl")
    j.event("elastic.resize", hosts=4)           # point event
    with j.span("compile", fn="train_step"):     # timed span
        ...
    obs.set_default(j)                           # process-global sink:
    obs.event("watchdog.stall", age_s=12.0)      # library code logs here

With no default installed, module-level ``span``/``event`` are cheap
no-ops (a null journal), so instrumented library code costs nothing in
un-observed runs.  ``TADNN_JOURNAL=<path>`` in the environment installs
a default sink automatically on first use.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
import warnings
from typing import Any, IO, Iterator


def _process_index() -> int:
    """Host index, without forcing jax (or its backend) to load."""
    try:
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return 0
        return jax.process_index()
    except Exception:
        return 0


class Journal:
    """Monotonic-timestamped JSONL event/span sink.

    ``path=None`` keeps records in memory only (``self.records``) — the
    test/tooling mode.  ``host0_only=True`` (default) makes non-zero
    hosts' journals silent no-ops so multi-host runs produce one file.

    ``max_bytes`` (or ``TADNN_JOURNAL_MAX_BYTES`` in the environment)
    caps the file: when a write crosses the cap the file rotates to
    ``<path>.1`` (one generation, overwritten) and the journal keeps
    appending to a fresh file — a long-running server's journal can
    never eat the disk.

    ``validate=True`` (or ``TADNN_JOURNAL_VALIDATE=1``) checks every
    record against the event schema registry (:mod:`.schema`) at emit
    time and raises :class:`~.schema.JournalContractError` on drift —
    the runtime half of the telemetry contract, on for CI smoke legs.
    """

    def __init__(self, path: str | None = None, *,
                 host0_only: bool = True, meta: dict | None = None,
                 max_bytes: int | None = None, validate: bool | None = None,
                 clock=time.monotonic):
        self.path = path
        if validate is None:
            validate = os.environ.get(
                "TADNN_JOURNAL_VALIDATE", "").strip() not in ("", "0")
        self.validate = validate
        self.enabled = (not host0_only) or _process_index() == 0
        # ``t`` stamps come from here: inject a virtual clock and every
        # record's event-time is replayable (the gateway's chaos test
        # journals byte-identical sequences across runs this way)
        self._clock = clock
        self._t0 = clock()
        self._depth = 0
        self._file: IO | None = None
        self.records: list[dict] = []  # in-memory sink when path is None
        self.counts: dict[str, int] = {}
        # live taps: called with each record as it is written (the
        # gateway's fleet controller folds windows from here without
        # re-reading the file)
        self._subscribers: list = []
        if max_bytes is None:
            try:
                max_bytes = int(
                    os.environ.get("TADNN_JOURNAL_MAX_BYTES", "0")) or None
            except ValueError:
                max_bytes = None
        self._max_bytes = max_bytes
        self.rotations = 0
        if self.enabled and path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._file = open(path, "a")
        if self.enabled:
            self.event("journal.start", **(meta or {}))

    # -- sinks --------------------------------------------------------------

    def _write(self, rec: dict) -> None:
        if not self.enabled:
            return
        if self.validate:
            # runtime contract enforcement (opt-in; CI smoke legs run
            # with TADNN_JOURNAL_VALIDATE=1): every record must honor
            # its declared schema or the producer fails loudly here,
            # at the drifting emission site
            from . import schema as _schema

            problems = _schema.validate_record(rec)
            if problems:
                detail = "; ".join(f"{c}: {m}" for c, m in problems)
                raise _schema.JournalContractError(
                    f"journal record violates its event schema "
                    f"({detail})")
        self.counts[rec.get("name", "?")] = (
            self.counts.get(rec.get("name", "?"), 0) + 1
        )
        if self._file is not None:
            self._file.write(json.dumps(rec, default=str) + "\n")
            self._file.flush()
            if (self._max_bytes and not getattr(self, "_rotating", False)
                    and self._file.tell() >= self._max_bytes):
                self._rotate()
        else:
            self.records.append(rec)
        for fn in self._subscribers:
            fn(rec)

    def subscribe(self, fn) -> None:
        """Register a live tap: ``fn(rec)`` runs for every record this
        journal writes, file-backed or in-memory — the streaming
        consumer path (LiveAggregator in-process) that doesn't re-read
        the file it is itself producing."""
        self._subscribers.append(fn)

    def _rotate(self) -> None:
        """Move the full file to ``<path>.1`` (replacing any previous
        generation) and reopen fresh.  The rotated event lands first in
        the new file so a reader knows records were shed."""
        self._file.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            # rotation is best-effort (read-only fs mid-run): keep
            # appending rather than lose the sink entirely
            self._file = open(self.path, "a")
            return
        self._file = open(self.path, "a")
        self.rotations += 1
        # _rotating guards the rotated event's own write: with a cap
        # smaller than one record it would otherwise recurse forever
        self._rotating = True
        try:
            self.event("journal.rotated", rotations=self.rotations,
                       max_bytes=self._max_bytes)
        finally:
            self._rotating = False

    def event(self, name: str, **fields: Any) -> dict | None:
        """One point-in-time record: ``{"kind": "event", "name": ...}``."""
        if not self.enabled:
            return None
        rec = {"kind": "event", "name": name,
               "t": self._clock() - self._t0, "wall": time.time(),
               "depth": self._depth, **fields}
        self._write(rec)
        return rec

    @contextlib.contextmanager
    def span(self, name: str, **fields: Any) -> Iterator[dict]:
        """Timed region.  Yields the record-in-progress so callers can
        attach result fields before it is written on exit; exceptions are
        recorded (``error`` field) and re-raised."""
        rec: dict[str, Any] = {"kind": "span", "name": name, **fields}
        if not self.enabled:
            yield rec
            return
        t_start = self._clock()
        rec["t"] = t_start - self._t0
        rec["wall"] = time.time()
        rec["depth"] = self._depth
        self._depth += 1
        try:
            yield rec
        except BaseException as e:
            rec["error"] = f"{type(e).__name__}: {e}"
            raise
        finally:
            self._depth -= 1
            rec["dur_s"] = self._clock() - t_start
            self._write(rec)

    def named(self, prefix: str) -> list[dict]:
        """In-memory records (``path=None`` mode) whose name is
        ``prefix`` or lives under it as a dotted namespace — ``'lint'``
        matches ``lint.finding`` and ``lint.summary``."""
        return [
            rec for rec in self.records
            if rec.get("name", "") == prefix
            or rec.get("name", "").startswith(prefix + ".")
        ]

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- reading ------------------------------------------------------------

    @staticmethod
    def read(path: str) -> list[dict]:
        """Parse a journal file, skipping torn/partial JSONL lines.

        A crashed writer leaves a torn final line; a concurrent writer
        can be seen mid-record.  Neither may take down ``tadnn report``,
        so bad lines are skipped — with ONE warning per file (not one
        per line, not silence: a silently-shrinking journal is the
        observability failure mode this layer exists to prevent).
        Non-dict JSON lines (bare numbers/strings) are torn too.
        """
        out: list[dict] = []
        bad = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
                else:
                    bad += 1
        if bad and path not in _warned_corrupt:
            _warned_corrupt.add(path)
            warnings.warn(
                f"journal {path}: skipped {bad} torn/corrupt line(s) "
                f"({len(out)} readable records kept)",
                stacklevel=2,
            )
        return out


    @staticmethod
    def follow(path: str, *, poll_s: float = 0.2,
               idle_timeout: float | None = None,
               stop=None, sleep=time.sleep) -> Iterator[dict]:
        """Tail a journal file as a concurrent writer appends to it.

        Yields each record as soon as its line is complete.  A torn
        final line — the writer seen mid-record — is buffered until its
        newline arrives, so a live reader never drops the record a
        crash-time reader would have skipped; interior corrupt lines
        are skipped with the same once-per-file warning as ``read``.

        Stops when ``stop()`` returns true (checked between polls) or
        after ``idle_timeout`` seconds with no new bytes (None = follow
        forever).  ``sleep`` is injectable so tests can drive the tail
        loop without real waiting.

        The path may not exist yet — a monitor is routinely started
        before the engine's first event (the gateway does exactly
        this): creation is polled for under the same ``idle_timeout``
        budget instead of raising.

        Size-capped rotation is survived: when the writer rotates the
        file out from under the tail (``os.replace`` to ``<path>.1`` —
        the open fd now points at the OLD generation) or truncates it,
        the follower detects the inode swap / size shrink, reopens the
        fresh file from the top, and warns once per rotation; a torn
        buffer from the old generation is dropped (its tail lives in
        ``<path>.1``, not the stream)."""
        buf = ""
        idle = 0.0
        while not os.path.exists(path):
            if stop is not None and stop():
                return
            if idle_timeout is not None and idle >= idle_timeout:
                return
            sleep(poll_s)
            idle += poll_s
        idle = 0.0
        f = open(path)
        try:
            while True:
                chunk = f.read()
                if chunk:
                    idle = 0.0
                    buf += chunk
                    while "\n" in buf:
                        line, _, buf = buf.partition("\n")
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            rec = None
                        if isinstance(rec, dict):
                            yield rec
                            continue
                        if path not in _warned_corrupt:
                            _warned_corrupt.add(path)
                            warnings.warn(
                                f"journal {path}: skipping torn/corrupt "
                                f"line(s) while following", stacklevel=2)
                    continue
                # no new bytes on the open fd: check whether the file
                # was rotated (replaced: different inode at the path)
                # or truncated (shrunk below our read position) and
                # re-attach to the live generation if so
                rotated = False
                try:
                    disk = os.stat(path)
                    here = os.fstat(f.fileno())
                    if disk.st_ino != here.st_ino:
                        rotated = True
                    elif disk.st_size < f.tell():
                        rotated = True
                except OSError:
                    # path briefly absent mid-replace: treat as idle,
                    # the next poll sees the new file
                    pass
                if rotated:
                    warnings.warn(
                        f"journal {path}: rotated mid-follow, "
                        f"re-attached to the new generation"
                        + (" (dropped a torn partial line)"
                           if buf.strip() else ""), stacklevel=2)
                    f.close()
                    f = open(path)
                    buf = ""
                    idle = 0.0
                    continue
                if stop is not None and stop():
                    return
                if idle_timeout is not None and idle >= idle_timeout:
                    return
                sleep(poll_s)
                idle += poll_s
        finally:
            f.close()


# paths already warned about corrupt lines (once-per-file, process-wide)
_warned_corrupt: set[str] = set()


class _NullJournal(Journal):
    """Sink of last resort: every call is a no-op."""

    def __init__(self):  # noqa: D401 — deliberately skips Journal.__init__
        self.path = None
        self.enabled = False
        self.validate = False
        self._file = None
        self.records = []
        self.counts = {}
        self._subscribers = []
        self._depth = 0
        self._clock = time.monotonic
        self._t0 = time.monotonic()


_NULL = _NullJournal()
_default: Journal | None = None


def set_default(journal: Journal | None) -> Journal | None:
    """Install (or clear, with None) the process-global journal."""
    global _default
    _default = journal
    return journal


def get_default() -> Journal:
    """The process-global journal; honors ``TADNN_JOURNAL`` env on first
    call; a silent null sink when nothing is configured."""
    global _default
    if _default is None:
        env = os.environ.get("TADNN_JOURNAL")
        if env:
            _default = Journal(env)
    return _default if _default is not None else _NULL


@contextlib.contextmanager
def as_default(journal: Journal | None) -> Iterator[Journal]:
    """Temporarily install ``journal`` as the process default (restores
    the previous default on exit).  ``None`` is a pass-through."""
    global _default
    if journal is None:
        yield get_default()
        return
    prev = _default
    _default = journal
    try:
        yield journal
    finally:
        _default = prev


def event(name: str, **fields: Any) -> dict | None:
    """Module-level event on the default journal (no-op when unset)."""
    return get_default().event(name, **fields)


def span(name: str, **fields: Any):
    """Module-level span on the default journal (no-op when unset)."""
    return get_default().span(name, **fields)
