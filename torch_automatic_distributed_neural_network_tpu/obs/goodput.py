"""Goodput accounting: bucket run wall-clock into where it actually went.

Buckets (the TorchTitan-style breakdown, PAPERS.md):

- ``compile``      jit trace + XLA compile (first step, shape-churn
                   recompiles, AOT compile_report calls)
- ``step``         steady-state training-step host time (the goodput)
- ``checkpoint``   save/restore + async-commit waits
- ``eval``         periodic evaluation passes
- ``trace``        profiler-instrumented steps (TrainerConfig.
                   trace_every_n, obs/trace.py) — fenced and captured,
                   so their wall time is overhead, not goodput
- ``input_stall``  waiting on the data source for the next batch
- ``idle``         everything unaccounted (guards, logging, callbacks,
                   host-side bookkeeping) — computed as the remainder

``summary()`` fractions are of total wall-clock and sum to ~1.0 by
construction; ``goodput`` is step / total.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

BUCKETS = ("compile", "step", "checkpoint", "eval", "trace",
           "input_stall", "idle")


class GoodputMeter:
    """Accumulates seconds per bucket against a run-start reference."""

    def __init__(self):
        self._t_start = time.monotonic()
        self.seconds: dict[str, float] = {b: 0.0 for b in BUCKETS}

    def add(self, bucket: str, seconds: float) -> None:
        if bucket not in self.seconds:
            raise ValueError(
                f"unknown goodput bucket {bucket!r}; expected one of {BUCKETS}"
            )
        self.seconds[bucket] += max(0.0, seconds)

    @contextlib.contextmanager
    def measure(self, bucket: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add(bucket, time.monotonic() - t0)

    def total_wall_s(self) -> float:
        return time.monotonic() - self._t_start

    def summary(self, total_wall_s: float | None = None) -> dict:
        """Bucket seconds + fractions-of-wall-clock summing to ~1.0.

        ``idle`` is the remainder of the wall clock not claimed by any
        measured bucket, clamped at 0 (measured buckets can slightly
        overlap the total on coarse clocks).
        """
        total = total_wall_s if total_wall_s is not None else self.total_wall_s()
        secs = dict(self.seconds)
        measured = sum(v for b, v in secs.items() if b != "idle")
        secs["idle"] = max(0.0, total - measured)
        total = max(total, 1e-9)
        return {
            "total_wall_s": total,
            "seconds": {b: secs[b] for b in BUCKETS},
            "fractions": {b: secs[b] / total for b in BUCKETS},
            "goodput": secs["step"] / total,
        }
