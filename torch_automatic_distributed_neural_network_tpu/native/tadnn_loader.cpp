// Native data loader (SURVEY.md C13; task brief: runtime components are
// native where the reference's are — torch's DataLoader workers are C++
// threads under the hood).
//
// Reads a binary token corpus (header + flat little-endian tokens),
// serves step-indexed [batch, seq_len+1] windows with a deterministic
// per-epoch affine shuffle, and prefetches ahead on a background thread
// so the host-side input pipeline never blocks the TPU dispatch loop.
//
// Determinism contract (mirrored bit-for-bit by the Python fallback in
// data/loader.py): window w of epoch e maps to file window
//   perm_e(w) = (a_e * w + c_e) % n_windows
// with a_e/c_e derived from splitmix64(seed, epoch) and a_e forced odd
// and coprime to n_windows, so batch(step) is a pure function of
// (file, seq_len, batch_size, seed, step) — elastic resume sees the
// same batches (training/elastic.py).
//
// File format "TADN" v1:
//   u32 magic 0x4E444154 ("TADN") | u32 version=1 | u32 dtype (2|4 bytes)
//   u64 n_tokens | tokens...

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x4E444154;  // "TADN" little-endian

uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

uint64_t gcd64(uint64_t a, uint64_t b) {
  while (b) {
    uint64_t t = a % b;
    a = b;
    b = t;
  }
  return a;
}

struct Header {
  uint32_t magic;
  uint32_t version;
  uint32_t dtype_bytes;
  uint32_t pad;
  uint64_t n_tokens;
};

struct Loader {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  const uint8_t* tokens = nullptr;  // past the header
  uint64_t n_tokens = 0;
  uint32_t dtype_bytes = 2;

  int64_t seq_len = 0;    // window is seq_len + 1 tokens
  int64_t batch = 0;
  uint64_t seed = 0;
  uint64_t n_windows = 0;

  // prefetch ring: slot s holds the batch for step ring_step[s]
  int depth = 0;
  std::vector<std::vector<uint32_t>> ring;
  std::vector<std::atomic<int64_t>> ring_step;
  std::atomic<int64_t> want{0};  // next step the consumer will ask for
  std::atomic<bool> stop{false};
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv;

  void epoch_params(uint64_t epoch, uint64_t* a, uint64_t* c) const {
    uint64_t s = splitmix64(seed ^ (epoch * 0x5851F42D4C957F2DULL + 1));
    uint64_t av = (splitmix64(s) % n_windows) | 1ULL;  // odd
    while (gcd64(av, n_windows) != 1) av += 2;
    *a = av % n_windows ? av % n_windows : 1;
    // av could reduce to 0 only if n_windows==1; guard keeps a valid
    *c = splitmix64(s + 1) % n_windows;
  }

  uint64_t window_start(int64_t global_row) const {
    uint64_t epoch = static_cast<uint64_t>(global_row) / n_windows;
    uint64_t w = static_cast<uint64_t>(global_row) % n_windows;
    uint64_t a, c;
    epoch_params(epoch, &a, &c);
    uint64_t pw = (a * w + c) % n_windows;
    return pw * static_cast<uint64_t>(seq_len);
  }

  void fill(int64_t step, uint32_t* out) const {
    const int64_t width = seq_len + 1;
    for (int64_t r = 0; r < batch; ++r) {
      uint64_t start = window_start(step * batch + r);
      const uint8_t* src = tokens + start * dtype_bytes;
      uint32_t* dst = out + r * width;
      if (dtype_bytes == 2) {
        const uint16_t* s16 = reinterpret_cast<const uint16_t*>(src);
        for (int64_t i = 0; i < width; ++i) dst[i] = s16[i];
      } else {
        std::memcpy(dst, src, width * sizeof(uint32_t));
      }
    }
  }

  // Slot protocol (seqlock-style): the worker marks a slot kFilling
  // before writing and stores the step after; a consumer that read
  // `step` before copying re-checks after the copy — any concurrent
  // overwrite leaves the slot != step at the re-check (a slot is reused
  // only for step + k*depth, never the same value), so a torn copy is
  // always detected and recomputed synchronously.
  static constexpr int64_t kFilling = -2;

  void prefetch_loop() {
    while (!stop.load(std::memory_order_acquire)) {
      int64_t base = want.load(std::memory_order_acquire);
      bool did = false;
      for (int d = 0; d < depth; ++d) {
        int64_t step = base + d;
        int slot = static_cast<int>(step % depth);
        if (ring_step[slot].load(std::memory_order_acquire) != step) {
          ring_step[slot].store(kFilling, std::memory_order_relaxed);
          // full fence: the kFilling store must become visible before
          // any of fill()'s plain data writes (store-store barrier), or
          // a consumer's torn copy could pass its re-check
          std::atomic_thread_fence(std::memory_order_seq_cst);
          fill(step, ring[slot].data());
          ring_step[slot].store(step, std::memory_order_release);
          did = true;
        }
      }
      if (!did) {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait_for(lk, std::chrono::milliseconds(50));
      }
    }
  }
};

}  // namespace

extern "C" {

void* tadnn_loader_open(const char* path, int64_t seq_len, int64_t batch,
                        uint64_t seed, int prefetch_depth) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  const Header* h = reinterpret_cast<const Header*>(map);
  if (h->magic != kMagic || h->version != 1 ||
      (h->dtype_bytes != 2 && h->dtype_bytes != 4)) {
    munmap(map, st.st_size);
    close(fd);
    return nullptr;
  }
  if (h->n_tokens > (UINT64_MAX - sizeof(Header)) / h->dtype_bytes) {
    munmap(map, st.st_size);  // header would overflow the size check
    close(fd);
    return nullptr;
  }
  uint64_t needed = sizeof(Header) + h->n_tokens * h->dtype_bytes;
  if (static_cast<uint64_t>(st.st_size) < needed ||
      h->n_tokens < static_cast<uint64_t>(seq_len) + 1) {
    munmap(map, st.st_size);
    close(fd);
    return nullptr;
  }

  Loader* L = new Loader();
  L->fd = fd;
  L->map = static_cast<const uint8_t*>(map);
  L->map_len = st.st_size;
  L->tokens = L->map + sizeof(Header);
  L->n_tokens = h->n_tokens;
  L->dtype_bytes = h->dtype_bytes;
  L->seq_len = seq_len;
  L->batch = batch;
  L->seed = seed;
  L->n_windows = (h->n_tokens - 1) / static_cast<uint64_t>(seq_len);
  L->depth = prefetch_depth > 0 ? prefetch_depth : 0;
  if (L->depth) {
    L->ring.resize(L->depth);
    for (auto& v : L->ring) v.resize(batch * (seq_len + 1));
    L->ring_step = std::vector<std::atomic<int64_t>>(L->depth);
    for (auto& s : L->ring_step) s.store(-1);
    L->worker = std::thread([L] { L->prefetch_loop(); });
  }
  return L;
}

int64_t tadnn_loader_n_windows(void* handle) {
  return static_cast<Loader*>(handle)->n_windows;
}

// Copies batch `step` into out[batch * (seq_len+1)] (uint32). Serves from
// the prefetch ring when the slot is ready, else computes synchronously.
int tadnn_loader_batch(void* handle, int64_t step, uint32_t* out) {
  Loader* L = static_cast<Loader*>(handle);
  if (step < 0) return -1;
  if (L->depth) {
    int slot = static_cast<int>(step % L->depth);
    bool served = false;
    if (L->ring_step[slot].load(std::memory_order_acquire) == step) {
      // Seqlock-pattern read: the memcpy races the worker's fill() when
      // the worker laps the ring between our two ring_step loads.  The
      // plain (non-atomic) copy of racing memory is formally UB in the
      // C++ memory model; it is the standard seqlock trade-off, accepted
      // deliberately here because (a) the re-check below discards any
      // torn copy before it is observable, (b) the data is plain
      // uint32 with no invariants a torn read could violate mid-copy,
      // and (c) copying through per-word relaxed atomics would forfeit
      // the vectorized memcpy on the hot path.  The acquire fence orders
      // the copy before the confirming load (the "version re-check").
      std::memcpy(out, L->ring[slot].data(),
                  L->ring[slot].size() * sizeof(uint32_t));
      std::atomic_thread_fence(std::memory_order_acquire);
      served =
          L->ring_step[slot].load(std::memory_order_relaxed) == step;
    }
    if (!served) L->fill(step, out);
    // monotonic max: replaying an old step (elastic resume) must not
    // rewind the ring and discard prefetched future batches
    int64_t cur = L->want.load(std::memory_order_relaxed);
    while (cur < step + 1 &&
           !L->want.compare_exchange_weak(cur, step + 1,
                                          std::memory_order_release)) {
    }
    L->cv.notify_one();
  } else {
    L->fill(step, out);
  }
  return 0;
}

void tadnn_loader_close(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  if (L->depth) {
    L->stop.store(true);
    L->cv.notify_one();
    if (L->worker.joinable()) L->worker.join();
  }
  munmap(const_cast<uint8_t*>(L->map), L->map_len);
  close(L->fd);
  delete L;
}

}  // extern "C"
