"""Partition planner (component C2).

Reference capability (SURVEY.md C2; BASELINE.json north star): inspect the
model structure and device topology and emit a shard plan, automatically
choosing between data-parallel, tensor-parallel and FSDP-style execution
(BASELINE.json:8-11) so that a one-line ``AutoDistribute(model)`` runs
unmodified.

TPU-native realization: the plan is a ``jax.sharding.Mesh`` plus a pytree of
``PartitionSpec`` — GSPMD then inserts all collectives.  The planner is a
pure function ``(abstract params, mesh, policy) -> ShardPlan`` and is fully
unit-testable without devices.

Strategy catalogue (mirrors the reference's exercised configs):

- ``dp``        replicate params, shard batch on ``data``  (DDP analog)
- ``fsdp``      ZeRO-3: shard every param's largest divisible axis on the
                ``fsdp`` mesh axis; optimizer state inherits the same specs
- ``tp``        Megatron column/row splits on attention/MLP weights over the
                ``tensor`` axis, chosen by name-pattern rules
- ``tp_fsdp``   TP rules first, FSDP on what remains
- ``auto``      pick one of the above from model size vs per-chip HBM and
                mesh shape
- ``tuned``     cost-model-driven search over candidate factorizations
                (tune/ subsystem: enumerate -> score -> cache); falls
                back to the ``auto`` heuristic when the space is
                degenerate
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Callable, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import topology as topo_mod

# ---------------------------------------------------------------------------
# Sharding rules
# ---------------------------------------------------------------------------

Axis = str | tuple[str, ...] | None


@dataclasses.dataclass(frozen=True)
class Rule:
    """Name-pattern sharding rule.

    ``pattern`` is a regex searched against the '/'-joined parameter path
    (e.g. ``"layers_3/attn/q_proj/kernel"``).  ``dim_axes`` assigns mesh
    axes to the *trailing* dimensions of the parameter: the last
    ``len(dim_axes)`` dims get the listed axes; leading dims are
    unsharded.  First matching rule wins.
    """

    pattern: str
    dim_axes: tuple[Axis, ...]

    def matches(self, path: str) -> bool:
        return re.search(self.pattern, path) is not None


# Megatron-style transformer rules (SURVEY.md C5): column-split the
# fan-out projections (QKV, MLP up/gate), row-split the fan-in
# projections (attention out, MLP down).  Embeddings vocab-split.
TRANSFORMER_RULES: tuple[Rule, ...] = (
    Rule(r"(q_proj|k_proj|v_proj|qkv|query|key|value|wq|wk|wv)/kernel", (None, "tensor")),
    Rule(r"(o_proj|out_proj|attn_out|wo|proj_out)/kernel", ("tensor", None)),
    Rule(r"(up_proj|gate_proj|fc1|wi|w1|w3|mlp_in)/kernel", (None, "tensor")),
    Rule(r"(down_proj|fc2|wo_mlp|w2|mlp_out)/kernel", ("tensor", None)),
    Rule(r"(embed|embedding|wte|tok_embed)[^/]*/(embedding|kernel)", ("tensor", None)),
    Rule(r"(lm_head|output_proj|unembed)/kernel", (None, "tensor")),
    # biases of column-split layers follow the split output dim
    Rule(r"(q_proj|k_proj|v_proj|qkv|up_proj|gate_proj|fc1|wi|w1|w3)/bias", ("tensor",)),
    # torch-bridge naming (models/torch_bridge.py): MHA weights keep the
    # TORCH [out, in] layout — packed qkv `in_w` [3d, d] column-splits
    # dim 0, `out_w` [d, d] row-splits its contraction (input) dim 1 —
    # while Linear kernels are transposed to flax [in, out] layout
    # (lin1 fan-out -> column, lin2 fan-in -> row).
    Rule(r"(sa|ca)\.in_w$", ("tensor", None)),
    Rule(r"(sa|ca)\.in_b$", ("tensor",)),
    Rule(r"(sa|ca)\.out_w$", (None, "tensor")),
    Rule(r"lin1\.kernel$", (None, "tensor")),
    Rule(r"lin1\.bias$", ("tensor",)),
    Rule(r"lin2\.kernel$", ("tensor", None)),
    # norms / scalars replicated
    Rule(r"(norm|ln|layernorm|rmsnorm|scale)", ()),
)

# MoE expert banks (models/moe.py): [.., E, d, f] einsum weights — the E
# dim (third-from-last, stable under nn.scan layer stacking) shards over
# the ``expert`` mesh axis (SURVEY.md §2.2 EP row); routers replicate.
MOE_RULES: tuple[Rule, ...] = (
    Rule(r"(experts?_(up|gate|down)|expert_bank|moe_w\d)[^/]*$", ("expert", None, None)),
    Rule(r"router/", ()),
)

# ep_tp (Mixtral-style EP x TP): experts on the ``expert`` axis AND each
# expert Megatron-split on ``tensor`` — fan-out banks [E, d, f] column-
# split the f dim, the fan-in bank [E, f, d] row-splits it; the down
# contraction then reduces over tensor (GSPMD psum), exactly the dense
# Megatron pattern per expert.
MOE_TP_RULES: tuple[Rule, ...] = (
    # fan-in first: 'down' banks and the w2 of the w1/w2/w3 convention
    # ([E, f, d]) row-split — contraction dim f on tensor
    Rule(r"(experts?_down|moe_w2)[^/]*$", ("expert", "tensor", None)),
    # fan-out ([E, d, f]) column-split — output dim f on tensor
    Rule(r"(experts?_(up|gate)|expert_bank|moe_w[13])[^/]*$",
         ("expert", None, "tensor")),
    # unknown-orientation banks: expert axis only (the MOE_RULES layout)
    Rule(r"moe_w\d[^/]*$", ("expert", None, None)),
    Rule(r"router/", ()),
)


# ---------------------------------------------------------------------------
# Plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ShardPlan:
    """The planner's output: everything needed to jit a sharded step.

    ``opt_spec_tree`` (set when ``zero1=True``) is a params-structured
    PartitionSpec tree for the OPTIMIZER state only: each param's
    largest still-unsharded divisible dim additionally shards over the
    ``data`` axis (ZeRO-1 cross-replica weight-update sharding, arxiv
    2004.13336) while the params themselves keep ``param_specs``.
    """

    mesh: Mesh
    strategy: str
    param_specs: Any  # pytree of PartitionSpec, same structure as params
    batch_spec: P  # spec for the leading (batch) dim of inputs
    remat: bool = False
    zero1: bool = False
    opt_spec_tree: Any = None  # params-structured specs for opt state

    def param_shardings(self) -> Any:
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def opt_shardings(self) -> Any:
        """NamedShardings for the optimizer-state specs (param specs
        when no distinct zero1 tree exists)."""
        specs = (self.opt_spec_tree if self.opt_spec_tree is not None
                 else self.param_specs)
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec)

    def describe(self) -> str:
        strat = self.strategy + ("+zero1" if self.zero1 else "")
        lines = [f"ShardPlan(strategy={strat}, mesh={dict(zip(self.mesh.axis_names, self.mesh.devices.shape))})"]
        flat = _flatten_with_paths(self.param_specs)
        opt_flat = (_flatten_with_paths(self.opt_spec_tree)
                    if self.opt_spec_tree is not None else None)
        for i, (path, spec) in enumerate(flat):
            line = f"  {path}: {spec}"
            if opt_flat is not None and opt_flat[i][1] != spec:
                line += f"  [opt: {opt_flat[i][1]}]"
            lines.append(line)
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def _flatten_with_paths(tree: Any) -> list[tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, P)
    )
    out = []
    for keypath, leaf in flat:
        out.append((path_str(keypath), leaf))
    return out


def path_str(keypath: Sequence[Any]) -> str:
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _axis_size(axis: Axis, degrees: Mapping[str, int]) -> int:
    if axis is None:
        return 1
    if isinstance(axis, tuple):
        return math.prod(degrees.get(a, 1) for a in axis)
    return degrees.get(axis, 1)


def _norm_spec(dims: Sequence[Axis]) -> P:
    """Drop trailing unsharded dims so P(None) == P() comparisons hold."""
    dims = list(dims)
    while dims and dims[-1] is None:
        dims.pop()
    return P(*dims)


def _spec_from_rule(
    rule: Rule, shape: tuple[int, ...], degrees: Mapping[str, int]
) -> P | None:
    """Build a PartitionSpec from a rule, or None if shapes don't divide."""
    n = len(rule.dim_axes)
    if n > len(shape):
        return None
    dims: list[Axis] = [None] * (len(shape) - n) + list(rule.dim_axes)
    for d, ax in enumerate(dims):
        size = _axis_size(ax, degrees)
        if size > 1 and shape[d] % size != 0:
            return None  # indivisible — caller falls back
    return _norm_spec(dims)


def _fsdp_spec(
    shape: tuple[int, ...],
    degrees: Mapping[str, int],
    existing: P | None = None,
    fsdp_axes: tuple[str, ...] = ("fsdp",),
) -> P:
    """Shard the largest still-unsharded, divisible dim over the fsdp axes.

    ZeRO-3 pattern (SURVEY.md C6, PAPERS.md:5,7): parameters are stored
    sharded and all-gathered on use by GSPMD; optimizer state inherits the
    spec, giving ZeRO-1/2 for free.
    """
    size = math.prod(_axis_size(a, degrees) for a in fsdp_axes)
    if size <= 1:
        return existing or P()
    used: list[Axis] = list(existing) if existing is not None else [None] * len(shape)
    while len(used) < len(shape):
        used.append(None)
    # prefer the largest dim; tie-break on the first
    order = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in order:
        if used[d] is None and shape[d] % size == 0:
            used[d] = fsdp_axes if len(fsdp_axes) > 1 else fsdp_axes[0]
            return _norm_spec(used)
    return _norm_spec(used)  # nothing divisible — stays as-is


# ---------------------------------------------------------------------------
# Planner entry points
# ---------------------------------------------------------------------------

# Rough per-chip HBM capacities (bytes) by device kind substring.
_HBM_BYTES = {
    "v5 lite": 16 * 2**30,
    "v5e": 16 * 2**30,
    "v4": 32 * 2**30,
    "v5p": 95 * 2**30,
    "v6": 32 * 2**30,
    "cpu": 8 * 2**30,
}


def _hbm_bytes(device_kind: str) -> int:
    dk = device_kind.lower()
    for k, v in _HBM_BYTES.items():
        if k in dk:
            return v
    return 16 * 2**30


# Parameter paths holding a scanned layer stack (leading [n_layers, ...]
# dim, models/transformer_core.py nn.scan) — the dim pipeline parallelism
# shards into stages.
PIPE_STACK_PATTERN = r"(^|/)layers/"


def param_spec_tree(
    abstract_params: Any,
    mesh: Mesh,
    strategy: str,
    rules: Sequence[Rule] = TRANSFORMER_RULES,
    fsdp_axes: tuple[str, ...] = ("fsdp",),
    pipe_stack_pattern: str = PIPE_STACK_PATTERN,
) -> Any:
    """Assign a PartitionSpec to every parameter by path+shape.

    Pure function over abstract shapes — the unit-testable core (SURVEY.md
    §7 phase 3).
    """
    degrees = topo_mod.mesh_degrees(mesh)
    use_tp = (strategy in ("tp", "tp_fsdp", "ep_tp")
              and degrees.get("tensor", 1) > 1)
    use_fsdp = (
        strategy in ("fsdp", "tp_fsdp", "ep_fsdp")
        and _axis_size(fsdp_axes, degrees) > 1
    )
    use_ep = degrees.get("expert", 1) > 1
    pipe = degrees.get("pipe", 1)

    def assign(keypath, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        path = path_str(keypath)
        spec: P | None = None
        if (
            pipe > 1
            and re.search(pipe_stack_pattern, path)
            and shape
            and shape[0] % pipe == 0
        ):
            # leading layer-stack dim -> pipeline stages (parallel/
            # pipeline.py); under pipe x tp the trailing dims keep their
            # Megatron col/row split (the stage-local TP composition)
            entries: list[Axis] = [None] * len(shape)
            if use_tp:
                for rule in rules:
                    if rule.matches(path):
                        tp = _spec_from_rule(rule, shape, degrees)
                        if tp is not None:
                            entries = list(tp)
                            entries += [None] * (len(shape) - len(entries))
                        break
            if entries[0] is None:
                entries[0] = "pipe"
            spec = _norm_spec(entries)
        if spec is None and use_ep:
            for rule in (MOE_TP_RULES if use_tp else MOE_RULES):
                if rule.matches(path):
                    spec = _spec_from_rule(rule, shape, degrees)
                    break
        if spec is None and use_tp:
            for rule in rules:
                if rule.matches(path):
                    spec = _spec_from_rule(rule, shape, degrees)
                    break
        if use_fsdp and len(shape) >= 1:
            spec = _fsdp_spec(shape, degrees, existing=spec, fsdp_axes=fsdp_axes)
        return spec if spec is not None else P()

    tree = jax.tree_util.tree_map_with_path(assign, abstract_params)
    if use_tp and not any(
        _spec_uses_axis(s, "tensor")
        for s in jax.tree.leaves(tree, is_leaf=lambda x: isinstance(x, P))
    ):
        import warnings

        warnings.warn(
            f"strategy {strategy!r} requests tensor parallelism but ZERO "
            "parameters matched a tensor rule: the 'tensor' mesh axis "
            "will sit unused and every parameter is replicated across "
            "it (silent tp degradation).  Models with nonstandard param "
            "names — e.g. hand-written modules or from_torch bridges of "
            "custom architectures — need custom rules: pass "
            "AutoDistribute(..., rules=(planner.Rule(r'my_proj/kernel', "
            "(None, 'tensor')), ...)) mapping your param paths to "
            "column/row splits (see planner.TRANSFORMER_RULES).",
            stacklevel=2,
        )
    return tree


def _spec_uses_axis(spec: P, axis: str) -> bool:
    for entry in spec:
        if entry == axis:
            return True
        if isinstance(entry, (tuple, list)) and axis in entry:
            return True
    return False


def zero1_spec_tree(
    abstract_params: Any,
    mesh: Mesh,
    param_specs: Any,
) -> Any:
    """ZeRO-1 optimizer-state spec tree (arxiv 2004.13336).

    Per param: the largest still-unsharded divisible dim additionally
    shards over the ``data`` axis, so the optimizer moments (and the
    weight update itself) live 1/dp-th per replica while the params keep
    their own specs.  Indivisible leaves keep the param spec — their
    moments stay replicated and are charged honestly by the memory
    model.  Pure shape math; ``mesh`` may be a degrees mapping.
    """
    degrees = topo_mod.mesh_degrees(mesh)
    if degrees.get("data", 1) <= 1:
        return param_specs  # no data replicas — nothing to shard over
    spec_flat, treedef = jax.tree_util.tree_flatten(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    leaves = jax.tree.leaves(abstract_params)
    if len(spec_flat) != len(leaves):
        raise ValueError(
            f"param_specs ({len(spec_flat)} leaves) does not match "
            f"abstract_params ({len(leaves)} leaves)"
        )
    out = []
    for spec, leaf in zip(spec_flat, leaves):
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape:
            out.append(spec)
            continue
        out.append(_fsdp_spec(shape, degrees, existing=spec,
                              fsdp_axes=("data",)))
    return jax.tree_util.tree_unflatten(treedef, out)


def batch_partition_spec(mesh: Mesh) -> P:
    """Batch dim sharded over every data-carrying axis present in the mesh.

    The ``expert`` axis carries batch too (EP groups double as DP ranks,
    DeepSpeed-MoE style): tokens ride the expert axis until the MoE
    dispatch all_to_all regroups them by expert.
    """
    degrees = topo_mod.mesh_degrees(mesh)
    axes = tuple(a for a in ("data", "fsdp", "expert") if degrees.get(a, 1) > 1)
    return P(axes) if axes else P(None)


def tree_bytes(abstract_params: Any) -> int:
    leaves = jax.tree.leaves(abstract_params)
    total = 0
    for leaf in leaves:
        shape = getattr(leaf, "shape", ())
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        total += math.prod(shape) * dtype.itemsize if shape else dtype.itemsize
    return total


def _expert_banks(abstract_params: Any) -> list[tuple[str, Any]]:
    """(path, leaf) of every MoE expert bank ([..., E, d, f], models/moe.py)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(abstract_params)
    return [
        (path_str(keypath), leaf)
        for keypath, leaf in flat
        if len(tuple(getattr(leaf, "shape", ()))) >= 3
        and re.search(MOE_RULES[0].pattern, path_str(keypath))
    ]


def detect_expert_count(abstract_params: Any) -> int | None:
    """Number of experts E if the model has MoE expert banks, else None.

    E is third-from-last in the bank shape, stable under the scanned
    [n_layers, ...] stacking.
    """
    banks = _expert_banks(abstract_params)
    return int(banks[0][1].shape[-3]) if banks else None


def tp_applicable(abstract_params: Any, rules: Sequence[Rule]) -> bool:
    """True if any rule would actually shard a dim of this model's params
    on the 'tensor' axis (replication/bias rules don't count)."""
    paths = [p for p, _ in _flatten_with_paths(
        jax.tree.map(lambda x: P(), abstract_params))]
    tp_rules = [
        r for r in rules
        if any(
            ax == "tensor" or (isinstance(ax, tuple) and "tensor" in ax)
            for ax in r.dim_axes
        )
    ]
    return any(r.matches(p) for p in paths for r in tp_rules)


def choose_strategy(
    abstract_params: Any,
    topo: topo_mod.Topology,
    rules: Sequence[Rule] = TRANSFORMER_RULES,
    state_factor: float = 4.0,
) -> tuple[str, dict[str, int]]:
    """Auto policy: pick (strategy, mesh axis degrees) from model size vs
    HBM and whether TP rules apply to this model's parameter names.

    Heuristics (documented, deliberately simple — SURVEY.md §7 'hard parts'
    #1 says start rule-based and fail loudly):

    - 1 device -> no-op DP (identity path, BASELINE.json:7)
    - params + grads + adam state (~4x param bytes in fp32 master) fit in
      60% of one chip's HBM -> plain DP (cheapest collectives)
    - else if any TP rule matches and a tensor degree <= 8 divides the
      device count -> tp_fsdp (TP inside, FSDP across)
    - else -> FSDP over all devices
    """
    n = topo.num_devices
    if n == 1:
        return "dp", {"data": 1}
    pbytes = tree_bytes(abstract_params)
    # params + grads + 2 adam moments; state_factor scales param bytes to
    # full train-state bytes (4.0 for uniform fp32; training/precision.py
    # supplies the mixed-precision value, e.g. 2.5 for fp32 master + bf16
    # grads/moments)
    train_state_bytes = state_factor * pbytes
    e_count = detect_expert_count(abstract_params)
    if e_count:
        # MoE model: put the expert dim on its own axis so dispatch rides
        # one all_to_all instead of replicating every expert everywhere.
        e = math.gcd(n, e_count)
        if e > 1:
            rest = n // e
            # per-device bytes: only the expert banks shard under 'ep';
            # dense params stay replicated unless fsdp joins in.
            expert_b = sum(
                math.prod(leaf.shape)
                * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
                for _, leaf in _expert_banks(abstract_params)
            )
            dense_b = pbytes - expert_b
            per_device = state_factor * (dense_b + expert_b / e)
            if per_device < 0.6 * _hbm_bytes(topo.device_kind):
                return "ep", {"expert": e, "data": rest}
            # Memory-tight: the fsdp axis must be real (>=2) or dense
            # params stay replicated — shrink the expert degree once to
            # free devices for it (e divides n, so one shrink to a proper
            # divisor always leaves n // e >= 2).
            if n // e < 2:
                e = max(d for d in range(1, e) if e % d == 0)
            if e > 1:
                return "ep_fsdp", {"expert": e, "fsdp": n // e}
            # can't keep both axes nontrivial -> fall through to fsdp/dp
    if train_state_bytes < 0.6 * _hbm_bytes(topo.device_kind):
        return "dp", {"data": n}
    if tp_applicable(abstract_params, rules):
        for t in (8, 4, 2):
            # both axes must stay nontrivial: n == t would leave a dead
            # degree-1 fsdp axis (spurious PL004 downstream)
            if n % t == 0 and n // t >= 2:
                return "tp_fsdp", {"fsdp": n // t, "tensor": t}
    # defensive: a degenerate topology must never reach the fsdp
    # catch-all — a {"fsdp": 1} mesh is a dead axis, not a strategy
    if n == 1:
        return "dp", {"data": 1}
    return "fsdp", {"fsdp": n}


def spec_axes(spec: P) -> set[str]:
    """Mesh axis names a PartitionSpec actually uses."""
    out: set[str] = set()
    for entry in spec:
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            if ax:
                out.add(ax)
    return out


# pre-analysis/ name; tune/ and external callers may still use it
_spec_axes = spec_axes


# ---------------------------------------------------------------------------
# Reshard slicing (sharded-checkpoint support, training/shards.py)
# ---------------------------------------------------------------------------


def spec_to_json(spec: P) -> list:
    """A PartitionSpec as a JSON-serializable list (axis name, list of
    axis names, or None per dim) — the on-disk form a sharded-checkpoint
    manifest records so a restore under a different mesh can re-derive
    the writer's slicing."""
    out: list = []
    for entry in spec:
        if isinstance(entry, (tuple, list)):
            out.append(list(entry))
        else:
            out.append(entry)
    return out


def spec_from_json(dims: Sequence[Any]) -> P:
    """Inverse of :func:`spec_to_json`."""
    return P(*[tuple(d) if isinstance(d, list) else d for d in dims])


def leaf_shard_slices(
    shape: Sequence[int],
    spec: P,
    degrees: Mapping[str, int],
) -> list[tuple[tuple[int, int], ...]]:
    """The unique shard slices of one leaf under ``spec`` on a mesh with
    the given axis ``degrees`` — pure index math, no devices.

    Each element is a per-dim ``(start, stop)`` tuple; together they tile
    the global shape exactly (replicas collapsed — this is the replica-0
    set a sharded checkpoint writes and the coverage a restore verifies
    against).  A dim whose sharding degree does not divide it is treated
    as unsharded, matching the planner's divisibility rules.
    """
    per_dim: list[list[tuple[int, int]]] = []
    for d, size in enumerate(shape):
        axes = spec[d] if d < len(spec) else None
        deg = _axis_size(axes, degrees) if axes else 1
        if deg <= 1 or size % deg != 0:
            per_dim.append([(0, int(size))])
            continue
        chunk = size // deg
        per_dim.append([(i * chunk, (i + 1) * chunk) for i in range(deg)])
    out: list[tuple[tuple[int, int], ...]] = [()]
    for choices in per_dim:
        out = [prefix + (c,) for prefix in out for c in choices]
    return sorted(out)


def expected_collective_bytes(
    plan: ShardPlan,
    abstract_params: Any,
    *,
    grad_dtype: Any = np.float32,
    grad_accum: int = 1,
) -> dict:
    """Analytic per-step collective traffic implied by a ShardPlan.

    Derived purely from the plan + abstract param shapes — the expected
    cost of the collectives GSPMD inserts for the *parameter/gradient*
    path, per device per optimizer step:

    - ``grad_allreduce``: gradients of params replicated across a
      batch-carrying axis (dp; dense params under ep) are all-reduced
      over it.  Payload = the param's (possibly tp-sharded) grad bytes.
    - ``param_allgather``: ZeRO-3 params sharded on a batch-carrying
      axis (fsdp) are all-gathered on use — counted twice (forward +
      backward re-gather, the remat-compatible schedule).
    - ``grad_reduce_scatter``: the matching gradient shard reduction.

    With ``plan.zero1`` (cross-replica weight-update sharding, arxiv
    2004.13336) two more categories appear for the leaves whose
    ``opt_spec_tree`` spec shards over axes the param spec does not:

    - ``zero1_grad_reduce_scatter``: the grad all-reduce over those
      axes is REPLACED by a reduce-scatter onto the optimizer shard
      (wire ``(n-1)/n`` instead of ``2(n-1)/n`` of payload);
    - ``zero1_param_allgather``: the freshly updated params are
      all-gathered once per optimizer step (NOT per accumulation
      slice — the update runs once, after accumulation).

    Wire bytes use the ring formulas (allreduce ``2(n-1)/n``, gather/
    scatter ``(n-1)/n`` of payload).  Gradient-path collectives run once
    per accumulation slice, so everything except the zero1 param
    all-gather scales by ``grad_accum``.

    Activation-shaped traffic (tp activation all-reduces, MoE dispatch
    all_to_all, pipeline stage p2p) depends on model internals invisible
    to abstract param shapes; it is reported under ``model_dependent``
    as explicit unknowns rather than silently omitted.  Cross-check the
    whole estimate against XLA's measured ``bytes_accessed``
    (utils.profiling.compiled_cost / obs.comms.crosscheck).
    """
    degrees = topo_mod.mesh_degrees(plan.mesh)
    batch_axes = [
        a for a in _spec_axes(plan.batch_spec) if degrees.get(a, 1) > 1
    ]
    grad_itemsize = np.dtype(grad_dtype).itemsize

    specs = jax.tree.leaves(plan.param_specs,
                            is_leaf=lambda x: isinstance(x, P))
    leaves = jax.tree.leaves(abstract_params)
    if len(specs) != len(leaves):
        raise ValueError(
            f"param_specs ({len(specs)} leaves) does not match "
            f"abstract_params ({len(leaves)} leaves)"
        )

    zero1_active = bool(getattr(plan, "zero1", False))
    opt_specs = None
    if zero1_active and getattr(plan, "opt_spec_tree", None) is not None:
        opt_specs = jax.tree.leaves(plan.opt_spec_tree,
                                    is_leaf=lambda x: isinstance(x, P))
        if len(opt_specs) != len(specs):
            raise ValueError(
                f"opt_spec_tree ({len(opt_specs)} leaves) does not match "
                f"param_specs ({len(specs)} leaves)"
            )

    cats = {
        "grad_allreduce": {"payload_bytes": 0.0, "wire_bytes": 0.0},
        "param_allgather": {"payload_bytes": 0.0, "wire_bytes": 0.0},
        "grad_reduce_scatter": {"payload_bytes": 0.0, "wire_bytes": 0.0},
    }
    if opt_specs is not None:
        cats["zero1_grad_reduce_scatter"] = {
            "payload_bytes": 0.0, "wire_bytes": 0.0}
        cats["zero1_param_allgather"] = {
            "payload_bytes": 0.0, "wire_bytes": 0.0}
    for i, (spec, leaf) in enumerate(zip(specs, leaves)):
        shape = tuple(getattr(leaf, "shape", ()))
        count = math.prod(shape) if shape else 1
        p_itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        axes_used = spec_axes(spec)
        # axes the zero1 opt spec adds beyond the param spec: the grad
        # all-reduce over them becomes RS + (post-update) param AG
        z1_deg = 1
        if opt_specs is not None:
            for a in spec_axes(opt_specs[i]) - axes_used:
                z1_deg *= degrees.get(a, 1)
        # fraction of the param each device holds after non-batch-axis
        # sharding (tensor / pipe / expert)
        f_other = 1.0
        for a in axes_used:
            if a not in batch_axes:
                f_other /= degrees.get(a, 1)
        # 'expert'-sharded banks communicate via the token all_to_all
        # (model_dependent below), not via param gather/grad reduce —
        # exclude the expert axis from both paths for those leaves.
        reduce_deg = 1
        zero3_deg = 1
        z1_axes = (spec_axes(opt_specs[i]) - axes_used
                   if opt_specs is not None else set())
        for a in batch_axes:
            if a == "expert" and a in axes_used:
                continue
            if a in axes_used:
                zero3_deg *= degrees[a]
            elif a in z1_axes:
                pass  # replaced by the zero1 RS/AG below
            else:
                reduce_deg *= degrees[a]
        grad_payload = count * f_other / max(1, zero3_deg) * grad_itemsize
        if z1_deg > 1:
            cats["zero1_grad_reduce_scatter"]["payload_bytes"] += (
                grad_payload)
            cats["zero1_grad_reduce_scatter"]["wire_bytes"] += (
                (z1_deg - 1) / z1_deg * grad_payload
            )
            ag = count * f_other / max(1, zero3_deg) * p_itemsize
            cats["zero1_param_allgather"]["payload_bytes"] += ag
            cats["zero1_param_allgather"]["wire_bytes"] += (
                (z1_deg - 1) / z1_deg * ag
            )
        if reduce_deg > 1:
            # any residual reduction (e.g. expert for dense params under
            # ep) operates on the zero1 shard when one exists
            payload = grad_payload / z1_deg
            cats["grad_allreduce"]["payload_bytes"] += payload
            cats["grad_allreduce"]["wire_bytes"] += (
                2 * (reduce_deg - 1) / reduce_deg * payload
            )
        if zero3_deg > 1:
            ag = count * f_other * p_itemsize * 2  # fwd + bwd re-gather
            rs = count * f_other * grad_itemsize
            cats["param_allgather"]["payload_bytes"] += ag
            cats["param_allgather"]["wire_bytes"] += (
                (zero3_deg - 1) / zero3_deg * ag
            )
            cats["grad_reduce_scatter"]["payload_bytes"] += rs
            cats["grad_reduce_scatter"]["wire_bytes"] += (
                (zero3_deg - 1) / zero3_deg * rs
            )
    for name, c in cats.items():
        # the zero1 param all-gather happens once per optimizer step,
        # after accumulation — it does not repeat per slice
        k = 1 if name == "zero1_param_allgather" else grad_accum
        c["payload_bytes"] = int(c["payload_bytes"] * k)
        c["wire_bytes"] = int(c["wire_bytes"] * k)
    model_dependent = {}
    if degrees.get("tensor", 1) > 1:
        model_dependent["tp_activation_allreduce"] = None
    if degrees.get("expert", 1) > 1:
        model_dependent["ep_dispatch_all_to_all"] = None
    if degrees.get("pipe", 1) > 1:
        model_dependent["pipe_stage_p2p"] = None
    if degrees.get("seq", 1) > 1:
        model_dependent["cp_kv_exchange"] = None
    return {
        "strategy": plan.strategy,
        "mesh": dict(degrees),
        "grad_accum": grad_accum,
        "grad_dtype": str(np.dtype(grad_dtype)),
        "per_device": cats,
        "total_wire_bytes": int(sum(c["wire_bytes"] for c in cats.values())),
        "model_dependent": model_dependent,
        "assumptions": [
            "ring collectives: allreduce 2(n-1)/n, gather/scatter (n-1)/n",
            "ZeRO-3 params all-gathered twice per step (fwd + bwd)",
            "gradient-path collectives repeat per grad_accum slice",
            "activation-shaped traffic (tp/ep/pipe/cp) is model-dependent"
            " and reported as unknown, not zero",
        ],
    }


def make_plan(
    abstract_params: Any,
    *,
    mesh: Mesh | None = None,
    strategy: str = "auto",
    rules: Sequence[Rule] = TRANSFORMER_RULES,
    devices: Sequence[jax.Device] | None = None,
    remat: bool | None = None,
    seq: int = 1,
    pipe: int = 1,
    state_factor: float = 4.0,
    tune_policy: Any = None,
    zero1: bool = False,
) -> ShardPlan:
    """The planner: abstract params + topology -> ShardPlan.

    ``abstract_params`` is any pytree of objects with ``.shape``/``.dtype``
    (e.g. the output of ``jax.eval_shape``).  If ``mesh`` is given the
    strategy is applied on it as-is; otherwise the mesh is built from the
    chosen/requested strategy.  ``pipe`` > 1 adds a pipeline axis; layer
    stacks shard their leading dim onto it (parallel/pipeline.py).

    ``strategy='tuned'`` hands the choice to the tune/ subsystem
    (enumerate candidate factorizations, rank by the analytic cost
    model, cache the decision); ``tune_policy`` is an optional
    ``tune.TunePolicy`` refining the search (batch size, grad-accum
    choices, cache on/off).  Falls back to the ``auto`` heuristic when
    the candidate space is degenerate (e.g. 1 device).

    ``zero1=True`` reshards the optimizer state over the ``data`` axis
    (ZeRO-1 / cross-replica weight-update sharding, arxiv 2004.13336):
    the plan gains an ``opt_spec_tree`` distinct from ``param_specs``,
    and the trainer's update path reduce-scatters grads onto the
    optimizer shard and all-gathers fresh params.  A no-op when the
    mesh has no nontrivial ``data`` axis.  Under ``strategy='tuned'``
    the tuner may also pick a zero1 variant itself.
    """
    known = ("auto", "tuned", "dp", "fsdp", "tp", "tp_fsdp", "ep",
             "ep_fsdp", "ep_tp")
    if strategy not in known:
        raise ValueError(f"Unknown strategy {strategy!r}; expected one of {known}")
    if pipe > 1 and strategy in ("ep", "ep_fsdp", "ep_tp"):
        raise ValueError(
            "pipeline parallelism composes with dp/fsdp/tp (v2); "
            f"strategy {strategy!r} + pipe={pipe} is not supported"
        )
    topo = topo_mod.detect(devices)
    resolved = strategy
    if mesh is None:
        n = topo.num_devices
        if seq > 1 and pipe > 1:
            raise ValueError(
                "seq-parallel + pipeline in one plan is a design "
                "constraint (both are manual-collective regions); raise "
                "microbatches for per-stage memory, or use seq without "
                "pipe — README strategy-composition matrix"
            )
        if pipe > 1:
            if n % pipe:
                raise ValueError(
                    f"pipeline degree {pipe} does not divide {n} devices"
                )
            n //= pipe
        if seq > 1:
            if n % seq:
                raise ValueError(
                    f"seq-parallel degree {seq} does not divide "
                    f"{n} devices"
                )
            n //= seq
        if strategy in ("auto", "tuned"):
            sub_topo = dataclasses.replace(topo, num_devices=n)
            if strategy == "tuned":
                from . import tune as tune_mod

                result = tune_mod.tune(
                    abstract_params, sub_topo, rules=rules,
                    policy=tune_policy
                    or tune_mod.TunePolicy(state_factor=state_factor),
                )
                resolved, degrees = result.strategy, dict(result.degrees)
                zero1 = zero1 or bool(getattr(result, "zero1", False))
            else:
                resolved, degrees = choose_strategy(
                    abstract_params, sub_topo, rules,
                    state_factor=state_factor,
                )
            if pipe > 1 and resolved in ("ep", "ep_fsdp"):
                import warnings

                warnings.warn(
                    f"{strategy} strategy chose {resolved!r} but pipeline "
                    f"parallelism does not compose with expert parallelism "
                    f"(README strategy-composition matrix); falling back "
                    f"to 'fsdp' — the expert banks shard on the fsdp axis "
                    f"instead of having their own all_to_all dispatch",
                    stacklevel=2,
                )
                resolved, degrees = "fsdp", {"fsdp": n}
        elif strategy == "dp":
            degrees = {"data": n}
        elif strategy == "fsdp":
            degrees = {"fsdp": n}
        elif strategy == "tp":
            degrees = {"tensor": n}
        elif strategy == "tp_fsdp":
            t = min(8, n)
            while n % t:
                t //= 2
            # keep both axes nontrivial when possible (8 devs -> 4x2 not 8x1)
            while t > 2 and n // t < 2:
                t //= 2
            degrees = {"fsdp": n // t, "tensor": t}
        elif strategy in ("ep", "ep_fsdp", "ep_tp"):
            e_count = detect_expert_count(abstract_params)
            if not e_count:
                raise ValueError(
                    "strategy 'ep' needs MoE expert banks "
                    "(parameters matching MOE_RULES, e.g. experts_up); "
                    "none found in this model"
                )
            e = math.gcd(n, e_count)
            if e == 1 and n > 1:
                raise ValueError(
                    f"strategy {strategy!r}: gcd(n_devices={n}, "
                    f"n_experts={e_count}) == 1 — no expert axis is "
                    "possible on this device count; use fsdp/dp or change "
                    "the device count / expert count"
                )
            if strategy == "ep_tp":
                # keep room for a nontrivial tensor axis: halve the expert
                # degree (still divides n and e_count) until >=2 devices
                # remain for tensor
                rem = n // e
                while rem < 2 and e > 1 and e % 2 == 0:
                    e //= 2
                    rem = n // e
                if rem < 2 and n > 1:
                    import warnings

                    warnings.warn(
                        f"strategy 'ep_tp': {n} devices leave no room for "
                        f"a tensor axis next to expert={e} — degenerating "
                        f"to pure 'ep' (no per-expert Megatron split)",
                        stacklevel=2,
                    )
                t = min(8, rem)
                while rem % t:
                    t //= 2
                degrees = {"expert": e, "tensor": t, "data": rem // t}
            else:
                degrees = {"expert": e,
                           ("data" if strategy == "ep" else "fsdp"): n // e}
        else:
            raise ValueError(f"Unknown strategy {strategy!r}")
        if seq > 1:
            degrees["seq"] = seq
        if pipe > 1:
            degrees["pipe"] = pipe
        mesh = topo_mod.build_mesh(devices=devices, **degrees)
    else:
        if pipe > 1 and topo_mod.mesh_degrees(mesh).get("pipe", 1) != pipe:
            raise ValueError(
                f"pipe={pipe} conflicts with the explicit mesh "
                f"(its 'pipe' axis is "
                f"{topo_mod.mesh_degrees(mesh).get('pipe', 1)})"
            )
        if seq > 1 and topo_mod.mesh_degrees(mesh).get("seq", 1) != seq:
            raise ValueError(
                f"seq_parallel={seq} conflicts with the explicit mesh "
                f"(its 'seq' axis is {topo_mod.mesh_degrees(mesh).get('seq', 1)}); "
                "build the mesh with seq=<degree> or drop seq_parallel"
            )
        if strategy in ("auto", "tuned"):
            # an explicit mesh fixes every degree — nothing to tune
            d = topo_mod.mesh_degrees(mesh)
            if d.get("expert", 1) > 1:
                if d.get("tensor", 1) > 1:
                    resolved = "ep_tp"
                else:
                    resolved = "ep_fsdp" if d.get("fsdp", 1) > 1 else "ep"
            elif d.get("tensor", 1) > 1 and d.get("fsdp", 1) > 1:
                resolved = "tp_fsdp"
            elif d.get("tensor", 1) > 1:
                resolved = "tp"
            elif d.get("fsdp", 1) > 1:
                resolved = "fsdp"
            else:
                resolved = "dp"

    param_specs = param_spec_tree(abstract_params, mesh, resolved, rules)
    degrees_final = topo_mod.mesh_degrees(mesh)
    if resolved in ("tp", "tp_fsdp", "ep_tp") and degrees_final.get(
            "tensor", 1) > 1:
        sharded = any(
            "tensor" in (ax for dim in spec for ax in
                         (dim if isinstance(dim, tuple) else (dim,)) if ax)
            for _, spec in _flatten_with_paths(param_specs)
        )
        if not sharded:
            import warnings

            warnings.warn(
                f"Strategy {resolved!r} requested a tensor axis of "
                f"{degrees_final['tensor']} but no parameter matched any TP "
                "rule — the model will run unsharded on that axis. Pass "
                "custom rules= matching your parameter names.",
                stacklevel=2,
            )
    if remat is None:
        remat = resolved in ("fsdp", "tp_fsdp", "ep_fsdp")
        if not remat:
            # Replicated params (dp/tp/ep): turn checkpointing on when
            # the per-device train state (params+grads+2 adam moments,
            # fp32, after tensor/expert/pipe sharding) eats half a chip's
            # HBM — activations would not fit otherwise.
            pb = tree_bytes(abstract_params)
            e_deg = degrees_final.get("expert", 1)
            if e_deg > 1:
                eb = sum(
                    math.prod(leaf.shape)
                    * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
                    for _, leaf in _expert_banks(abstract_params)
                )
                pb = (pb - eb) + eb // e_deg
            pb //= max(1, degrees_final.get("tensor", 1))
            pb //= max(1, degrees_final.get("pipe", 1))
            remat = state_factor * pb > 0.5 * _hbm_bytes(topo.device_kind)
    opt_spec_tree = None
    if zero1:
        opt_spec_tree = zero1_spec_tree(abstract_params, mesh, param_specs)
        if degrees_final.get("data", 1) <= 1:
            # no data axis to shard over: the plan is honest about being
            # a no-op (opt state follows params) but keeps the flag off
            # so downstream paths don't pay the branch
            zero1 = False
            opt_spec_tree = None
    return ShardPlan(
        mesh=mesh,
        strategy=resolved,
        param_specs=param_specs,
        batch_spec=batch_partition_spec(mesh),
        remat=remat,
        zero1=zero1,
        opt_spec_tree=opt_spec_tree,
    )
