"""Topology discovery and device-mesh construction (component C10).

Reference capability (SURVEY.md C10; BASELINE.json north star): the reference
enumerates CUDA devices (``torch.cuda.device_count``) and the TPU-native
version must "learn TPU pod mesh topology (v4/v5 ICI rings)".

TPU-native realization: ``jax.devices()`` + ``mesh_utils.create_device_mesh``
(which is ICI-topology-aware on real TPU slices) and
``create_hybrid_device_mesh`` for multi-slice (ICI x DCN) deployments.

The canonical mesh axes used throughout the framework:

=========  =======================================================
axis       used by
=========  =======================================================
``data``   data parallelism (batch sharding; DDP/bucketed-DDP analog)
``fsdp``   ZeRO-3 parameter/optimizer sharding (can alias ``data``)
``tensor`` Megatron-style tensor parallelism (col/row weight splits)
``seq``    sequence / context parallelism (ring attention, Ulysses)
``pipe``   pipeline parallelism (stage meshes)
``expert`` expert parallelism (MoE all_to_all dispatch)
=========  =======================================================

Axes are ordered slowest-varying first so that axes that carry the most
traffic (``tensor``, ``seq``) land on the fastest (innermost ICI) links,
and ``data`` — which only carries one gradient allreduce per step — can be
placed across DCN on hybrid meshes.
"""

from __future__ import annotations

import dataclasses
import math
import os
import warnings
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Canonical axis ordering: outermost (slowest links OK) -> innermost
# (fastest links required).  DCN-friendly axes first.
MESH_AXES: tuple[str, ...] = ("pipe", "data", "fsdp", "expert", "seq", "tensor")

# Axes whose collectives are latency/bandwidth critical and must ride ICI.
ICI_AXES: frozenset[str] = frozenset({"tensor", "seq", "expert", "fsdp"})
# Axes that tolerate DCN (one collective per step, overlappable).
DCN_OK_AXES: tuple[str, ...] = ("pipe", "data")


@dataclasses.dataclass(frozen=True)
class Topology:
    """A snapshot of the accelerator topology visible to this process.

    Also the *hypothetical* fleet handle for the what-if planner
    (:func:`parse_topology`): ``chip_override`` carries a per-sweep
    :class:`ChipSpec` (e.g. a DCN bandwidth/latency variant) so the
    tune/simulate cost models can sweep interconnect assumptions
    without editing the datasheet table.
    """

    num_devices: int
    num_hosts: int
    platform: str  # 'tpu' | 'cpu' | 'gpu' | 'axon' ...
    device_kind: str
    num_slices: int = 1
    devices_per_slice: int | None = None
    chip_override: "ChipSpec | None" = None

    @property
    def is_multihost(self) -> bool:
        return self.num_hosts > 1

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1

    @property
    def chip(self) -> "ChipSpec":
        """Per-chip peak numbers for this topology's device kind."""
        if self.chip_override is not None:
            return self.chip_override
        return chip_spec(self.device_kind)


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-chip peak performance numbers used by the tune/ cost model.

    Bandwidths are bytes/s per chip (one direction); ``ici`` is the
    intra-slice interconnect, ``dcn`` the data-center network between
    slices/hosts.  Latencies are per-hop.  Public datasheet ballpark,
    deliberately coarse: the cost model only needs relative magnitudes
    to *rank* candidate plans, and ``tune/measure.py`` exists for the
    cases where ranking by these numbers isn't enough.
    """

    flops_per_s: float  # peak dense bf16 matmul
    hbm_bytes: int  # capacity (mirrors planner._HBM_BYTES)
    hbm_bytes_per_s: float
    ici_bytes_per_s: float
    dcn_bytes_per_s: float
    ici_latency_s: float = 1e-6
    dcn_latency_s: float = 25e-6


# Keyed by device-kind substring, like planner._HBM_BYTES.  The 'cpu'
# entry models the 8-device host-platform sim: tiny compute, shared
# memory "links" — numbers only need to keep ranking sane in CI.
_CHIP_SPECS: dict[str, ChipSpec] = {
    "v5 lite": ChipSpec(197e12, 16 * 2**30, 8.2e11, 1.86e11, 6.25e9),
    "v5e": ChipSpec(197e12, 16 * 2**30, 8.2e11, 1.86e11, 6.25e9),
    "v5p": ChipSpec(459e12, 95 * 2**30, 2.77e12, 4.8e11, 6.25e9),
    "v4": ChipSpec(275e12, 32 * 2**30, 1.23e12, 3.0e11, 6.25e9),
    "v6": ChipSpec(918e12, 32 * 2**30, 1.64e12, 3.58e11, 6.25e9),
    "cpu": ChipSpec(5e10, 8 * 2**30, 2e10, 1e9, 1e8,
                    ici_latency_s=5e-6, dcn_latency_s=100e-6),
}

_DEFAULT_CHIP = ChipSpec(1e14, 16 * 2**30, 8e11, 1e11, 6.25e9)


def chip_spec(device_kind: str) -> ChipSpec:
    """Look up :class:`ChipSpec` by device-kind substring (conservative
    TPU-ish default for unknown kinds)."""
    dk = device_kind.lower()
    for k, v in _CHIP_SPECS.items():
        if k in dk:
            return v
    return _DEFAULT_CHIP


# Chips per host for hypothetical fleets: TPU hosts carry 4 chips
# (v4/v5/v6 boards); the CPU "fleet" is the 8-device host-platform sim.
_CHIPS_PER_HOST = {"cpu": 8}
_DEFAULT_CHIPS_PER_HOST = 4


def parse_topology(
    spec: str,
    *,
    dcn_bytes_per_s: float | None = None,
    dcn_latency_s: float | None = None,
) -> Topology:
    """A hypothetical :class:`Topology` from a TPU-SKU spelling.

    ``"v5p-1024"`` is a single-slice 1024-chip fleet;
    ``"v5e-256x4"`` is 4 slices of 256 chips joined by DCN.  The kind
    must name a known :data:`_CHIP_SPECS` entry EXACTLY — a typo'd SKU
    must fail the sweep loudly, not silently price a fantasy fleet with
    the conservative default chip.

    ``dcn_bytes_per_s`` / ``dcn_latency_s`` override the datasheet DCN
    numbers (stored as ``chip_override``), which is how ``tadnn
    simulate`` sweeps inter-slice interconnect assumptions.
    """
    text = str(spec).strip().lower()
    kind, sep, shape = text.partition("-")
    if not sep or not shape:
        raise ValueError(
            f"cannot parse topology {spec!r} — expected '<kind>-<chips>' "
            f"or '<kind>-<chips_per_slice>x<slices>' (e.g. 'v5p-1024', "
            f"'v5e-256x4')")
    if kind not in _CHIP_SPECS:
        raise ValueError(
            f"unknown TPU SKU {kind!r} in topology {spec!r} — known "
            f"kinds: {sorted(_CHIP_SPECS)}")
    per_slice_s, x, slices_s = shape.partition("x")
    try:
        per_slice = int(per_slice_s)
        num_slices = int(slices_s) if x else 1
    except ValueError:
        raise ValueError(
            f"cannot parse topology {spec!r}: {shape!r} is not "
            f"'<chips>' or '<chips_per_slice>x<slices>'") from None
    if per_slice < 1 or num_slices < 1:
        raise ValueError(
            f"topology {spec!r} needs >= 1 chip per slice and >= 1 "
            f"slice, got {per_slice}x{num_slices}")
    num_devices = per_slice * num_slices
    chip = _CHIP_SPECS[kind]
    override = None
    if dcn_bytes_per_s is not None or dcn_latency_s is not None:
        override = dataclasses.replace(
            chip,
            dcn_bytes_per_s=(chip.dcn_bytes_per_s
                             if dcn_bytes_per_s is None
                             else float(dcn_bytes_per_s)),
            dcn_latency_s=(chip.dcn_latency_s if dcn_latency_s is None
                           else float(dcn_latency_s)),
        )
    per_host = _CHIPS_PER_HOST.get(kind, _DEFAULT_CHIPS_PER_HOST)
    return Topology(
        num_devices=num_devices,
        num_hosts=max(1, num_devices // per_host),
        platform="cpu" if kind == "cpu" else "tpu",
        device_kind=kind,
        num_slices=num_slices,
        devices_per_slice=per_slice,
        chip_override=override,
    )


_SIZE_UNITS = {
    "": 1, "B": 1,
    "KB": 10**3, "MB": 10**6, "GB": 10**9, "TB": 10**12,
    "KIB": 2**10, "MIB": 2**20, "GIB": 2**30, "TIB": 2**40,
    # Bare K/M/G/T read as the binary units HBM sizes are quoted in.
    "K": 2**10, "M": 2**20, "G": 2**30, "T": 2**40,
}


def parse_size(s: str | int | float) -> int:
    """'16GiB' / '95 GB' / '1.5e9' / 8589934592 → bytes.

    Binary suffixes (KiB/MiB/GiB/TiB, or bare K/M/G/T) are powers of
    1024; decimal ones (KB/MB/GB/TB) powers of 1000.
    """
    if isinstance(s, (int, float)):
        return int(s)
    text = str(s).strip()
    i = len(text)
    while i > 0 and not (text[i - 1].isdigit() or text[i - 1] == "."):
        i -= 1
    num, unit = text[:i].strip(), text[i:].strip().upper()
    if not num or unit not in _SIZE_UNITS:
        raise ValueError(
            f"cannot parse size {s!r} — expected e.g. '16GiB', '32GB', "
            "or a plain byte count")
    return int(float(num) * _SIZE_UNITS[unit])


def detect(devices: Sequence[jax.Device] | None = None) -> Topology:
    """Discover the visible device topology.

    Equivalent of the reference's CUDA device enumeration, but also derives
    slice structure (for DCN-aware hybrid meshes) from device attributes.
    """
    devices = list(devices if devices is not None else jax.devices())
    slice_ids = {getattr(d, "slice_index", 0) or 0 for d in devices}
    num_slices = max(len(slice_ids), 1)
    return Topology(
        num_devices=len(devices),
        num_hosts=max(len({d.process_index for d in devices}), 1),
        platform=devices[0].platform if devices else "cpu",
        device_kind=devices[0].device_kind if devices else "unknown",
        num_slices=num_slices,
        devices_per_slice=len(devices) // num_slices if devices else None,
    )


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Resolved per-axis parallelism degrees for a mesh build."""

    axes: Mapping[str, int]

    def degree(self, axis: str) -> int:
        return int(self.axes.get(axis, 1))

    @property
    def total(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1


def _resolve_degrees(
    num_devices: int, requested: Mapping[str, int | None]
) -> dict[str, int]:
    """Fill in unspecified (-1/None) axis degrees so the product covers all
    devices.  At most one axis may be -1; unmentioned axes get 1; if nothing
    is specified, everything goes to ``data``."""
    degrees: dict[str, int] = {}
    infer_axis: str | None = None
    for ax in MESH_AXES:
        v = requested.get(ax)
        if v in (-1, None) and ax in requested:
            if infer_axis is not None:
                raise ValueError(
                    f"At most one mesh axis may be -1 (got {infer_axis!r} and {ax!r})"
                )
            infer_axis = ax
        elif v is not None:
            if v < 1:
                raise ValueError(f"Axis {ax!r} degree must be >=1 or -1, got {v}")
            degrees[ax] = int(v)
    specified = math.prod(degrees.values()) if degrees else 1
    if infer_axis is not None:
        if num_devices % specified:
            raise ValueError(
                f"{num_devices} devices not divisible by specified axes product "
                f"{specified} ({degrees})"
            )
        degrees[infer_axis] = num_devices // specified
    elif not degrees:
        degrees["data"] = num_devices
    else:
        if specified != num_devices:
            # Auto-expand the data axis to absorb remaining devices.
            if num_devices % specified:
                raise ValueError(
                    f"Mesh axes {degrees} (product {specified}) do not divide "
                    f"{num_devices} devices"
                )
            degrees["data"] = degrees.get("data", 1) * (num_devices // specified)
    full = {ax: degrees.get(ax, 1) for ax in MESH_AXES}
    assert math.prod(full.values()) == num_devices
    return full


def hybrid_factorization(
    degrees: Mapping[str, int], num_slices: int
) -> tuple[list[int], list[int]] | None:
    """Split every mesh-axis degree into (in-slice, cross-slice) factors.

    Greedy gcd over the DCN-tolerant axes in MESH_AXES order: ``pipe``
    absorbs as much of the slice count as divides it, then ``data`` takes
    the rest — so BOTH may span DCN at once (e.g. 4 slices with pipe=2,
    data=2x in-slice batch).  ICI-critical axes (tensor/seq/expert/fsdp)
    never cross slices.  Returns ``(ici_shape, dcn_shape)`` ordered like
    MESH_AXES, or None when the DCN-tolerant degrees cannot cover the
    slice count (caller falls back to a flat mesh, loudly).
    """
    dcn_shape: list[int] = []
    ici_shape: list[int] = []
    remaining = num_slices
    for ax in MESH_AXES:
        d = int(degrees.get(ax, 1))
        if ax in DCN_OK_AXES and remaining > 1:
            g = math.gcd(d, remaining)
            dcn_shape.append(g)
            ici_shape.append(d // g)
            remaining //= g
        else:
            dcn_shape.append(1)
            ici_shape.append(d)
    if remaining != 1:
        return None
    return ici_shape, dcn_shape


def build_mesh(
    *,
    data: int | None = None,
    fsdp: int | None = None,
    tensor: int | None = None,
    seq: int | None = None,
    pipe: int | None = None,
    expert: int | None = None,
    devices: Sequence[jax.Device] | None = None,
    allow_split_physical_axes: bool = False,
) -> Mesh:
    """Build an ICI-aware ``jax.sharding.Mesh`` over the visible devices.

    Unspecified axes default to 1; pass ``-1`` for exactly one axis to infer
    its degree from the device count; with no axes specified all devices go
    to ``data`` (pure DP — the reference's DDP default, BASELINE.json:8).

    On real TPU slices ``mesh_utils.create_device_mesh`` orders devices so
    each mesh axis maps onto ICI rings; on multi-slice topologies a hybrid
    ICI x DCN mesh is built with DCN-tolerant axes (``pipe``, ``data``)
    spanning slices.
    """
    devices = list(devices if devices is not None else jax.devices())
    topo = detect(devices)
    requested = {
        "data": data,
        "fsdp": fsdp,
        "tensor": tensor,
        "seq": seq,
        "pipe": pipe,
        "expert": expert,
    }
    requested = {k: v for k, v in requested.items() if v is not None}
    degrees = _resolve_degrees(len(devices), requested)
    shape = tuple(degrees[ax] for ax in MESH_AXES)

    if topo.is_multislice and topo.devices_per_slice:
        fact = hybrid_factorization(degrees, topo.num_slices)
        if fact is not None:
            ici_shape, dcn_shape = fact
            assert math.prod(ici_shape) == topo.devices_per_slice
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape,
                dcn_shape,
                devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
            return Mesh(dev_array, MESH_AXES)
        # Loud fall-through: a flat mesh on a multi-slice topology puts
        # ICI-critical collectives on DCN — legal but slow, and the user
        # should know why and how to fix the axis degrees.
        warnings.warn(
            f"Cannot factor mesh axes {dict(degrees)} so that the "
            f"DCN-tolerant axes {DCN_OK_AXES} cover {topo.num_slices} "
            f"slices (their combined degree must be divisible by the "
            f"slice count). Falling back to a FLAT device mesh: "
            f"tensor/fsdp/expert collectives may cross DCN and be "
            f"slow. Raise the pipe/data degrees to a multiple of the "
            f"slice count to get a hybrid ICIxDCN mesh.",
            stacklevel=2,
        )

    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    except (ValueError, NotImplementedError, AssertionError):
        # CPU sim / odd topologies: plain row-major reshape is always valid.
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """Trivial 1-device mesh — the no-op path (BASELINE.json:7)."""
    device = device or jax.devices()[0]
    return Mesh(
        np.asarray([device]).reshape((1,) * len(MESH_AXES)), MESH_AXES
    )


def mesh_degrees(mesh: Mesh | Mapping[str, int]) -> dict[str, int]:
    """Axis-name -> degree of a ``Mesh``, or of a plain degrees mapping.

    Accepting a mapping lets the planner's pure functions
    (``param_spec_tree``, ``batch_partition_spec``,
    ``expected_collective_bytes``) run on *hypothetical* meshes — the
    tune/ subsystem scores candidate factorizations without ever
    building a device array.
    """
    if isinstance(mesh, Mapping):
        return {ax: int(n) for ax, n in mesh.items()}
    return {ax: int(n) for ax, n in zip(mesh.axis_names, mesh.devices.shape)}


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Turn on JAX's persistent compilation cache.

    Big-model XLA:TPU compiles run 20-40s+ (minutes at 1B+ scale); the
    cache amortizes them across process restarts — which the elastic
    story (training/elastic.py restart-based recovery) hits every
    resume.  Safe to call multiple times; returns the cache dir.
    ``tadnn run`` enables it by default (TADNN_NO_COMPILE_CACHE=1 opts
    out).
    """
    cache_dir = cache_dir or os.path.expanduser("~/.cache/tadnn_xla")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache everything that took meaningful compile time
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return cache_dir


def initialize_distributed(**kwargs) -> None:
    """Multi-host runtime init — the ``torchrun``/``mp.spawn`` analog (C9).

    Single-controller JAX needs no per-device spawn; on multi-host
    deployments each host calls this once (coordinator discovered from
    env or explicit kwargs).  No-op when single-process.
    """
    coord = kwargs.get("coordinator_address") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if not coord and "num_processes" not in kwargs:
        return  # single-process launch — nothing to initialize
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if "already" in str(e).lower():
            return  # idempotent: a second call is a no-op
        raise
