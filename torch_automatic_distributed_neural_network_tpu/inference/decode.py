"""KV-cached autoregressive decoding (inference path for the C12 models).

The training path (models/transformer_core.py) is jit-compiled over full
sequences; decoding re-runs the same weights through a functional cache:

- ``prefill``: one chunked pass over the prompt that both computes logits
  and writes the KV cache — O(prompt) attention, no per-token loop;
- ``decode_step``: a single-token step against the cache — the lax.scan
  body of :func:`generate`, so the whole generation loop is ONE compiled
  program (no Python in the loop, XLA-friendly static shapes).

The cache is an explicit pytree (no flax mutable collections), so it
shards like any other activation: [L, B, S_max, kvH, hd] with batch on
the data axes and kv heads on the tensor axis (``generate(mesh=...)`` or
``AutoDistribute.generate`` applies the constraints; GSPMD propagates
them through the cache updates).  Works for both decoder families
(GPT-2: layernorm / learned-pos / gelu / tied; Llama: rmsnorm / rope /
swiglu / GQA / untied) and for MoE models (MoELM), two routing modes
(``moe_decode=``): ``'dense'`` (default) is dispatch-free — all experts
run on the (tiny) decode chunk and the top-k gate weights combine them,
matching the training router exactly when no token is dropped;
``'routed'`` reuses the TRAINING capacity router (parallel/expert.
moe_ffn) so capacity-dropping configs decode bit-identically to their
training forward and large expert counts pay routed, not dense, FLOPs.

Single source of truth: the per-layer math is the TRAINING modules
applied piecewise — ``make_norm`` for norms, ``SelfAttention`` methods
``qkv``/``out_proj`` for the projections+rope, ``MLPBlock`` for the
dense FFN, and ``parallel.expert.expert_mlp`` for the expert FFN
einsums.  The only decode-specific code is the cache update, the cached
attention mask, and the dispatch-free router combine (round-2 weak #5:
this file used to re-implement all of it).

Numerics are cross-checked against ``model.apply`` on the full prefix in
tests/test_generate.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ..models.transformer_core import (
    MLPBlock,
    SelfAttention,
    TransformerConfig,
    make_norm,
)
from ..parallel.expert import expert_mlp
from .quant import (
    dequantize_leaf,
    dequantize_tree,
    embedding_lookup,
    is_quantized_leaf,
)


class KVCache(NamedTuple):
    """Per-layer stacked KV: [n_layers, B, S_max, kv_heads, head_dim]."""

    k: jax.Array
    v: jax.Array
    length: jax.Array  # scalar int32: tokens already cached

    @classmethod
    def init(cls, cfg: TransformerConfig, batch: int, max_len: int,
             dtype=jnp.bfloat16) -> "KVCache":
        shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )


def _cached_attention(q, k_cache, v_cache, q_pos, kv_len, window=None):
    """q: [B, T, H, hd] at absolute positions q_pos..q_pos+T-1;
    k/v_cache: [B, S_max, kvH, hd] with kv_len entries valid (the current
    chunk already written).  Causality over absolute positions is encoded
    in the mask; the numerics (GQA broadcast, fp32 softmax, mask bias)
    are ops/attention.xla_attention's.

    ``window`` bands the mask (sliding-window models): key j is visible
    to query i iff ``i - window < j <= i`` — exactly the training
    semantics, so windowed decode is correct at ANY total length.  The
    cache still stores every key (O(total) memory, same as the dense
    cache); a rolling O(window) buffer is a possible future optimization,
    not a correctness requirement."""
    from ..ops.attention import xla_attention

    T = q.shape[1]
    S = k_cache.shape[1]
    key_idx = jnp.arange(S)[None, :]
    q_idx = (q_pos + jnp.arange(T))[:, None]
    mask = (key_idx <= q_idx) & (key_idx < kv_len)  # [T, S]
    if window is not None:
        mask &= key_idx > q_idx - window
    return xla_attention(q, k_cache, v_cache, causal=False,
                         mask=mask[None, None])


def _moe_mlp_cached(lp_mlp: Any, h: jax.Array, cfg) -> jax.Array:
    """Dispatch-free MoE FFN for decode chunks: run every expert on the
    chunk and combine with the router's renormalized top-k gates.

    Matches parallel/expert.top_k_routing numerics (greedy top-k on the
    softmax, renormalized gates) in the no-drop regime — decode never
    drops tokens since there is no capacity buffer.  Costs E/k times the
    routed FLOPs, which is irrelevant at decode chunk sizes.  The expert
    FFN einsums are parallel/expert.expert_mlp — the same code the
    training dispatch path runs — on a broadcast [B, E, C=T, d] layout;
    only the router combine is decode-specific.
    """
    B, T, d = h.shape
    E = lp_mlp["experts_up"].shape[0]
    logits = jnp.einsum(
        "btd,de->bte", h.astype(jnp.float32), lp_mlp["router"]["kernel"]
    )
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, cfg.top_k)
    gates = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    w = (jax.nn.one_hot(topi, E, dtype=jnp.float32)
         * gates[..., None]).sum(-2)  # [B,T,E]

    h_e = jnp.broadcast_to(h[:, None], (B, E, T, d))  # every expert sees all
    y = expert_mlp(
        h_e,
        lp_mlp["experts_up"].astype(h.dtype),
        (lp_mlp["experts_gate"].astype(h.dtype)
         if "experts_gate" in lp_mlp else None),
        lp_mlp["experts_down"].astype(h.dtype),
        jax.nn.silu if "experts_gate" in lp_mlp else jax.nn.gelu,
    )  # [B, E, T, d]
    return jnp.einsum("betd,bte->btd", y, w.astype(h.dtype))


def _moe_mlp_routed(lp_mlp: Any, h: jax.Array, cfg, mesh=None,
                    capacity_override: int | None = None) -> jax.Array:
    """Capacity-based decode routing: the TRAINING ``moe_ffn`` (same
    top_k_routing, same capacity math, same dispatch/combine einsums and
    expert-axis sharding constraints) applied to the decode chunk.

    Same routing RULE as training, with expert capacity derived from
    the decode chunk's token count: a prefill chunk routes as one group
    of T tokens, so drop decisions match a training batch only when the
    chunk length equals the training group size (pass
    ``capacity_override`` to pin the training value exactly).  The
    dense-combine fast path above silently keeps dropped tokens.
    Single-token decode steps are a 1-token group — ``expert_capacity``
    clamps to >= 8 slots, so steps never drop and match the dense
    combine exactly.  Cost: the
    O(capacity * E) dispatch tensors per chunk vs dense's O(E * T)
    broadcast — worth it for large E or when training/serving parity in
    dropping configs is required (VERDICT r3 weak #5).
    """
    from ..parallel.expert import moe_ffn

    logits = jnp.einsum(
        "btd,de->bte", h.astype(jnp.float32), lp_mlp["router"]["kernel"]
    )
    gate = lp_mlp.get("experts_gate")
    y, _metrics = moe_ffn(
        h,
        logits,
        lp_mlp["experts_up"].astype(h.dtype),
        lp_mlp["experts_down"].astype(h.dtype),
        w_gate=None if gate is None else gate.astype(h.dtype),
        top_k=cfg.top_k,
        capacity_factor=cfg.capacity_factor,
        act=jax.nn.silu if gate is not None else jax.nn.gelu,
        mesh=mesh,
        capacity=capacity_override,
    )
    return y


def forward_cached(
    params: Any,
    cfg: TransformerConfig,
    tokens: jax.Array,  # [B, T] chunk (prompt at prefill, 1 token after)
    cache: KVCache,
    *,
    moe_decode: str = "dense",  # 'dense' | 'routed' (capacity-based)
    moe_capacity: int | None = None,  # pin the training group's capacity
    mesh=None,
    all_logits: bool = False,
) -> tuple[jax.Array, KVCache]:
    """Run the decoder on a chunk against the cache; returns (logits of
    the chunk's last position [B, vocab] — or of every position
    [B, T, vocab] with ``all_logits=True`` — and the updated cache).

    ``moe_decode='dense'`` (default) runs every expert on the chunk and
    combines with the gates — exact in no-drop configs and cheapest for
    tiny E.  ``'routed'`` reuses the training capacity router
    (:func:`_moe_mlp_routed`) so a capacity-dropping config decodes
    bit-identically to its training forward and large-E models pay
    routed instead of dense FLOPs."""
    if moe_decode not in ("dense", "routed"):
        raise ValueError(f"unknown moe_decode {moe_decode!r}")
    if "layers" not in params:
        raise ValueError(
            "forward_cached needs the scanned parameter layout (a stacked "
            "'layers' entry); this model was built with scan_layers=False "
            "(layers_0..layers_N params), which the decode path does not "
            "support"
        )
    B, T = tokens.shape
    pos0 = cache.length
    dtype = cfg.dtype

    # The per-layer math is the TRAINING modules applied piecewise on the
    # stacked per-layer params — one implementation for train and decode.
    norm = make_norm(cfg)
    attn = SelfAttention(cfg)
    mlp = MLPBlock(cfg)

    x = embedding_lookup(params["embed"]["embedding"], tokens, dtype)
    positions = pos0 + jnp.arange(T)[None, :]
    if cfg.pos == "learned":
        pe = params["pos_embed"].astype(dtype)
        x = x + jax.lax.dynamic_slice_in_dim(pe, pos0, T, axis=0)[None]

    def layer(x, layer_params_and_kv):
        lp, k_cache, v_cache = layer_params_and_kv
        # int8 weight-only decode: dequantize INSIDE the scan body so
        # only this layer's weights convert per step — the stacked int8
        # arrays are what lives in HBM (inference/quant.py)
        lp = dequantize_tree(lp, dtype)
        h = norm.apply({"params": lp["attn_norm"]}, x)
        q, k, v = attn.apply(
            {"params": lp["attn"]}, h, positions, method="qkv"
        )
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), pos0, axis=1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), pos0, axis=1)
        o = _cached_attention(q, k_cache, v_cache, pos0, pos0 + T,
                              window=cfg.sliding_window)
        x = x + attn.apply(
            {"params": lp["attn"]}, o.astype(dtype), method="out_proj"
        )
        h = norm.apply({"params": lp["mlp_norm"]}, x)
        if "experts_up" in lp["mlp"]:
            if moe_decode == "routed":
                x = x + _moe_mlp_routed(lp["mlp"], h, cfg, mesh,
                                        moe_capacity)
            else:
                x = x + _moe_mlp_cached(lp["mlp"], h, cfg)
        else:
            x = x + mlp.apply({"params": lp["mlp"]}, h)
        return x, (k_cache, v_cache)

    def scan_body(x, xs):
        x, kv = layer(x, xs)
        return x, kv

    x, (new_k, new_v) = jax.lax.scan(
        scan_body, x, (params["layers"], cache.k, cache.v)
    )

    x = norm.apply({"params": params["final_norm"]}, x)
    # all_logits=True: logits at EVERY chunk position (speculative
    # verification reads the whole chunk); default: last position only
    feats = (x if all_logits else x[:, -1]).astype(jnp.float32)
    if cfg.tie_embeddings:
        emb = params["embed"]["embedding"]
        if is_quantized_leaf(emb):
            emb = dequantize_leaf(emb, jnp.float32)
        logits = feats @ emb.astype(jnp.float32).T
    else:
        head = params["lm_head"]["kernel"]
        if is_quantized_leaf(head):
            head = dequantize_leaf(head, jnp.float32)
        logits = feats @ head.astype(jnp.float32)
    new_cache = KVCache(k=new_k, v=new_v, length=pos0 + T)
    return logits, new_cache


@dataclasses.dataclass(frozen=True)
class SampleConfig:
    temperature: float = 1.0  # 0 -> greedy
    top_k: int = 0  # 0 -> full distribution
    top_p: float = 1.0  # nucleus: keep the smallest set with mass >= p

    def __post_init__(self):
        if not 0.0 < self.top_p <= 1.0:
            # top_p=0 would mask EVERY token and categorical would then
            # silently emit id 0 forever; for greedy use temperature=0
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p} "
                f"(for greedy decoding use temperature=0)"
            )


def _sample(logits: jax.Array, rng: jax.Array, sc: SampleConfig) -> jax.Array:
    if sc.temperature == 0.0:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    logits = logits / sc.temperature
    if sc.top_k:
        kth = jnp.sort(logits, -1)[:, -sc.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if sc.top_p < 1.0:
        # nucleus filter (composes after top-k, the HF convention): keep
        # the highest-probability tokens whose cumulative mass reaches p;
        # the first token crossing the threshold is always kept
        sorted_logits = jnp.sort(logits, -1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, -1)
        cum = jnp.cumsum(probs, -1)
        keep = cum - probs < sc.top_p  # mass BEFORE this token
        # threshold = smallest kept logit per row
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), -1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


def cache_partition_spec(
    cfg, mesh,
    batch_axes: tuple[str, ...] = ("data", "fsdp", "expert"),
    head_axis: str = "tensor",
):
    """PartitionSpec for the [L, B, S, kvH, hd] cache under ``mesh``:
    batch rows on the data axes, kv heads on the tensor axis (matching
    the col-split k/v projections) when the head count divides it."""
    from jax.sharding import PartitionSpec as P

    degrees = dict(zip(mesh.axis_names, mesh.devices.shape))
    present = tuple(a for a in batch_axes if degrees.get(a, 1) > 1)
    t = degrees.get(head_axis, 1)
    head_entry = head_axis if t > 1 and cfg.kv_heads % t == 0 else None
    return P(None, present if present else None, None, head_entry, None)


def generate(
    model,
    variables: Any,
    prompt: jax.Array,  # [B, P] int32
    *,
    max_new_tokens: int,
    sample: SampleConfig | None = None,
    rng: jax.Array | None = None,
    cache_dtype=jnp.bfloat16,
    mesh=None,
    eos_id: int | None = None,
    moe_decode: str = "dense",
    moe_capacity: int | None = None,
    early_stop: bool = False,
    return_lengths: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Autoregressive generation: prefill + one-token lax.scan decode.

    Returns [B, P + max_new_tokens].  The whole loop compiles to a single
    XLA program; re-invoking with the same shapes reuses the executable.
    With ``mesh``, the KV cache is sharding-constrained (batch on data
    axes, kv heads on tensor — :func:`cache_partition_spec`) so decode
    runs sharded under a plan's mesh (AutoDistribute.generate wraps this
    with the right jit shardings).

    ``eos_id``: once a row samples it, every later position in that row
    is ``eos_id`` (the output stays fixed-shape — XLA needs static trip
    counts — but rows are individually final after their EOS).

    ``early_stop=True`` (requires ``eos_id``) swaps the scan for a
    ``lax.while_loop`` that exits as soon as EVERY row has sampled its
    EOS — a batch of short answers stops paying per-token steps once the
    longest row finishes instead of running to ``max_new_tokens``.  The
    output is bit-identical to the scan path (same pre-split step keys,
    same eos-fill: unreached positions hold ``eos_id``) but the returned
    buffer shape stays [B, P + max_new_tokens] — XLA outputs are static.

    ``return_lengths=True`` additionally returns per-row valid lengths
    [B] int32: prompt + generated tokens up to and INCLUDING the first
    EOS (or ``P + max_new_tokens`` for rows that never sampled it) —
    ``out[i, :lengths[i]]`` is row i's real content, the rest is fill.
    """
    if sample is None:
        sample = SampleConfig(temperature=0.0)
    if early_stop and eos_id is None:
        raise ValueError("early_stop=True requires eos_id")
    cfg: TransformerConfig = model.cfg
    params = variables["params"]
    B, P = prompt.shape
    if max_new_tokens < 1:
        if return_lengths:
            return prompt, jnp.full((B,), P, jnp.int32)
        return prompt
    rng = jax.random.key(0) if rng is None else rng
    rng, first_rng = jax.random.split(rng)

    cache = KVCache.init(cfg, B, P + max_new_tokens, dtype=cache_dtype)
    if mesh is not None:
        from jax.sharding import NamedSharding

        kv_sharding = NamedSharding(mesh, cache_partition_spec(cfg, mesh))
        cache = KVCache(
            k=jax.lax.with_sharding_constraint(cache.k, kv_sharding),
            v=jax.lax.with_sharding_constraint(cache.v, kv_sharding),
            length=cache.length,
        )
    logits, cache = forward_cached(params, cfg, prompt, cache,
                                   moe_decode=moe_decode,
                                   moe_capacity=moe_capacity, mesh=mesh)
    first = _sample(logits, first_rng, sample)
    done0 = (
        first == eos_id if eos_id is not None
        else jnp.zeros_like(first, bool)
    )

    def body(carry, step_rng):
        cache, tok, done = carry
        # single-token steps never drop (the >=8-slot clamp), so the
        # training-capacity pin only matters for prefill; forwarding it
        # here would inflate every step's dispatch tensors to the
        # training capacity for identical outputs
        logits, cache = forward_cached(params, cfg, tok[:, None], cache,
                                       moe_decode=moe_decode,
                                       moe_capacity=None, mesh=mesh)
        nxt = _sample(logits, step_rng, sample)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = jnp.logical_or(done, nxt == eos_id)
        return (cache, nxt, done), nxt

    if max_new_tokens > 1 and early_stop:
        # while_loop variant: same body, same PRE-SPLIT step keys (key
        # i is consumed at step i whether or not earlier rows stopped,
        # so sampled outputs match the scan path exactly); positions a
        # finished batch never reaches keep their eos_id buffer fill —
        # identical to what the scan's done-row clamp would have written
        step_keys = jax.random.split(rng, max_new_tokens - 1)
        buf0 = jnp.full((B, max_new_tokens), eos_id, jnp.int32)
        buf0 = buf0.at[:, 0].set(first)

        def w_cond(carry):
            _, _, done, step, _ = carry
            return (step < max_new_tokens - 1) & ~jnp.all(done)

        def w_body(carry):
            cache, tok, done, step, buf = carry
            (cache, nxt, done), _ = body((cache, tok, done),
                                         step_keys[step])
            buf = buf.at[:, step + 1].set(nxt)
            return cache, nxt, done, step + 1, buf

        *_, new_tokens = jax.lax.while_loop(
            w_cond, w_body,
            (cache, first, done0, jnp.zeros((), jnp.int32), buf0),
        )
    elif max_new_tokens > 1:
        (_, _, _), rest = jax.lax.scan(
            body, (cache, first, done0),
            jax.random.split(rng, max_new_tokens - 1),
        )
        new_tokens = jnp.concatenate([first[:, None], rest.T], axis=1)
    else:
        new_tokens = first[:, None]
    out = jnp.concatenate([prompt, new_tokens], axis=1)
    if not return_lengths:
        return out
    if eos_id is None:
        lengths = jnp.full((B,), P + max_new_tokens, jnp.int32)
    else:
        is_eos = new_tokens == eos_id
        hit = is_eos.any(axis=1)
        first_eos = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
        lengths = P + jnp.where(hit, first_eos + 1, max_new_tokens)
    return out, lengths.astype(jnp.int32)
