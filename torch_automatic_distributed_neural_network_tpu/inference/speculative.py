"""Greedy speculative decoding: a small draft model proposes, the
target verifies in one chunked forward.

Single-token decode is latency-bound on the TARGET's weight streaming;
speculative decoding amortizes it: the draft greedily proposes ``k``
tokens (k cheap steps), the target runs ONE (k+1)-token cached forward
over the proposal, and the longest prefix where the target's own greedy
choices agree is accepted — plus the target's next token as a bonus, so
every round emits between 1 and k+1 tokens with exactly one target
chunk.

The greedy variant's contract: the emitted sequence matches plain
greedy decoding of the target alone, for ANY draft model — a bad draft
only costs speed (acceptance rate), never correctness.  "Matches" is
exact up to floating-point chunk-width reassociation: verifying k+1
positions in one chunk can reassociate reductions differently than
k+1 single-token steps, so logits near an exact argmax tie may flip on
low-precision accumulations.  tests/test_speculative.py pins equality
at fp32 on the CPU sim with both a self-draft (always accepts) and an
unrelated random-init draft (rarely accepts).

Both models run through the same :func:`..inference.decode.
forward_cached` as everything else (sliding windows, GQA, int8-
quantized params all compose); cache roll-back after a partial accept
is just ``length = n_accepted`` — entries past ``length`` are masked
out of cached attention and overwritten by the next round.

Scope: greedy only (temperature-0; the sampled variant needs the
rejection-resampling scheme), batch 1 (accept counts are per-sequence),
``eos_id`` unsupported.  The whole loop is one ``lax.while_loop``
program: dynamic trip count, static shapes throughout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .decode import KVCache, forward_cached


def ngram_propose(history, k: int, *, max_n: int = 3) -> list:
    """Prompt-lookup drafting, host-side: propose ``k`` tokens by
    matching the longest trailing n-gram (n = max_n..1) against its most
    recent earlier occurrence in ``history`` and replaying what followed.
    Free — no draft model, no device work — and surprisingly effective on
    repetitive serving traffic.  Falls back to repeating the last token,
    so the proposal is always exactly ``k`` long (the fixed-shape verify
    chunk needs that).
    """
    hist = [int(t) for t in history]
    if k < 1:
        return []
    if not hist:
        return [0] * k
    drafts: list = []
    for n in range(min(max_n, len(hist) - 1), 0, -1):
        tail = hist[-n:]
        # most recent earlier occurrence wins (local context beats old)
        for i in range(len(hist) - n - 1, -1, -1):
            if hist[i:i + n] == tail:
                drafts = hist[i + n:i + n + k]
                break
        if drafts:
            break
    while len(drafts) < k:
        drafts.append(drafts[-1] if drafts else hist[-1])
    return drafts[:k]


def accept_length(drafts, targets) -> int:
    """Longest accepted prefix under the greedy-speculative rule:
    ``drafts[i]`` is accepted while it equals the target's own greedy
    choice ``targets[i]`` at that position.  Host-side mirror of the
    argmin-over-agreement inside :func:`speculative_generate`; the serve
    engine uses it per slot after the batched verify step."""
    a = 0
    for d, t in zip(drafts, targets):
        if int(d) != int(t):
            break
        a += 1
    return a


def speculative_generate(
    model,
    variables,
    draft_model,
    draft_variables,
    prompt: jax.Array,  # [1, P] int32
    *,
    max_new_tokens: int,
    k: int = 4,
    cache_dtype=jnp.bfloat16,
) -> jax.Array:
    """Greedy speculative generation; returns [1, P + max_new_tokens].

    ``model``/``variables`` is the target (whose output this exactly
    reproduces); ``draft_model``/``draft_variables`` the cheap proposer.
    Both must share the tokenizer/vocab.
    """
    cfg, dcfg = model.cfg, draft_model.cfg
    params = variables["params"]
    dparams = draft_variables["params"]
    if cfg.vocab_size != dcfg.vocab_size:
        raise ValueError(
            f"target and draft vocabularies differ "
            f"({cfg.vocab_size} vs {dcfg.vocab_size})"
        )
    B, P = prompt.shape
    if B != 1:
        raise NotImplementedError(
            "speculative decoding accepts batch 1 (accept counts are "
            "per-sequence); vmap or loop over rows"
        )
    if max_new_tokens < 1:
        return prompt
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")

    # +k+1 slack: a round may overshoot max_new before the final slice
    max_len = P + max_new_tokens + k + 1
    for name, c in (("target", cfg), ("draft", dcfg)):
        if c.pos == "learned" and c.max_seq_len < max_len:
            # a verify chunk past the table would CLAMP the position
            # slice and silently shift every chunk embedding — breaking
            # the bit-exactness contract with no error
            raise ValueError(
                f"{name} max_seq_len={c.max_seq_len} < prompt + "
                f"max_new_tokens + k + 1 = {max_len}: speculative rounds "
                f"need k+1 positions of headroom past the last emitted "
                f"token (shorten the generation or rebuild the model "
                f"with a larger max_seq_len)"
            )
    cache = KVCache.init(cfg, B, max_len, dtype=cache_dtype)
    dcache = KVCache.init(dcfg, B, max_len, dtype=cache_dtype)

    # Prefill both on the prompt; `last` = the one emitted-but-uncached
    # token (invariant: caches hold keys for tokens[0..length-1])
    logits, cache = forward_cached(params, cfg, prompt, cache)
    first = jnp.argmax(logits, -1).astype(jnp.int32)  # [1]
    _, dcache = forward_cached(dparams, dcfg, prompt, dcache)

    out = jnp.zeros((B, max_new_tokens + k + 1), jnp.int32)
    out = jax.lax.dynamic_update_slice(out, first[:, None], (0, 0))

    def draft_step(carry, _):
        dcache, tok = carry
        lg, dcache = forward_cached(dparams, dcfg, tok[:, None], dcache)
        nxt = jnp.argmax(lg, -1).astype(jnp.int32)
        return (dcache, nxt), nxt

    def round_body(state):
        cache, dcache, out, n_emitted, last = state
        # 1) draft proposes k greedy tokens continuing from `last`.
        # k+1 scan steps, not k: the last step's OUTPUT is discarded but
        # its input write puts d_k's key in the draft cache, so a
        # full-accept round leaves the cache complete for the next one.
        (dcache, _), drafts_all = jax.lax.scan(
            draft_step, (dcache, last), None, length=k + 1)
        drafts = drafts_all[:k, 0]  # [k] proposals d_1..d_k
        # 2) target verifies [last, d_1..d_k] in ONE chunk
        chunk = jnp.concatenate([last, drafts])[None, :]  # [1, k+1]
        lg, cache = forward_cached(params, cfg, chunk, cache,
                                   all_logits=True)  # [1, k+1, V]
        t = jnp.argmax(lg[0], -1).astype(jnp.int32)  # [k+1] greedy targets
        # 3) accept the longest prefix where draft_i == target_{i-1};
        # appending a 0 makes argmin return k when every draft agrees
        agree = drafts == t[:k]  # [k]
        a = jnp.argmin(jnp.concatenate(
            [agree.astype(jnp.int32), jnp.zeros((1,), jnp.int32)]))
        # emitted this round: d_1..d_a then the bonus t_a  (a+1 tokens;
        # positions past a hold t_a copies — overwritten next round or
        # sliced off at the end)
        d_pad = jnp.concatenate([drafts, jnp.zeros((1,), jnp.int32)])
        emit = jnp.where(jnp.arange(k + 1) < a, d_pad, t[a])
        out = jax.lax.dynamic_update_slice(
            out, emit[None, :], (0, n_emitted))
        new_last = t[a][None]
        n_keys = cache.length - (k + 1) + a + 1  # roll back stale keys
        cache = cache._replace(length=n_keys)
        dcache = dcache._replace(length=jnp.minimum(dcache.length, n_keys))
        return cache, dcache, out, n_emitted + a + 1, new_last

    def cond(state):
        return state[3] < max_new_tokens

    state = (cache, dcache, out, jnp.ones((), jnp.int32), first)
    *_, out, _, _ = jax.lax.while_loop(cond, round_body, state)
    return jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)
