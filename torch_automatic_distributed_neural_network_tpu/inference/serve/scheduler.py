"""Continuous-batching scheduler: iteration-level admission/eviction.

The unit of scheduling is the *decode step*, not the batch: between any
two steps the scheduler may evict finished sequences (freeing their KV
blocks) and admit queued requests into the vacated slots — new work
joins a running batch without draining it.  This is the vLLM-style
discipline the serving literature shows decides TPU serving economics
(PAPERS.md, arxiv 2605.25645): decode slots stay occupied instead of
waiting for the longest request of a static batch.

Admission is gated by a **static KV fit check** — a request enters a
slot only if the pool can cover its blocks under the chosen policy:

- ``"reserve"`` (default): allocate the WORST-CASE blocks up front
  (prompt + max_new_tokens).  A running request can never hit an
  allocation failure mid-decode, so there is no preemption; admission
  is simply blocked until enough blocks free up.  Predictable, and the
  right default when parity/testing matters.
- ``"optimistic"``: allocate only the prompt's blocks at admission and
  grow one block at a time as decode crosses block boundaries.  Higher
  occupancy (no reservation for tokens that may never be generated —
  most requests stop at EOS early), at the price of mid-decode
  allocation failures resolved by **preempting the youngest slot**:
  its blocks are freed and the request is re-queued in FIFO submission
  order to be recomputed from scratch later (recompute-style — no
  cache swap to host).  ``Request.preempted`` counts the restarts.
  Requeue position is by ``(priority, t_submit, rid)``, NOT the queue
  front:
  front-requeueing let a young victim jump ahead of earlier-submitted
  requests still waiting for their first admission, inverting FIFO
  fairness exactly when the pool is most contended.

Multi-tenant state rides along: each request may name a LoRA
``adapter``; the scheduler pins it in the ``AdapterPool`` exactly when
the request enters the RUNNING state and unpins on evict/preempt, so
queued/prefilling/preempted requests never hold a pinned reference
(``check_invariants`` asserts it — pins only ever back live decode
reads, and preemption cannot leak adapter slots).


The scheduler owns no device state: it moves ``Request`` objects
between queue and slots and block ids between the allocator and block
tables.  The engine asks it what changed and mirrors that into the
slot-padded device arrays.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Callable, Sequence

from .adapters import IDENTITY_ADAPTER
from .kv_pool import NULL_BLOCK, BlockAllocator, blocks_for_tokens

_rid_counter = itertools.count()


# -- pure decision functions --------------------------------------------------
#
# The scheduler's POLICY, factored out of its pool/slot state: plain
# functions of integers and tuples, no Request objects, no allocator, no
# device anywhere.  ``Scheduler`` routes every admission / prefill-order
# / growth / preemption decision through these, and the what-if
# simulator (tune/simulate.py) replays serving traffic against the SAME
# functions — the prediction can never drift from the policy the engine
# actually runs.  Behavior is pinned by the scheduler invariant tests.


def blocks_at_admission(n_prompt: int, max_new_tokens: int, *,
                        block_size: int, admission: str,
                        spec_lookahead: int = 0) -> int:
    """KV blocks a request must be granted to enter a slot.

    ``reserve`` takes the worst case up front (prompt + full generation
    budget + the speculative write lookahead — a reserved request must
    NEVER fail mid-decode); ``optimistic`` takes only the prompt's
    blocks and grows during decode.
    """
    if admission == "reserve":
        return blocks_for_tokens(
            n_prompt + max_new_tokens + spec_lookahead, block_size)
    return blocks_for_tokens(n_prompt, block_size)


def admission_plan(queued: Sequence[tuple], n_free_slots: int,
                   n_free_blocks: int, *, block_size: int, admission: str,
                   spec_lookahead: int = 0, n_evictable: int = 0) -> int:
    """How many queue-front requests to admit this step.

    ``queued`` is the FIFO queue as ``(n_prompt, max_new_tokens)``
    pairs — or, under prefix caching, ``(n_prompt, max_new_tokens,
    n_cached_tokens)`` triples: blocks covered by a prefix-cache match
    are shared references into already-resident KV, so admission
    charges only the UNCACHED remainder against the free list.
    ``n_evictable`` extends the block budget by what the radix index
    can reclaim on demand (unreferenced leaves) — the scheduler drops
    those before ever preempting a live slot, so planning against them
    is sound.  Walks the front while a free slot remains and the pool
    covers the fit check; stops at the FIRST request that does not fit
    (strict FIFO — later, possibly smaller, requests wait rather than
    jump the queue).
    """
    n_admit = 0
    free = int(n_free_blocks) + int(n_evictable)
    for item in queued:
        n_prompt, max_new = item[0], item[1]
        cached_tokens = item[2] if len(item) > 2 else 0
        if n_admit >= n_free_slots:
            break
        need = blocks_at_admission(
            n_prompt, max_new, block_size=block_size,
            admission=admission, spec_lookahead=spec_lookahead)
        need -= cached_tokens // block_size
        if need > free:
            break
        free -= need
        n_admit += 1
    return n_admit


def prefill_schedule(prefilling: Sequence[tuple[float | None, int]],
                     max_chunks: int | None) -> list[int]:
    """Which prefilling slots advance a chunk this step: FIFO by
    ``(t_admit, slot)``, at most ``max_chunks`` of them — the cap
    bounds how much prefill work can delay a step's decode.
    ``max_chunks=None`` means no cap: disaggregated serving runs
    prefill on its own mesh slice, so every prefilling slot advances
    each step without stealing decode time."""
    order = sorted(((t or 0.0), s) for t, s in prefilling)
    if max_chunks is not None:
        order = order[:max_chunks]
    return [s for _, s in order]


def decode_needs_block(n_prompt: int, n_generated: int, n_blocks: int, *,
                       block_size: int, spec_lookahead: int = 0) -> bool:
    """True when a running request's next decode step writes KV beyond
    its owned blocks.  This step writes from absolute position
    ``n_prompt + n_generated - 1`` (the first generated token came from
    prefill, before any paged write) through ``spec_lookahead``
    positions beyond it."""
    pos = n_prompt + n_generated - 1 + spec_lookahead
    return pos // block_size >= n_blocks


def preemption_victim(occupied: Sequence[tuple[float | None, int]]
                      ) -> int | None:
    """The slot to preempt: most recently admitted, earliest slot index
    on ties (``occupied`` is ``(t_admit, slot)`` in slot order).  None
    when no slot is occupied."""
    best_t: float | None = None
    best_slot: int | None = None
    for t, slot in occupied:
        t = t or 0.0
        if best_t is None or t > best_t:
            best_t, best_slot = t, slot
    return best_slot


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle bookkeeping."""

    prompt: list[int]
    max_new_tokens: int
    rid: int = dataclasses.field(
        default_factory=lambda: next(_rid_counter))
    eos_id: int | None = None
    # LoRA tenant: referenced by NAME until the request is running, at
    # which point the scheduler pins it and adapter_idx holds its pool
    # slot (IDENTITY_ADAPTER for base-model requests and all non-running
    # states)
    adapter: str | None = None
    adapter_idx: int = IDENTITY_ADAPTER
    # admission class: lower value is more urgent (the gateway maps
    # "interactive" -> 0, "batch" -> 1).  Queue order is
    # ``(priority, t_submit, rid)`` — strict FIFO WITHIN a class, and
    # the default 0 for every request degenerates to the legacy pure
    # FIFO order
    priority: int = 0

    # lifecycle: queued -> [prefilling ->] running -> done (preemption
    # loops back to queued; "prefilling" only under the engine's
    # chunked-prefill mode, where a slot streams its prompt across
    # steps before joining decode)
    state: str = "queued"
    slot: int | None = None
    blocks: list[int] = dataclasses.field(default_factory=list)
    out_tokens: list[int] = dataclasses.field(default_factory=list)
    preempted: int = 0
    # prefix-cache accounting, set at admission: the first
    # ``cached_blocks`` entries of ``blocks`` are SHARED references
    # into KV an earlier request computed (covering ``cached_tokens``
    # prompt tokens) — prefill starts after them and commit skips them
    cached_blocks: int = 0
    cached_tokens: int = 0
    # memoized chained block hashes of the prompt (admission planning
    # re-matches every queued request every step; the prompt is
    # immutable, so hash it once)
    _prefix_keys: list | None = dataclasses.field(
        default=None, repr=False, compare=False)

    # wall-clock marks for the serve.request_done span fields
    t_submit: float = dataclasses.field(default_factory=time.monotonic)
    t_admit: float | None = None
    t_first_token: float | None = None
    # disaggregated serving: when this request's prefill KV blocks were
    # shipped from the prefill slice into the decode slice's pool
    t_kv_shipped: float | None = None
    t_done: float | None = None
    # per-token emission stamps (scheduler clock): consecutive diffs
    # are the inter-token latencies; cleared with out_tokens on
    # preemption — only the surviving attempt's stream is reported
    token_walls: list[float] = dataclasses.field(
        default_factory=list, repr=False, compare=False)
    # chunked-prefill accounting, cumulative across attempts (preempted
    # work was still computed — it belongs in the phase attribution)
    prefill_chunks: int = 0
    prefill_compute_s: float = 0.0
    # wall time spent in attempts that were later thrown away
    # (admit -> preempt/requeue): the recompute tax, per request
    lost_s: float = 0.0

    @property
    def n_prompt(self) -> int:
        return len(self.prompt)

    @property
    def n_generated(self) -> int:
        return len(self.out_tokens)

    @property
    def max_tokens_total(self) -> int:
        return self.n_prompt + self.max_new_tokens

    def finished(self) -> bool:
        if self.n_generated >= self.max_new_tokens:
            return True
        return (self.eos_id is not None and self.out_tokens
                and self.out_tokens[-1] == self.eos_id)


class Scheduler:
    """Queue + slots + block accounting (host-side, no device state)."""

    def __init__(self, *, n_slots: int, allocator: BlockAllocator,
                 block_size: int, admission: str = "reserve",
                 adapter_pool=None, spec_lookahead: int = 0,
                 prefix_cache=None, match_align: int | None = None,
                 clock: Callable[[], float] = time.monotonic):
        if admission not in ("reserve", "optimistic"):
            raise ValueError(f"unknown admission policy {admission!r}")
        self.n_slots = n_slots
        self.allocator = allocator
        self.block_size = block_size
        self.admission = admission
        self.adapter_pool = adapter_pool
        # cross-request prefix reuse (prefix_cache.PrefixCache): matched
        # prompt blocks are ref'd into the table instead of allocated,
        # admission charges only the uncached remainder, and index
        # leaves are evicted before any live slot is preempted.
        # ``match_align`` floors a match to a multiple of this many
        # tokens (>= block_size; the engine passes the prefill-chunk
        # lcm in int8 mode so reuse stays bit-exact)
        self.prefix_cache = prefix_cache
        self.match_align = int(match_align or block_size)
        if self.match_align % block_size:
            raise ValueError(
                f"match_align {self.match_align} must be a multiple of "
                f"block_size {block_size}")
        # speculative decode writes up to `spec_lookahead` extra KV
        # positions per step — block coverage must lead by that much
        self.spec_lookahead = int(spec_lookahead)
        # timestamps come from here so a discrete-event replay can run
        # the scheduler on virtual time instead of the wall clock
        self.clock = clock
        self.queue: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * n_slots
        self.n_finished = 0
        self.n_preemptions = 0
        # disaggregated serving: finished-prefill KV transfers into the
        # decode slice (record_ship), mirrored by the replay simulator
        self.n_kv_ships = 0
        self.shipped_blocks = 0

    # -- introspection -------------------------------------------------------

    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self.slots)

    @property
    def n_queued(self) -> int:
        return len(self.queue)

    @property
    def n_decoding(self) -> int:
        """Slots actively decoding (excludes chunked-prefill slots)."""
        return sum(r is not None and r.state == "running"
                   for r in self.slots)

    @property
    def n_prefilling(self) -> int:
        return sum(r is not None and r.state == "prefilling"
                   for r in self.slots)

    def idle(self) -> bool:
        return self.n_active == 0 and not self.queue

    def check_invariants(self) -> None:
        """Structural invariants; raises AssertionError on violation.

        Cheap enough to run every test step.  Refcount discipline (the
        multiset extension of the old no-block-on-two-tables rule): a
        block appears at most once PER table, and its allocator
        refcount equals the number of tables holding it plus one if the
        radix index holds it — sharing is accounted, never implicit.
        Also: no live request holds the null block, the live set is
        exactly (tables union index), and free + live == num_blocks-1.
        """
        table_count: dict[int, int] = {}
        for r in self.slots:
            if r is None:
                continue
            mine: set[int] = set()
            for b in r.blocks:
                assert b != NULL_BLOCK, (
                    f"request {r.rid} holds the null block")
                assert b not in mine, (
                    f"block {b} twice on request {r.rid}'s table")
                mine.add(b)
                table_count[b] = table_count.get(b, 0) + 1
            assert r.cached_blocks <= len(r.blocks)
        index_blocks = (self.prefix_cache.blocks()
                        if self.prefix_cache is not None else set())
        assert NULL_BLOCK not in index_blocks, (
            "radix index holds the null block")
        live = set(table_count) | index_blocks
        assert live == self.allocator._live, (
            f"allocator live set {sorted(self.allocator._live)} != "
            f"tables+index {sorted(live)}")
        assert (self.allocator.n_free + len(live)
                == self.allocator.num_blocks - 1), "block leak"
        for b in live:
            want = table_count.get(b, 0) + (1 if b in index_blocks else 0)
            assert self.allocator.refcount(b) == want, (
                f"block {b}: refcount {self.allocator.refcount(b)} != "
                f"{table_count.get(b, 0)} table holders "
                f"+ {int(b in index_blocks)} index reference")
        for r in self.queue:
            assert not r.blocks, (
                f"queued request {r.rid} still holds blocks")
        # adapter pins back live decode reads ONLY: a slot pins exactly
        # while running, so preemption/eviction can never leak a pin
        for r in self.slots:
            if r is not None and r.state != "running":
                assert r.adapter_idx == IDENTITY_ADAPTER, (
                    f"{r.state} request {r.rid} holds a pinned adapter "
                    f"reference (idx {r.adapter_idx})")
        for r in self.queue:
            assert r.adapter_idx == IDENTITY_ADAPTER, (
                f"queued/preempted request {r.rid} holds a pinned "
                f"adapter reference (idx {r.adapter_idx})")
        if self.adapter_pool is not None:
            want: dict[str, int] = {}
            for r in self.slots:
                if (r is not None and r.state == "running"
                        and r.adapter is not None
                        and r.adapter_idx != IDENTITY_ADAPTER):
                    want[r.adapter] = want.get(r.adapter, 0) + 1
            have = self.adapter_pool.allocator.pinned_names()
            assert want == have, (
                f"adapter pin leak: running slots pin {want}, pool "
                f"holds {have}")

    # -- admission / eviction ------------------------------------------------

    @staticmethod
    def _queue_key(req: Request) -> tuple[int, float, int]:
        return (req.priority, req.t_submit, req.rid)

    def submit(self, req: Request) -> None:
        req.state = "queued"
        # priority-ordered insert: ahead of every queued request in a
        # LOWER class (higher priority value), behind every peer in its
        # own class — FIFO within a class.  With the default priority 0
        # everywhere this is a plain append.
        if not self.queue or self._queue_key(self.queue[-1]) < \
                self._queue_key(req):
            self.queue.append(req)
        else:
            self._requeue_fifo(req)

    def _blocks_at_admission(self, req: Request) -> int:
        return blocks_at_admission(
            req.n_prompt, req.max_new_tokens, block_size=self.block_size,
            admission=self.admission, spec_lookahead=self.spec_lookahead)

    # -- adapter pins --------------------------------------------------------

    def pin_adapter(self, req: Request) -> dict | None:
        """Pin ``req``'s adapter for decode; called exactly at the
        transition into the RUNNING state.  Returns a fault-info dict
        ({} for base-model requests), or None when every pool slot is
        pinned by other running requests — the caller must NOT run the
        request (the engine requeues it)."""
        if req.adapter is None or self.adapter_pool is None:
            return {}
        got = self.adapter_pool.acquire(req.adapter)
        if got is None:
            return None
        slot, was_resident, evicted = got
        req.adapter_idx = slot
        return {"idx": slot, "hit": was_resident, "evicted": evicted}

    def unpin_adapter(self, req: Request) -> None:
        if req.adapter_idx != IDENTITY_ADAPTER and req.adapter is not None:
            assert self.adapter_pool is not None
            self.adapter_pool.release(req.adapter)
        req.adapter_idx = IDENTITY_ADAPTER

    def _requeue_fifo(self, req: Request) -> None:
        """Re-insert by ``(priority, t_submit, rid)``: admission order
        is FIFO by submission within a priority class, so a bounced
        request rejoins exactly where its class and arrival put it —
        ahead of later submissions in its class and of any lower class,
        never ahead of an earlier same-class request still waiting."""
        key = self._queue_key(req)
        idx = next((i for i, r in enumerate(self.queue)
                    if self._queue_key(r) > key), len(self.queue))
        self.queue.insert(idx, req)

    def requeue(self, slot: int) -> Request:
        """Bounce a slot's request back to the queue (blocks freed,
        recompute-style) — the adapter-stall path: its prefill finished
        but every adapter pool slot is pinned by other running requests.
        Counted as a preemption."""
        req = self.slots[slot]
        assert req is not None, f"requeue of empty slot {slot}"
        self.unpin_adapter(req)
        self.allocator.free(req.blocks)
        req.blocks = []
        req.cached_blocks = req.cached_tokens = 0
        req.slot = None
        req.state = "queued"
        req.out_tokens = []
        req.token_walls = []
        if req.t_admit is not None:
            req.lost_s += max(0.0, self.clock() - req.t_admit)
        req.preempted += 1
        self.n_preemptions += 1
        self.slots[slot] = None
        self._requeue_fifo(req)
        return req

    def match_prefix(self, req: Request) -> tuple[list[int], int]:
        """The request's longest reusable prompt prefix in the radix
        index, capped so at least one prompt token is recomputed (the
        final chunk must produce first-token logits) and floored to
        ``match_align`` tokens."""
        if self.prefix_cache is None:
            return [], 0
        if req._prefix_keys is None:
            from .prefix_cache import block_hashes

            req._prefix_keys = block_hashes(req.prompt, self.block_size)
        cap = ((req.n_prompt - 1) // self.match_align) * self.match_align
        return self.prefix_cache.match(req.prompt, max_tokens=cap,
                                       keys=req._prefix_keys)

    def admit(self) -> list[tuple[int, Request]]:
        """Move queued requests into free slots (FIFO) while the fit
        check passes; returns the (slot, request) pairs admitted this
        step — the engine prefills exactly these.

        Under prefix caching each admitted request refs its matched
        blocks (shared, already resident) and allocates only the
        uncached remainder; the plan may count on index eviction, so a
        shortfall mid-loop reclaims cold leaves before granting.
        """
        free_slots = [s for s in range(self.n_slots)
                      if self.slots[s] is None]
        if not free_slots or not self.queue:
            return []
        pc = self.prefix_cache
        n_admit = admission_plan(
            [(r.n_prompt, r.max_new_tokens, self.match_prefix(r)[1])
             for r in self.queue],
            len(free_slots), self.allocator.n_free,
            block_size=self.block_size, admission=self.admission,
            spec_lookahead=self.spec_lookahead,
            n_evictable=(pc.n_evictable() if pc is not None else 0))
        admitted: list[tuple[int, Request]] = []
        for slot in free_slots[:n_admit]:
            req = self.queue.popleft()
            matched, n_cached = self.match_prefix(req)
            # ref matched blocks FIRST: they must not be reclaimed by
            # the eviction pass that makes room for the fresh remainder
            for b in matched:
                self.allocator.ref(b)
            need = self._blocks_at_admission(req) - len(matched)
            short = need - self.allocator.n_free
            if short > 0 and pc is not None:
                pc.evict(short)
            got = self.allocator.acquire(need)
            if got is None:
                # an eviction shrank a later match the plan counted on;
                # undo and keep strict FIFO (retry next step)
                self.allocator.release(matched)
                self.queue.appendleft(req)
                break
            if pc is not None:
                pc.record_query(n_cached)
            req.blocks = matched + got
            req.cached_blocks = len(matched)
            req.cached_tokens = n_cached
            req.slot = slot
            req.state = "running"
            req.out_tokens = []
            req.t_admit = self.clock()
            self.slots[slot] = req
            admitted.append((slot, req))
        return admitted

    def record_ship(self, slot: int, n_blocks: int) -> None:
        """Account one finished prefill's KV-block transfer into decode
        (disaggregated serving: the prefill slice hands ``n_blocks`` to
        the decode slice's pool).  Stamps the request and the running
        totals — the same counters the discrete-event replay accrues,
        so predicted and measured ship traffic are comparable."""
        req = self.slots[slot]
        assert req is not None, f"record_ship on empty slot {slot}"
        req.t_kv_shipped = self.clock()
        self.n_kv_ships += 1
        self.shipped_blocks += int(n_blocks)

    def prefill_plan(self, max_chunks: int | None
                     ) -> list[tuple[int, Request]]:
        """The prefilling slots due a chunk this step: FIFO by
        admission time, at most ``max_chunks`` of them.  The engine
        advances each returned slot by exactly one chunk, so this cap
        bounds how much prefill work can delay a step's decode
        (``None`` = uncapped, the disaggregated prefill slice)."""
        by_slot = {r.slot: r for r in self.slots
                   if r is not None and r.state == "prefilling"}
        order = prefill_schedule(
            [(r.t_admit, s) for s, r in by_slot.items()], max_chunks)
        return [(slot, by_slot[slot]) for slot in order]

    def evict(self, slot: int) -> Request:
        """Finished request out of its slot; blocks back to the pool."""
        req = self.slots[slot]
        assert req is not None, f"evict of empty slot {slot}"
        self.unpin_adapter(req)
        self.allocator.free(req.blocks)
        req.blocks = []
        req.cached_blocks = req.cached_tokens = 0
        req.slot = None
        req.state = "done"
        req.t_done = self.clock()
        self.slots[slot] = None
        self.n_finished += 1
        return req

    def preempt_youngest(self) -> Request | None:
        """Free the most-recently-admitted slot's blocks and requeue it
        in FIFO submission order (it regenerates from scratch —
        recompute-style).  Returns the victim, or None when no slot is
        occupied."""
        slot = preemption_victim(
            [(r.t_admit, r.slot) for r in self.slots if r is not None])
        if slot is None:
            return None
        victim = self.slots[slot]
        assert victim is not None
        self.unpin_adapter(victim)
        self.allocator.free(victim.blocks)
        victim.blocks = []
        victim.cached_blocks = victim.cached_tokens = 0
        victim.slot = None
        victim.state = "queued"
        victim.out_tokens = []
        victim.token_walls = []
        if victim.t_admit is not None:
            victim.lost_s += max(0.0, self.clock() - victim.t_admit)
        victim.preempted += 1
        self.n_preemptions += 1
        self.slots[slot] = None
        self._requeue_fifo(victim)
        return victim

    def grow_for_step(self) -> list[Any]:
        """Optimistic mode: before a decode step, every running request
        about to write tokens through ``ctx + spec_lookahead`` must own
        block ``(ctx + spec_lookahead) // bs`` (speculative steps write
        up to k extra KV positions).  Grows tables one block at a time;
        on allocation failure, preempts the youngest slot and retries
        (the shrunk batch frees blocks).  Returns the requests that
        were preempted."""
        preempted: list[Request] = []
        if self.admission != "optimistic":
            return preempted
        for slot in range(self.n_slots):
            while True:
                req = self.slots[slot]
                if req is None or req.state != "running":
                    # prefilling slots own their prompt blocks already
                    # and take no decode write this step
                    break
                if not decode_needs_block(
                        req.n_prompt, req.n_generated, len(req.blocks),
                        block_size=self.block_size,
                        spec_lookahead=self.spec_lookahead):
                    break  # every write fits in owned blocks
                got = self.allocator.alloc(1)
                if got is None and self.prefix_cache is not None:
                    # drop cold reusable KV before touching live work:
                    # an unreferenced radix leaf is strictly cheaper to
                    # reclaim than a preempt-and-recompute
                    if self.prefix_cache.evict(1):
                        got = self.allocator.alloc(1)
                if got is not None:
                    req.blocks.extend(got)
                    continue  # lookahead may span a second block
                victim = self.preempt_youngest()
                if victim is None:
                    raise RuntimeError(
                        "cannot grow KV blocks with no slot to preempt")
                preempted.append(victim)
                # if we preempted OURSELVES the slot is now empty and
                # the outer loop moves on
        return preempted
