"""Continuous-batching serving: paged KV cache, iteration-level
scheduler, slot-padded jitted decode engine (`tadnn serve`)."""

from .engine import ServeEngine
from .kv_pool import (
    NULL_BLOCK,
    BlockAllocator,
    PagedKVPool,
    blocks_for_tokens,
    gather_blocks,
    pool_kv_bytes,
    write_token,
)
from .scheduler import Request, Scheduler

__all__ = [
    "NULL_BLOCK",
    "BlockAllocator",
    "PagedKVPool",
    "Request",
    "Scheduler",
    "ServeEngine",
    "blocks_for_tokens",
    "gather_blocks",
    "pool_kv_bytes",
    "write_token",
]
