"""Continuous-batching serving: paged KV cache, iteration-level
scheduler, paged LoRA adapter pool, slot-padded jitted decode engine
with optional speculative verify steps (`tadnn serve`)."""

from .adapters import (
    IDENTITY_ADAPTER,
    AdapterAllocator,
    AdapterPool,
    pool_adapter_bytes,
    random_adapter,
)
from .engine import ServeEngine
from .prefix_cache import PrefixCache, block_hashes
from .kv_pool import (
    NULL_BLOCK,
    BlockAllocator,
    PagedKVPool,
    blocks_for_tokens,
    gather_blocks,
    pool_kv_bytes,
    write_token,
)
from .scheduler import (
    Request,
    Scheduler,
    admission_plan,
    blocks_at_admission,
    decode_needs_block,
    preemption_victim,
    prefill_schedule,
)

__all__ = [
    "IDENTITY_ADAPTER",
    "NULL_BLOCK",
    "AdapterAllocator",
    "AdapterPool",
    "BlockAllocator",
    "PagedKVPool",
    "PrefixCache",
    "Request",
    "Scheduler",
    "ServeEngine",
    "admission_plan",
    "block_hashes",
    "blocks_at_admission",
    "blocks_for_tokens",
    "decode_needs_block",
    "preemption_victim",
    "prefill_schedule",
    "gather_blocks",
    "pool_adapter_bytes",
    "pool_kv_bytes",
    "random_adapter",
    "write_token",
]
