"""Cross-request prefix reuse: a radix index over resident KV blocks.

Production serving traffic is prefix-heavy — thousands of tenants share
one system prompt or few-shot preamble, and re-prefilling that preamble
per request is redundant compute (the first-order serving cost at scale
per the TPU serving comparisons in PAPERS.md).  The paged pool already
stores KV block-granularly; this module adds the missing piece: an
index from *prompt content* to *resident blocks*, so a new request's
prompt is matched block-by-block against KV some earlier request
already computed and only the uncached suffix is prefilled.

Granularity is the FULL block: a block is reusable only when every one
of its ``block_size`` token positions is determined by the prompt
prefix it covers.  Keys are **chained content hashes** — block ``i``'s
key hashes its own token ids together with block ``i-1``'s key, so a
key names the entire prefix up to and including the block, never just
its local tokens (two prompts sharing block 3's tokens but differing in
block 0 must not collide).  The chain makes the index a radix tree over
block-sized token runs: each node is one (prefix-hash -> block id)
mapping, children extend the prefix by one block.

Reference discipline (the allocator is ref-counted, kv_pool):

- the index holds exactly ONE reference per cached block, taken at
  ``insert`` and dropped at eviction;
- ``match`` takes NO references — the caller (scheduler admission)
  refs each matched block into the request's table;
- a node is *evictable* only when it is a leaf (no children — dropping
  an interior node would orphan the chained keys below it) and the
  index holds the block's only reference (refcount == 1, i.e. no live
  request's table points at it).  ``evict`` drops evictable leaves
  LRU-first by last hit; freeing a leaf may expose its parent, so one
  call can reclaim a whole cold chain.

The index stores block *ids*, never KV payloads — pool memory is
shared, not copied, which is the whole point.  Host-side metadata is
O(live blocks) small (a hash string, a couple of pointers and a
timestamp per node; serve_lint charges it in ``serve_estimate``).
"""

from __future__ import annotations

import hashlib
import time
from typing import Callable

from .kv_pool import BlockAllocator, NULL_BLOCK


def block_hashes(tokens: list[int], block_size: int) -> list[str]:
    """Chained content keys of every FULL block of ``tokens``.

    ``h_i = H(h_{i-1} || tokens[i*bs : (i+1)*bs])`` — each key commits
    to the whole prefix through its block.  Trailing partial blocks
    get no key (their positions are not fully prompt-determined)."""
    keys: list[str] = []
    prev = b"root"
    for i in range(len(tokens) // block_size):
        blk = tokens[i * block_size:(i + 1) * block_size]
        h = hashlib.sha256(
            prev + b"|" + ",".join(map(str, blk)).encode())
        keys.append(h.hexdigest()[:24])
        prev = h.digest()
    return keys


class _Node:
    __slots__ = ("key", "block", "parent", "children", "last_hit",
                 "expires_at")

    def __init__(self, key: str, block: int, parent: "_Node | None",
                 last_hit: float, expires_at: float | None = None):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: dict[str, _Node] = {}
        self.last_hit = last_hit
        # lease expiry (clock units); None = pinned until evicted by
        # pressure.  An expired node is dead to ``match`` immediately
        # and physically reclaimed lazily (match/evict sweeps).
        self.expires_at = expires_at


class PrefixCache:
    """Radix index of resident prompt-prefix KV blocks.

    Owns one allocator reference per indexed block; all block ids point
    into the engine's :class:`~.kv_pool.PagedKVPool`.
    """

    def __init__(self, *, block_size: int, allocator: BlockAllocator,
                 clock: Callable[[], float] = time.monotonic,
                 journal=None):
        self.block_size = int(block_size)
        self.allocator = allocator
        self.clock = clock
        # optional obs.journal.Journal: TTL reclamation emits
        # ``serve.prefix kind=expire`` events through it
        self.journal = journal
        self._root = _Node("", NULL_BLOCK, None, 0.0)
        self._nodes: dict[str, _Node] = {}
        # earliest lease expiry across the index, or None when no node
        # carries a TTL — lets the expiry sweep short-circuit on the
        # (default) TTL-free hot path
        self._next_expiry: float | None = None
        # lifetime counters (report/bench surface these)
        self.queries = 0
        self.hit_requests = 0
        self.hit_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0
        self.expired_blocks = 0

    # -- introspection -------------------------------------------------------

    @property
    def n_blocks(self) -> int:
        """Blocks currently indexed."""
        return len(self._nodes)

    def blocks(self) -> set[int]:
        """The indexed block ids (invariant checks)."""
        return {n.block for n in self._nodes.values()}

    def n_evictable(self) -> int:
        """Blocks reclaimable RIGHT NOW: unreferenced leaves plus the
        chain links they would expose — i.e. every block whose whole
        subtree is index-only.  This is the slack admission control may
        plan against on top of the allocator's free list."""

        def count(node: _Node) -> tuple[int, bool]:
            n = 0
            all_evictable = True
            for c in node.children.values():
                cn, ce = count(c)
                n += cn
                all_evictable &= ce
            if node is self._root:
                return n, all_evictable
            mine = (all_evictable
                    and self.allocator.refcount(node.block) == 1)
            return n + (1 if mine else 0), mine

        return count(self._root)[0]

    # -- lookup / publish ----------------------------------------------------

    def match(self, tokens: list[int], *, max_tokens: int | None = None,
              keys: list[str] | None = None) -> tuple[list[int], int]:
        """Longest indexed prefix of ``tokens``: (block ids, n tokens).

        Walks the chained keys from the root; stops at the first miss.
        ``max_tokens`` caps the match (the caller passes ``n_prompt - 1``
        rounded down to its alignment unit, so at least one prompt
        token is always recomputed — first-token logits must exist —
        and, in int8 mode, reuse stays on prefill-chunk boundaries for
        bit-exact parity with the uncached path).  Takes no block
        references and does not bump counters — ``record_query`` does,
        once per admitted request.  ``keys`` supplies precomputed
        chained hashes (admission planning matches every queued request
        every step; the scheduler memoizes them per request).
        """
        limit = len(tokens) if max_tokens is None else max_tokens
        if keys is None:
            keys = block_hashes(tokens, self.block_size)
        self.expire()
        blocks: list[int] = []
        node = self._root
        now = self.clock()
        for i, key in enumerate(keys):
            if (i + 1) * self.block_size > limit:
                break
            child = node.children.get(key)
            if child is None:
                break
            if child.expires_at is not None and now >= child.expires_at:
                # lease lapsed but the block is still pinned by a live
                # table (the sweep could not drop it): dead to matching
                # regardless — stale content must not extend its own
                # residency by being re-hit
                break
            child.last_hit = now
            blocks.append(child.block)
            node = child
        return blocks, len(blocks) * self.block_size

    def record_query(self, n_cached_tokens: int) -> None:
        """Bump hit/miss counters for one admitted request."""
        self.queries += 1
        if n_cached_tokens:
            self.hit_requests += 1
            self.hit_tokens += n_cached_tokens

    def insert(self, tokens: list[int], blocks: list[int], *,
               ttl_s: float | None = None) -> int:
        """Publish a prefill's full prompt blocks; returns how many new
        nodes were indexed.  ``blocks[i]`` must hold the KV of tokens
        ``[i*bs, (i+1)*bs)`` (the caller passes a committed table
        prefix).  Prefixes already indexed are left as-is — the first
        publisher wins, even if this request recomputed the same
        content into different blocks — and each NEWLY indexed block
        gains one allocator reference owned by the index.

        ``ttl_s`` bounds residency: nodes published with a TTL stop
        matching ``ttl_s`` clock units after their LAST publish and are
        reclaimed lazily (the match/evict expiry sweeps) — one tenant's
        stale system prompts cannot pin index leaves forever.  A
        re-publish of already-indexed content renews its lease (the
        content is demonstrably still live traffic)."""
        new = 0
        node = self._root
        now = self.clock()
        expires = None if ttl_s is None else now + float(ttl_s)
        for i, key in enumerate(block_hashes(tokens, self.block_size)):
            if i >= len(blocks):
                break
            child = node.children.get(key)
            if child is None:
                child = _Node(key, blocks[i], node, now, expires)
                node.children[key] = child
                self._nodes[key] = child
                self.allocator.ref(blocks[i])
                new += 1
            elif ttl_s is not None:
                child.last_hit = now
                if child.expires_at is not None:
                    child.expires_at = max(child.expires_at, expires)
            node = child
        if expires is not None and new:
            if self._next_expiry is None or expires < self._next_expiry:
                self._next_expiry = expires
        self.inserted_blocks += new
        return new

    # -- eviction ------------------------------------------------------------

    def _evictable_leaves(self) -> list[_Node]:
        return [n for n in self._nodes.values()
                if not n.children
                and self.allocator.refcount(n.block) == 1]

    def expire(self) -> int:
        """Reclaim every expired-lease block that is droppable right
        now (unreferenced leaf, walking up exposed parents); returns
        how many were freed.  Lazy: runs at the top of ``match`` and
        ``evict``, never on a timer, and short-circuits to a no-op
        until the earliest lease in the index has actually lapsed.
        Expired nodes still pinned by a live table stay resident (the
        pool reference discipline owns them) but never match; they are
        picked up by a later sweep once released."""
        now = self.clock()
        if self._next_expiry is None or now < self._next_expiry:
            return 0
        freed = 0
        while True:
            victims = [node for node in self._evictable_leaves()
                       if node.expires_at is not None
                       and now >= node.expires_at]
            if not victims:
                break
            for node in victims:
                self._drop(node)
            freed += len(victims)
        self._next_expiry = min(
            (node.expires_at for node in self._nodes.values()
             if node.expires_at is not None), default=None)
        if freed:
            self.expired_blocks += freed
            if self.journal is not None:
                self.journal.event("serve.prefix", kind="expire",
                                   n_blocks=freed,
                                   index_blocks=len(self._nodes))
        return freed

    def evict(self, n: int) -> int:
        """Reclaim up to ``n`` blocks, expired leases first, then the
        coldest (least-recent hit) unreferenced leaves; returns how
        many were freed.  Runs under allocator pressure BEFORE any live
        slot is preempted — dropping cold reusable KV is strictly
        cheaper than recomputing a live request."""
        freed = 0
        expired = self.expire()
        if expired >= n:
            return expired
        n -= expired
        while freed < n:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            victim = min(leaves, key=lambda x: (x.last_hit, x.key))
            self._drop(victim)
            freed += 1
        self.evicted_blocks += freed
        return expired + freed

    def _drop(self, node: _Node) -> None:
        assert not node.children, "evicting an interior radix node"
        assert node.parent is not None
        del node.parent.children[node.key]
        del self._nodes[node.key]
        self.allocator.release([node.block])

    def clear(self) -> int:
        """Drop every index-only chain (shutdown/tests)."""
        total = 0
        while True:
            got = self.evict(len(self._nodes) or 1)
            total += got
            if not got:
                return total
