"""Paged KV cache: block-granular storage with per-request block tables.

``decode.KVCache`` reserves a contiguous [B, S_max] strip per row, so a
batch of mixed-length requests pays worst-case memory for every slot.
Serving flips that: the pool owns ONE block-granular store per layer,

    k, v: [L, num_blocks, block_size, kvH, hd]

and each live request holds an ordered list of block ids (its *block
table*).  Token ``p`` of a request lives at ``(table[p // bs], p % bs)``
— the classic paged layout.  Memory is O(tokens actually cached), blocks
return to the free list the step a request finishes, and a new prefill
can reuse them immediately (iteration-level batching never drains).

Block 0 is the **null block**: never allocated, never read through an
active mask.  Inactive decode slots keep a table of zeros, so the fully
vectorized slot-padded decode step can scatter their (garbage) token
writes somewhere harmless without per-slot branching.

int8 mode (``quantize=True``) stores ``{"q": int8, "scale": fp32}``
per side via :func:`..quant.quantize_kv` — per-token-per-head scales,
written at the same (block, offset) the token lands in, so a block's
tokens quantize independently and freeing/reusing a block needs no
scale bookkeeping.  ~2x KV capacity per byte of HBM; the numerics bound
is pinned in tests/test_quant.py.

Reads inside the jitted decode step go through :func:`gather_blocks`
(table-indexed gather to a dense [S, max_len, kvH, hd] view feeding the
stock ``xla_attention``).  That is the correctness-first choice — a
fused paged-attention kernel that never materializes the gathered view
is the known follow-up (ROADMAP), not a prerequisite: on the CPU sim
mesh and at smoke scale the gather is XLA-fused and exact.

Sharding: the pool leaf spec is ``cache_partition_spec`` with NO batch
axes (blocks are a global resource, any slot may use any block) — kv
heads split over the tensor axis exactly like the dense decode cache.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from ...models.transformer_core import TransformerConfig
from ..decode import cache_partition_spec
from ..quant import is_quantized_leaf, kv_leaf_parts, quantize_kv

NULL_BLOCK = 0  # reserved scratch target for inactive-slot writes


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` cache entries."""
    return max(1, math.ceil(n_tokens / block_size))


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` block ids.

    Block 0 (:data:`NULL_BLOCK`) is reserved and never handed out.
    ``alloc`` is all-or-nothing (returns None rather than a partial
    grant — admission control wants a clean fit check), ``free`` rejects
    double-frees and foreign ids loudly: a block on two tables at once
    is silent cross-request cache corruption, the one failure mode a
    paged cache must make impossible.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (one is the reserved null block), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool pages are the ones still warm in cache on real hardware)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._live: set[int] = set()

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_live(self) -> int:
        return len(self._live)

    def alloc(self, n: int) -> list[int] | None:
        """``n`` block ids, or None if the pool cannot cover them."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        self._live.update(got)
        return got

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if b not in self._live:
                raise ValueError(
                    f"free of block {b} not currently allocated "
                    f"(double-free or foreign id)")
            self._live.remove(b)
            self._free.append(b)


def pool_kv_bytes(cfg: TransformerConfig, num_blocks: int, block_size: int,
                  dtype=jnp.bfloat16, quantize: bool = False) -> int:
    """Global bytes of the k+v pool arrays (scales included in int8
    mode) — the static number admission control and `check --serving`
    budget against."""
    n_cells = cfg.n_layers * num_blocks * block_size * cfg.kv_heads
    if quantize:
        per_cell = cfg.head_dim * 1 + 4  # int8 payload + fp32 scale
    else:
        per_cell = cfg.head_dim * jnp.dtype(dtype).itemsize
    return 2 * n_cells * per_cell  # k and v


def _zeros_side(shape, dtype, quantize: bool):
    if not quantize:
        return jnp.zeros(shape, dtype)
    return {
        "q": jnp.zeros(shape, jnp.int8),
        "scale": jnp.ones(shape[:-1] + (1,), jnp.float32),
    }


def gather_blocks(kv_layer: Any, table: jax.Array,
                  dtype=jnp.bfloat16) -> jax.Array:
    """Dense per-slot view of one layer's paged KV — the REFERENCE path.

    ``kv_layer``: [NB, bs, kvH, hd] (or its ``{"q","scale"}`` int8
    form); ``table``: [S, max_blocks] int32 —> [S, max_blocks*bs, kvH,
    hd].  Table rows are padded with :data:`NULL_BLOCK`; the garbage
    gathered from those pages sits beyond each slot's context length
    and the attention mask never admits it.  Dequantize-on-gather keeps
    the int8 arrays as what lives in HBM (same contract as the weight
    path) — only the gathered working set converts; an fp pool skips
    the dequantize pass entirely (no per-element convert when the pool
    already stores ``dtype``).

    This materialized view is what the fused kernel
    (ops/paged_attention.py) exists to eliminate; it stays as the
    engine's ``attention_impl="dense"`` path and as the oracle every
    kernel parity test compares against.
    """
    payload, scale = kv_leaf_parts(kv_layer)
    if scale is not None:
        g = (payload[table].astype(jnp.float32)
             * scale[table]).astype(dtype)
    else:
        g = payload[table]
        if g.dtype != dtype:
            g = g.astype(dtype)
    S, MB, bs, H, hd = g.shape
    return g.reshape(S, MB * bs, H, hd)


def write_token(kv_layer: Any, table: jax.Array, pos: jax.Array,
                new: jax.Array) -> Any:
    """Scatter one token per slot into its paged position.

    ``new``: [S, kvH, hd] (this step's k or v), ``pos``: [S] absolute
    context positions.  The target is ``(table[s, pos // bs], pos % bs)``
    per slot; inactive slots carry all-null tables so their writes land
    in the scratch block.  int8 mode quantizes the token in place with
    its own per-head scale.
    """
    bs = kv_leaf_parts(kv_layer)[0].shape[1]
    S = table.shape[0]
    blk = jnp.take_along_axis(
        table, (pos // bs)[:, None].astype(jnp.int32), axis=1)[:, 0]
    off = pos % bs
    if is_quantized_leaf(kv_layer):
        q = quantize_kv(new)
        return {
            "q": kv_layer["q"].at[blk, off].set(q["q"]),
            "scale": kv_layer["scale"].at[blk, off].set(q["scale"]),
        }
    return kv_layer.at[blk, off].set(new.astype(kv_layer.dtype))


class PagedKVPool:
    """Device storage + allocator + host-side table building.

    The arrays live as a pytree ``{"k": .., "v": ..}`` with leading
    layer axis on every leaf so the engine's ``lax.scan`` over layers
    threads them exactly like ``forward_cached`` threads the dense
    cache.  The pool object itself is host state (free list, shapes);
    the arrays are swapped wholesale through the jitted step (donated),
    so there is no device<->host copy per token.
    """

    def __init__(self, cfg: TransformerConfig, *, num_blocks: int,
                 block_size: int, dtype=jnp.bfloat16,
                 quantize: bool = False, mesh=None):
        self.cfg = cfg
        self.block_size = int(block_size)
        self.dtype = dtype
        self.quantize = bool(quantize)
        self.allocator = BlockAllocator(num_blocks)
        # prefill->decode block-transfer accounting (disaggregated
        # serving ships finished prefill KV through ship_prefill)
        self.n_transfers = 0
        self.transferred_blocks = 0
        self.transferred_bytes = 0
        shape = (cfg.n_layers, num_blocks, block_size,
                 cfg.kv_heads, cfg.head_dim)
        self.kv = {"k": _zeros_side(shape, dtype, quantize),
                   "v": _zeros_side(shape, dtype, quantize)}
        self.spec = None
        if mesh is not None:
            self.spec = cache_partition_spec(cfg, mesh, batch_axes=())
            from jax.sharding import NamedSharding

            sh = NamedSharding(mesh, self.spec)

            def place(x):
                return jax.device_put(x, sh)

            self.kv = {
                side: ({"q": place(leaf["q"]),
                        "scale": place(leaf["scale"])}
                       if is_quantized_leaf(leaf) else place(leaf))
                for side, leaf in self.kv.items()
            }

    @property
    def num_blocks(self) -> int:
        return self.allocator.num_blocks

    @property
    def total_bytes(self) -> int:
        return pool_kv_bytes(self.cfg, self.num_blocks, self.block_size,
                             self.dtype, self.quantize)

    @property
    def bytes_per_block(self) -> int:
        """Global bytes one block id holds across all layers, k and v
        (scales included in int8 mode) — the unit the block-transfer
        accounting charges per shipped block."""
        return pool_kv_bytes(self.cfg, 1, self.block_size,
                             self.dtype, self.quantize)

    def alloc(self, n: int) -> list[int] | None:
        return self.allocator.alloc(n)

    def free(self, blocks: list[int]) -> None:
        self.allocator.free(blocks)

    def table_row(self, blocks: list[int], max_blocks: int) -> list[int]:
        """Fixed-width table row: allocated ids then null padding."""
        if len(blocks) > max_blocks:
            raise ValueError(
                f"{len(blocks)} blocks exceed table width {max_blocks}")
        return list(blocks) + [NULL_BLOCK] * (max_blocks - len(blocks))

    def write_prefill(self, blocks: list[int], k: jax.Array,
                      v: jax.Array) -> None:
        """Copy a dense prefill cache slice into allocated blocks.

        ``k``/``v``: [L, P, kvH, hd] (the batch-1 prefill cache row,
        squeezed).  P is right-padded with zeros to a whole number of
        blocks here; the pad cells are dead until the decode steps that
        overwrite them, and the mask excludes them meanwhile.
        """
        L, P, H, hd = k.shape
        n = len(blocks)
        pad = n * self.block_size - P
        if pad < 0:
            raise ValueError(
                f"{P} prefill tokens need "
                f"{blocks_for_tokens(P, self.block_size)} blocks, "
                f"got {n}")
        idx = jnp.asarray(blocks, jnp.int32)
        for side, dense in (("k", k), ("v", v)):
            x = jnp.pad(dense, ((0, 0), (0, pad), (0, 0), (0, 0)))
            view = x.reshape(L, n, self.block_size, H, hd)
            leaf = self.kv[side]
            if self.quantize:
                q = quantize_kv(view)
                self.kv[side] = {
                    "q": leaf["q"].at[:, idx].set(q["q"]),
                    "scale": leaf["scale"].at[:, idx].set(q["scale"]),
                }
            else:
                self.kv[side] = leaf.at[:, idx].set(
                    view.astype(leaf.dtype))

    def ship_prefill(self, blocks: list[int], k: jax.Array,
                     v: jax.Array) -> int:
        """``write_prefill`` plus block-transfer accounting — the
        disaggregated engine's path for handing a finished prefill's KV
        to the decode slice.  The payload is the same either way (the
        pool write IS the transfer when both slices share one process);
        what this adds is the metric: blocks and bytes shipped at pool
        storage precision, i.e. what crosses the wire when prefill and
        decode live on distinct mesh slices.  Returns the bytes moved.
        """
        self.write_prefill(blocks, k, v)
        moved = len(blocks) * self.bytes_per_block
        self.n_transfers += 1
        self.transferred_blocks += len(blocks)
        self.transferred_bytes += moved
        return moved
