"""Paged KV cache: block-granular storage with per-request block tables.

``decode.KVCache`` reserves a contiguous [B, S_max] strip per row, so a
batch of mixed-length requests pays worst-case memory for every slot.
Serving flips that: the pool owns ONE block-granular store per layer,

    k, v: [L, num_blocks, block_size, kvH, hd]

and each live request holds an ordered list of block ids (its *block
table*).  Token ``p`` of a request lives at ``(table[p // bs], p % bs)``
— the classic paged layout.  Memory is O(tokens actually cached), blocks
return to the free list the step a request finishes, and a new prefill
can reuse them immediately (iteration-level batching never drains).

Block 0 is the **null block**: never allocated, never read through an
active mask.  Inactive decode slots keep a table of zeros, so the fully
vectorized slot-padded decode step can scatter their (garbage) token
writes somewhere harmless without per-slot branching.

int8 mode (``quantize=True``) stores ``{"q": int8, "scale": fp32}``
per side via :func:`..quant.quantize_kv` — per-token-per-head scales,
written at the same (block, offset) the token lands in, so a block's
tokens quantize independently and freeing/reusing a block needs no
scale bookkeeping.  ~2x KV capacity per byte of HBM; the numerics bound
is pinned in tests/test_quant.py.

Reads inside the jitted decode step go through :func:`gather_blocks`
(table-indexed gather to a dense [S, max_len, kvH, hd] view feeding the
stock ``xla_attention``).  That is the correctness-first choice — a
fused paged-attention kernel that never materializes the gathered view
is the known follow-up (ROADMAP), not a prerequisite: on the CPU sim
mesh and at smoke scale the gather is XLA-fused and exact.

Sharding: the pool leaf spec is ``cache_partition_spec`` with NO batch
axes (blocks are a global resource, any slot may use any block) — kv
heads split over the tensor axis exactly like the dense decode cache.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from ...models.transformer_core import TransformerConfig
from ..decode import cache_partition_spec
from ..quant import is_quantized_leaf, kv_leaf_parts, quantize_kv

NULL_BLOCK = 0  # reserved scratch target for inactive-slot writes


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` cache entries."""
    return max(1, math.ceil(n_tokens / block_size))


class BlockAllocator:
    """Ref-counted free-list allocator over ``num_blocks`` block ids.

    Block 0 (:data:`NULL_BLOCK`) is reserved and never handed out.
    ``acquire`` is all-or-nothing (returns None rather than a partial
    grant — admission control wants a clean fit check) and hands out
    blocks at refcount 1; ``ref`` adds a reference so a block can back
    several owners at once (cross-request prefix sharing: many block
    tables plus the radix index may all point at one block);
    ``release`` decrements and returns the block to the free list only
    at refcount 0.  A release of a block with no outstanding reference
    still raises loudly — a double-release from the same owner is the
    refcount-era shape of the double-free bug, and silent over-release
    is cross-request cache corruption, the one failure mode a paged
    cache must make impossible.  ``alloc``/``free`` remain as aliases
    for the single-owner call sites.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (one is the reserved null block), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool pages are the ones still warm in cache on real hardware)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._refs: dict[int, int] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_live(self) -> int:
        return len(self._refs)

    @property
    def _live(self) -> set[int]:
        """Live block ids (refcount >= 1) — invariant-check view."""
        return set(self._refs)

    def refcount(self, block: int) -> int:
        """Outstanding references on ``block`` (0 when free)."""
        return self._refs.get(block, 0)

    def acquire(self, n: int) -> list[int] | None:
        """``n`` fresh block ids at refcount 1, or None if the pool
        cannot cover them."""
        if n < 0:
            raise ValueError(f"acquire({n})")
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        for b in got:
            self._refs[b] = 1
        return got

    def ref(self, block: int) -> None:
        """Add a reference to an already-live block (a new owner)."""
        if block not in self._refs:
            raise ValueError(
                f"ref of block {block} not currently allocated")
        self._refs[block] += 1

    def release(self, blocks: list[int]) -> None:
        for b in blocks:
            n = self._refs.get(b, 0)
            if n <= 0:
                raise ValueError(
                    f"release of block {b} with no outstanding "
                    f"reference (double-free or foreign id)")
            if n == 1:
                del self._refs[b]
                self._free.append(b)
            else:
                self._refs[b] = n - 1

    # single-owner aliases (the pre-refcount API)
    alloc = acquire
    free = release


def pool_kv_bytes(cfg: TransformerConfig, num_blocks: int, block_size: int,
                  dtype=jnp.bfloat16, quantize: bool = False) -> int:
    """Global bytes of the k+v pool arrays (scales included in int8
    mode) — the static number admission control and `check --serving`
    budget against."""
    n_cells = cfg.n_layers * num_blocks * block_size * cfg.kv_heads
    if quantize:
        per_cell = cfg.head_dim * 1 + 4  # int8 payload + fp32 scale
    else:
        per_cell = cfg.head_dim * jnp.dtype(dtype).itemsize
    return 2 * n_cells * per_cell  # k and v


def _zeros_side(shape, dtype, quantize: bool):
    if not quantize:
        return jnp.zeros(shape, dtype)
    return {
        "q": jnp.zeros(shape, jnp.int8),
        "scale": jnp.ones(shape[:-1] + (1,), jnp.float32),
    }


def gather_blocks(kv_layer: Any, table: jax.Array,
                  dtype=jnp.bfloat16) -> jax.Array:
    """Dense per-slot view of one layer's paged KV — the REFERENCE path.

    ``kv_layer``: [NB, bs, kvH, hd] (or its ``{"q","scale"}`` int8
    form); ``table``: [S, max_blocks] int32 —> [S, max_blocks*bs, kvH,
    hd].  Table rows are padded with :data:`NULL_BLOCK`; the garbage
    gathered from those pages sits beyond each slot's context length
    and the attention mask never admits it.  Dequantize-on-gather keeps
    the int8 arrays as what lives in HBM (same contract as the weight
    path) — only the gathered working set converts; an fp pool skips
    the dequantize pass entirely (no per-element convert when the pool
    already stores ``dtype``).

    This materialized view is what the fused kernel
    (ops/paged_attention.py) exists to eliminate; it stays as the
    engine's ``attention_impl="dense"`` path and as the oracle every
    kernel parity test compares against.
    """
    payload, scale = kv_leaf_parts(kv_layer)
    if scale is not None:
        g = (payload[table].astype(jnp.float32)
             * scale[table]).astype(dtype)
    else:
        g = payload[table]
        if g.dtype != dtype:
            g = g.astype(dtype)
    S, MB, bs, H, hd = g.shape
    return g.reshape(S, MB * bs, H, hd)


def write_token(kv_layer: Any, table: jax.Array, pos: jax.Array,
                new: jax.Array) -> Any:
    """Scatter one token per slot into its paged position.

    ``new``: [S, kvH, hd] (this step's k or v), ``pos``: [S] absolute
    context positions.  The target is ``(table[s, pos // bs], pos % bs)``
    per slot; inactive slots carry all-null tables so their writes land
    in the scratch block.  int8 mode quantizes the token in place with
    its own per-head scale.
    """
    bs = kv_leaf_parts(kv_layer)[0].shape[1]
    S = table.shape[0]
    blk = jnp.take_along_axis(
        table, (pos // bs)[:, None].astype(jnp.int32), axis=1)[:, 0]
    off = pos % bs
    if is_quantized_leaf(kv_layer):
        q = quantize_kv(new)
        return {
            "q": kv_layer["q"].at[blk, off].set(q["q"]),
            "scale": kv_layer["scale"].at[blk, off].set(q["scale"]),
        }
    return kv_layer.at[blk, off].set(new.astype(kv_layer.dtype))


class PagedKVPool:
    """Device storage + allocator + host-side table building.

    The arrays live as a pytree ``{"k": .., "v": ..}`` with leading
    layer axis on every leaf so the engine's ``lax.scan`` over layers
    threads them exactly like ``forward_cached`` threads the dense
    cache.  The pool object itself is host state (free list, shapes);
    the arrays are swapped wholesale through the jitted step (donated),
    so there is no device<->host copy per token.
    """

    def __init__(self, cfg: TransformerConfig, *, num_blocks: int,
                 block_size: int, dtype=jnp.bfloat16,
                 quantize: bool = False, mesh=None):
        self.cfg = cfg
        self.block_size = int(block_size)
        self.dtype = dtype
        self.quantize = bool(quantize)
        self.allocator = BlockAllocator(num_blocks)
        # prefill->decode block-transfer accounting (disaggregated
        # serving ships finished prefill KV through ship_prefill)
        self.n_transfers = 0
        self.transferred_blocks = 0
        self.transferred_bytes = 0
        shape = (cfg.n_layers, num_blocks, block_size,
                 cfg.kv_heads, cfg.head_dim)
        self.kv = {"k": _zeros_side(shape, dtype, quantize),
                   "v": _zeros_side(shape, dtype, quantize)}
        self.spec = None
        if mesh is not None:
            self.spec = cache_partition_spec(cfg, mesh, batch_axes=())
            from jax.sharding import NamedSharding

            sh = NamedSharding(mesh, self.spec)

            def place(x):
                return jax.device_put(x, sh)

            self.kv = {
                side: ({"q": place(leaf["q"]),
                        "scale": place(leaf["scale"])}
                       if is_quantized_leaf(leaf) else place(leaf))
                for side, leaf in self.kv.items()
            }

    @property
    def num_blocks(self) -> int:
        return self.allocator.num_blocks

    @property
    def total_bytes(self) -> int:
        return pool_kv_bytes(self.cfg, self.num_blocks, self.block_size,
                             self.dtype, self.quantize)

    @property
    def bytes_per_block(self) -> int:
        """Global bytes one block id holds across all layers, k and v
        (scales included in int8 mode) — the unit the block-transfer
        accounting charges per shipped block."""
        return pool_kv_bytes(self.cfg, 1, self.block_size,
                             self.dtype, self.quantize)

    def alloc(self, n: int) -> list[int] | None:
        return self.allocator.alloc(n)

    def free(self, blocks: list[int]) -> None:
        self.allocator.free(blocks)

    def fork_block(self, src: int) -> int | None:
        """Copy-on-write fork: acquire a fresh block, copy ``src``'s
        device content into it, return the new id (None when the pool
        is exhausted — the caller must evict or preempt first).  The
        caller owns the table update and the release of its reference
        on ``src``; the copy itself is one fused per-leaf scatter, no
        host round-trip."""
        got = self.allocator.acquire(1)
        if got is None:
            return None
        dst = got[0]
        for side, leaf in self.kv.items():
            if is_quantized_leaf(leaf):
                self.kv[side] = {
                    "q": leaf["q"].at[:, dst].set(leaf["q"][:, src]),
                    "scale": leaf["scale"].at[:, dst].set(
                        leaf["scale"][:, src]),
                }
            else:
                self.kv[side] = leaf.at[:, dst].set(leaf[:, src])
        return dst

    def read_blocks(self, blocks: list[int], max_blocks: int,
                    dtype=jnp.bfloat16) -> tuple[jax.Array, jax.Array]:
        """Dense dequantized view of a block list, padded to a fixed
        width: (k, v) each ``[L, max_blocks * bs, kvH, hd]``.  This is
        the prefix-cache seeding path — a matched prompt prefix reads
        its resident KV back into the [1, max_len] prefill temp cache
        instead of recomputing it.  The fixed ``max_blocks`` width
        (rows past the real blocks gather null-block garbage the
        cursor/mask never admits before they are overwritten) keeps the
        op's shape constant, so it compiles once per engine config."""
        table = jnp.asarray(self.table_row(blocks, max_blocks), jnp.int32)
        out = []
        for side in ("k", "v"):
            payload, scale = kv_leaf_parts(self.kv[side])
            g = jnp.take(payload, table, axis=1)  # [L, MB, bs, H, hd]
            if scale is not None:
                g = (g.astype(jnp.float32)
                     * jnp.take(scale, table, axis=1)).astype(dtype)
            elif g.dtype != dtype:
                g = g.astype(dtype)
            L, MB, bs, H, hd = g.shape
            out.append(g.reshape(L, MB * bs, H, hd))
        return out[0], out[1]

    def table_row(self, blocks: list[int], max_blocks: int) -> list[int]:
        """Fixed-width table row: allocated ids then null padding."""
        if len(blocks) > max_blocks:
            raise ValueError(
                f"{len(blocks)} blocks exceed table width {max_blocks}")
        return list(blocks) + [NULL_BLOCK] * (max_blocks - len(blocks))

    def write_prefill(self, blocks: list[int], k: jax.Array,
                      v: jax.Array) -> None:
        """Copy a dense prefill cache slice into allocated blocks.

        ``k``/``v``: [L, P, kvH, hd] (the batch-1 prefill cache row,
        squeezed) — or, in int8 mode, the already-quantized
        ``{"q", "scale"}`` form of those rows: the chunked prefill
        trace quantizes each chunk as it lands in the temp cache, and
        committing those exact (q, scale) pairs (instead of
        re-quantizing the dequantized rows) is what makes a
        prefix-cache read-back bit-identical to the rows the original
        prefill attended to.  P is right-padded with zeros to a whole
        number of blocks here; the pad cells are dead until the decode
        steps that overwrite them, and the mask excludes them
        meanwhile.
        """
        if is_quantized_leaf(k) != is_quantized_leaf(v):
            raise ValueError("k/v must both be dense or both quantized")
        if is_quantized_leaf(k):
            if not self.quantize:
                raise ValueError(
                    "quantized prefill rows into a dense pool")
            L, P, H, hd = k["q"].shape
        else:
            L, P, H, hd = k.shape
        n = len(blocks)
        pad = n * self.block_size - P
        if pad < 0:
            raise ValueError(
                f"{P} prefill tokens need "
                f"{blocks_for_tokens(P, self.block_size)} blocks, "
                f"got {n}")
        idx = jnp.asarray(blocks, jnp.int32)

        def blocked(x, fill=0):
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)),
                        constant_values=fill)
            return x.reshape(L, n, self.block_size, H, x.shape[-1])

        for side, rows in (("k", k), ("v", v)):
            leaf = self.kv[side]
            if is_quantized_leaf(rows):
                self.kv[side] = {
                    "q": leaf["q"].at[:, idx].set(blocked(rows["q"])),
                    "scale": leaf["scale"].at[:, idx].set(
                        blocked(rows["scale"], fill=1)),
                }
            elif self.quantize:
                q = quantize_kv(blocked(rows))
                self.kv[side] = {
                    "q": leaf["q"].at[:, idx].set(q["q"]),
                    "scale": leaf["scale"].at[:, idx].set(q["scale"]),
                }
            else:
                self.kv[side] = leaf.at[:, idx].set(
                    blocked(rows).astype(leaf.dtype))

    def ship_prefill(self, blocks: list[int], k: jax.Array,
                     v: jax.Array) -> int:
        """``write_prefill`` plus block-transfer accounting — the
        disaggregated engine's path for handing a finished prefill's KV
        to the decode slice.  The payload is the same either way (the
        pool write IS the transfer when both slices share one process);
        what this adds is the metric: blocks and bytes shipped at pool
        storage precision, i.e. what crosses the wire when prefill and
        decode live on distinct mesh slices.  Returns the bytes moved.
        """
        self.write_prefill(blocks, k, v)
        moved = len(blocks) * self.bytes_per_block
        self.n_transfers += 1
        self.transferred_blocks += len(blocks)
        self.transferred_bytes += moved
        return moved
