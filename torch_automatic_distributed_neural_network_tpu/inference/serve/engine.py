"""Serving engine: persistent jitted decode over a slot-padded batch.

One fixed-shape decode step serves every live request at once.  The
batch axis is ``n_slots`` *slots*, not requests: a slot is either bound
to a running request or inactive (null block table, masked sampling).
Each call advances EVERY active request by one token; between calls the
scheduler evicts finished requests and admits queued ones, so the step
executable compiles once and runs for the life of the server — no
recompiles as the request mix churns (prefill is the only shape-varying
entry point, one trace per distinct prompt length).

Per-layer math is the TRAINING modules applied piecewise — the same
single-source-of-truth discipline as ``decode.forward_cached``, from
which this step differs in exactly three ways:

- positions/lengths are PER-SLOT vectors (requests at different depths
  share a step), so rope angles and the attention mask row vary by slot;
- KV reads/writes go through the paged pool (``kv_pool.gather_blocks``
  / ``write_token``) instead of a contiguous cache strip;
- sampled tokens are masked to 0 on inactive slots.

Prefill reuses ``forward_cached`` itself on a dense temp cache, then
copies the rows into the request's blocks — numerically the exact
prefill ``generate()`` runs, which is what makes token-parity with
sequential generation testable (greedy decoding is deterministic; for
stochastic sampling the engine is reproducible under its own rng but
not per-request-identical to ``generate()``, since one categorical
call samples all slots).  By default prefill is CHUNKED: the prompt
streams through one jitted [1, C]-chunk trace against a fixed
[1, max_len] temp cache (C snapped to a divisor of max_len), one chunk
per engine step per prefilling slot, INTERLEAVED with decode — a long
prompt no longer stalls every running request for its whole prefill,
and no per-prompt-length retrace exists.  ``prefill_chunk=None``
restores the legacy single-shot prefill (one [1, P] pass at
admission, one trace per distinct P).

The decode-step attention is config-gated (``attention_impl``):
``"paged"`` (default) runs the fused Pallas kernel that reads the
block table in-kernel (ops/paged_attention.py — no dense gather);
``"dense"`` keeps the reference ``gather_blocks`` + ``xla_attention``
path the kernel is parity-pinned against.

Telemetry: every finished request journals a ``serve.request`` event
(queue/prefill/decode/total seconds, tokens/s, preemption count) and
every step a ``serve.step`` event (slot occupancy, free blocks) through
``obs.journal`` — ``tadnn report`` renders p50/p99 latency, goodput
and occupancy from exactly these records.
"""

from __future__ import annotations

import dataclasses
import math
import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer_core import (
    MLPBlock,
    SelfAttention,
    TransformerConfig,
    make_norm,
)
from ...obs import journal as _journal
from ..decode import (
    KVCache,
    SampleConfig,
    _moe_mlp_cached,
    _sample,
    forward_cached,
)
from ..quant import dequantize_leaf, dequantize_tree, embedding_lookup, \
    is_quantized_leaf
from .kv_pool import (
    PagedKVPool,
    blocks_for_tokens,
    gather_blocks,
    write_token,
)
from .scheduler import Request, Scheduler


def _paged_decode_step(params, kv, tables, ctx_lens, last_tok, active,
                       rng, *, cfg: TransformerConfig,
                       sample: SampleConfig, moe_decode: str,
                       attention_impl: str = "paged",
                       mesh=None, spec=None):
    """One token for every slot.  [S] vectors throughout; static shapes
    (S slots, tables [S, max_blocks]) so this traces exactly once.

    ``attention_impl`` picks the per-layer KV read:

    - ``"paged"`` (default): the fused Pallas kernel
      (ops/paged_attention.py) reads the block table in-kernel — the
      dense gathered view never materializes, int8 dequantize happens
      on load inside the kernel;
    - ``"dense"``: the reference path — ``gather_blocks`` to a dense
      [S, max_len] view, then stock ``xla_attention`` under an explicit
      mask.  Kept as the parity oracle and the fallback.
    """
    from ...ops.attention import xla_attention
    from ...ops.paged_attention import paged_attention

    dtype = cfg.dtype
    norm = make_norm(cfg)
    attn = SelfAttention(cfg)
    mlp = MLPBlock(cfg)
    if mesh is not None and spec is not None:
        from jax.sharding import NamedSharding

        sh = NamedSharding(mesh, spec)
        kv = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sh), kv)

    x = embedding_lookup(
        params["embed"]["embedding"], last_tok[:, None], dtype)  # [S,1,d]
    positions = ctx_lens[:, None]  # [S, 1] — per-slot rope angles
    if cfg.pos == "learned":
        pe = params["pos_embed"].astype(dtype)
        x = x + pe[positions]

    mask = None
    if attention_impl == "dense":
        n_keys = tables.shape[1] * (
            kv["k"]["q"] if is_quantized_leaf(kv["k"]) else kv["k"]
        ).shape[2]
        key_idx = jnp.arange(n_keys)[None, :]
        # the step writes this token at ctx_lens, then attends keys
        # 0..ctx_lens inclusive; table padding beyond a slot's blocks
        # gathers null-block garbage that this mask never admits
        mask = key_idx <= ctx_lens[:, None]
        if cfg.sliding_window is not None:
            mask &= key_idx > ctx_lens[:, None] - cfg.sliding_window
        mask = mask[:, None, None, :]  # [S, 1, 1, K]

    def layer(x, xs):
        lp, k_layer, v_layer = xs
        lp = dequantize_tree(lp, dtype)
        h = norm.apply({"params": lp["attn_norm"]}, x)
        q, k, v = attn.apply(
            {"params": lp["attn"]}, h, positions, method="qkv")
        k_layer = write_token(k_layer, tables, ctx_lens, k[:, 0])
        v_layer = write_token(v_layer, tables, ctx_lens, v[:, 0])
        if attention_impl == "paged":
            # fused path: block table consumed in-kernel, same ctx/window
            # mask semantics, no [S, max_len] gather
            o = paged_attention(
                q[:, 0], k_layer, v_layer, tables, ctx_lens,
                window=cfg.sliding_window)[:, None]
        else:
            kd = gather_blocks(k_layer, tables, dtype)
            vd = gather_blocks(v_layer, tables, dtype)
            o = xla_attention(q, kd, vd, causal=False, mask=mask)
        x = x + attn.apply(
            {"params": lp["attn"]}, o.astype(dtype), method="out_proj")
        h = norm.apply({"params": lp["mlp_norm"]}, x)
        if "experts_up" in lp["mlp"]:
            x = x + _moe_mlp_cached(lp["mlp"], h, cfg)
        else:
            x = x + mlp.apply({"params": lp["mlp"]}, h)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv["k"], kv["v"]))

    x = norm.apply({"params": params["final_norm"]}, x)
    feats = x[:, -1].astype(jnp.float32)
    if cfg.tie_embeddings:
        emb = params["embed"]["embedding"]
        if is_quantized_leaf(emb):
            emb = dequantize_leaf(emb, jnp.float32)
        logits = feats @ emb.astype(jnp.float32).T
    else:
        head = params["lm_head"]["kernel"]
        if is_quantized_leaf(head):
            head = dequantize_leaf(head, jnp.float32)
        logits = feats @ head.astype(jnp.float32)
    nxt = _sample(logits, rng, sample)
    nxt = jnp.where(active, nxt, 0)
    return {"k": new_k, "v": new_v}, nxt


def _prefill_chunk_step(params, tokens, cache, last_idx, *,
                        cfg: TransformerConfig, moe_decode: str):
    """One fixed-shape prefill chunk: [1, C] tokens through
    ``forward_cached`` against the fixed [1, max_len] temp cache.

    Every chunk of every prompt reuses this ONE jitted trace: the chunk
    length is constant and both the cache cursor (``cache.length``) and
    ``last_idx`` are traced scalars.  The final chunk of a prompt may
    be right-padded; ``last_idx`` selects the last REAL token's logits,
    and causal masking keeps the pad positions (which sit after it) out
    of that row entirely.
    """
    logits, cache = forward_cached(
        params, cfg, tokens, cache, moe_decode=moe_decode, mesh=None,
        all_logits=True)
    last = jax.lax.dynamic_index_in_dim(
        logits, last_idx, axis=1, keepdims=False)
    return last, cache


@dataclasses.dataclass
class _PrefillState:
    """Host-side cursor of one in-flight chunked prefill: the [1,
    max_len] temp cache being filled and how many prompt tokens have
    streamed through it so far."""

    cache: KVCache
    pos: int = 0


class ServeEngine:
    """Continuous-batching server over a model + paged KV pool.

        eng = ServeEngine(model, variables, n_slots=8, max_len=256)
        eng.submit([1, 2, 3], max_new_tokens=32, eos_id=0)
        done = eng.run()          # [Request] with .prompt + .out_tokens

    ``submit`` is non-blocking (requests queue); ``step()`` advances the
    world by one decode iteration (evict / admit+prefill / grow /
    decode); ``run()`` steps until idle.  A long-lived server calls
    ``submit`` from its frontend and ``step`` in a loop — nothing here
    blocks on a full batch.
    """

    def __init__(self, model, variables: Any, *,
                 n_slots: int = 8,
                 max_len: int = 256,
                 block_size: int = 16,
                 num_blocks: int | None = None,
                 quant_kv: bool = False,
                 cache_dtype=jnp.bfloat16,
                 sample: SampleConfig | None = None,
                 admission: str = "reserve",
                 moe_decode: str = "dense",
                 attention_impl: str = "paged",
                 prefill_chunk: int | None = 32,
                 prefill_chunks_per_step: int = 1,
                 mesh=None,
                 rng: jax.Array | None = None,
                 journal: Any = None):
        if attention_impl not in ("paged", "dense"):
            raise ValueError(
                f"unknown attention_impl {attention_impl!r} "
                f"(expected 'paged' or 'dense')")
        self.cfg: TransformerConfig = model.cfg
        self.params = variables["params"]
        self.sample = sample or SampleConfig(temperature=0.0)
        self.n_slots = n_slots
        self.max_len = max_len
        self.moe_decode = moe_decode
        self.attention_impl = attention_impl
        if prefill_chunk is not None:
            # snap the chunk to a divisor of max_len: the temp cache is
            # exactly [1, max_len], so the cursor can never run past it
            # (a learned-pos dynamic_slice would clamp its start and
            # silently corrupt the chunk's position embeddings)
            prefill_chunk = math.gcd(
                min(int(prefill_chunk), max_len), max_len)
        self.prefill_chunk = prefill_chunk
        self.prefill_chunks_per_step = max(1, int(prefill_chunks_per_step))
        self.mesh = mesh
        self.max_blocks = blocks_for_tokens(max_len, block_size)
        if num_blocks is None:
            # worst case every slot full-length, plus the null block
            num_blocks = n_slots * self.max_blocks + 1
        self.pool = PagedKVPool(
            self.cfg, num_blocks=num_blocks, block_size=block_size,
            dtype=cache_dtype, quantize=quant_kv, mesh=mesh)
        self.scheduler = Scheduler(
            n_slots=n_slots, allocator=self.pool.allocator,
            block_size=block_size, admission=admission)
        self.journal = journal or _journal.get_default()
        self._rng = jax.random.key(0) if rng is None else rng
        self._step_count = 0
        self._occupancy_sum = 0.0
        self.finished: list[Request] = []
        self._prefill: dict[int, _PrefillState] = {}
        self._step_fn = jax.jit(
            partial(_paged_decode_step, cfg=self.cfg, sample=self.sample,
                    moe_decode=moe_decode, attention_impl=attention_impl,
                    mesh=mesh, spec=self.pool.spec),
            donate_argnums=(1,))
        self._prefill_fn = jax.jit(
            partial(_prefill_chunk_step, cfg=self.cfg,
                    moe_decode=moe_decode))
        if self.journal is not None:
            self.journal.event(
                "serve.engine", attention_impl=attention_impl,
                prefill_chunk=self.prefill_chunk,
                n_slots=n_slots, max_len=max_len, block_size=block_size,
                quant_kv=bool(quant_kv))

    # -- request intake ------------------------------------------------------

    def submit(self, prompt: list[int], max_new_tokens: int,
               eos_id: int | None = None) -> Request:
        total = len(prompt) + max_new_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"= {total} exceeds engine max_len {self.max_len}")
        if not prompt:
            raise ValueError("empty prompt")
        need = blocks_for_tokens(total, self.pool.block_size)
        if need > self.pool.num_blocks - 1:
            # the pool could NEVER cover this request even alone —
            # admitting it would preempt-thrash forever in optimistic
            # mode and deadlock admission in reserve mode
            raise ValueError(
                f"request needs {need} blocks but the pool has "
                f"{self.pool.num_blocks - 1} allocatable")
        req = Request(prompt=list(map(int, prompt)),
                      max_new_tokens=max_new_tokens, eos_id=eos_id)
        self.scheduler.submit(req)
        return req

    # -- one serving iteration ----------------------------------------------

    def _prefill_into_slot(self, slot: int, req: Request) -> None:
        tokens = jnp.asarray(req.prompt, jnp.int32)[None, :]
        cache = KVCache.init(self.cfg, 1, tokens.shape[1],
                             dtype=jnp.bfloat16)
        # forward_cached retraces per distinct prompt length — the only
        # shape-varying compile in the serving loop
        logits, cache = forward_cached(
            self.params, self.cfg, tokens, cache,
            moe_decode=self.moe_decode, mesh=None)
        req_rng = jax.random.fold_in(self._rng, req.rid)
        _, first_rng = jax.random.split(req_rng)
        first = int(jax.device_get(
            _sample(logits, first_rng, self.sample))[0])
        self.pool.write_prefill(req.blocks[:blocks_for_tokens(
            req.n_prompt, self.pool.block_size)],
            cache.k[:, 0], cache.v[:, 0])
        req.out_tokens = [first]
        req.t_first_token = time.monotonic()

    def _start_prefill(self, slot: int, req: Request) -> None:
        """Admission entry point: legacy single-shot prefill, or flip
        the slot to "prefilling" so step() streams the prompt through
        the shared chunk trace, interleaved with decode."""
        if self.prefill_chunk is None:
            self._prefill_into_slot(slot, req)
            return
        req.state = "prefilling"
        self._prefill[req.rid] = _PrefillState(
            cache=KVCache.init(self.cfg, 1, self.max_len,
                               dtype=jnp.bfloat16))

    def _advance_prefill(self, slot: int, req: Request) -> None:
        """One [1, C] chunk of ``req``'s prompt.  On the final chunk:
        sample the first token (identical rng derivation to single-shot
        prefill), copy the filled temp-cache rows into the request's
        blocks, and hand the slot to decode."""
        st = self._prefill[req.rid]
        C = self.prefill_chunk
        chunk = req.prompt[st.pos:st.pos + C]
        n_real = len(chunk)
        tokens = jnp.asarray(chunk + [0] * (C - n_real), jnp.int32)[None]
        t0 = time.monotonic()
        logits, st.cache = self._prefill_fn(
            self.params, tokens, st.cache, n_real - 1)
        st.pos += n_real
        done = st.pos >= req.n_prompt
        if done:
            req_rng = jax.random.fold_in(self._rng, req.rid)
            _, first_rng = jax.random.split(req_rng)
            first = int(jax.device_get(
                _sample(logits, first_rng, self.sample))[0])
            self.pool.write_prefill(
                req.blocks[:blocks_for_tokens(
                    req.n_prompt, self.pool.block_size)],
                st.cache.k[:, 0, :req.n_prompt],
                st.cache.v[:, 0, :req.n_prompt])
            req.out_tokens = [first]
            req.t_first_token = time.monotonic()
            req.state = "running"
            del self._prefill[req.rid]
        if self.journal is not None:
            self.journal.event(
                "serve.prefill_chunk", rid=req.rid, slot=slot,
                pos=min(st.pos, req.n_prompt), n_tokens=n_real,
                seconds=time.monotonic() - t0, done=done)

    def _decode_all(self) -> None:
        S, MB = self.n_slots, self.max_blocks
        tables = np.zeros((S, MB), np.int32)
        ctx = np.zeros((S,), np.int32)
        last = np.zeros((S,), np.int32)
        act = np.zeros((S,), bool)
        for s, req in enumerate(self.scheduler.slots):
            if req is None or req.state != "running":
                # prefilling slots keep an all-null table here: the
                # step's unconditional KV write lands in the scratch
                # block instead of their half-filled prompt blocks
                continue
            tables[s, :len(req.blocks)] = req.blocks
            # this step writes token n_generated at absolute position
            # n_prompt + n_generated - 1 (the first generated token
            # came from prefill and was never written)
            ctx[s] = req.n_prompt + req.n_generated - 1
            last[s] = req.out_tokens[-1]
            act[s] = True
        step_rng = jax.random.fold_in(self._rng, 2**20 + self._step_count)
        self.pool.kv, nxt = self._step_fn(
            self.params, self.pool.kv, jnp.asarray(tables),
            jnp.asarray(ctx), jnp.asarray(last), jnp.asarray(act),
            step_rng)
        nxt = np.asarray(jax.device_get(nxt))
        for s, req in enumerate(self.scheduler.slots):
            if req is not None:
                req.out_tokens.append(int(nxt[s]))

    def _finish(self, slot: int) -> None:
        req = self.scheduler.evict(slot)
        self.finished.append(req)
        if self.journal is None:
            return
        queue_s = (req.t_admit or req.t_submit) - req.t_submit
        prefill_s = ((req.t_first_token - req.t_admit)
                     if req.t_first_token and req.t_admit else None)
        decode_s = ((req.t_done - req.t_first_token)
                    if req.t_first_token else None)
        total_s = req.t_done - req.t_submit
        self.journal.event(
            "serve.request", rid=req.rid, n_prompt=req.n_prompt,
            n_new=req.n_generated, queue_s=queue_s,
            prefill_s=prefill_s, decode_s=decode_s, total_s=total_s,
            tokens_per_s=(req.n_generated / decode_s
                          if decode_s else None),
            preempted=req.preempted)

    def step(self) -> None:
        """One serving iteration: evict finished, admit queued, advance
        prefill chunks, grow/preempt (optimistic), decode every
        decoding slot.  Prefill chunks INTERLEAVE with decode steps —
        a long prompt costs each iteration one bounded chunk instead of
        stalling the whole batch for its full prefill."""
        sched = self.scheduler
        for s in range(self.n_slots):
            req = sched.slots[s]
            if (req is not None and req.state == "running"
                    and req.finished()):
                self._finish(s)
        for slot, req in sched.admit():
            self._start_prefill(slot, req)
            if req.state == "running" and req.finished():
                self._finish(slot)  # single-shot, max_new_tokens == 1
        prefill_s = 0.0
        for slot, req in sched.prefill_plan(self.prefill_chunks_per_step):
            t0 = time.monotonic()
            self._advance_prefill(slot, req)
            prefill_s += time.monotonic() - t0
            if req.state == "running" and req.finished():
                self._finish(slot)  # chunked, max_new_tokens == 1
        for victim in sched.grow_for_step():
            self._prefill.pop(victim.rid, None)
            if self.journal is not None:
                self.journal.event("serve.preempt", rid=victim.rid,
                                   n_regenerate=victim.n_prompt)
        decode_s = 0.0
        if sched.n_decoding:
            t0 = time.monotonic()
            self._decode_all()
            decode_s = time.monotonic() - t0
        self._step_count += 1
        self._occupancy_sum += sched.n_active / self.n_slots
        if self.journal is not None:
            self.journal.event(
                "serve.step", step=self._step_count,
                n_active=sched.n_active, n_queued=sched.n_queued,
                n_prefilling=sched.n_prefilling,
                occupancy=sched.n_active / self.n_slots,
                free_blocks=self.pool.allocator.n_free,
                prefill_s=prefill_s, decode_s=decode_s)

    @property
    def mean_occupancy(self) -> float | None:
        """Mean active-slot fraction over every step so far."""
        if not self._step_count:
            return None
        return self._occupancy_sum / self._step_count

    def run(self) -> list[Request]:
        """Step until queue and slots drain; returns finished requests
        (every submitted request, in completion order)."""
        while not self.scheduler.idle():
            self.step()
        return list(self.finished)
