"""Serving engine: persistent jitted decode over a slot-padded batch.

One fixed-shape decode step serves every live request at once.  The
batch axis is ``n_slots`` *slots*, not requests: a slot is either bound
to a running request or inactive (null block table, masked sampling).
Each call advances EVERY active request by one token; between calls the
scheduler evicts finished requests and admits queued ones, so the step
executable compiles once and runs for the life of the server — no
recompiles as the request mix churns (prefill is the only shape-varying
entry point, one trace per distinct prompt length).

Per-layer math is the TRAINING modules applied piecewise — the same
single-source-of-truth discipline as ``decode.forward_cached``, from
which this step differs in exactly three ways:

- positions/lengths are PER-SLOT vectors (requests at different depths
  share a step), so rope angles and the attention mask row vary by slot;
- KV reads/writes go through the paged pool (``kv_pool.gather_blocks``
  / ``write_token``) instead of a contiguous cache strip;
- sampled tokens are masked to 0 on inactive slots.

Prefill reuses ``forward_cached`` itself on a dense temp cache, then
copies the rows into the request's blocks — numerically the exact
prefill ``generate()`` runs, which is what makes token-parity with
sequential generation testable (greedy decoding is deterministic; for
stochastic sampling the engine is reproducible under its own rng but
not per-request-identical to ``generate()``, since one categorical
call samples all slots).  By default prefill is CHUNKED: the prompt
streams through one jitted [1, C]-chunk trace against a fixed
[1, max_len] temp cache (C snapped to a divisor of max_len), one chunk
per engine step per prefilling slot, INTERLEAVED with decode — a long
prompt no longer stalls every running request for its whole prefill,
and no per-prompt-length retrace exists.  ``prefill_chunk=None``
restores the legacy single-shot prefill (one [1, P] pass at
admission, one trace per distinct P).

The decode-step attention is config-gated (``attention_impl``):
``"paged"`` (default) runs the fused Pallas kernel that reads the
block table in-kernel (ops/paged_attention.py — no dense gather);
``"dense"`` keeps the reference ``gather_blocks`` + ``xla_attention``
path the kernel is parity-pinned against.

Multi-tenant LoRA (``lora_spec=...``): each request may name a
registered adapter; the decode step gathers its (A, B) factors from the
fixed-shape adapter pool by per-slot id and applies the segmented
low-rank delta inside the scanned layer body, so heterogeneous tenants
(and the base model, via identity adapter 0) share the ONE decode
trace.  Prefill merges the tenant's factors into the weights INSIDE a
jitted chunk step (rank-r matmul fused into the weight load, factors
are traced operands — still one chunk trace for every tenant).
Adapters are pinned in the pool only while their request is RUNNING;
if every pool slot is pinned when a prefill completes, the request is
bounced back to the queue recompute-style (see scheduler.requeue).

Speculative decoding (``speculative=k``, greedy only): each step drafts
k tokens per slot host-side (prompt-lookup n-grams — no draft model),
verifies ``[last, d_1..d_k]`` in the same batched step (the chunk axis
T = 1+k is baked into the trace), and accepts the longest agreeing
prefix plus the target's bonus token — between 1 and k+1 tokens per
slot per step, token-identical to plain greedy.  Rolled-back draft KV
needs no cleanup: positions past a slot's context are masked out of
attention and overwritten by the next step's writes.  Accept rates
journal as ``serve.speculate`` events.

Telemetry: every finished request journals a ``serve.request_done``
event carrying its full span timeline — submit -> admit (queue wait)
-> prefill chunks (prefix-cache skip included) -> KV ship
(disaggregated) -> first token (TTFT) -> per-token inter-token
latencies -> preempt/recompute tax -> finish — and every step a
``serve.step`` event (slot occupancy, free blocks, tokens emitted,
adapter residency) through ``obs.journal``.  ``tadnn report`` renders
p50/p99 latency, TTFT/ITL percentiles, goodput, occupancy, and
speculative accept rates from exactly these records, and ``tadnn
monitor`` (obs/slo_monitor) folds the same stream into rolling SLO
windows while the engine is still running.  Timeline stamps route
through the scheduler's injectable clock so a discrete-event replay
produces the same fields on virtual time.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer_core import (
    MLPBlock,
    SelfAttention,
    TransformerConfig,
    make_norm,
    rope,
)
from ...obs import journal as _journal
from ...training.lora import LoraSpec, merge_lora
from ..decode import (
    KVCache,
    SampleConfig,
    _moe_mlp_cached,
    _sample,
    forward_cached,
)
from ..quant import dequantize_kv, dequantize_leaf, dequantize_tree, \
    embedding_lookup, is_quantized_leaf, quantize_kv
from ..speculative import accept_length, ngram_propose
from .adapters import IDENTITY_ADAPTER, AdapterPool, factor_rows
from .kv_pool import (
    PagedKVPool,
    blocks_for_tokens,
    gather_blocks,
    write_token,
)
from .prefix_cache import PrefixCache
from .scheduler import Request, Scheduler


def _paged_decode_step(params, kv, tables, ctx_lens, tok, active,
                       adapters, adapter_ids, rng, *,
                       cfg: TransformerConfig,
                       sample: SampleConfig, moe_decode: str,
                       attention_impl: str = "paged",
                       lora_scaling: float = 1.0,
                       mesh=None, spec=None):
    """A [S, T] token chunk for every slot — T == 1 is plain one-token
    decode, T == 1+k is a speculative verify step (position t attends
    keys 0..ctx+t, exactly the sequential semantics).  Static shapes
    throughout (S slots, T chunk, tables [S, max_blocks]) so each
    engine configuration traces exactly once.

    ``attention_impl`` picks the per-layer KV read:

    - ``"paged"`` (default): the fused Pallas kernel
      (ops/paged_attention.py) reads the block table in-kernel — the
      dense gathered view never materializes, int8 dequantize happens
      on load inside the kernel; single-query only, so T > 1 verify
      steps fall back to the dense path below;
    - ``"dense"``: the reference path — ``gather_blocks`` to a dense
      [S, max_len] view, then stock ``xla_attention`` under an explicit
      mask.  Kept as the parity oracle and the fallback.

    ``adapters`` is the AdapterPool's factor pytree ({} when serving
    the base model only): per layer and per q/k/v/o site, stacked
    ``a [A, d_in, r]`` / ``b [A, r, d_out]`` factors.  Each slot
    gathers its ``adapter_ids`` row and adds the segmented low-rank
    delta ``scaling * (x @ A) @ B`` to that projection's output —
    slot 0 holds zero factors (IDENTITY_ADAPTER), so base-model slots
    pay one gather of zeros instead of a second trace.  q/k deltas are
    rope-rotated like the projections they perturb (rope is linear, so
    rotating the delta IS the merged-weight semantics).

    Returns the updated kv plus sampled tokens [S] (T == 1) or the
    target's greedy choices [S, T] (verify steps are temperature-0 by
    contract — sampled speculative needs rejection resampling).
    """
    from ...ops.attention import xla_attention
    from ...ops.paged_attention import paged_attention

    dtype = cfg.dtype
    T = tok.shape[1]
    norm = make_norm(cfg)
    attn = SelfAttention(cfg)
    mlp = MLPBlock(cfg)
    if mesh is not None and spec is not None:
        from jax.sharding import NamedSharding

        sh = NamedSharding(mesh, spec)
        kv = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sh), kv)

    x = embedding_lookup(
        params["embed"]["embedding"], tok, dtype)  # [S, T, d]
    # per-slot, per-chunk-offset absolute positions
    positions = ctx_lens[:, None] + jnp.arange(T)[None, :]  # [S, T]
    if cfg.pos == "learned":
        pe = params["pos_embed"].astype(dtype)
        x = x + pe[positions]

    mask = None
    if attention_impl == "dense" or T > 1:
        n_keys = tables.shape[1] * (
            kv["k"]["q"] if is_quantized_leaf(kv["k"]) else kv["k"]
        ).shape[2]
        key_idx = jnp.arange(n_keys)[None, None, :]
        # chunk position t writes at positions[s, t] then attends keys
        # 0..positions[s, t] inclusive — the causal triangle across the
        # chunk plus the full context below it; table padding beyond a
        # slot's blocks gathers null-block garbage this never admits
        mask = key_idx <= positions[:, :, None]
        if cfg.sliding_window is not None:
            mask &= key_idx > positions[:, :, None] - cfg.sliding_window
        mask = mask[:, None]  # [S, 1, T, K]

    def layer(x, xs):
        lp, k_layer, v_layer, ad = xs
        lp = dequantize_tree(lp, dtype)
        h = norm.apply({"params": lp["attn_norm"]}, x)
        q, k, v = attn.apply(
            {"params": lp["attn"]}, h, positions, method="qkv")
        if ad:
            hf = h.astype(jnp.float32)

            def delta(site, inp):
                a = factor_rows(ad[site]["a"], adapter_ids)  # [S, d_in, r]
                b = factor_rows(ad[site]["b"], adapter_ids)  # [S, r, d_out]
                t2 = jnp.einsum("std,sdr->str", inp, a)
                return lora_scaling * jnp.einsum("str,sro->sto", t2, b)

            def adapted(tensor, site, inp, rotate=False):
                d = delta(site, inp).reshape(tensor.shape)
                if rotate and cfg.pos == "rope":
                    d = rope(d, positions, cfg.rope_theta)
                return (tensor.astype(jnp.float32) + d).astype(tensor.dtype)

            if "q" in ad:
                q = adapted(q, "q", hf, rotate=True)
            if "k" in ad:
                k = adapted(k, "k", hf, rotate=True)
            if "v" in ad:
                v = adapted(v, "v", hf)
        for t in range(T):  # T is static and small (1 + draft length)
            k_layer = write_token(k_layer, tables, ctx_lens + t, k[:, t])
            v_layer = write_token(v_layer, tables, ctx_lens + t, v[:, t])
        if attention_impl == "paged" and T == 1:
            # fused path: block table consumed in-kernel, same ctx/window
            # mask semantics, no [S, max_len] gather; with a mesh the
            # kernel shard_maps over the tensor axis (kv-head parallel)
            o = paged_attention(
                q[:, 0], k_layer, v_layer, tables, ctx_lens,
                window=cfg.sliding_window, mesh=mesh)[:, None]
        else:
            kd = gather_blocks(k_layer, tables, dtype)
            vd = gather_blocks(v_layer, tables, dtype)
            o = xla_attention(q, kd, vd, causal=False, mask=mask)
        ao = attn.apply(
            {"params": lp["attn"]}, o.astype(dtype), method="out_proj")
        if ad and "o" in ad:
            of = o.reshape(o.shape[0], o.shape[1], -1).astype(jnp.float32)
            ao = adapted(ao, "o", of)
        x = x + ao
        h = norm.apply({"params": lp["mlp_norm"]}, x)
        if "experts_up" in lp["mlp"]:
            x = x + _moe_mlp_cached(lp["mlp"], h, cfg)
        else:
            x = x + mlp.apply({"params": lp["mlp"]}, h)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv["k"], kv["v"], adapters))

    x = norm.apply({"params": params["final_norm"]}, x)
    feats = x.astype(jnp.float32)  # [S, T, d]
    if cfg.tie_embeddings:
        emb = params["embed"]["embedding"]
        if is_quantized_leaf(emb):
            emb = dequantize_leaf(emb, jnp.float32)
        logits = feats @ emb.astype(jnp.float32).T
    else:
        head = params["lm_head"]["kernel"]
        if is_quantized_leaf(head):
            head = dequantize_leaf(head, jnp.float32)
        logits = feats @ head.astype(jnp.float32)
    if T == 1:
        nxt = _sample(logits[:, 0], rng, sample)
        return {"k": new_k, "v": new_v}, jnp.where(active, nxt, 0)
    # verify step: the target's own greedy choice at every chunk
    # position (the all-logits discipline of decode.generate)
    tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, T]
    return {"k": new_k, "v": new_v}, jnp.where(active[:, None], tgt, 0)


def _prefill_chunk_step(params, tokens, cache, last_idx, *,
                        cfg: TransformerConfig, moe_decode: str,
                        quantize: bool = False):
    """One fixed-shape prefill chunk: [1, C] tokens through
    ``forward_cached`` against the fixed [1, max_len] temp cache.

    Every chunk of every prompt reuses this ONE jitted trace: the chunk
    length is constant and both the cache cursor (``cache.length``) and
    ``last_idx`` are traced scalars.  The final chunk of a prompt may
    be right-padded; ``last_idx`` selects the last REAL token's logits,
    and causal masking keeps the pad positions (which sit after it) out
    of that row entirely.

    ``quantize=True`` (int8 KV pools) round-trips the chunk's fresh
    cache rows through the pool's (q, scale) representation before the
    next chunk attends to them, and ALSO returns that quantized chunk
    so the commit scatters the exact same (q, scale) pairs — no second
    quantization.  The point is a single KV representation everywhere:
    later prefill chunks, decode, and any future request that reuses
    these rows through the prefix cache all see bit-identical values,
    which is what makes cache-on vs cache-off token parity exact in
    int8 mode instead of merely close.
    """
    pos0 = cache.length
    logits, cache = forward_cached(
        params, cfg, tokens, cache, moe_decode=moe_decode, mesh=None,
        all_logits=True)
    last = jax.lax.dynamic_index_in_dim(
        logits, last_idx, axis=1, keepdims=False)
    if not quantize:
        return last, cache
    T = tokens.shape[1]
    k_rows = jax.lax.dynamic_slice_in_dim(
        cache.k, pos0, T, axis=2)[:, 0]  # [L, T, kvH, hd]
    v_rows = jax.lax.dynamic_slice_in_dim(cache.v, pos0, T, axis=2)[:, 0]
    qk, qv = quantize_kv(k_rows), quantize_kv(v_rows)
    cache = cache._replace(
        k=jax.lax.dynamic_update_slice_in_dim(
            cache.k, dequantize_kv(qk, cache.k.dtype)[:, None],
            pos0, axis=2),
        v=jax.lax.dynamic_update_slice_in_dim(
            cache.v, dequantize_kv(qv, cache.v.dtype)[:, None],
            pos0, axis=2))
    return last, cache, {"k": qk, "v": qv}


def _prefill_chunk_lora_step(params, lora, tokens, cache, last_idx, *,
                             cfg: TransformerConfig, moe_decode: str,
                             lora_spec: LoraSpec, quantize: bool = False):
    """Chunked prefill through per-tenant merged weights: ``merge_lora``
    runs INSIDE the jit (the rank-r matmul fuses into the weight load),
    so ONE trace serves every tenant — the factor tree is a traced
    operand and the merged weights never materialize on the host."""
    merged = merge_lora(params, lora, lora_spec)
    return _prefill_chunk_step(merged, tokens, cache, last_idx,
                               cfg=cfg, moe_decode=moe_decode,
                               quantize=quantize)


def _cat_qchunks(qchunks: list, n_tokens: int):
    """Concatenate the prefill trace's per-chunk quantized KV along the
    token axis and trim the final chunk's pad rows: two ``{"q",
    "scale"}`` leaves of [L, n_tokens, kvH, *], ready for
    ``write_prefill`` to scatter without re-quantizing."""
    out = []
    for side in ("k", "v"):
        q = jnp.concatenate([c[side]["q"] for c in qchunks], axis=1)
        s = jnp.concatenate([c[side]["scale"] for c in qchunks], axis=1)
        out.append({"q": q[:, :n_tokens], "scale": s[:, :n_tokens]})
    return out[0], out[1]


@dataclasses.dataclass
class _PrefillState:
    """Host-side cursor of one in-flight chunked prefill: the [1,
    max_len] temp cache being filled, how many prompt tokens have
    streamed through it so far (a prefix-cache hit starts the cursor
    past the reused rows), the tenant's factor tree (None for
    base-model requests), and — int8 pools only — the per-chunk
    (q, scale) pairs the commit will scatter verbatim."""

    cache: KVCache
    pos: int = 0
    lora: Any = None
    qchunks: list = dataclasses.field(default_factory=list)


class ServeEngine:
    """Continuous-batching server over a model + paged KV pool.

        eng = ServeEngine(model, variables, n_slots=8, max_len=256)
        eng.submit([1, 2, 3], max_new_tokens=32, eos_id=0)
        done = eng.run()          # [Request] with .prompt + .out_tokens

    ``submit`` is non-blocking (requests queue); ``step()`` advances the
    world by one decode iteration (evict / admit+prefill / grow /
    decode); ``run()`` steps until idle.  A long-lived server calls
    ``submit`` from its frontend and ``step`` in a loop — nothing here
    blocks on a full batch.
    """

    def __init__(self, model, variables: Any, *,
                 n_slots: int = 8,
                 max_len: int = 256,
                 block_size: int = 16,
                 num_blocks: int | None = None,
                 quant_kv: bool = False,
                 cache_dtype=jnp.bfloat16,
                 sample: SampleConfig | None = None,
                 admission: str = "reserve",
                 moe_decode: str = "dense",
                 attention_impl: str = "paged",
                 prefill_chunk: int | None = 32,
                 prefill_chunks_per_step: int = 1,
                 lora_spec: LoraSpec | None = None,
                 n_adapters: int = 8,
                 quant_adapters: bool = False,
                 speculative: int = 0,
                 prefix_cache: bool = False,
                 prefix_ttl_s: float | None = None,
                 mesh=None,
                 disaggregate: bool = False,
                 rng: jax.Array | None = None,
                 journal: Any = None,
                 export_cache: Any = None,
                 export_tags: Any = None):
        if attention_impl not in ("paged", "dense"):
            raise ValueError(
                f"unknown attention_impl {attention_impl!r} "
                f"(expected 'paged' or 'dense')")
        self.cfg: TransformerConfig = model.cfg
        self.params = variables["params"]
        self.sample = sample or SampleConfig(temperature=0.0)
        self.n_slots = n_slots
        self.max_len = max_len
        self.moe_decode = moe_decode
        self.attention_impl = attention_impl
        self.speculative = int(speculative)
        if self.speculative < 0:
            raise ValueError(f"speculative={speculative} must be >= 0")
        if self.speculative and self.sample.temperature != 0.0:
            raise ValueError(
                "speculative decoding is greedy-only (the accept rule "
                "compares against the target's argmax; sampled variants "
                "need rejection resampling) — use temperature=0.0")
        if prefill_chunk is not None:
            # snap the chunk to a divisor of max_len: the temp cache is
            # exactly [1, max_len], so the cursor can never run past it
            # (a learned-pos dynamic_slice would clamp its start and
            # silently corrupt the chunk's position embeddings)
            prefill_chunk = math.gcd(
                min(int(prefill_chunk), max_len), max_len)
        self.prefill_chunk = prefill_chunk
        self.prefill_chunks_per_step = max(1, int(prefill_chunks_per_step))
        self.mesh = mesh
        # disaggregated mode: prefill runs on its own mesh slice, so a
        # step's prefill chunks don't serialize with decode — every
        # prefilling slot advances each step (no chunks-per-step cap),
        # finished KV ships through pool.ship_prefill, and the step's
        # modeled wall time is max(prefill, decode) instead of the sum.
        # Token-identical to colocated: the phases touch disjoint state
        # (temp caches vs the pool), so only the time model changes.
        self.disaggregate = bool(disaggregate)
        self.max_blocks = blocks_for_tokens(max_len, block_size)
        if num_blocks is None:
            # worst case every slot full-length, plus the null block
            num_blocks = n_slots * self.max_blocks + 1
        self.pool = PagedKVPool(
            self.cfg, num_blocks=num_blocks, block_size=block_size,
            dtype=cache_dtype, quantize=quant_kv, mesh=mesh)
        self.lora_spec = lora_spec
        self.adapter_pool: AdapterPool | None = None
        if lora_spec is not None:
            self.adapter_pool = AdapterPool(
                self.params, lora_spec, n_adapters=n_adapters,
                quantize=quant_adapters, mesh=mesh)
        # cross-request prefix caching: radix index over resident
        # prompt-prefix blocks; matched prefixes are ref'd into the new
        # request's table and their chunks skipped.  Chunked-prefill
        # only: the reuse path seeds the chunk trace's temp cache.
        # Match alignment: block granularity in fp mode; in int8 mode
        # additionally snapped to prefill-chunk boundaries, so the
        # cache-off run's chunk partition of the recomputed suffix is
        # reproduced exactly (bit-identical tokens either way).
        self.journal = journal or _journal.get_default()
        self._prefix_cache = None
        # publish lease: prompts enter the radix index with this TTL
        # (clock units), so stale preambles age out instead of pinning
        # leaves until pressure eviction; None = no expiry (legacy)
        self.prefix_ttl_s = prefix_ttl_s
        match_align = None
        if prefix_cache:
            if prefill_chunk is None:
                raise ValueError(
                    "prefix_cache requires chunked prefill "
                    "(prefill_chunk=None is the legacy single-shot "
                    "path, which cannot resume from a cached prefix)")
            self._prefix_cache = PrefixCache(
                block_size=block_size, allocator=self.pool.allocator,
                journal=self.journal)
            match_align = (math.lcm(block_size, self.prefill_chunk)
                           if quant_kv else block_size)
            # pre-compile the hit-seeding reads (fixed shapes compile
            # exactly once) so the first matched request doesn't pay
            # them inside its prefill window
            kd, vd = self.pool.read_blocks(
                [], self.max_blocks, dtype=jnp.bfloat16)
            jax.block_until_ready(
                (kd[:, None, :max_len], vd[:, None, :max_len]))
        self.prefix_queries = 0
        self.prefix_hits = 0
        self.prefix_cached_tokens = 0
        self.prefix_saved_chunks = 0
        self.cow_forks = 0
        self.scheduler = Scheduler(
            n_slots=n_slots, allocator=self.pool.allocator,
            block_size=block_size, admission=admission,
            adapter_pool=self.adapter_pool,
            spec_lookahead=self.speculative,
            prefix_cache=self._prefix_cache, match_align=match_align)
        self._rng = jax.random.key(0) if rng is None else rng
        # TADNN_DEBUG_INVARIANTS=1: run the scheduler/allocator/adapter
        # invariant audit after EVERY step (CI serve-smoke legs set it;
        # off by default — it walks all slots and the free list)
        self._debug_invariants = (
            os.environ.get("TADNN_DEBUG_INVARIANTS", "") not in ("", "0"))
        self._step_count = 0
        self._occupancy_sum = 0.0
        # per-phase busy time, the bench's per-slice breakdown: what
        # each slice spent working, and what the steps would cost
        # end-to-end under the disaggregated overlap model
        self.prefill_busy_s = 0.0
        self.decode_busy_s = 0.0
        self.overlapped_wall_s = 0.0
        self.spec_drafted = 0   # lifetime draft-token counters (k > 0)
        self.spec_accepted = 0
        # lifetime generated-token count; step() diffs it to put a
        # per-step new_tokens field on serve.step (the live monitor's
        # smooth tok/s signal — request completions are too lumpy)
        self.tokens_emitted = 0
        self.finished: list[Request] = []
        self._prefill: dict[int, _PrefillState] = {}
        self._step_fn = jax.jit(
            partial(_paged_decode_step, cfg=self.cfg, sample=self.sample,
                    moe_decode=moe_decode, attention_impl=attention_impl,
                    lora_scaling=(lora_spec.scaling if lora_spec else 1.0),
                    mesh=mesh, spec=self.pool.spec),
            donate_argnums=(1,))
        self._prefill_fn = jax.jit(
            partial(_prefill_chunk_step, cfg=self.cfg,
                    moe_decode=moe_decode, quantize=bool(quant_kv)))
        self._prefill_lora_fn = None
        if lora_spec is not None:
            self._prefill_lora_fn = jax.jit(
                partial(_prefill_chunk_lora_step, cfg=self.cfg,
                        moe_decode=moe_decode, lora_spec=lora_spec,
                        quantize=bool(quant_kv)))
        # AOT executable cache (export/): replica spin-up goes
        # cache-first on the two fixed-shape serve traces, so a warm
        # replica deserializes the decode step and the prefill chunk
        # instead of paying their XLA compiles before the first token.
        self.export_info: list[dict] = []
        from ...export import cache as _export_cache_mod

        _cache = _export_cache_mod.resolve(export_cache)
        if _cache is not None:
            self._export_compiled(
                _cache, dict(export_tags or {}),
                num_blocks=num_blocks, block_size=block_size,
                quant_kv=bool(quant_kv), cache_dtype=cache_dtype,
                n_adapters=n_adapters,
                quant_adapters=bool(quant_adapters))
        if self.journal is not None:
            from ...ops.paged_attention import tensor_degree

            self.journal.event(
                "serve.engine", attention_impl=attention_impl,
                prefill_chunk=self.prefill_chunk,
                n_slots=n_slots, max_len=max_len, block_size=block_size,
                quant_kv=bool(quant_kv),
                n_adapters=(n_adapters if lora_spec else 0),
                adapter_rank=(lora_spec.rank if lora_spec else None),
                quant_adapters=bool(quant_adapters and lora_spec),
                speculative=self.speculative,
                prefix_cache=self._prefix_cache is not None,
                disaggregate=self.disaggregate,
                tp=tensor_degree(mesh))

    def _export_compiled(self, cache, tags: dict, *, num_blocks: int,
                         block_size: int, quant_kv: bool, cache_dtype,
                         n_adapters: int, quant_adapters: bool) -> None:
        """Cache-first AOT for the two fixed-shape serve traces (decode
        step and base prefill chunk).  Abstract args come from
        ``jax.eval_shape`` over the exact runtime operands — nothing is
        materialized, and the traces match dispatch bit-for-bit.  The
        per-prompt-length LoRA prefill stays lazy (one trace per tenant
        factor tree isn't worth pinning)."""
        from ...export import aot as aot_mod
        from ...export import cache as export_cache_mod
        from ...topology import detect
        from ...tune import cache as tune_cache

        S, MB, T = self.n_slots, self.max_blocks, 1 + self.speculative
        devices = (list(self.mesh.devices.flat)
                   if self.mesh is not None else None)
        topo_fp = tune_cache.topology_fingerprint(detect(devices))
        sig = tune_cache.params_signature(self.params)
        # everything the serve traces close over: two engines that
        # differ in any of these must compile separately
        program = {
            "n_slots": S, "max_len": self.max_len,
            "block_size": block_size, "num_blocks": num_blocks,
            "attention_impl": self.attention_impl,
            "speculative": self.speculative,
            "moe_decode": self.moe_decode,
            "quant_kv": quant_kv,
            "cache_dtype": str(np.dtype(cache_dtype)),
            "sample": dataclasses.asdict(self.sample),
            "prefill_chunk": self.prefill_chunk,
            # int8 chunked prefill round-trips + returns (q, scale)
            # chunks — a different program than the pre-prefix-cache
            # trace, so quantized engines must not load stale payloads
            **({"prefill_q_commit": True} if quant_kv else {}),
            "lora": ([self.lora_spec.rank, self.lora_spec.scaling,
                      n_adapters, quant_adapters]
                     if self.lora_spec is not None else None),
        }
        factors = (self.adapter_pool.factors
                   if self.adapter_pool is not None else {})
        decode_abs = jax.eval_shape(lambda: (
            self.params, self.pool.kv,
            jnp.zeros((S, MB), jnp.int32), jnp.zeros((S,), jnp.int32),
            jnp.zeros((S, T), jnp.int32), jnp.zeros((S,), jnp.bool_),
            factors, jnp.zeros((S,), jnp.int32),
            jax.random.fold_in(self._rng, 2**20)))
        res = aot_mod.cached_compile(
            self._step_fn, decode_abs, cache=cache, kind="serve_decode",
            key=export_cache_mod.executable_key(
                "serve_decode", sig, topo_fp, program, tags))
        if res is not None:
            self._step_fn = aot_mod.ExportedCallable(
                res.compiled, self._step_fn, "serve_decode")
            self.export_info.append(res.to_json())
        if self.prefill_chunk:
            C = self.prefill_chunk
            prefill_abs = jax.eval_shape(lambda: (
                self.params, jnp.zeros((1, C), jnp.int32),
                KVCache.init(self.cfg, 1, self.max_len,
                             dtype=jnp.bfloat16),
                np.int32(0)))
            res = aot_mod.cached_compile(
                self._prefill_fn, prefill_abs, cache=cache,
                kind="serve_prefill",
                key=export_cache_mod.executable_key(
                    "serve_prefill", sig, topo_fp, program, tags))
            if res is not None:
                self._prefill_fn = aot_mod.ExportedCallable(
                    res.compiled, self._prefill_fn, "serve_prefill")
                self.export_info.append(res.to_json())

    # -- request intake ------------------------------------------------------

    def register_adapter(self, name: str, lora_params) -> None:
        """Stage a tenant's LoRA factors for serving (see
        AdapterPool.register).  Requires ``lora_spec`` at construction."""
        if self.adapter_pool is None:
            raise ValueError(
                "engine built without lora_spec — pass lora_spec=... to "
                "serve adapters")
        self.adapter_pool.register(name, lora_params)

    def submit(self, prompt: list[int], max_new_tokens: int,
               eos_id: int | None = None,
               adapter: str | None = None,
               priority: int = 0) -> Request:
        total = len(prompt) + max_new_tokens
        # speculative steps write up to k draft keys past the emitted
        # context — that lookahead must fit the slot's table too
        need_len = total + self.speculative
        if need_len > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                + (f"+ speculative lookahead {self.speculative} "
                   if self.speculative else "")
                + f"= {need_len} exceeds engine max_len {self.max_len}")
        if not prompt:
            raise ValueError("empty prompt")
        if adapter is not None:
            if self.adapter_pool is None:
                raise ValueError(
                    "engine built without lora_spec cannot serve "
                    f"adapter {adapter!r}")
            if not self.adapter_pool.has(adapter):
                raise ValueError(
                    f"unknown adapter {adapter!r} — register_adapter() "
                    "it first")
        need = blocks_for_tokens(need_len, self.pool.block_size)
        if need > self.pool.num_blocks - 1:
            # the pool could NEVER cover this request even alone —
            # admitting it would preempt-thrash forever in optimistic
            # mode and deadlock admission in reserve mode
            raise ValueError(
                f"request needs {need} blocks but the pool has "
                f"{self.pool.num_blocks - 1} allocatable")
        req = Request(prompt=list(map(int, prompt)),
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      adapter=adapter, priority=int(priority))
        self.scheduler.submit(req)
        return req

    # -- one serving iteration ----------------------------------------------

    def _bind_adapter(self, slot: int, req: Request) -> bool:
        """Pin the request's adapter at the transition into decode
        (pins back live decode reads ONLY — prefilling slots reference
        adapters by name).  When every pool slot is pinned by other
        running requests, the request bounces back to the queue
        recompute-style; pins are held by running slots only, so some
        slot is always making progress and the bounce cannot livelock.
        Size ``n_adapters > n_slots`` to never hit this path."""
        info = self.scheduler.pin_adapter(req)
        if info is None:
            self._prefill.pop(req.rid, None)
            self.scheduler.requeue(slot)
            if self.journal is not None:
                self.journal.event("serve.adapter", kind="stall",
                                   rid=req.rid, adapter=req.adapter)
            return False
        if info and self.journal is not None:
            self.journal.event(
                "serve.adapter", kind="hit" if info["hit"] else "fault",
                rid=req.rid, adapter=req.adapter, idx=info["idx"],
                evicted=info["evicted"])
        return True

    def _req_lora(self, req: Request):
        if req.adapter is None:
            return None
        return self.adapter_pool.effective_lora(req.adapter)

    def _commit_prefill(self, slot: int, req: Request,
                        k: Any, v: Any) -> None:
        """Land a finished prefill's computed cache rows in the
        request's blocks — only the UNCACHED suffix: rows ``k``/``v``
        start at token ``req.cached_tokens`` (a prefix-cache hit's
        reused blocks already hold their KV and are never rewritten).
        Colocated mode writes in place; disaggregated mode routes
        through ``pool.ship_prefill`` — same payload, plus the
        block/byte transfer accounting that becomes DCN traffic when
        the prefill slice is a distinct pod slice — and journals the
        shipment.  Afterwards the request's full prompt blocks are
        published into the radix index (for disaggregated serving that
        IS ship time: a block is only advertised for reuse once it is
        resident in the decode slice's pool)."""
        full = blocks_for_tokens(req.n_prompt, self.pool.block_size)
        blocks = req.blocks[req.cached_blocks:full]
        if not self.disaggregate:
            self.pool.write_prefill(blocks, k, v)
        else:
            moved = self.pool.ship_prefill(blocks, k, v)
            self.scheduler.record_ship(slot, len(blocks))
            if self.journal is not None:
                self.journal.event(
                    "serve.kv_ship", rid=req.rid, slot=slot,
                    n_blocks=len(blocks), bytes=moved)
        if self._prefix_cache is not None:
            # publish every FULL prompt block: decode writes start at
            # position n_prompt, so these rows are immutable (CoW
            # guards the manufactured-sharing corner regardless)
            n_pub = req.n_prompt // self.pool.block_size
            new = self._prefix_cache.insert(
                req.prompt[:n_pub * self.pool.block_size],
                req.blocks[:n_pub], ttl_s=self.prefix_ttl_s)
            if new and self.journal is not None:
                self.journal.event(
                    "serve.prefix", kind="publish", rid=req.rid,
                    n_blocks=new)

    def _prefill_into_slot(self, slot: int, req: Request) -> None:
        tokens = jnp.asarray(req.prompt, jnp.int32)[None, :]
        cache = KVCache.init(self.cfg, 1, tokens.shape[1],
                             dtype=jnp.bfloat16)
        lora = self._req_lora(req)
        params = (self.params if lora is None
                  else merge_lora(self.params, lora, self.lora_spec))
        # forward_cached retraces per distinct prompt length — the only
        # shape-varying compile in the serving loop
        logits, cache = forward_cached(
            params, self.cfg, tokens, cache,
            moe_decode=self.moe_decode, mesh=None)
        req_rng = jax.random.fold_in(self._rng, req.rid)
        _, first_rng = jax.random.split(req_rng)
        first = int(jax.device_get(
            _sample(logits, first_rng, self.sample))[0])
        self._commit_prefill(slot, req, cache.k[:, 0], cache.v[:, 0])
        req.out_tokens = [first]
        req.t_first_token = self.scheduler.clock()
        req.token_walls = [req.t_first_token]
        self.tokens_emitted += 1

    def _start_prefill(self, slot: int, req: Request) -> None:
        """Admission entry point: legacy single-shot prefill, or flip
        the slot to "prefilling" so step() streams the prompt through
        the shared chunk trace, interleaved with decode.

        A prefix-cache hit seeds the temp cache by reading the matched
        blocks' KV back from the pool (``pool.read_blocks``) and starts
        the cursor after them — the chunk trace then computes only the
        uncached suffix, attending to the reused rows exactly as the
        original prefill's later chunks attended to them."""
        if self.prefill_chunk is None:
            # single-shot requests go straight to running, so the pin
            # happens here (before the prefill work, cheaply bounced)
            if not self._bind_adapter(slot, req):
                return
            self._prefill_into_slot(slot, req)
            return
        req.state = "prefilling"
        cache = KVCache.init(self.cfg, 1, self.max_len,
                             dtype=jnp.bfloat16)
        if self._prefix_cache is not None:
            self.prefix_queries += 1
            if req.cached_tokens:
                self.prefix_hits += 1
                self.prefix_cached_tokens += req.cached_tokens
                C = self.prefill_chunk
                self.prefix_saved_chunks += (
                    -(-req.n_prompt // C)
                    - -(-(req.n_prompt - req.cached_tokens) // C))
                kd, vd = self.pool.read_blocks(
                    req.blocks[:req.cached_blocks], self.max_blocks,
                    dtype=cache.k.dtype)
                cache = cache._replace(
                    k=kd[:, None, :self.max_len],
                    v=vd[:, None, :self.max_len],
                    length=jnp.asarray(req.cached_tokens, jnp.int32))
            if self.journal is not None:
                self.journal.event(
                    "serve.prefix", kind="match", rid=req.rid,
                    hit=bool(req.cached_tokens),
                    cached_tokens=req.cached_tokens,
                    cached_blocks=req.cached_blocks)
        self._prefill[req.rid] = _PrefillState(
            cache=cache, pos=req.cached_tokens,
            lora=self._req_lora(req))

    def _advance_prefill(self, slot: int, req: Request) -> None:
        """One [1, C] chunk of ``req``'s prompt.  On the final chunk:
        pin the adapter (bouncing the request if the pool is full),
        sample the first token (identical rng derivation to single-shot
        prefill), copy the filled temp-cache rows into the request's
        blocks, and hand the slot to decode."""
        st = self._prefill[req.rid]
        C = self.prefill_chunk
        chunk = req.prompt[st.pos:st.pos + C]
        n_real = len(chunk)
        tokens = jnp.asarray(chunk + [0] * (C - n_real), jnp.int32)[None]
        t0 = time.monotonic()
        # np.int32, not a weak-typed python int: the AOT-exported trace
        # pins the cursor's dtype, and jit would silently retrace
        last_idx = np.int32(n_real - 1)
        fn, args = self._prefill_fn, (self.params, tokens, st.cache,
                                      last_idx)
        if st.lora is not None:
            fn, args = self._prefill_lora_fn, (
                self.params, st.lora, tokens, st.cache, last_idx)
        if self.pool.quantize:
            logits, st.cache, qchunk = fn(*args)
            st.qchunks.append(qchunk)
        else:
            logits, st.cache = fn(*args)
        st.pos += n_real
        done = st.pos >= req.n_prompt
        bounced = done and not self._bind_adapter(slot, req)
        if done and not bounced:
            req_rng = jax.random.fold_in(self._rng, req.rid)
            _, first_rng = jax.random.split(req_rng)
            first = int(jax.device_get(
                _sample(logits, first_rng, self.sample))[0])
            n_suffix = req.n_prompt - req.cached_tokens
            if self.pool.quantize:
                # commit the trace's own (q, scale) chunks verbatim —
                # re-quantizing the round-tripped rows would not be
                # idempotent through a bf16 temp cache
                k_rows, v_rows = _cat_qchunks(st.qchunks, n_suffix)
            else:
                k_rows = st.cache.k[:, 0,
                                    req.cached_tokens:req.n_prompt]
                v_rows = st.cache.v[:, 0,
                                    req.cached_tokens:req.n_prompt]
            self._commit_prefill(slot, req, k_rows, v_rows)
            req.out_tokens = [first]
            req.t_first_token = self.scheduler.clock()
            req.token_walls = [req.t_first_token]
            self.tokens_emitted += 1
            req.state = "running"
            del self._prefill[req.rid]
        chunk_s = time.monotonic() - t0
        req.prefill_chunks += 1
        req.prefill_compute_s += chunk_s
        if self.journal is not None:
            self.journal.event(
                "serve.prefill_chunk", rid=req.rid, slot=slot,
                pos=min(st.pos, req.n_prompt), n_tokens=n_real,
                seconds=chunk_s,
                done=bool(done and not bounced))

    def _cow_fork_writes(self) -> None:
        """Copy-on-write guard, run right before the decode step: any
        block this step will WRITE into (positions ctx..ctx+lookahead)
        that is shared (refcount > 1 — some other table or the radix
        index also points at it) is forked to a private copy first, so
        the write can never corrupt another owner's view.  In natural
        traffic this never fires — matches are capped below the prompt
        end and published blocks sit strictly before the first decode
        write — but the guard makes sharing safe by construction, not
        by traffic shape."""
        bs = self.pool.block_size
        alloc = self.pool.allocator
        for req in self.scheduler.slots:
            if req is None or req.state != "running":
                continue
            ctx = req.n_prompt + req.n_generated - 1
            for t in range(1 + self.speculative):
                bi = (ctx + t) // bs
                if bi >= len(req.blocks):
                    break  # optimistic growth handles coverage
                b = req.blocks[bi]
                if alloc.refcount(b) <= 1:
                    continue
                nb = self.pool.fork_block(b)
                if (nb is None and self._prefix_cache is not None
                        and self._prefix_cache.evict(1)):
                    nb = self.pool.fork_block(b)
                if nb is None:
                    raise RuntimeError(
                        f"cannot fork shared block {b}: pool exhausted "
                        f"and no evictable index leaf")
                req.blocks[bi] = nb
                alloc.release([b])
                self.cow_forks += 1
                if self.journal is not None:
                    self.journal.event(
                        "serve.prefix", kind="cow", rid=req.rid,
                        block=b, fork=nb)

    def _decode_all(self) -> None:
        S, MB = self.n_slots, self.max_blocks
        k_spec = self.speculative
        T = 1 + k_spec
        tables = np.zeros((S, MB), np.int32)
        ctx = np.zeros((S,), np.int32)
        tok = np.zeros((S, T), np.int32)
        ids = np.zeros((S,), np.int32)
        act = np.zeros((S,), bool)
        for s, req in enumerate(self.scheduler.slots):
            if req is None or req.state != "running":
                # prefilling slots keep an all-null table here: the
                # step's unconditional KV write lands in the scratch
                # block instead of their half-filled prompt blocks
                continue
            tables[s, :len(req.blocks)] = req.blocks
            # this step writes token n_generated at absolute position
            # n_prompt + n_generated - 1 (the first generated token
            # came from prefill and was never written)
            ctx[s] = req.n_prompt + req.n_generated - 1
            tok[s, 0] = req.out_tokens[-1]
            if k_spec:
                tok[s, 1:] = ngram_propose(
                    req.prompt + req.out_tokens, k_spec)
            ids[s] = req.adapter_idx
            act[s] = True
        step_rng = jax.random.fold_in(self._rng, 2**20 + self._step_count)
        factors = (self.adapter_pool.factors
                   if self.adapter_pool is not None else {})
        self.pool.kv, out = self._step_fn(
            self.params, self.pool.kv, jnp.asarray(tables),
            jnp.asarray(ctx), jnp.asarray(tok), jnp.asarray(act),
            factors, jnp.asarray(ids), step_rng)
        out = np.asarray(jax.device_get(out))
        # one stamp per step: every token this step emits shares it (a
        # speculative burst lands together, so its interior ITLs are 0)
        now = self.scheduler.clock()
        if not k_spec:
            for s, req in enumerate(self.scheduler.slots):
                if req is not None and req.state == "running":
                    req.out_tokens.append(int(out[s]))
                    req.token_walls.append(now)
                    self.tokens_emitted += 1
            return
        drafted = accepted = n_active = 0
        for s, req in enumerate(self.scheduler.slots):
            if req is None or req.state != "running":
                continue
            n_active += 1
            drafts = tok[s, 1:]
            tgt = out[s]  # [1+k] target greedy choices over the chunk
            a = accept_length(drafts, tgt)
            # d_1..d_a agreed; tgt[a] is the target's own next token
            # after them (the free bonus) — 1..k+1 tokens per step
            emit = [int(d) for d in drafts[:a]] + [int(tgt[a])]
            drafted += k_spec
            accepted += a
            # clip to the generation budget, and stop at EOS exactly
            # where sequential decode would have
            emit = emit[:req.max_new_tokens - req.n_generated]
            if req.eos_id is not None and req.eos_id in emit:
                emit = emit[:emit.index(req.eos_id) + 1]
            req.out_tokens.extend(emit)
            req.token_walls.extend([now] * len(emit))
            self.tokens_emitted += len(emit)
        self.spec_drafted += drafted
        self.spec_accepted += accepted
        if self.journal is not None:
            self.journal.event(
                "serve.speculate", step=self._step_count + 1, k=k_spec,
                n_active=n_active, drafted=drafted, accepted=accepted,
                accept_rate=(accepted / drafted if drafted else None))

    def _finish(self, slot: int) -> None:
        # evict() zeroes the prefix-cache accounting with the block
        # table; read it while the request still owns its slot
        cached_tokens = self.scheduler.slots[slot].cached_tokens
        req = self.scheduler.evict(slot)
        self.finished.append(req)
        if self.journal is None:
            return
        # phase attribution: queue_s runs submit -> LAST admission (so
        # it absorbs time spent queued again after a preemption; lost_s
        # separates out the thrown-away attempts), prefill_s runs
        # admission -> first token, decode_s first token -> done
        queue_s = (req.t_admit or req.t_submit) - req.t_submit
        prefill_s = ((req.t_first_token - req.t_admit)
                     if req.t_first_token and req.t_admit else None)
        decode_s = ((req.t_done - req.t_first_token)
                    if req.t_first_token else None)
        total_s = req.t_done - req.t_submit
        walls = req.token_walls
        itl_s = [round(b - a, 6) for a, b in zip(walls, walls[1:])]
        self.journal.event(
            "serve.request_done", rid=req.rid, n_prompt=req.n_prompt,
            n_new=req.n_generated, queue_s=queue_s,
            prefill_s=prefill_s, decode_s=decode_s, total_s=total_s,
            tokens_per_s=(req.n_generated / decode_s
                          if decode_s else None),
            preempted=req.preempted,
            ttft_s=((req.t_first_token - req.t_submit)
                    if req.t_first_token else None),
            itl_s=itl_s,
            itl_mean_s=(sum(itl_s) / len(itl_s) if itl_s else None),
            kv_ship_s=((req.t_kv_shipped - req.t_admit)
                       if req.t_kv_shipped and req.t_admit else None),
            cached_tokens=cached_tokens or None,
            prefill_chunks=req.prefill_chunks or None,
            prefill_compute_s=(round(req.prefill_compute_s, 6)
                               if req.prefill_chunks else None),
            lost_s=req.lost_s or None)

    def step(self) -> None:
        """One serving iteration: evict finished, admit queued, advance
        prefill chunks, grow/preempt (optimistic), decode every
        decoding slot.  Colocated (default): prefill chunks INTERLEAVE
        with decode steps — at most ``prefill_chunks_per_step`` per
        iteration, their time serializing with decode on the one chip.
        Disaggregated: EVERY prefilling slot advances each step (the
        prefill slice has nothing else to do) and the step's modeled
        wall time is ``max(prefill, decode)`` — the slices run
        concurrently, only the KV-block shipment couples them."""
        sched = self.scheduler
        tokens_before = self.tokens_emitted
        for s in range(self.n_slots):
            req = sched.slots[s]
            if (req is not None and req.state == "running"
                    and req.finished()):
                self._finish(s)
        for slot, req in sched.admit():
            self._start_prefill(slot, req)
            if req.state == "running" and req.finished():
                self._finish(slot)  # single-shot, max_new_tokens == 1
        prefill_s = 0.0
        budget = None if self.disaggregate else self.prefill_chunks_per_step
        for slot, req in sched.prefill_plan(budget):
            t0 = time.monotonic()
            self._advance_prefill(slot, req)
            prefill_s += time.monotonic() - t0
            if req.state == "running" and req.finished():
                self._finish(slot)  # chunked, max_new_tokens == 1
        for victim in sched.grow_for_step():
            self._prefill.pop(victim.rid, None)
            if self.journal is not None:
                self.journal.event("serve.preempt", rid=victim.rid,
                                   n_regenerate=victim.n_prompt)
        decode_s = 0.0
        if sched.n_decoding:
            if self._prefix_cache is not None:
                self._cow_fork_writes()
            t0 = time.monotonic()
            self._decode_all()
            decode_s = time.monotonic() - t0
        self._step_count += 1
        self._occupancy_sum += sched.n_active / self.n_slots
        self.prefill_busy_s += prefill_s
        self.decode_busy_s += decode_s
        # the step's cost under this mode's concurrency model: one chip
        # serializes the phases; distinct slices overlap them
        overlap_s = (max(prefill_s, decode_s) if self.disaggregate
                     else prefill_s + decode_s)
        self.overlapped_wall_s += overlap_s
        if self.journal is not None:
            adapter_stats = {}
            if self.adapter_pool is not None:
                alloc = self.adapter_pool.allocator
                adapter_stats = dict(
                    adapters_resident=alloc.n_resident,
                    adapters_pinned=alloc.n_pinned)
            if self._prefix_cache is not None:
                adapter_stats.update(
                    prefix_blocks=self._prefix_cache.n_blocks,
                    prefix_hit_tokens=self._prefix_cache.hit_tokens)
            self.journal.event(
                "serve.step", step=self._step_count,
                n_active=sched.n_active, n_queued=sched.n_queued,
                n_prefilling=sched.n_prefilling,
                new_tokens=self.tokens_emitted - tokens_before,
                occupancy=sched.n_active / self.n_slots,
                free_blocks=self.pool.allocator.n_free,
                prefill_s=prefill_s, decode_s=decode_s,
                mode=("disaggregated" if self.disaggregate
                      else "colocated"),
                overlap_s=overlap_s,
                **adapter_stats)
        if self._debug_invariants:
            sched.check_invariants()

    @property
    def prefix_cache(self) -> PrefixCache | None:
        """The engine's radix reuse index (None when disabled)."""
        return self._prefix_cache

    @property
    def mean_occupancy(self) -> float | None:
        """Mean active-slot fraction over every step so far."""
        if not self._step_count:
            return None
        return self._occupancy_sum / self._step_count

    def run(self) -> list[Request]:
        """Step until queue and slots drain; returns finished requests
        (every submitted request, in completion order)."""
        while not self.scheduler.idle():
            self.step()
        return list(self.finished)
