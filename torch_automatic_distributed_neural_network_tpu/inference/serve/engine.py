"""Serving engine: persistent jitted decode over a slot-padded batch.

One fixed-shape decode step serves every live request at once.  The
batch axis is ``n_slots`` *slots*, not requests: a slot is either bound
to a running request or inactive (null block table, masked sampling).
Each call advances EVERY active request by one token; between calls the
scheduler evicts finished requests and admits queued ones, so the step
executable compiles once and runs for the life of the server — no
recompiles as the request mix churns (prefill is the only shape-varying
entry point, one trace per distinct prompt length).

Per-layer math is the TRAINING modules applied piecewise — the same
single-source-of-truth discipline as ``decode.forward_cached``, from
which this step differs in exactly three ways:

- positions/lengths are PER-SLOT vectors (requests at different depths
  share a step), so rope angles and the attention mask row vary by slot;
- KV reads/writes go through the paged pool (``kv_pool.gather_blocks``
  / ``write_token``) instead of a contiguous cache strip;
- sampled tokens are masked to 0 on inactive slots.

Prefill reuses ``forward_cached`` itself on a [1, P] dense temp cache,
then copies the rows into the request's blocks — numerically the exact
prefill ``generate()`` runs, which is what makes token-parity with
sequential generation testable (greedy decoding is deterministic; for
stochastic sampling the engine is reproducible under its own rng but
not per-request-identical to ``generate()``, since one categorical
call samples all slots).

Telemetry: every finished request journals a ``serve.request`` event
(queue/prefill/decode/total seconds, tokens/s, preemption count) and
every step a ``serve.step`` event (slot occupancy, free blocks) through
``obs.journal`` — ``tadnn report`` renders p50/p99 latency, goodput
and occupancy from exactly these records.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer_core import (
    MLPBlock,
    SelfAttention,
    TransformerConfig,
    make_norm,
)
from ...obs import journal as _journal
from ..decode import (
    KVCache,
    SampleConfig,
    _moe_mlp_cached,
    _sample,
    forward_cached,
)
from ..quant import dequantize_leaf, dequantize_tree, embedding_lookup, \
    is_quantized_leaf
from .kv_pool import (
    PagedKVPool,
    blocks_for_tokens,
    gather_blocks,
    write_token,
)
from .scheduler import Request, Scheduler


def _paged_decode_step(params, kv, tables, ctx_lens, last_tok, active,
                       rng, *, cfg: TransformerConfig,
                       sample: SampleConfig, moe_decode: str,
                       mesh=None, spec=None):
    """One token for every slot.  [S] vectors throughout; static shapes
    (S slots, tables [S, max_blocks]) so this traces exactly once."""
    from ...ops.attention import xla_attention

    dtype = cfg.dtype
    norm = make_norm(cfg)
    attn = SelfAttention(cfg)
    mlp = MLPBlock(cfg)
    if mesh is not None and spec is not None:
        from jax.sharding import NamedSharding

        sh = NamedSharding(mesh, spec)
        kv = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sh), kv)

    x = embedding_lookup(
        params["embed"]["embedding"], last_tok[:, None], dtype)  # [S,1,d]
    positions = ctx_lens[:, None]  # [S, 1] — per-slot rope angles
    if cfg.pos == "learned":
        pe = params["pos_embed"].astype(dtype)
        x = x + pe[positions]

    n_keys = tables.shape[1] * (
        kv["k"]["q"] if is_quantized_leaf(kv["k"]) else kv["k"]
    ).shape[2]
    key_idx = jnp.arange(n_keys)[None, :]
    # the step writes this token at ctx_lens, then attends keys
    # 0..ctx_lens inclusive; table padding beyond a slot's blocks
    # gathers null-block garbage that this mask never admits
    mask = key_idx <= ctx_lens[:, None]
    if cfg.sliding_window is not None:
        mask &= key_idx > ctx_lens[:, None] - cfg.sliding_window
    mask = mask[:, None, None, :]  # [S, 1, 1, K]

    def layer(x, xs):
        lp, k_layer, v_layer = xs
        lp = dequantize_tree(lp, dtype)
        h = norm.apply({"params": lp["attn_norm"]}, x)
        q, k, v = attn.apply(
            {"params": lp["attn"]}, h, positions, method="qkv")
        k_layer = write_token(k_layer, tables, ctx_lens, k[:, 0])
        v_layer = write_token(v_layer, tables, ctx_lens, v[:, 0])
        kd = gather_blocks(k_layer, tables, dtype)
        vd = gather_blocks(v_layer, tables, dtype)
        o = xla_attention(q, kd, vd, causal=False, mask=mask)
        x = x + attn.apply(
            {"params": lp["attn"]}, o.astype(dtype), method="out_proj")
        h = norm.apply({"params": lp["mlp_norm"]}, x)
        if "experts_up" in lp["mlp"]:
            x = x + _moe_mlp_cached(lp["mlp"], h, cfg)
        else:
            x = x + mlp.apply({"params": lp["mlp"]}, h)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv["k"], kv["v"]))

    x = norm.apply({"params": params["final_norm"]}, x)
    feats = x[:, -1].astype(jnp.float32)
    if cfg.tie_embeddings:
        emb = params["embed"]["embedding"]
        if is_quantized_leaf(emb):
            emb = dequantize_leaf(emb, jnp.float32)
        logits = feats @ emb.astype(jnp.float32).T
    else:
        head = params["lm_head"]["kernel"]
        if is_quantized_leaf(head):
            head = dequantize_leaf(head, jnp.float32)
        logits = feats @ head.astype(jnp.float32)
    nxt = _sample(logits, rng, sample)
    nxt = jnp.where(active, nxt, 0)
    return {"k": new_k, "v": new_v}, nxt


class ServeEngine:
    """Continuous-batching server over a model + paged KV pool.

        eng = ServeEngine(model, variables, n_slots=8, max_len=256)
        eng.submit([1, 2, 3], max_new_tokens=32, eos_id=0)
        done = eng.run()          # [Request] with .prompt + .out_tokens

    ``submit`` is non-blocking (requests queue); ``step()`` advances the
    world by one decode iteration (evict / admit+prefill / grow /
    decode); ``run()`` steps until idle.  A long-lived server calls
    ``submit`` from its frontend and ``step`` in a loop — nothing here
    blocks on a full batch.
    """

    def __init__(self, model, variables: Any, *,
                 n_slots: int = 8,
                 max_len: int = 256,
                 block_size: int = 16,
                 num_blocks: int | None = None,
                 quant_kv: bool = False,
                 cache_dtype=jnp.bfloat16,
                 sample: SampleConfig | None = None,
                 admission: str = "reserve",
                 moe_decode: str = "dense",
                 mesh=None,
                 rng: jax.Array | None = None,
                 journal: Any = None):
        self.cfg: TransformerConfig = model.cfg
        self.params = variables["params"]
        self.sample = sample or SampleConfig(temperature=0.0)
        self.n_slots = n_slots
        self.max_len = max_len
        self.moe_decode = moe_decode
        self.mesh = mesh
        self.max_blocks = blocks_for_tokens(max_len, block_size)
        if num_blocks is None:
            # worst case every slot full-length, plus the null block
            num_blocks = n_slots * self.max_blocks + 1
        self.pool = PagedKVPool(
            self.cfg, num_blocks=num_blocks, block_size=block_size,
            dtype=cache_dtype, quantize=quant_kv, mesh=mesh)
        self.scheduler = Scheduler(
            n_slots=n_slots, allocator=self.pool.allocator,
            block_size=block_size, admission=admission)
        self.journal = journal or _journal.get_default()
        self._rng = jax.random.key(0) if rng is None else rng
        self._step_count = 0
        self._occupancy_sum = 0.0
        self.finished: list[Request] = []
        self._step_fn = jax.jit(
            partial(_paged_decode_step, cfg=self.cfg, sample=self.sample,
                    moe_decode=moe_decode, mesh=mesh, spec=self.pool.spec),
            donate_argnums=(1,))

    # -- request intake ------------------------------------------------------

    def submit(self, prompt: list[int], max_new_tokens: int,
               eos_id: int | None = None) -> Request:
        total = len(prompt) + max_new_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"= {total} exceeds engine max_len {self.max_len}")
        if not prompt:
            raise ValueError("empty prompt")
        need = blocks_for_tokens(total, self.pool.block_size)
        if need > self.pool.num_blocks - 1:
            # the pool could NEVER cover this request even alone —
            # admitting it would preempt-thrash forever in optimistic
            # mode and deadlock admission in reserve mode
            raise ValueError(
                f"request needs {need} blocks but the pool has "
                f"{self.pool.num_blocks - 1} allocatable")
        req = Request(prompt=list(map(int, prompt)),
                      max_new_tokens=max_new_tokens, eos_id=eos_id)
        self.scheduler.submit(req)
        return req

    # -- one serving iteration ----------------------------------------------

    def _prefill_into_slot(self, slot: int, req: Request) -> None:
        tokens = jnp.asarray(req.prompt, jnp.int32)[None, :]
        cache = KVCache.init(self.cfg, 1, tokens.shape[1],
                             dtype=jnp.bfloat16)
        # forward_cached retraces per distinct prompt length — the only
        # shape-varying compile in the serving loop
        logits, cache = forward_cached(
            self.params, self.cfg, tokens, cache,
            moe_decode=self.moe_decode, mesh=None)
        req_rng = jax.random.fold_in(self._rng, req.rid)
        _, first_rng = jax.random.split(req_rng)
        first = int(jax.device_get(
            _sample(logits, first_rng, self.sample))[0])
        self.pool.write_prefill(req.blocks[:blocks_for_tokens(
            req.n_prompt, self.pool.block_size)],
            cache.k[:, 0], cache.v[:, 0])
        req.out_tokens = [first]
        req.t_first_token = time.monotonic()

    def _decode_all(self) -> None:
        S, MB = self.n_slots, self.max_blocks
        tables = np.zeros((S, MB), np.int32)
        ctx = np.zeros((S,), np.int32)
        last = np.zeros((S,), np.int32)
        act = np.zeros((S,), bool)
        for s, req in enumerate(self.scheduler.slots):
            if req is None:
                continue
            tables[s, :len(req.blocks)] = req.blocks
            # this step writes token n_generated at absolute position
            # n_prompt + n_generated - 1 (the first generated token
            # came from prefill and was never written)
            ctx[s] = req.n_prompt + req.n_generated - 1
            last[s] = req.out_tokens[-1]
            act[s] = True
        step_rng = jax.random.fold_in(self._rng, 2**20 + self._step_count)
        self.pool.kv, nxt = self._step_fn(
            self.params, self.pool.kv, jnp.asarray(tables),
            jnp.asarray(ctx), jnp.asarray(last), jnp.asarray(act),
            step_rng)
        nxt = np.asarray(jax.device_get(nxt))
        for s, req in enumerate(self.scheduler.slots):
            if req is not None:
                req.out_tokens.append(int(nxt[s]))

    def _finish(self, slot: int) -> None:
        req = self.scheduler.evict(slot)
        self.finished.append(req)
        if self.journal is None:
            return
        queue_s = (req.t_admit or req.t_submit) - req.t_submit
        prefill_s = ((req.t_first_token - req.t_admit)
                     if req.t_first_token and req.t_admit else None)
        decode_s = ((req.t_done - req.t_first_token)
                    if req.t_first_token else None)
        total_s = req.t_done - req.t_submit
        self.journal.event(
            "serve.request", rid=req.rid, n_prompt=req.n_prompt,
            n_new=req.n_generated, queue_s=queue_s,
            prefill_s=prefill_s, decode_s=decode_s, total_s=total_s,
            tokens_per_s=(req.n_generated / decode_s
                          if decode_s else None),
            preempted=req.preempted)

    def step(self) -> None:
        """One serving iteration: evict finished, admit+prefill queued,
        grow/preempt (optimistic), decode every active slot."""
        sched = self.scheduler
        for s in range(self.n_slots):
            req = sched.slots[s]
            if req is not None and req.finished():
                self._finish(s)
        for slot, req in sched.admit():
            self._prefill_into_slot(slot, req)
            if req.finished():  # max_new_tokens == 1
                self._finish(slot)
        for victim in sched.grow_for_step():
            if self.journal is not None:
                self.journal.event("serve.preempt", rid=victim.rid,
                                   n_regenerate=victim.n_prompt)
        if sched.n_active:
            self._decode_all()
        self._step_count += 1
        self._occupancy_sum += sched.n_active / self.n_slots
        if self.journal is not None:
            self.journal.event(
                "serve.step", step=self._step_count,
                n_active=sched.n_active, n_queued=sched.n_queued,
                occupancy=sched.n_active / self.n_slots,
                free_blocks=self.pool.allocator.n_free)

    @property
    def mean_occupancy(self) -> float | None:
        """Mean active-slot fraction over every step so far."""
        if not self._step_count:
            return None
        return self._occupancy_sum / self._step_count

    def run(self) -> list[Request]:
        """Step until queue and slots drain; returns finished requests
        (every submitted request, in completion order)."""
        while not self.scheduler.idle():
            self.step()
        return list(self.finished)
