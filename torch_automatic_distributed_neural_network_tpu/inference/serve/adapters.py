"""Paged LoRA adapter pool for multi-tenant serving.

Serving thousands of fine-tuned variants of ONE base model cannot merge
adapters per request — a merge materializes a full weight copy and
forces a trace per tenant.  Instead this module mirrors the paged KV
design (kv_pool.py) one level up: a fixed-shape device pool of low-rank
factors indexed by adapter slot, so the batched decode step GATHERS each
sequence slot's (A, B) by integer id and applies the segmented delta

    y = x @ W + scaling * (x @ A_id) @ B_id

inside the scanned layer body.  Heterogeneous tenants (and the base
model itself) share one jitted trace for the server's life; only the
``adapter_ids [S]`` operand changes per step.

Layout.  One pool entry ("site") per adapted attention projection, keyed
``q/k/v/o``, each a pair of stacked factors

    a: [L, A, d_in, r]      b: [L, A, r, d_out]

LAYER-major (A = pool size) so ``jax.lax.scan`` slices per-layer factors
alongside the weight stack and the KV pool — the kv_pool ``[L, NB, ..]``
convention, not the ``[A, L, ..]`` order a per-tenant view would
suggest.  With ``quantize=True`` each factor is int8 with per-out-channel
fp32 scales (quant.quantize_lora_factor); tenants are quantized ONCE at
``register()`` so decode, prefill, and any parity oracle all see the
same roundtripped numbers, and the decode gather dequantizes only the
gathered rows (embedding_lookup discipline).

Slot 0 is ``IDENTITY_ADAPTER`` — all-zero factors, so its delta is
exactly 0 and base-model requests run through the same gather unchanged
(the null-KV-block trick applied to weights).  The allocator hands out
slots 1..A-1 with LRU eviction and pinned-while-referenced semantics:
a tenant decoding in some sequence slot can never be evicted out from
under the live trace; unpinned residents stay warm until capacity
demands their slot.  Pins are held by RUNNING sequence slots only —
queued/prefilling requests reference adapters by NAME, which is what
makes preemption leak-free (scheduler.check_invariants asserts it).
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import numpy as np

from ...planner import path_str
from ...training.lora import LoraSpec, adapter_shapes
from ..quant import dequantize_leaf, is_quantized_leaf, quantize_lora_factor

# Slot 0 of every factor stack: all-zero factors, delta exactly 0 — the
# base model.  Mirrors kv_pool.NULL_BLOCK.
IDENTITY_ADAPTER = 0

# Only the scanned attention projections are poolable: they are the
# classic LoRA recipe, their [L, ...] stacks slice through the decode
# scan, and their matrix views are unambiguous.
_SITE_RE = re.compile(r"^layers/attn/(q_proj|k_proj|v_proj|o_proj)/kernel$")
_SITE_KEY = {"q_proj": "q", "k_proj": "k", "v_proj": "v", "o_proj": "o"}


class AdapterAllocator:
    """LRU slot allocator with pin counts over slots 1..n_adapters-1.

    ``acquire`` pins (refcount +1) and faults the name in if absent,
    evicting the least-recently-used UNPINNED resident when full;
    returns None when every slot is pinned (caller backs off — in the
    engine that requeues the request, never stalls the trace).
    ``release`` unpins but leaves the tenant resident, so a bursty
    tenant re-acquires its warm slot as a hit.  Mirrors kv_pool's
    BlockAllocator discipline: loud double-release, ``_live``-style
    accounting via refcounts, slot 0 never handed out.
    """

    def __init__(self, n_adapters: int):
        if n_adapters < 2:
            raise ValueError(
                f"n_adapters={n_adapters}: need slot 0 (identity) plus at "
                "least one tenant slot"
            )
        self.n_adapters = n_adapters
        # LIFO free list like BlockAllocator: slot 1 pops first
        self._free = list(range(n_adapters - 1, 0, -1))
        self._slot: dict[str, int] = {}   # resident name -> slot
        self._refs: dict[str, int] = {}   # resident name -> pin count
        self._order: list[str] = []       # LRU order, least-recent first
        self.hits = 0
        self.faults = 0
        self.evictions = 0

    @property
    def n_resident(self) -> int:
        return len(self._slot)

    @property
    def n_pinned(self) -> int:
        return sum(1 for c in self._refs.values() if c > 0)

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.faults
        return self.hits / total if total else 0.0

    def slot_of(self, name: str) -> int | None:
        return self._slot.get(name)

    def pinned_names(self) -> dict[str, int]:
        """name -> pin count for every pinned resident (invariant checks)."""
        return {n: c for n, c in self._refs.items() if c > 0}

    def _touch(self, name: str) -> None:
        self._order.remove(name)
        self._order.append(name)

    def acquire(self, name: str) -> tuple[int, bool, str | None] | None:
        """Pin ``name``; returns (slot, was_resident, evicted_name) or
        None when every slot is pinned by someone else."""
        if name in self._slot:
            self.hits += 1
            self._refs[name] += 1
            self._touch(name)
            return self._slot[name], True, None
        evicted = None
        if self._free:
            slot = self._free.pop()
        else:
            victim = next(
                (n for n in self._order if self._refs[n] == 0), None)
            if victim is None:
                return None
            slot = self._slot.pop(victim)
            del self._refs[victim]
            self._order.remove(victim)
            self.evictions += 1
            evicted = victim
        self.faults += 1
        self._slot[name] = slot
        self._refs[name] = 1
        self._order.append(name)
        return slot, False, evicted

    def release(self, name: str) -> None:
        if self._refs.get(name, 0) < 1:
            raise ValueError(
                f"release of adapter {name!r} that holds no pinned "
                "reference — double release or never acquired"
            )
        self._refs[name] -= 1

    def invalidate(self, name: str) -> None:
        """Drop an unpinned resident (re-register path).  Pinned -> error:
        a live decode slot is reading those factors."""
        if name not in self._slot:
            return
        if self._refs[name] > 0:
            raise ValueError(
                f"cannot invalidate adapter {name!r}: pinned by "
                f"{self._refs[name]} running slot(s)"
            )
        self._free.append(self._slot.pop(name))
        del self._refs[name]
        self._order.remove(name)


def _zeros_factor(shape, quantize: bool, dtype):
    if not quantize:
        return jnp.zeros(shape, dtype)
    # int8 q=0 dequantizes to exactly 0 whatever the scale; scales start
    # at 1 to keep the leaf well-formed
    return {"q": jnp.zeros(shape, jnp.int8),
            "scale": jnp.ones(shape[:-2] + (1, shape[-1]), jnp.float32)}


def factor_rows(leaf, ids):
    """Per-slot factor gather: [A, m, n]-leading pool leaf -> [S, m, n]
    fp32.  int8 leaves dequantize only the GATHERED rows (the
    embedding_lookup gather-then-dequantize discipline), so the pool
    itself stays int8 in HBM."""
    if is_quantized_leaf(leaf):
        return leaf["q"][ids].astype(jnp.float32) * leaf["scale"][ids]
    return leaf[ids].astype(jnp.float32)


class AdapterPool:
    """Fixed-shape device pool of per-tenant LoRA factors.

    ``register()`` validates and stages a tenant's factor tree on the
    host registry (quantizing once if ``quantize``); ``acquire()`` pins
    it into a device slot (loading on fault); ``release()`` unpins.
    ``factors`` is the pytree the jitted decode step consumes — its
    structure and shapes never change after construction, so slot loads
    (functional ``.at[:, slot].set``) never retrace.

    Sharding: with a ``mesh``, each site's ``b`` factor ([L, A, r,
    d_out]) splits its output channels over the tensor axis when they
    divide — the same split the projection weight itself carries under
    TP, so the per-shard delta composes with the per-shard matmul
    without any extra collective (the o_proj all-reduce that already
    exists covers it).  ``a`` stays replicated: its output dim is the
    rank, far below any useful shard count.  Without a mesh (or when
    d_out doesn't divide) everything is replicated, the pre-TP
    behavior.
    """

    def __init__(self, base_params, spec: LoraSpec, *, n_adapters: int = 8,
                 quantize: bool = False, dtype=jnp.float32, mesh=None):
        self.spec = spec
        self.n_adapters = int(n_adapters)
        self.quantize = bool(quantize)
        self.dtype = dtype
        self.allocator = AdapterAllocator(self.n_adapters)
        # key -> (path, L, d_in, d_out); geometry from training/lora.py
        # so pool layout can't drift from trained factor shapes
        self.sites: dict[str, tuple[str, int, int, int]] = {}
        for path, (lead, d_in, d_out) in adapter_shapes(
                base_params, spec).items():
            m = _SITE_RE.match(path)
            if m is None or len(lead) != 1:
                raise NotImplementedError(
                    f"the serving adapter pool factorizes the scanned "
                    f"attention projections (layers/attn/{{q,k,v,o}}_proj) "
                    f"only, but LoraSpec matched {path!r} with lead dims "
                    f"{tuple(lead)} — MLP/head/unscanned targets need the "
                    "merge-per-request path"
                )
            self.sites[_SITE_KEY[m.group(1)]] = (path, lead[0], d_in, d_out)
        self.factors: dict[str, dict] = {}
        for key, (_, n_layers, d_in, d_out) in self.sites.items():
            r = spec.rank
            self.factors[key] = {
                "a": _zeros_factor((n_layers, self.n_adapters, d_in, r),
                                   self.quantize, dtype),
                "b": _zeros_factor((n_layers, self.n_adapters, r, d_out),
                                   self.quantize, dtype),
            }
        self._registry: dict[str, dict] = {}
        self.mesh = mesh
        # key -> {"a": NamedSharding|None, "b": ...}; None = leave the
        # factor wherever jax puts it (single device / replicated)
        self._shardings: dict[str, dict] = {}
        if mesh is not None:
            from ...ops.paged_attention import tensor_degree
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            t = tensor_degree(mesh)
            for key, (_, _, _, d_out) in self.sites.items():
                b_spec = (P(None, None, None, "tensor")
                          if t > 1 and d_out % t == 0 else P())
                # one spec per factor; for int8 leaves it acts as a
                # pytree prefix over {"q", "scale"} — the scale's
                # [L, A, 1, d_out] last dim splits identically
                self._shardings[key] = {
                    "a": NamedSharding(mesh, P()),
                    "b": NamedSharding(mesh, b_spec),
                }
            self._place_all()

    def _place(self, key: str, side: str, leaf):
        sh = self._shardings.get(key, {}).get(side)
        if sh is None:
            return leaf
        if is_quantized_leaf(leaf):
            return {"q": jax.device_put(leaf["q"], sh),
                    "scale": jax.device_put(leaf["scale"], sh)}
        return jax.device_put(leaf, sh)

    def _place_all(self) -> None:
        for key, pool in self.factors.items():
            for side in ("a", "b"):
                pool[side] = self._place(key, side, pool[side])

    # -- host registry ----------------------------------------------------

    def has(self, name: str) -> bool:
        return name in self._registry

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(self._registry)

    def register(self, name: str, lora_params) -> None:
        """Stage a tenant's factor tree (init_lora_params layout) for
        later fault-in.  Validates the tree matches this pool's spec
        exactly; quantizes ONCE here when the pool is int8 so every
        consumer sees identical roundtripped numbers.  Re-registering a
        resident-but-unpinned tenant drops its slot (next acquire faults
        the new factors in); pinned tenants refuse."""
        flat = jax.tree_util.tree_flatten_with_path(lora_params)[0]
        got: dict[str, dict] = {}
        for path, leaf in flat:
            p = path_str(path)
            site_path, _, fac = p.rpartition("/")
            if fac not in ("a", "b"):
                raise ValueError(
                    f"adapter {name!r}: unexpected leaf {p!r} — expected "
                    "{'a', 'b'} factor pairs from init_lora_params"
                )
            got.setdefault(site_path, {})[fac] = jnp.asarray(
                leaf, jnp.float32)
        want = {path: key for key, (path, *_1) in self.sites.items()}
        if set(got) != set(want):
            raise ValueError(
                f"adapter {name!r} factor sites {sorted(got)} do not match "
                f"the pool's spec sites {sorted(want)}"
            )
        entry: dict[str, dict] = {}
        for site_path, fac in got.items():
            key = want[site_path]
            _, n_layers, d_in, d_out = self.sites[key]
            r = self.spec.rank
            a, b = fac.get("a"), fac.get("b")
            if a is None or b is None:
                raise ValueError(
                    f"adapter {name!r}: site {site_path!r} is missing an "
                    "'a' or 'b' factor"
                )
            if a.shape != (n_layers, d_in, r) or b.shape != (n_layers, r,
                                                             d_out):
                raise ValueError(
                    f"adapter {name!r}: site {site_path!r} factor shapes "
                    f"a{a.shape} / b{b.shape} do not match the pool's "
                    f"a{(n_layers, d_in, r)} / b{(n_layers, r, d_out)}"
                )
            if self.quantize:
                entry[key] = {"a": quantize_lora_factor(a),
                              "b": quantize_lora_factor(b)}
            else:
                entry[key] = {"a": a.astype(self.dtype),
                              "b": b.astype(self.dtype)}
        self.allocator.invalidate(name)
        self._registry[name] = entry

    def effective_lora(self, name: str):
        """The EXACT factors decode serves (int8 pools roundtrip through
        quantization), as the nested fp32 tree ``merge_lora`` consumes.
        The engine's prefill path and the sequential parity oracle both
        use this, so prefill KV, the batched segmented decode, and the
        merge_lora+generate() reference all see one set of numbers."""
        entry = self._registry[name]
        out: dict = {}
        for key, fac in entry.items():
            path = self.sites[key][0]
            node = out
            parts = path.split("/")
            for k in parts[:-1]:
                node = node.setdefault(k, {})
            node[parts[-1]] = {
                side: (dequantize_leaf(fac[side], jnp.float32)
                       if is_quantized_leaf(fac[side]) else fac[side])
                for side in ("a", "b")
            }
        return out

    # -- device slots ------------------------------------------------------

    def acquire(self, name: str) -> tuple[int, bool, str | None] | None:
        """Pin ``name`` into a device slot, loading factors on fault.
        Returns (slot, was_resident, evicted_name) or None when every
        slot is pinned."""
        if name not in self._registry:
            raise KeyError(
                f"unknown adapter {name!r} — register() it before submit"
            )
        res = self.allocator.acquire(name)
        if res is None:
            return None
        slot, was_resident, evicted = res
        if not was_resident:
            self._load(slot, name)
        return slot, was_resident, evicted

    def release(self, name: str) -> None:
        self.allocator.release(name)

    def _load(self, slot: int, name: str) -> None:
        for key, fac in self._registry[name].items():
            pool = self.factors[key]
            for side in ("a", "b"):
                host, leaf = fac[side], pool[side]
                if self.quantize:
                    loaded = {
                        "q": leaf["q"].at[:, slot].set(host["q"]),
                        "scale": leaf["scale"].at[:, slot].set(
                            host["scale"]),
                    }
                else:
                    loaded = leaf.at[:, slot].set(host)
                # re-pin the sharding the .at[].set may have dropped
                pool[side] = self._place(key, side, loaded)

    # -- accounting --------------------------------------------------------

    @property
    def total_bytes(self) -> int:
        return sum(int(x.size) * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(self.factors))


def pool_adapter_bytes(cfg, *, rank: int, n_adapters: int,
                       quantize: bool = False,
                       degrees: dict | None = None) -> int:
    """Device-free PER-DEVICE HBM cost of an AdapterPool under the
    DEFAULT LoraSpec recipe (q_proj + v_proj) — the serve_estimate term.
    fp32 factors, or int8 payload + per-out-channel fp32 scales when
    ``quantize``.  Under a tensor degree (``degrees={"tensor": t}``)
    each ``b`` factor splits its output channels t ways when they
    divide (AdapterPool's sharding rule), so only b/t lands on a
    shard; ``a`` factors stay replicated."""
    t = int((degrees or {}).get("tensor", 1)) or 1
    per_adapter_layer = 0
    q_out = cfg.n_heads * cfg.head_dim
    v_out = cfg.kv_heads * cfg.head_dim
    for d_out in (q_out, v_out):
        shard = t if t > 1 and d_out % t == 0 else 1
        a_elems = cfg.d_model * rank
        b_elems = rank * (d_out // shard)
        o_local = d_out // shard
        if quantize:
            per_adapter_layer += a_elems + 4 * rank      # int8 + [1, r] f32
            per_adapter_layer += b_elems + 4 * o_local   # int8 + [1, o] f32
        else:
            per_adapter_layer += 4 * (a_elems + b_elems)
    return int(cfg.n_layers) * int(n_adapters) * per_adapter_layer


def random_adapter(base_params, spec: LoraSpec, *, seed: int = 0,
                   scale: float = 0.02):
    """A seeded random tenant for load-gen, smokes, and benches:
    init_lora_params geometry with a non-zero B factor so the delta is
    real (b starts at zero in training init — an all-zero tenant would
    make multi-tenant parity vacuous)."""
    from ...training.lora import init_lora_params

    lora = init_lora_params(jax.random.PRNGKey(seed), base_params, spec)
    rs = np.random.RandomState(seed)

    def bump(path, x):
        if getattr(path[-1], "key", None) == "b":
            return jnp.asarray(rs.normal(scale=scale, size=x.shape),
                               jnp.float32)
        return x

    return jax.tree_util.tree_map_with_path(bump, lora)
