"""Weight-only int8 quantization for KV-cached decode.

Single-token decode is HBM-bandwidth-bound: every step streams the full
parameter set through the chip to do rank-1 work.  Storing weights as
int8 with per-output-channel fp32 scales halves that traffic vs bf16
(the matmuls still run in bf16/fp32 — only the STORAGE is quantized,
dequantized on the fly where XLA fuses the convert+scale into the
weight load).

    qvars = quantize_for_decode(variables)      # once, on host or device
    out = generate(model, qvars, prompt, ...)   # decode reads int8

Symmetric per-channel scheme: for a kernel in its matrix view
``[.., d_in, d_out]`` the scale is ``max|W|`` over d_in per output
channel / 127; embeddings scale per row (each row is both a lookup
result and a tied-head output channel).  Norm scales/biases stay fp32 —
they are O(d) and numerically load-bearing.

The quantized tree swaps each targeted leaf for ``{"q": int8,
"scale": fp32}`` (same tree shape otherwise), so nn.scan-stacked layer
stacks slice through unchanged and ``forward_cached`` dequantizes
per-layer INSIDE the scan body — the int8 arrays are what lives in HBM.

Accuracy contract (pinned in tests/test_quant.py): elementwise
``|W - dequant(W)| <= scale/2``, and decode logits track the
full-precision path to <5% of their dynamic range (measured ~2% on
the test models).  Training is NOT
quantized — this is a serving-path feature (weight-only, like the
standard int8 LLM-serving recipe).
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp

from ..planner import path_str
from ..training.lora import KERNEL_MATRIX_VIEWS, matrix_view

# Embeddings quantize per ROW (each row is both a lookup result and a
# tied-head output channel); kernels share training/lora.py's
# matrix-view table — ONE definition of the kernel-family split.
_EMBED_PAT = re.compile(r"(embed|seg_embed)/embedding$")


def is_quantized_leaf(x) -> bool:
    return isinstance(x, dict) and set(x) == {"q", "scale"}


def _quantize(leaf, reduce_axes):
    """Symmetric int8 with per-channel scales over ``reduce_axes``."""
    w = jnp.asarray(leaf, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale.astype(jnp.float32)}


def quantize_for_decode(variables):
    """variables (or a bare params tree) -> same tree with every known
    kernel/embedding leaf swapped for ``{"q", "scale"}``.  Leaves the
    rest (norms, biases, already-quantized leaves) untouched."""
    bare = not (isinstance(variables, dict) and "params" in variables)
    params = variables if bare else variables["params"]

    def visit(path, leaf):
        if is_quantized_leaf(leaf) or jnp.ndim(leaf) < 2:
            return leaf
        p = path_str(path)
        if _EMBED_PAT.search(p):  # [V, d] -> scale [V, 1]
            return _quantize(leaf, (jnp.ndim(leaf) - 1,))
        for target in KERNEL_MATRIX_VIEWS:
            if re.search(target.pattern, p):
                # reduce over the target's input dims; lead dims derive
                # from the shape (lora.matrix_view), so scanned stacks
                # and unstacked kernels both resolve without heuristics
                lead, _, _ = matrix_view(jnp.shape(leaf), target)
                n_lead = len(lead)
                return _quantize(
                    leaf, tuple(range(n_lead, n_lead + target.in_dims)))
        return leaf

    qparams = jax.tree_util.tree_map_with_path(
        visit, params, is_leaf=is_quantized_leaf)
    return qparams if bare else {**variables, "params": qparams}


def dequantize_leaf(x, dtype=jnp.bfloat16):
    """{"q", "scale"} -> dense array (XLA fuses the convert + scale into
    the consuming matmul, so HBM traffic stays int8)."""
    return (x["q"].astype(jnp.float32) * x["scale"]).astype(dtype)


def dequantize_tree(tree, dtype=jnp.bfloat16):
    """Replace every quantized leaf in a (sub)tree with its dense form."""
    if is_quantized_leaf(tree):
        return dequantize_leaf(tree, dtype)
    if isinstance(tree, dict):
        return {k: dequantize_tree(v, dtype) for k, v in tree.items()}
    return tree


def quantize_kv(x):
    """int8 KV storage with per-token-per-head scales.

    ``x`` is any KV tensor whose LAST axis is head_dim (a [.., kvH, hd]
    cache block, a single written token, a whole pooled cache).  The
    scale reduces over head_dim only — one fp32 scale per (token, head)
    — so a loud head cannot crush a quiet head's resolution and each
    token requantizes independently when written into a paged block.
    Same symmetric scheme and ``{"q", "scale"}`` leaf convention as the
    weight path, so ``is_quantized_leaf``/``dequantize_leaf`` apply.
    """
    return _quantize(x, (jnp.ndim(x) - 1,))


def quantize_lora_factor(x):
    """int8 storage for a LoRA low-rank factor.

    ``x`` is an A-factor ``[.., d_in, r]`` or B-factor ``[.., r, d_out]``
    — either way the second-to-last axis is the CONTRACTION axis of the
    rank-r matmul, so the scale reduces over it: one fp32 scale per
    output channel of the factor, the same per-out-channel scheme the
    weight path uses.  ``{"q", "scale"}`` leaf convention throughout, so
    ``is_quantized_leaf``/``dequantize_leaf`` apply unchanged.  Used by
    the serving adapter pool (inference/serve/adapters.py) to hold
    ~4x more tenants per byte of HBM.
    """
    return _quantize(x, (jnp.ndim(x) - 2,))


def dequantize_kv(qkv, dtype=jnp.bfloat16):
    """{"q", "scale"} KV leaf -> dense [.., kvH, hd] in ``dtype``."""
    return dequantize_leaf(qkv, dtype)


def kv_leaf_parts(x):
    """``(payload, scale | None)`` view of a KV-pool leaf.

    This is the storage contract the fused paged-attention kernel
    (ops/paged_attention.py) consumes IN-KERNEL: the int8 payload and
    its per-(token, head) fp32 scales stream into VMEM as separate
    operands and multiply right before the dot, so the dense bf16 form
    of a block never materializes in HBM.  Dense (fp) leaves have no
    scale pass at all — callers skip dequantize entirely.
    """
    if is_quantized_leaf(x):
        return x["q"], x["scale"]
    return x, None


def embedding_lookup(emb, tokens, dtype=jnp.bfloat16):
    """Gather-then-dequantize: only the LOOKED-UP rows convert, the
    [V, d] table itself stays int8 in HBM."""
    if is_quantized_leaf(emb):
        rows = emb["q"][tokens].astype(jnp.float32)
        return (rows * emb["scale"][tokens]).astype(dtype)
    return emb[tokens].astype(dtype)
