"""Online serving gateway: ingress, routing, and closed-loop autoscale.

Three layers over the continuous-batching serve engine:

- :mod:`.ingress` — :class:`Gateway` (admission: token-bucket rate
  limits, bounded per-tenant queues, priority classes) plus
  :class:`HttpIngress`, a stdlib-asyncio HTTP server streaming tokens
  over SSE;
- :mod:`.router` — :class:`Router` places requests on the replica
  already owning the deepest cached prefix (by the radix index's
  chained block hashes), falling back to least-loaded;
- :mod:`.controller` — :class:`FleetController` watches live SLO
  windows and resizes the fleet through the planner's serving replay.

:mod:`.fault` adds the fleet fault-tolerance layer — per-replica
circuit breakers, tail hedging, and the degrade ladder — which the
gateway wires to heartbeat-expiry failover and an exactly-once
per-request token ledger.

:mod:`.chaos` scripts the whole loop on a virtual clock (traffic flip
→ breach → replan → recover, plus seeded replica kill/stall/slow) as
byte-replayable scenarios — ``tadnn gateway --smoke`` and ``tadnn
gateway --chaos`` in CI.
"""

from .chaos import chaos_smoke, fleet_chaos, run_scenario
from .controller import AutoscalePolicy, FleetController
from .fault import BreakerPolicy, CircuitBreaker, HedgePolicy
from .ingress import (
    Gateway,
    GatewayError,
    HttpIngress,
    RateLimited,
    Saturated,
    TokenBucket,
    serve_forever,
    sse_generate,
)
from .router import EngineReplica, NoHealthyReplica, Router, SimReplica

__all__ = [
    "AutoscalePolicy",
    "BreakerPolicy",
    "CircuitBreaker",
    "EngineReplica",
    "FleetController",
    "Gateway",
    "GatewayError",
    "HedgePolicy",
    "HttpIngress",
    "NoHealthyReplica",
    "RateLimited",
    "Router",
    "Saturated",
    "SimReplica",
    "TokenBucket",
    "chaos_smoke",
    "fleet_chaos",
    "run_scenario",
    "serve_forever",
    "sse_generate",
]
