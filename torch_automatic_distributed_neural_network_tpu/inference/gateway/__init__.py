"""Online serving gateway: ingress, routing, and closed-loop autoscale.

Three layers over the continuous-batching serve engine:

- :mod:`.ingress` — :class:`Gateway` (admission: token-bucket rate
  limits, bounded per-tenant queues, priority classes) plus
  :class:`HttpIngress`, a stdlib-asyncio HTTP server streaming tokens
  over SSE;
- :mod:`.router` — :class:`Router` places requests on the replica
  already owning the deepest cached prefix (by the radix index's
  chained block hashes), falling back to least-loaded;
- :mod:`.controller` — :class:`FleetController` watches live SLO
  windows and resizes the fleet through the planner's serving replay.

:mod:`.chaos` scripts the whole loop on a virtual clock (traffic flip
→ breach → replan → recover) as a byte-replayable smoke scenario —
``tadnn gateway --smoke`` in CI.
"""

from .chaos import chaos_smoke, run_scenario
from .controller import AutoscalePolicy, FleetController
from .ingress import (
    Gateway,
    GatewayError,
    HttpIngress,
    RateLimited,
    Saturated,
    TokenBucket,
    serve_forever,
    sse_generate,
)
from .router import EngineReplica, NoHealthyReplica, Router, SimReplica

__all__ = [
    "AutoscalePolicy",
    "EngineReplica",
    "FleetController",
    "Gateway",
    "GatewayError",
    "HttpIngress",
    "NoHealthyReplica",
    "RateLimited",
    "Router",
    "Saturated",
    "SimReplica",
    "TokenBucket",
    "chaos_smoke",
    "run_scenario",
    "serve_forever",
    "sse_generate",
]
