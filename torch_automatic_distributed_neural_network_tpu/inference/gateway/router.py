"""Multi-replica front: prefix-affinity routing over serving replicas.

One ``ServeEngine`` is one replica — its paged pool, radix index and
scheduler are private.  A fleet of N replicas therefore has N disjoint
prefix caches, and WHERE a request lands decides whether its shared
preamble is a hit or a cold re-prefill.  The router's job is to make
that placement content-aware: requests are keyed by the SAME chained
block content hashes the radix index uses (``prefix_cache.
block_hashes``), and each hash key remembers which replica first
prefilled it.  A new request walks its own keys front-to-back and goes
to the replica owning its DEEPEST indexed prefix — shared-prefix
traffic piles onto the replica where its KV already lives, unique
traffic falls through to least-loaded.  This is the standard
cache-aware routing result (e.g. SGLang's router): affinity beats
round-robin/least-loaded on hit rate precisely when traffic is
prefix-heavy, which is what production multi-tenant mixes are.

Affinity yields to load: when the owning replica's queue is more than
``imbalance_factor``× the least-loaded replica's (plus its slot count,
so small absolute differences never trigger), the request falls back
to least-loaded — a hot system prompt must not starve the rest of the
fleet behind one replica.

Two replica flavors, one protocol (submit/step/load/drain/idle):

- :class:`EngineReplica` wraps a real :class:`ServeEngine` — the HTTP
  serving and bench paths.
- :class:`SimReplica` is the discrete-event twin: the REAL
  ``Scheduler`` + ``PrefixCache`` + ``BlockAllocator`` on an injected
  virtual clock with modeled step costs, emitting the same
  ``serve.step`` / ``serve.request_done`` / ``serve.prefix`` journal
  records as the engine (tune/simulate's replay discipline, made
  incremental so N replicas interleave under one gateway loop).  The
  chaos autoscale test runs entirely on these — no device, no sleeps,
  byte-replayable.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence

from ..serve.kv_pool import BlockAllocator, blocks_for_tokens
from ..serve.prefix_cache import PrefixCache, block_hashes
from ..serve.scheduler import Request, Scheduler


class NoHealthyReplica(RuntimeError):
    """Every replica is draining, retired, or heartbeat-stale."""


class SimReplica:
    """Virtual-time serving replica: real scheduling, modeled compute.

    Mirrors ``tune/simulate.replay_serve`` phase-for-phase (evict →
    admit → prefill chunk → decode), but steps ONE iteration per call
    so a gateway can interleave many replicas and inject traffic
    between steps.  Token values are emulated (EOS exactly at each
    request's ``n_decode``); timestamps come from the shared injected
    clock, which the gateway advances between ticks.
    """

    def __init__(self, name: str, *, n_slots: int = 4,
                 block_size: int = 8, max_len: int = 256,
                 num_blocks: int | None = None,
                 admission: str = "reserve",
                 prefill_chunk: int = 8,
                 prefill_chunks_per_step: int = 1,
                 prefix_cache: bool = True,
                 prefix_ttl_s: float | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 journal=None):
        self.name = name
        self.clock = clock
        self.journal = journal
        self.n_slots = int(n_slots)
        self.block_size = int(block_size)
        self.max_len = int(max_len)
        self.admission = admission
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_chunks_per_step = int(prefill_chunks_per_step)
        self.prefix_ttl_s = prefix_ttl_s
        if num_blocks is None:
            num_blocks = (n_slots
                          * blocks_for_tokens(max_len, block_size) + 1)
        self.allocator = BlockAllocator(num_blocks)
        self.prefix_cache = (
            PrefixCache(block_size=block_size, allocator=self.allocator,
                        clock=clock, journal=journal)
            if prefix_cache else None)
        self.scheduler = Scheduler(
            n_slots=n_slots, allocator=self.allocator,
            block_size=block_size, admission=admission,
            prefix_cache=self.prefix_cache, clock=clock)
        self._prefill_pos: dict[int, int] = {}
        self._n_decode: dict[int, int] = {}
        self.finished: list[Request] = []
        self._taken = 0  # finished-list cursor for take_finished
        self.draining = False
        self.retired = False
        # chaos fault state: a dead replica stops heartbeating (the
        # gateway's failover trigger); a stalled one heartbeats but
        # never advances (the circuit breaker's target); slow_factor n
        # makes only every n-th step do work (the hedging target)
        self.alive = True
        self.stalled = False
        self.slow_factor = 1
        self._slow_phase = 0
        self.last_step_t = clock()
        self.steps = 0
        self.prompt_tokens = 0

    # -- protocol ------------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               eos_id: int | None = 0, priority: int = 0,
               n_decode: int | None = None,
               rid: int | None = None) -> Request:
        """Queue one request.  ``n_decode`` is the emulated true decode
        length (EOS emitted there); defaults to the full budget.
        ``rid`` lets the gateway mint ids itself — the module-global
        rid counter is process-lifetime, which would make two chaos
        runs in one process journal different ids."""
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} "
                f"exceeds replica max_len {self.max_len}")
        kw = {} if rid is None else {"rid": int(rid)}
        req = Request(prompt=list(map(int, prompt)),
                      max_new_tokens=int(max_new_tokens),
                      eos_id=eos_id, priority=int(priority), **kw)
        # the dataclass stamps wall time; this replica lives on the
        # injected clock
        req.t_submit = self.clock()
        self._n_decode[req.rid] = int(n_decode or max_new_tokens)
        self.prompt_tokens += len(prompt)
        self.scheduler.submit(req)
        return req

    def resubmit(self, req: Request, *,
                 n_decode: int | None = None) -> Request:
        """Re-queue a request drained off a retiring replica: keeps its
        identity (rid, t_submit, priority) so the request's span still
        measures from ORIGINAL submission — a scale-in must show up in
        the victim requests' latency, not hide it."""
        self._n_decode[req.rid] = int(n_decode or req.max_new_tokens)
        self.scheduler.submit(req)
        return req

    def load(self) -> int:
        return self.scheduler.n_queued + self.scheduler.n_active

    def idle(self) -> bool:
        return self.scheduler.idle()

    def take_finished(self) -> list[Request]:
        out = self.finished[self._taken:]
        self._taken = len(self.finished)
        return out

    # -- fault injection / recovery ------------------------------------------

    def kill(self) -> None:
        """Hard-kill: the replica stops stepping AND stops advancing
        its heartbeat, so the gateway's expiry check sees it die."""
        self.alive = False

    def cancel(self, rid: int) -> bool:
        """Remove one request (by rid) from this replica without
        journaling a completion — the hedge loser / stale-copy path.
        Queue first, then slots (blocks freed, no request_done span).
        Returns False when no copy of ``rid`` is resident here."""
        sched = self.scheduler
        for r in list(sched.queue):
            if r.rid == rid:
                sched.queue.remove(r)
                self._n_decode.pop(rid, None)
                return True
        for s in range(self.n_slots):
            r = sched.slots[s]
            if r is not None and r.rid == rid:
                sched.evict(s)
                self._n_decode.pop(rid, None)
                self._prefill_pos.pop(rid, None)
                return True
        return False

    # -- one serving iteration ----------------------------------------------

    def _emit(self, req: Request) -> None:
        eos_at = self._n_decode.get(req.rid, req.max_new_tokens)
        req.out_tokens.append(
            0 if req.n_generated + 1 >= eos_at else 1)
        req.token_walls.append(self.clock())

    def _finish(self, req: Request) -> None:
        self._n_decode.pop(req.rid, None)
        self._prefill_pos.pop(req.rid, None)
        self.finished.append(req)
        if self.journal is None:
            return
        itl = [b - a for a, b in zip(req.token_walls,
                                     req.token_walls[1:])]
        total = (req.t_done - req.t_submit
                 if req.t_done is not None else None)
        self.journal.event(
            "serve.request_done", rid=req.rid, replica=self.name,
            n_prompt=req.n_prompt, n_new=req.n_generated,
            queue_s=(req.t_admit - req.t_submit
                     if req.t_admit is not None else None),
            total_s=total,
            tokens_per_s=(req.n_generated / total
                          if total else None),
            preempted=req.preempted,
            ttft_s=(req.t_first_token - req.t_submit
                    if req.t_first_token is not None else None),
            itl_s=itl,
            itl_mean_s=(sum(itl) / len(itl) if itl else None),
            cached_tokens=req.cached_tokens,
            prefill_chunks=req.prefill_chunks, lost_s=req.lost_s)

    def step(self) -> int:
        """One iteration: evict finished, admit, advance prefill
        chunks, decode every running slot.  Returns tokens emitted.
        Journals ``serve.step`` only when there was work — an idle
        replica is silent, like an idle engine."""
        if not self.alive:
            return 0  # dead: no heartbeat, no progress
        sched = self.scheduler
        self.last_step_t = self.clock()
        if self.stalled:
            return 0  # wedged: heartbeats but never advances
        if self.slow_factor > 1:
            self._slow_phase = (self._slow_phase + 1) % self.slow_factor
            if self._slow_phase != 0:
                return 0
        if sched.idle():
            return 0
        new_tokens = 0
        for s in range(self.n_slots):
            req = sched.slots[s]
            if (req is not None and req.state == "running"
                    and req.finished()):
                self._finish(sched.evict(s))
        step_pf = 0
        for slot, req in sched.admit():
            if req.cached_tokens and self.journal is not None:
                self.journal.event(
                    "serve.prefix", kind="match", rid=req.rid,
                    replica=self.name, hit=True,
                    cached_tokens=req.cached_tokens,
                    cached_blocks=req.cached_blocks)
            req.state = "prefilling"
            self._prefill_pos[req.rid] = req.cached_tokens
        started: set[int] = set()
        for slot, req in sched.prefill_plan(self.prefill_chunks_per_step):
            pos = self._prefill_pos[req.rid]
            pos += min(self.prefill_chunk, req.n_prompt - pos)
            self._prefill_pos[req.rid] = pos
            req.prefill_chunks += 1
            step_pf += 1
            if pos >= req.n_prompt:
                del self._prefill_pos[req.rid]
                if self.prefix_cache is not None:
                    n_pub = req.n_prompt // self.block_size
                    new = self.prefix_cache.insert(
                        req.prompt[:n_pub * self.block_size],
                        req.blocks[:n_pub], ttl_s=self.prefix_ttl_s)
                    if new and self.journal is not None:
                        self.journal.event(
                            "serve.prefix", kind="publish",
                            rid=req.rid, replica=self.name,
                            n_blocks=new)
                self._emit(req)
                req.t_first_token = self.clock()
                req.state = "running"
                started.add(req.rid)
                new_tokens += 1
                if req.finished():
                    self._finish(sched.evict(slot))
        for req in list(sched.slots):
            if (req is not None and req.state == "running"
                    and req.rid not in started):
                self._emit(req)
                new_tokens += 1
        self.steps += 1
        if self.journal is not None:
            self.journal.event(
                "serve.step", replica=self.name,
                n_active=sched.n_active, n_queued=sched.n_queued,
                new_tokens=new_tokens,
                occupancy=sched.n_active / self.n_slots,
                free_blocks=self.allocator.n_free,
                prefill_chunks=step_pf)
        return new_tokens

    # -- elastic resize ------------------------------------------------------

    def drain(self) -> list[Request]:
        """Drain-then-retire: bounce every occupied slot back through
        the scheduler's requeue path (blocks freed, recompute-style),
        then hand the whole queue back for resubmission elsewhere.
        The replica is retired afterwards."""
        self.draining = True
        sched = self.scheduler
        for s in range(self.n_slots):
            if sched.slots[s] is not None:
                sched.requeue(s)
        out = list(sched.queue)
        sched.queue.clear()
        for req in out:
            req.state = "queued"
            self._n_decode.pop(req.rid, None)
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
        self.retired = True
        return out

    # -- stats ---------------------------------------------------------------

    def prefix_stats(self) -> dict:
        pc = self.prefix_cache
        if pc is None:
            return {"queries": 0, "hit_requests": 0, "hit_tokens": 0,
                    "expired_blocks": 0}
        return {"queries": pc.queries, "hit_requests": pc.hit_requests,
                "hit_tokens": pc.hit_tokens,
                "expired_blocks": pc.expired_blocks}


class EngineReplica:
    """A real :class:`ServeEngine` behind the replica protocol.

    The engine journals its own ``serve.*`` spans (pass the gateway's
    journal at engine construction so all replicas share one file);
    this wrapper adds only the fleet bookkeeping the router and
    controller need — load, heartbeat, drain."""

    def __init__(self, name: str, engine, *,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.engine = engine
        self.clock = clock
        self.n_slots = engine.n_slots
        self.block_size = engine.pool.block_size
        self.max_len = engine.max_len
        self.draining = False
        self.retired = False
        self.alive = True
        self.last_step_t = clock()
        self._taken = 0
        self.prompt_tokens = 0

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               eos_id: int | None = None, priority: int = 0,
               n_decode: int | None = None,
               rid: int | None = None) -> Request:
        # ``rid`` is ignored: the engine mints its own (real serving
        # doesn't need cross-run id determinism; the virtual-time
        # chaos runs do, and those use SimReplica)
        self.prompt_tokens += len(prompt)
        return self.engine.submit(list(prompt), max_new_tokens,
                                  eos_id=eos_id, priority=priority)

    def resubmit(self, req: Request, *,
                 n_decode: int | None = None) -> Request:
        self.engine.scheduler.submit(req)
        return req

    def load(self) -> int:
        s = self.engine.scheduler
        return s.n_queued + s.n_active

    def idle(self) -> bool:
        return self.engine.scheduler.idle()

    def step(self) -> int:
        before = self.engine.tokens_emitted
        if not self.engine.scheduler.idle():
            self.engine.step()
        self.last_step_t = self.clock()
        return self.engine.tokens_emitted - before

    def take_finished(self) -> list[Request]:
        out = self.engine.finished[self._taken:]
        self._taken = len(self.engine.finished)
        return out

    def cancel(self, rid: int) -> bool:
        """Drop one request (hedge loser) without a completion span —
        the engine twin of :meth:`SimReplica.cancel`."""
        sched = self.engine.scheduler
        for r in list(sched.queue):
            if r.rid == rid:
                sched.queue.remove(r)
                return True
        for s in range(sched.n_slots):
            r = sched.slots[s]
            if r is not None and r.rid == rid:
                sched.evict(s)
                return True
        return False

    def drain(self) -> list[Request]:
        self.draining = True
        sched = self.engine.scheduler
        for s in range(sched.n_slots):
            if sched.slots[s] is not None:
                sched.requeue(s)
        out = list(sched.queue)
        sched.queue.clear()
        for req in out:
            req.state = "queued"
        if self.engine._prefix_cache is not None:
            self.engine._prefix_cache.clear()
        self.retired = True
        return out

    def prefix_stats(self) -> dict:
        pc = self.engine._prefix_cache
        if pc is None:
            return {"queries": 0, "hit_requests": 0, "hit_tokens": 0,
                    "expired_blocks": 0}
        return {"queries": pc.queries, "hit_requests": pc.hit_requests,
                "hit_tokens": pc.hit_tokens,
                "expired_blocks": pc.expired_blocks}


class Router:
    """Content-hash affinity placement with least-loaded fallback."""

    def __init__(self, replicas: Sequence, *, block_size: int,
                 policy: str = "affinity",
                 imbalance_factor: float = 2.0,
                 heartbeat_s: float | None = None,
                 gate: Callable[[Any], bool] | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 journal=None):
        if policy not in ("affinity", "least_loaded"):
            raise ValueError(f"unknown router policy {policy!r}")
        self.replicas: list = list(replicas)
        self.block_size = int(block_size)
        self.policy = policy
        self.imbalance_factor = float(imbalance_factor)
        self.heartbeat_s = heartbeat_s
        # optional routing gate (the gateway's per-replica circuit
        # breakers): a replica the gate vetoes takes no NEW placements
        # but keeps its in-flight work and its affinity claims
        self.gate = gate
        self.clock = clock
        self.journal = journal
        # chained content-hash key -> replica NAME that first prefilled
        # it (first owner wins, exactly the index's first-publisher
        # rule; retiring a replica forgets its claims)
        self._owner: dict[str, str] = {}
        self.n_routed = 0
        self.n_affinity = 0
        self.n_fallback = 0
        self.n_decayed = 0

    def healthy(self) -> list:
        out = []
        now = self.clock()
        for r in self.replicas:
            if r.draining or r.retired or not getattr(r, "alive", True):
                continue
            if (self.heartbeat_s is not None
                    and now - r.last_step_t > self.heartbeat_s):
                continue
            if self.gate is not None and not self.gate(r):
                continue
            out.append(r)
        return out

    def _owner_dead(self, name: str | None,
                    by_name: dict[str, Any]) -> bool:
        """True when a claim's owner no longer exists as a live
        replica (retired, killed, or forgotten) — its KV is gone for
        good, so the claim is a corpse, not a temporary outage."""
        if name is None:
            return False
        rep = by_name.get(name)
        return (rep is None or rep.retired
                or not getattr(rep, "alive", True))

    def route(self, prompt: Sequence[int]):
        """Pick the replica for ``prompt`` and stamp its content keys.

        Affinity: deepest contiguous owned prefix wins, unless the
        owner is overloaded vs the least-loaded healthy replica; ties
        and unknown content go least-loaded (stable by name)."""
        cands = self.healthy()
        if not cands:
            raise NoHealthyReplica(
                f"no healthy replica among {len(self.replicas)}")
        least = min(cands, key=lambda r: (r.load(), r.name))
        keys = block_hashes(list(prompt), self.block_size)
        chosen = least
        depth = 0
        if self.policy == "affinity" and keys:
            by_name = {r.name: r for r in cands}
            node = None
            for key in keys:
                owner = self._owner.get(key)
                if owner is None or owner not in by_name:
                    break
                node = owner
                depth += 1
            if node is not None:
                aff = by_name[node]
                # affinity yields to gross imbalance: a hot prefix
                # must not serialize the fleet behind one replica
                if (aff.load() <= self.imbalance_factor * least.load()
                        + aff.n_slots):
                    chosen = aff
                else:
                    depth = 0
        self.n_routed += 1
        if depth:
            self.n_affinity += 1
        else:
            self.n_fallback += 1
        all_by_name = {r.name: r for r in self.replicas}
        for key in keys:
            cur = self._owner.get(key)
            if cur is None:
                self._owner[key] = chosen.name
            elif self._owner_dead(cur, all_by_name):
                # decay: the owning replica is dead, its KV with it —
                # re-own the block where this traffic actually lands
                # so failover traffic stops chasing the corpse
                self._owner[key] = chosen.name
                self.n_decayed += 1
        return chosen

    def forget(self, name: str) -> int:
        """Drop a retired replica's content claims (its index is gone);
        returns how many keys were released."""
        dead = [k for k, v in self._owner.items() if v == name]
        for k in dead:
            del self._owner[k]
        return len(dead)

    def stats(self) -> dict:
        return {"n_routed": self.n_routed,
                "n_affinity": self.n_affinity,
                "n_fallback": self.n_fallback,
                "n_decayed": self.n_decayed,
                "owned_keys": len(self._owner)}
