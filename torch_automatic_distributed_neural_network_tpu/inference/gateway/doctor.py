"""Fleet post-mortem: ``tadnn doctor --gateway-dir``.

The serving twin of ``doctor --launch-dir`` (training/launch.py): read
a gateway journal — including its rotated ``<path>.1`` generation —
and reconstruct the fleet's failure story offline: per-replica last
heartbeats, failovers with the rids they salvaged, hedge win/loss
record, circuit-breaker transitions, the degrade/restore history, and
which replica broke the cohort first.  The verdict (``ok``) is the
serving contract itself: every accepted request either completed or is
explicitly accounted for as lost.
"""

from __future__ import annotations

import os

from ...obs.journal import Journal


def _journal_path(gateway_dir: str) -> str | None:
    """Accept a journal file directly or a directory holding one."""
    if os.path.isfile(gateway_dir):
        return gateway_dir
    if os.path.isdir(gateway_dir):
        for name in ("journal.jsonl", "gateway.jsonl", "chaos.jsonl"):
            p = os.path.join(gateway_dir, name)
            if os.path.isfile(p):
                return p
        jsonl = sorted(
            n for n in os.listdir(gateway_dir)
            if n.endswith(".jsonl"))
        if jsonl:
            return os.path.join(gateway_dir, jsonl[0])
    return None


def gateway_doctor(gateway_dir: str) -> dict:
    """Fleet health from a gateway journal (rotation-aware)."""
    path = _journal_path(gateway_dir)
    if path is None:
        return {"directory": os.path.abspath(gateway_dir),
                "error": "no journal (*.jsonl) found", "ok": False}
    records: list[dict] = []
    rotated = path + ".1"
    if os.path.isfile(rotated):
        records.extend(Journal.read(rotated))
    records.extend(Journal.read(path))

    t_end = 0.0
    replicas: dict[str, dict] = {}
    accepted: dict[int, dict] = {}
    done: set[int] = set()
    failovers: list[dict] = []
    parked: list[int] = []
    hedges = {"dispatched": 0, "won": 0, "lost": 0}
    breaker: list[dict] = []
    degrade: list[dict] = []
    rejects: dict[str, int] = {}
    faults: list[dict] = []

    def rep(name: str) -> dict:
        return replicas.setdefault(name, {
            "last_heartbeat_t": None, "steps": 0, "failed_over": False,
            "fault": None, "breaker_opens": 0})

    for r in records:
        name = r.get("name")
        t = r.get("t")
        if isinstance(t, (int, float)):
            t_end = max(t_end, t)
        if name == "serve.step":
            info = rep(r.get("replica", "?"))
            info["steps"] += 1
            if isinstance(t, (int, float)):
                info["last_heartbeat_t"] = t
        elif name == "gateway.request":
            accepted[r.get("rid")] = {
                "tenant": r.get("tenant"),
                "replica": r.get("replica")}
        elif name == "serve.request_done":
            done.add(r.get("rid"))
        elif name == "gateway.reject":
            kind = r.get("kind", "?")
            rejects[kind] = rejects.get(kind, 0) + 1
        elif name == "gateway.failover":
            if r.get("kind") == "parked":
                parked.append(r.get("rid"))
            else:
                failovers.append({
                    "t": t, "replica": r.get("replica"),
                    "reason": r.get("reason"),
                    "n_requeued": r.get("n_requeued"),
                    "rids": r.get("rids")})
                rep(r.get("replica", "?"))["failed_over"] = True
        elif name == "gateway.hedge":
            if r.get("kind") == "dispatch":
                hedges["dispatched"] += 1
            elif r.get("kind") == "win":
                key = ("won" if r.get("winner") == "hedge" else "lost")
                hedges[key] += 1
        elif name == "gateway.breaker":
            breaker.append({"t": t, "replica": r.get("replica"),
                            "from": r.get("from"), "to": r.get("to")})
            if r.get("to") == "open":
                rep(r.get("replica", "?"))["breaker_opens"] += 1
        elif name in ("gateway.degrade", "gateway.restore"):
            degrade.append({
                "t": t, "kind": name.split(".", 1)[1],
                "level": r.get("level"), "prev": r.get("prev"),
                "reason": r.get("reason"),
                "shed_classes": r.get("shed_classes")})
        elif name == "chaos.fault":
            faults.append({"t": t, "kind": r.get("kind"),
                           "replica": r.get("replica")})
            rep(r.get("replica", "?"))["fault"] = r.get("kind")

    for info in replicas.values():
        hb = info["last_heartbeat_t"]
        info["heartbeat_age_s"] = (round(t_end - hb, 6)
                                   if hb is not None else None)

    lost = sorted(rid for rid in accepted if rid not in done)
    # "who broke the cohort": the earliest hard failure signal —
    # a failover beats a breaker-open beats an injected fault
    culprit = None
    candidates = (
        [(f["t"], "failover", f["replica"]) for f in failovers]
        + [(b["t"], "breaker_open", b["replica"])
           for b in breaker if b["to"] == "open"]
        + [(f["t"], f"fault:{f['kind']}", f["replica"])
           for f in faults])
    if candidates:
        t0, how, who = min(candidates,
                           key=lambda c: (c[0] if c[0] is not None
                                          else float("inf")))
        culprit = {"replica": who, "how": how, "t": t0}

    return {
        "directory": os.path.abspath(gateway_dir),
        "journal": path,
        "rotated_generation": os.path.isfile(rotated),
        "n_records": len(records),
        "replicas": {k: replicas[k] for k in sorted(replicas)},
        "accepted": len(accepted),
        "done": len(done & set(accepted)),
        "lost_rids": lost,
        "rejects": rejects,
        "failovers": failovers,
        "parked_rids": parked,
        "hedges": hedges,
        "breaker_transitions": breaker,
        "degrade_history": degrade,
        "culprit": culprit,
        "ok": not lost,
    }


def format_gateway_doctor(doc: dict) -> str:
    if doc.get("error"):
        return (f"gateway dir: {doc['directory']}\n"
                f"error: {doc['error']}")
    lines = [f"gateway journal: {doc['journal']}"
             + (" (+ rotated generation)"
                if doc.get("rotated_generation") else "")]
    lines.append(
        f"requests: {doc['accepted']} accepted, {doc['done']} done, "
        f"{len(doc['lost_rids'])} lost"
        + (f", rejects {doc['rejects']}" if doc["rejects"] else ""))
    for name, info in doc.get("replicas", {}).items():
        age = info.get("heartbeat_age_s")
        bits = [f"{info['steps']} steps",
                ("last beat " + (f"{age:.3f}s before end"
                                 if age is not None else "never"))]
        if info.get("failed_over"):
            bits.append("FAILED OVER")
        if info.get("breaker_opens"):
            bits.append(f"breaker opened x{info['breaker_opens']}")
        if info.get("fault"):
            bits.append(f"injected fault: {info['fault']}")
        lines.append(f"  {name}: " + ", ".join(bits))
    for f in doc.get("failovers", []):
        lines.append(
            f"failover: {f['replica']} ({f['reason']}) salvaged "
            f"{f['n_requeued']} request(s) at t={f['t']:.3f}s")
    h = doc.get("hedges", {})
    if h.get("dispatched"):
        lines.append(f"hedges: {h['dispatched']} dispatched, "
                     f"{h['won']} won, {h['lost']} lost")
    for d in doc.get("degrade_history", []):
        lines.append(f"{d['kind']}: level {d.get('prev')} -> "
                     f"{d['level']} ({d.get('reason') or '?'})"
                     + (f", shed {d['shed_classes']}"
                        if d.get("shed_classes") else ""))
    c = doc.get("culprit")
    if c:
        lines.append(f"cohort broken first by: {c['replica']} "
                     f"({c['how']}, t={c['t']:.3f}s)")
    lines.append("verdict: "
                 + ("OK — every accepted request completed"
                    if doc.get("ok")
                    else f"LOST {len(doc['lost_rids'])} request(s): "
                         f"{doc['lost_rids'][:16]}"))
    return "\n".join(lines)
