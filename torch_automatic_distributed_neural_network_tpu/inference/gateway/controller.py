"""Closed-loop SLO autoscaler: live windows in, replica resizes out.

This is the piece that turns the observability stack from a report
into a control system.  The controller consumes the SAME telemetry the
offline tools do — ``LiveAggregator`` event-time windows folded from
the gateway's journal stream, judged by ``SLOMonitor``'s hysteresis
state machine — and on a sustained breach asks the planner's serving
replay (``tune/simulate.replay_serve``: the REAL scheduler policy on
virtual time) what the cheapest replica count restoring the SLO is.
The answer becomes a ``gateway.replan`` journal event plus a live
resize: scale-out adds replicas (prewarmed through the export cache
when the factory supports it), scale-in drains the victim through the
scheduler's requeue path and resubmits its requests through the
router.  Scale-in is the mirrored conservative path: only after
``scale_in_after`` consecutive clean windows, and only when the replay
predicts n-1 replicas still meet the SLO.

Everything runs on the gateway's injected clock and pure record
streams — zero wall-clock reads, zero sleeps — so a chaos scenario
(traffic flip → breach → replan → recover) replays byte-identically
in CI.  The prediction source is deliberately the planner, not a
reactive step rule: production autoscalers that resize on raw
utilization oscillate under bursty serving traffic; simulating the
candidate fleet against the measured mix prices queueing effects the
way TorchTitan-style elastic runtimes price reshard cost before
committing (PAPERS.md, arxiv 2410.06511).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ...obs.live import LiveAggregator
from ...obs.slo_monitor import MonitorPolicy, SLOMonitor
from ...tune.slo import SLOSpec


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Knobs of the closed loop (CLI: ``tadnn gateway --autoscale``)."""

    slo: SLOSpec = dataclasses.field(default_factory=SLOSpec)
    window_s: float = 1.0
    breach_after: int = 2         # hysteresis: windows before breach
    recover_after: int = 2        # ... and before recovery
    warmup_windows: int = 1
    min_replicas: int = 1
    max_replicas: int = 8
    # windows to hold fire after any resize (the new fleet needs at
    # least the hysteresis span to show up in the measurements)
    cooldown_windows: int = 4
    # consecutive clean windows before a scale-IN is even considered
    scale_in_after: int = 8
    # degraded modes: consecutive breached windows WITH the fleet
    # already at max_replicas before the controller walks the gateway
    # one degrade level down (shed/starve instead of scale); 0
    # disables.  Each further streak of the same length degrades one
    # more level, and recovery restores one level per clean streak.
    degrade_after: int = 4
    # candidate-evaluation traffic model: the replay must cover a
    # SUSTAINED stretch of the measured arrival rate — a too-short
    # burst drains inside the sim and under-prices queueing, which is
    # exactly the overload case the replan exists for.  ``sim_horizon_s``
    # seconds of traffic, capped at ``sim_requests`` arrivals.
    sim_horizon_s: float = 4.0
    sim_requests: int = 384
    sim_jitter: float = 0.0
    sim_seed: int = 0


class FleetController:
    """Feeds windows to the monitor; resizes the fleet on its verdicts.

    ``offer(record)`` is the only input — the gateway taps its journal
    and pushes every record here.  The controller never reads a clock
    and never sleeps; all its state advances on record event-time.
    """

    def __init__(self, gateway, policy: AutoscalePolicy, *,
                 journal=None):
        self.gateway = gateway
        self.policy = policy
        self.journal = journal
        self.aggregator = LiveAggregator(
            window_s=policy.window_s, clock=None)
        self.monitor = SLOMonitor(
            MonitorPolicy(slo=policy.slo, window_s=policy.window_s,
                          breach_after=policy.breach_after,
                          recover_after=policy.recover_after,
                          warmup_windows=policy.warmup_windows),
            journal=journal)
        self._cooldown = 0
        self._clean_streak = 0
        self._breach_at_max = 0
        self.replans: list[dict] = []
        self.windows_seen = 0

    # -- input ---------------------------------------------------------------

    def offer(self, rec: dict) -> None:
        name = rec.get("name", "")
        if not (isinstance(name, str) and name.startswith("serve.")):
            return
        for window in self.aggregator.add(rec):
            self._on_window(window)

    def finish(self) -> None:
        """Seal the in-progress window (end of a replayed scenario)."""
        w = self.aggregator.flush()
        if w is not None:
            self._on_window(w)

    # -- control law ---------------------------------------------------------

    def _on_window(self, window: dict) -> None:
        self.windows_seen += 1
        incident = self.monitor.observe(window)
        if self._cooldown > 0:
            self._cooldown -= 1
        breach_active = self.monitor.state == "breach"
        if breach_active:
            self._clean_streak = 0
        else:
            self._clean_streak += 1
        self._maybe_degrade(breach_active)
        if self._cooldown > 0:
            return
        n_now = self.gateway.n_active_replicas()
        if (incident and incident["kind"] == "breach") or (
                breach_active and n_now < self.policy.max_replicas):
            self._replan(window, reason="breach")
        elif (self._clean_streak >= self.policy.scale_in_after
              and n_now > self.policy.min_replicas):
            self._replan(window, reason="surplus")

    def _maybe_degrade(self, breach_active: bool) -> None:
        """Degrade ladder: when scaling out is no longer an option
        (breached AND at max_replicas) shedding load is — walk the
        gateway one level per sustained streak, and back one level per
        clean streak.  Degrade is NOT gated on the resize cooldown:
        shedding is the pressure valve for exactly the windows where a
        resize can't help."""
        pol = self.policy
        if pol.degrade_after <= 0 or not hasattr(self.gateway,
                                                 "set_degrade"):
            return
        at_max = (self.gateway.n_active_replicas()
                  >= pol.max_replicas)
        if breach_active and at_max:
            self._breach_at_max += 1
            if self._breach_at_max >= pol.degrade_after:
                self._breach_at_max = 0
                self.gateway.set_degrade(
                    self.gateway.degrade_level + 1,
                    reason="sustained breach at max fleet")
        else:
            self._breach_at_max = 0
            if (self.gateway.degrade_level > 0
                    and self._clean_streak >= pol.recover_after):
                self._clean_streak = 0
                self.gateway.set_degrade(
                    self.gateway.degrade_level - 1,
                    reason="slo recovered")

    def _replan(self, window: dict, *, reason: str) -> None:
        """Ask the serving replay for the cheapest compliant fleet
        shape under the measured traffic, journal the decision, and
        resize if it differs from the current fleet."""
        from ...tune.simulate import replay_serve

        pol = self.policy
        traffic = self.gateway.traffic_snapshot()
        n_now = self.gateway.n_active_replicas()
        if traffic["rate_per_s"] <= 0:
            return
        requests = self._candidate_requests(traffic)
        shape = self.gateway.replica_shape()
        candidates: list[dict] = []
        chosen = None
        for n in range(pol.min_replicas, pol.max_replicas + 1):
            # each replica sees a 1/n share of the measured arrivals:
            # same request list, arrival spacing stretched by n
            share = [(t * n, p, m, d) for t, p, m, d in requests]
            sim = replay_serve(
                share,
                n_slots=shape["n_slots"],
                block_size=shape["block_size"],
                max_len=shape["max_len"],
                admission=shape["admission"],
                prefill_chunk=shape["prefill_chunk"],
                prefill_chunks_per_step=shape["prefill_chunks_per_step"],
                decode_step_s=shape["decode_step_s"],
                prefill_chunk_s=shape["prefill_chunk_s"],
                prefix_cache=shape["prefix_cache"],
                shared_prefix=traffic.get("shared_prefix", 0),
            )
            pred = {
                "tok_s_per_chip": sim["tokens_per_s"],
                "p99_s": sim["p99_s"],
                "ttft_p99_s": sim["ttft_p99_s"],
                "itl_p99_s": sim["itl_p99_s"],
            }
            ok, violations = pol.slo.evaluate(pred)
            ok = ok and not sim["stalled"]
            candidates.append({
                "n_replicas": n, "ok": ok,
                "p99_s": sim["p99_s"], "ttft_p99_s": sim["ttft_p99_s"],
                "tok_s": sim["tokens_per_s"],
                "stalled": sim["stalled"],
                "violations": violations})
            if ok and chosen is None:
                chosen = n
                # later (larger) fleets only cost more; stop at the
                # cheapest compliant shape unless we still need the
                # full candidate table for the journal — we don't
                break
        if chosen is None:
            # nothing compliant within the cap: saturate at max — a
            # breached SLO with a maxed fleet is a capacity incident,
            # not a control error
            chosen = pol.max_replicas
        if reason == "breach":
            # a breach replan only ever grows the fleet: the replay
            # prices the CURRENT arrival rate, but the backlog that
            # tripped the SLO still has to drain — shrinking now would
            # re-breach immediately.  Scale-in waits for the surplus
            # path's clean-window streak.
            chosen = max(chosen, n_now)
        rec = {"reason": reason, "source": "tune.simulate.replay_serve",
               "current": n_now, "chosen": chosen,
               "window": window.get("window"),
               "rate_per_s": traffic["rate_per_s"],
               "prompt_mean": traffic["prompt_mean"],
               "decode_mean": traffic["decode_mean"],
               "candidates": candidates}
        self.replans.append(rec)
        if self.journal is not None:
            self.journal.event("gateway.replan", **rec)
        if chosen != n_now:
            self.gateway.scale_to(chosen, reason=reason)
        self._cooldown = pol.cooldown_windows

    def _candidate_requests(self, traffic: dict[str, Any]
                            ) -> list[tuple[float, int, int, int]]:
        from ...tune.simulate import TrafficMix

        pol = self.policy
        rate = max(traffic["rate_per_s"], 1e-6)
        n_req = max(32, min(pol.sim_requests,
                            int(rate * pol.sim_horizon_s)))
        mix = TrafficMix(
            rate_per_s=rate,
            n_requests=n_req,
            prompt_mean=max(1, int(traffic["prompt_mean"])),
            max_new=max(1, int(traffic["max_new"])),
            decode_mean=max(1, int(traffic["decode_mean"])),
            jitter=pol.sim_jitter, seed=pol.sim_seed,
            shared_prefix=int(traffic.get("shared_prefix", 0)))
        return mix.sample(max_len=self.gateway.replica_shape()["max_len"])

    def stats(self) -> dict:
        return {
            "windows": self.windows_seen,
            "replans": len(self.replans),
            "breaches": sum(1 for i in self.monitor.incidents
                            if i["kind"] == "breach"),
            "recoveries": sum(1 for i in self.monitor.incidents
                              if i["kind"] == "recover"),
            "state": self.monitor.state,
        }
