"""Async ingress: HTTP/SSE front door over a replica fleet.

Stdlib only (asyncio + json): the gateway is part of the serving
runtime, not a web-framework dependency.  One process owns N replicas,
a :class:`~.router.Router` placing requests by prefix affinity, and
(optionally) a :class:`~.controller.FleetController` resizing the
fleet against its SLO.  The HTTP layer streams tokens per request as
Server-Sent Events::

    POST /v1/generate        {"prompt": [1,2,3], "max_new_tokens": 16,
                              "tenant": "acme", "priority": "interactive"}
    -> 200 text/event-stream
       data: {"i": 0, "token": 42}
       ...
       data: {"done": true, "rid": 7, "usage": {...}}

Admission control happens BEFORE the scheduler ever sees a request:

- token-bucket rate limit per tenant (429; burst-tolerant, refilled on
  the injected clock);
- bounded in-flight queue per tenant (503 backpressure: a slow tenant
  queues against itself, not the fleet);
- priority classes ("interactive" < "batch") mapped onto
  ``Request.priority``, which the scheduler orders admission by.

Requests then flow through the SAME ``Scheduler``/``admission_plan``
interface and stamp the SAME ``serve.request_done`` spans as the
direct-engine path, so ``obs/live``, ``tadnn monitor`` and ``tadnn
report`` work unchanged on a gateway journal.

The :class:`Gateway` core is sync and clock-injected; the asyncio
server is a thin pump around it.  Tests and the chaos smoke drive
``Gateway.step()`` directly on virtual time — no sockets, no sleeps.
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque
from typing import Any, Callable, Sequence

from ...obs import journal as journal_mod
from ...obs.journal import Journal
from ..serve.scheduler import Request
from .controller import AutoscalePolicy, FleetController
from .fault import (
    MAX_DEGRADE_LEVEL,
    BreakerPolicy,
    CircuitBreaker,
    HedgePolicy,
    degrade_effects,
)
from .router import NoHealthyReplica, Router

PRIORITY_CLASSES = {"interactive": 0, "batch": 1}


class GatewayError(RuntimeError):
    status = 500
    # advisory back-off (seconds) the HTTP layer maps to a Retry-After
    # header on 429/503 — None means no estimate
    retry_after: float | None = None


class RateLimited(GatewayError):
    """Tenant exceeded its token-bucket rate (HTTP 429)."""
    status = 429


class Saturated(GatewayError):
    """Tenant's in-flight queue is full (HTTP 503 backpressure)."""
    status = 503


class TokenBucket:
    """Classic token bucket on an injected clock: ``rate_per_s``
    sustained, ``burst`` instantaneous."""

    def __init__(self, rate_per_s: float, burst: int, *,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self.clock = clock
        self.tokens = float(burst)
        self._last = clock()

    def try_take(self) -> bool:
        now = self.clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def seconds_until_token(self) -> float:
        """Refill time until the next ``try_take`` could succeed —
        the 429 response's Retry-After."""
        now = self.clock()
        tokens = min(self.burst,
                     self.tokens + (now - self._last) * self.rate)
        if tokens >= 1.0 or self.rate <= 0:
            return 0.0
        return (1.0 - tokens) / self.rate


class Gateway:
    """Sync, clock-injected gateway core: admission control, routing,
    fleet stepping, elastic resize.  The asyncio server and the chaos
    smoke are both thin loops over ``submit``/``step``."""

    def __init__(self, replicas: Sequence, *,
                 journal: Journal | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 router: Router | None = None,
                 autoscale: AutoscalePolicy | None = None,
                 make_replica: Callable[[str], Any] | None = None,
                 rate_limit_per_s: float | None = None,
                 burst: int | None = None,
                 queue_limit: int = 64,
                 router_policy: str = "affinity",
                 heartbeat_s: float | None = None,
                 hedge: HedgePolicy | None = None,
                 breaker: BreakerPolicy | None = None,
                 stream_retention: int = 65536,
                 step_costs: tuple[float, float] = (1e-3, 1e-3),
                 traffic_horizon_s: float = 8.0):
        if not replicas:
            raise ValueError("gateway needs at least one replica")
        self.clock = clock
        self.journal = (journal if journal is not None
                        else journal_mod.get_default())
        self.router = router or Router(
            replicas, block_size=replicas[0].block_size,
            policy=router_policy, clock=clock, journal=self.journal)
        self.make_replica = make_replica
        self._next_replica_idx = len(self.router.replicas)
        self.rate_limit_per_s = rate_limit_per_s
        self.burst = burst or (int(rate_limit_per_s * 2)
                               if rate_limit_per_s else 0)
        self.queue_limit = int(queue_limit)
        # (prefill_chunk_s, decode_step_s): the candidate-replay cost
        # model for the controller; SimReplica fleets pass the tick
        self.step_costs = step_costs
        self.traffic_horizon_s = float(traffic_horizon_s)
        self._buckets: dict[str, TokenBucket] = {}
        self._pending: dict[str, int] = {}       # tenant -> in flight
        self._meta: dict[int, dict] = {}         # rid -> bookkeeping
        # gateway-minted request ids: per-gateway, starting at 0, so a
        # virtual-clock scenario journals the SAME rids every run (the
        # scheduler's module-global counter is process-lifetime)
        self._next_rid = 0
        self._submits: deque = deque()           # (t, n_prompt, max_new, n_dec)
        self.n_accepted = 0
        self.n_rejected = 0
        self.n_done = 0
        # -- fault tolerance --------------------------------------------------
        # no heartbeat within this window => the replica is declared
        # dead and its in-flight work fails over (None disables)
        self.heartbeat_s = heartbeat_s
        self.hedge = hedge
        self.breaker_policy = breaker
        self._breakers: dict[str, CircuitBreaker] = {}
        self._replica_marker: dict[str, Any] = {}  # name -> last steps ctr
        if breaker is not None and self.router.gate is None:
            self.router.gate = self._breaker_allows
        # exactly-once token ledger: rid -> every token DELIVERED so
        # far, a monotone cursor over whichever copy of the request is
        # furthest along.  Preemption/failover shrink a copy's
        # out_tokens; the ledger never rolls back, so a resumed stream
        # is deduplicated (greedy recompute re-derives the same ids)
        self._delivered: dict[int, list[int]] = {}
        self._progress_t: dict[int, float] = {}  # rid -> last new token
        self._done_rids: deque = deque()
        self.stream_retention = int(stream_retention)
        self._rid_alias: dict[int, int] = {}     # engine hedge rid map
        self._orphans: list[Request] = []        # awaiting any replica
        self.n_failovers = 0
        self.n_hedges = 0
        self.n_hedge_wins = 0
        # degraded-mode state (fault.degrade_effects): level 0 = normal
        self.degrade_level = 0
        self.speculation_enabled = True
        self._admission_factor = 1.0
        self._shed_threshold: int | None = None
        self.controller = (FleetController(self, autoscale,
                                           journal=self.journal)
                           if autoscale is not None else None)
        if self.controller is not None:
            self.journal.subscribe(self.controller.offer)

    # -- admission -----------------------------------------------------------

    def _bucket(self, tenant: str) -> TokenBucket | None:
        if not self.rate_limit_per_s:
            return None
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = TokenBucket(
                self.rate_limit_per_s, self.burst, clock=self.clock)
        return b

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               tenant: str = "default",
               priority: int | str = "interactive",
               eos_id: int | None = None,
               n_decode: int | None = None) -> Request:
        """Admission-check, route, and queue one request.  Raises
        :class:`RateLimited` / :class:`Saturated` with the HTTP status
        the server maps them to; both are journaled so rejected load is
        visible in the report."""
        if isinstance(priority, str):
            if priority not in PRIORITY_CLASSES:
                raise ValueError(
                    f"unknown priority class {priority!r} "
                    f"(known: {sorted(PRIORITY_CLASSES)})")
            priority = PRIORITY_CLASSES[priority]
        # traffic is recorded at OFFER time, before admission: the
        # controller plans capacity against what clients are asking
        # for — planning against post-throttle throughput is the
        # classic autoscaler trap (a saturated fleet rejects its way
        # to a "healthy" accepted rate and never scales)
        self._submits.append((self.clock(), len(prompt),
                              int(max_new_tokens),
                              int(n_decode or max_new_tokens)))
        if (self._shed_threshold is not None
                and int(priority) >= self._shed_threshold):
            self.n_rejected += 1
            retry = self._queue_drain_estimate()
            self.journal.event("gateway.reject", kind="degraded",
                              tenant=tenant, priority=int(priority),
                              level=self.degrade_level,
                              retry_after=round(retry, 6))
            err = Saturated(
                f"priority class {int(priority)} shed at degrade "
                f"level {self.degrade_level}")
            err.retry_after = retry
            raise err
        bucket = self._bucket(tenant)
        if bucket is not None and not bucket.try_take():
            self.n_rejected += 1
            retry = bucket.seconds_until_token()
            self.journal.event("gateway.reject", kind="rate_limit",
                              tenant=tenant,
                              retry_after=round(retry, 6))
            err = RateLimited(f"tenant {tenant!r} over rate limit")
            err.retry_after = retry
            raise err
        limit = max(1, int(self.queue_limit * self._admission_factor))
        if self._pending.get(tenant, 0) >= limit:
            self.n_rejected += 1
            retry = self._queue_drain_estimate()
            self.journal.event("gateway.reject", kind="backpressure",
                              tenant=tenant,
                              pending=self._pending[tenant],
                              retry_after=round(retry, 6))
            err = Saturated(
                f"tenant {tenant!r} has {self._pending[tenant]} "
                f"requests in flight (limit {limit})")
            err.retry_after = retry
            raise err
        replica = self.router.route(prompt)
        rid = self._next_rid
        self._next_rid += 1
        req = replica.submit(prompt, max_new_tokens, eos_id=eos_id,
                             priority=int(priority), n_decode=n_decode,
                             rid=rid)
        if req.rid != rid:
            # EngineReplica mints its own rids; alias them back so the
            # ledger, hedging and failover all key on the gateway rid
            self._rid_alias[req.rid] = rid
        self._pending[tenant] = self._pending.get(tenant, 0) + 1
        self._meta[rid] = {"tenant": tenant, "replica": replica,
                           "n_decode": n_decode, "req": req,
                           "hedge": None, "n_hedges": 0}
        self._delivered[rid] = []
        self._progress_t[rid] = self.clock()
        self.n_accepted += 1
        self.journal.event("gateway.request", rid=rid,
                           tenant=tenant, priority=int(priority),
                           replica=replica.name, n_prompt=len(prompt))
        return req

    def _queue_drain_estimate(self) -> float:
        """Advisory Retry-After for 503s: pending work / fleet decode
        throughput, floored so clients never hot-loop."""
        pending = sum(self._pending.values())
        slots = sum(r.n_slots for r in self.active_replicas()) or 1
        decode_mean = 8.0
        if self._submits:
            decode_mean = (sum(s[3] for s in self._submits)
                           / len(self._submits))
        est = pending * decode_mean * self.step_costs[1] / slots
        return max(0.05, est)

    # -- serving loop --------------------------------------------------------

    def active_replicas(self) -> list:
        return [r for r in self.router.replicas
                if not r.retired and not r.draining]

    def n_active_replicas(self) -> int:
        return len(self.active_replicas())

    def idle(self) -> bool:
        return all(r.idle() for r in self.active_replicas())

    def step(self) -> list[Request]:
        """Advance every active replica one iteration; returns the
        requests that finished this step (pending counts released).
        The journal tap feeds the controller as records are written —
        a breach detected in this step's windows can resize the fleet
        before the next step.

        Fault-tolerance order matters: harvest tokens into the ledger
        BEFORE declaring anything dead (so failover never loses
        already-computed tokens), then breakers, then heartbeat
        failover, then hedging, then resolution."""
        self._place_orphans()
        finished: list[Request] = []
        for r in list(self.router.replicas):
            if r.retired:
                continue
            r.step()
            finished.extend(r.take_finished())
        now = self.clock()
        self._harvest(now)
        if self.breaker_policy is not None:
            self._feed_breakers(now)
        if self.heartbeat_s is not None:
            for r in list(self.router.replicas):
                if r.retired or r.draining:
                    continue
                if now - r.last_step_t > self.heartbeat_s:
                    self._failover(r, reason="heartbeat_expired")
        if self.hedge is not None:
            self._maybe_hedge(now)
        return self._resolve(finished)

    # -- fault tolerance -----------------------------------------------------

    def _gw_rid(self, rid: int) -> int:
        return self._rid_alias.get(rid, rid)

    def _breaker(self, name: str) -> CircuitBreaker:
        br = self._breakers.get(name)
        if br is None:
            br = self._breakers[name] = CircuitBreaker(
                name, self.breaker_policy, clock=self.clock,
                journal=self.journal)
        return br

    def _breaker_allows(self, replica) -> bool:
        br = self._breakers.get(replica.name)
        return br is None or br.allow()

    def _harvest(self, now: float) -> None:
        """Advance every rid's delivered-token ledger to the furthest
        copy.  The ledger is the exactly-once cursor: it only ever
        extends, so a preempted/failed-over copy whose out_tokens
        shrank is waited out (greedy recompute re-derives the same
        ids) and a hedged copy merges losslessly."""
        for rid, meta in self._meta.items():
            ledger = self._delivered.get(rid)
            if ledger is None:
                ledger = self._delivered[rid] = []
            best = meta["req"].out_tokens
            h = meta.get("hedge")
            if h is not None and len(h["req"].out_tokens) > len(best):
                best = h["req"].out_tokens
            if len(best) > len(ledger):
                ledger.extend(best[len(ledger):])
                self._progress_t[rid] = now

    def _feed_breakers(self, now: float) -> None:
        """One observation per loaded replica per step: ok iff its
        steps counter advanced.  A stalled-but-heartbeating replica
        accumulates failures and is opened out of routing before the
        heartbeat or the autoscaler can react."""
        for r in self.router.replicas:
            if r.retired:
                continue
            br = self._breaker(r.name)
            marker = getattr(r, "steps", None)
            if not getattr(r, "idle", lambda: True)():
                prev = self._replica_marker.get(r.name)
                br.observe(marker is None or marker != prev)
            self._replica_marker[r.name] = marker
            br.tick()

    def _failover(self, replica, *, reason: str) -> None:
        """Declare ``replica`` dead: drain its in-flight work through
        the scheduler's class-preserving requeue and re-route every
        request under its ORIGINAL rid.  Prefill restarts cheaply on
        the survivors via prefix-cache hits; the ledger guarantees the
        resumed stream is exactly-once."""
        salvaged = replica.drain()
        self.router.forget(replica.name)
        self.n_failovers += 1
        self.journal.event(
            "gateway.failover", kind="redispatch",
            replica=replica.name, reason=reason,
            n_requeued=len(salvaged),
            rids=[self._gw_rid(r.rid) for r in salvaged])
        self._redispatch(salvaged)

    def _redispatch(self, reqs: Sequence[Request], *,
                    quiet: bool = False) -> None:
        for req in reqs:
            rid = self._gw_rid(req.rid)
            meta = self._meta.get(rid)
            if meta is None:
                continue
            h = meta.get("hedge")
            if h is not None and req is h["req"]:
                # the dead replica held the hedge CLONE — drop it, the
                # primary copy elsewhere is still live
                meta["hedge"] = None
                if req.rid != rid:
                    self._rid_alias.pop(req.rid, None)
                continue
            try:
                target = self.router.route(req.prompt)
            except NoHealthyReplica:
                self._orphans.append(req)
                if not quiet:
                    self.journal.event("gateway.failover",
                                       kind="parked", rid=rid)
                continue
            target.resubmit(req, n_decode=meta.get("n_decode"))
            meta["replica"] = target

    def _place_orphans(self) -> None:
        """Retry requests salvaged while no replica was healthy."""
        if not self._orphans:
            return
        orphans, self._orphans = self._orphans, []
        self._redispatch(orphans, quiet=True)

    def _maybe_hedge(self, now: float) -> None:
        """Re-dispatch no-progress requests to a second replica under
        the same rid; first writer wins at resolve time."""
        pol = self.hedge
        for rid, meta in list(self._meta.items()):
            if meta.get("hedge") is not None:
                continue
            if meta["n_hedges"] >= pol.max_hedges_per_request:
                continue
            if now - self._progress_t.get(rid, now) < pol.after_s:
                continue
            current = meta["replica"]
            candidates = [r for r in self.router.healthy()
                          if r is not current]
            if not candidates:
                continue
            target = min(candidates, key=lambda r: r.load())
            req = meta["req"]
            clone = target.submit(
                list(req.prompt), req.max_new_tokens,
                eos_id=req.eos_id, priority=req.priority,
                n_decode=meta.get("n_decode"), rid=rid)
            if clone.rid != rid:
                self._rid_alias[clone.rid] = rid
            meta["hedge"] = {"req": clone, "replica": target}
            meta["n_hedges"] += 1
            self.n_hedges += 1
            self._progress_t[rid] = now
            self.journal.event(
                "gateway.hedge", kind="dispatch", rid=rid,
                replica=target.name, primary=current.name)

    def _resolve(self, finished: list[Request]) -> list[Request]:
        """Resolution with first-writer-wins hedge semantics: only the
        first copy of a rid to finish counts; the loser is cancelled
        on its replica and its finish (if it races in the same step)
        is ignored."""
        out: list[Request] = []
        for req in finished:
            rid = self._gw_rid(req.rid)
            meta = self._meta.pop(rid, None)
            if meta is None:
                continue  # the losing copy of an already-resolved rid
            h = meta.get("hedge")
            if h is not None:
                winner_is_hedge = req is h["req"]
                loser_req = meta["req"] if winner_is_hedge else h["req"]
                loser_rep = (meta["replica"] if winner_is_hedge
                             else h["replica"])
                if not getattr(loser_rep, "retired", False):
                    loser_rep.cancel(loser_req.rid)
                if winner_is_hedge:
                    self.n_hedge_wins += 1
                self.journal.event(
                    "gateway.hedge", kind="win", rid=rid,
                    winner=("hedge" if winner_is_hedge else "primary"))
            ledger = self._delivered.get(rid)
            if ledger is not None and len(req.out_tokens) > len(ledger):
                ledger.extend(req.out_tokens[len(ledger):])
            t = meta["tenant"]
            self._pending[t] = max(0, self._pending.get(t, 1) - 1)
            self.n_done += 1
            self._done_rids.append(rid)
            out.append(req)
        while len(self._done_rids) > self.stream_retention:
            old = self._done_rids.popleft()
            self._delivered.pop(old, None)
            self._progress_t.pop(old, None)
            # aliases (engine-minted rids) live until their stream is
            # trimmed so the HTTP pump can map finished requests back
            stale = [k for k, v in self._rid_alias.items() if v == old]
            for k in stale:
                del self._rid_alias[k]
        return out

    def delivered(self, rid: int) -> list[int]:
        """The exactly-once token stream for ``rid`` (a copy)."""
        return list(self._delivered.get(rid, ()))

    # -- degraded modes ------------------------------------------------------

    def set_degrade(self, level: int, *, reason: str = "") -> None:
        """Walk the degrade ladder (idempotent, journaled): level 1
        disables speculation and halves admission; level 2+ sheds
        priority classes lowest-first, never interactive."""
        level = max(0, min(MAX_DEGRADE_LEVEL, int(level)))
        if level == self.degrade_level:
            return
        rising = level > self.degrade_level
        effects = degrade_effects(
            level, list(PRIORITY_CLASSES.values()))
        self._apply_speculation(effects["speculation"])
        self._admission_factor = effects["admission_factor"]
        self._shed_threshold = effects["shed_threshold"]
        prev = self.degrade_level
        self.degrade_level = level
        self.journal.event(
            "gateway.degrade" if rising else "gateway.restore",
            level=level, prev=prev, reason=reason, **{
                k: v for k, v in effects.items() if k != "level"})

    def _apply_speculation(self, enabled: bool) -> None:
        if enabled == self.speculation_enabled:
            return
        self.speculation_enabled = enabled
        for r in self.router.replicas:
            engine = getattr(r, "engine", None)
            if engine is None or not hasattr(engine, "speculative"):
                continue
            if not enabled:
                r._stashed_speculative = engine.speculative
                engine.speculative = 0
            else:
                engine.speculative = getattr(
                    r, "_stashed_speculative", engine.speculative)

    def run_until_idle(self, *, max_steps: int = 100_000
                       ) -> list[Request]:
        out: list[Request] = []
        for _ in range(max_steps):
            if self.idle():
                break
            out.extend(self.step())
        return out

    # -- elastic resize ------------------------------------------------------

    def replica_shape(self) -> dict:
        """The active replicas' scheduling shape, for the controller's
        candidate replay (homogeneous fleet assumed)."""
        active = self.active_replicas()
        # after a failover storm the active set can momentarily be
        # empty; any replica's shape works (homogeneous fleet)
        r = active[0] if active else self.router.replicas[0]
        return {
            "n_slots": r.n_slots,
            "block_size": r.block_size,
            "max_len": r.max_len,
            "admission": getattr(r, "admission", "reserve"),
            "prefill_chunk": getattr(r, "prefill_chunk", 32) or 32,
            "prefill_chunks_per_step": getattr(
                r, "prefill_chunks_per_step", 1),
            "prefix_cache": getattr(r, "prefix_cache", None) is not None,
            "prefill_chunk_s": self.step_costs[0],
            "decode_step_s": self.step_costs[1],
        }

    def traffic_snapshot(self) -> dict:
        """The measured arrival process over the trailing horizon —
        what the controller simulates candidate fleets against."""
        now = self.clock()
        horizon = self.traffic_horizon_s
        while self._submits and self._submits[0][0] < now - horizon:
            self._submits.popleft()
        subs = list(self._submits)
        if not subs:
            return {"rate_per_s": 0.0, "prompt_mean": 1, "max_new": 1,
                    "decode_mean": 1, "shared_prefix": 0}
        span = max(now - subs[0][0], 1e-9)
        return {
            "rate_per_s": len(subs) / span,
            "prompt_mean": sum(s[1] for s in subs) / len(subs),
            "max_new": max(s[2] for s in subs),
            "decode_mean": sum(s[3] for s in subs) / len(subs),
            "shared_prefix": 0,
        }

    def scale_to(self, n: int, *, reason: str = "manual") -> None:
        """Resize the active fleet to ``n`` replicas.

        Scale-out: the ``make_replica`` factory builds each new replica
        (an engine factory resolves the export cache there, so the new
        engine's decode/prefill executables load AOT-compiled instead
        of tracing — the prewarm that makes scale-out fast).  Scale-in:
        retire the youngest replicas, drain each through the
        scheduler's requeue path, forget its router claims, and
        resubmit its requests through the router."""
        n = max(1, int(n))
        while self.n_active_replicas() < n:
            if self.make_replica is None:
                self.journal.event("gateway.scale", kind="blocked",
                                   reason="no replica factory")
                break
            name = f"replica{self._next_replica_idx}"
            self._next_replica_idx += 1
            replica = self.make_replica(name)
            self.router.replicas.append(replica)
            self.journal.event(
                "gateway.scale", kind="out", replica=name,
                reason=reason, n_replicas=self.n_active_replicas(),
                prewarmed=bool(getattr(replica, "prewarmed", False)))
        while self.n_active_replicas() > n:
            victim = self.active_replicas()[-1]
            drained = victim.drain()
            self.router.forget(victim.name)
            self.journal.event(
                "gateway.scale", kind="in", replica=victim.name,
                reason=reason, requeued=len(drained),
                n_replicas=self.n_active_replicas())
            self._redispatch(drained)

    # -- summary -------------------------------------------------------------

    def summary(self) -> dict:
        prefix = [r.prefix_stats() for r in self.router.replicas]
        out = {
            "n_replicas": self.n_active_replicas(),
            "accepted": self.n_accepted,
            "rejected": self.n_rejected,
            "done": self.n_done,
            "router": self.router.stats(),
            "prefix_hit_tokens": sum(p["hit_tokens"] for p in prefix),
            "prefix_queries": sum(p["queries"] for p in prefix),
            "prefix_hit_requests": sum(p["hit_requests"]
                                       for p in prefix),
            "failovers": self.n_failovers,
            "hedges": self.n_hedges,
            "hedge_wins": self.n_hedge_wins,
            "degrade_level": self.degrade_level,
            "parked": len(self._orphans),
        }
        if self._breakers:
            out["breakers"] = {name: br.state
                               for name, br in self._breakers.items()}
        if self.controller is not None:
            out["controller"] = self.controller.stats()
        return out


# -- asyncio HTTP/SSE layer ---------------------------------------------------


def _sse(data: dict) -> bytes:
    return f"data: {json.dumps(data)}\n\n".encode()


def _http_response(status: int, body: dict,
                   headers: dict[str, str] | None = None) -> bytes:
    payload = json.dumps(body).encode()
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              429: "Too Many Requests",
              503: "Service Unavailable"}.get(status, "Error")
    extra = "".join(f"{k}: {v}\r\n"
                    for k, v in (headers or {}).items())
    return (f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(payload)}\r\n"
            f"{extra}"
            f"Connection: close\r\n\r\n").encode() + payload


def _retry_headers(e: GatewayError) -> dict[str, str] | None:
    """RFC 7231 Retry-After (integer seconds, ceil, min 1) for
    throttle/backpressure responses carrying an estimate."""
    if e.retry_after is None:
        return None
    return {"Retry-After": str(max(1, int(-(-e.retry_after // 1))))}


_SSE_HEADER = (b"HTTP/1.1 200 OK\r\n"
               b"Content-Type: text/event-stream\r\n"
               b"Cache-Control: no-cache\r\n"
               b"Connection: close\r\n\r\n")


class HttpIngress:
    """Asyncio server pumping one :class:`Gateway`.

    A background task steps the gateway whenever any replica has work
    and fans fresh tokens out to per-request asyncio queues; request
    handlers await their queue and write SSE frames.  Everything runs
    on one event loop — the gateway core is not thread-safe and never
    needs to be."""

    def __init__(self, gateway: Gateway, *, host: str = "127.0.0.1",
                 port: int = 0, poll_s: float = 0.005):
        self.gateway = gateway
        self.host = host
        self.port = port
        self.poll_s = poll_s
        self._server: asyncio.AbstractServer | None = None
        self._pump_task: asyncio.Task | None = None
        self._streams: dict[int, asyncio.Queue] = {}
        self._sent: dict[int, int] = {}
        self._stopping = False

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._pump_task = asyncio.create_task(self._pump())

    async def stop(self) -> None:
        self._stopping = True
        if self._pump_task is not None:
            await self._pump_task
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- pump ----------------------------------------------------------------

    async def _pump(self) -> None:
        while not self._stopping:
            if self.gateway.idle() and not self._streams:
                await asyncio.sleep(self.poll_s)
                continue
            finished = (self.gateway.step()
                        if not self.gateway.idle() else [])
            gw = self.gateway
            for rid, q in list(self._streams.items()):
                # the gateway's delivered-token ledger IS the stream:
                # it survives preemption, failover and hedging and
                # only ever extends, so emitting everything past our
                # high-water mark is exactly-once by construction
                ledger = gw._delivered.get(rid)
                if ledger is None:
                    continue
                sent = self._sent.get(rid, 0)
                for i in range(sent, len(ledger)):
                    q.put_nowait({"i": i, "token": ledger[i]})
                self._sent[rid] = max(sent, len(ledger))
            for req in finished:
                rid = gw._gw_rid(req.rid)
                q = self._streams.get(rid)
                if q is not None:
                    total = (req.t_done - req.t_submit
                             if req.t_done is not None else None)
                    q.put_nowait({
                        "done": True, "rid": rid,
                        "usage": {"n_prompt": req.n_prompt,
                                  "n_new": req.n_generated,
                                  "cached_tokens": req.cached_tokens,
                                  "preempted": req.preempted,
                                  "total_s": total}})
            await asyncio.sleep(0)

    # -- connection handling -------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            await self._handle_inner(reader, writer)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _handle_inner(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        request_line = (await reader.readline()).decode("latin-1")
        parts = request_line.split()
        if len(parts) < 2:
            return
        method, path = parts[0], parts[1]
        headers: dict[str, str] = {}
        while True:
            line = (await reader.readline()).decode("latin-1").strip()
            if not line:
                break
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        if method == "GET" and path == "/healthz":
            gw = self.gateway
            writer.write(_http_response(200, {
                "ok": True, **gw.summary()}))
            await writer.drain()
            return
        if method != "POST" or path != "/v1/generate":
            writer.write(_http_response(404, {"error": "not found"}))
            await writer.drain()
            return
        n = int(headers.get("content-length", 0))
        body = await reader.readexactly(n) if n else b"{}"
        try:
            payload = json.loads(body)
            prompt = [int(t) for t in payload["prompt"]]
            max_new = int(payload.get("max_new_tokens", 16))
        except (ValueError, KeyError, TypeError) as e:
            writer.write(_http_response(400, {"error": str(e)}))
            await writer.drain()
            return
        try:
            req = self.gateway.submit(
                prompt, max_new,
                tenant=str(payload.get("tenant", "default")),
                priority=payload.get("priority", "interactive"),
                eos_id=payload.get("eos_id"))
        except (RateLimited, Saturated) as e:
            writer.write(_http_response(e.status, {"error": str(e)},
                                        headers=_retry_headers(e)))
            await writer.drain()
            return
        except (NoHealthyReplica, ValueError) as e:
            writer.write(_http_response(503, {"error": str(e)}))
            await writer.drain()
            return
        # key the stream by the GATEWAY rid (engines mint their own;
        # the ledger, failover and hedging all speak gateway rids)
        rid = self.gateway._gw_rid(req.rid)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[rid] = q
        self._sent[rid] = 0
        writer.write(_SSE_HEADER)
        await writer.drain()
        try:
            while True:
                item = await q.get()
                writer.write(_sse(item))
                await writer.drain()
                if item.get("done"):
                    break
        finally:
            self._streams.pop(rid, None)
            self._sent.pop(rid, None)


async def serve_forever(gateway: Gateway, *, host: str = "127.0.0.1",
                        port: int = 8080) -> None:
    """Run the ingress until cancelled (the CLI's --port mode)."""
    ingress = HttpIngress(gateway, host=host, port=port)
    await ingress.start()
    try:
        await asyncio.Event().wait()
    finally:
        await ingress.stop()


def sse_generate(host: str, port: int, payload: dict, *,
                 timeout: float = 60.0) -> list[dict]:
    """Blocking SSE client (stdlib http.client): POST a generate
    request, return every event frame.  Bench and tests drive real
    HTTP through this."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    body = json.dumps(payload)
    conn.request("POST", "/v1/generate", body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    if resp.status != 200:
        data = resp.read().decode()
        conn.close()
        raise GatewayError(f"HTTP {resp.status}: {data}")
    events: list[dict] = []
    buf = ""
    while True:
        chunk = resp.read(1024)
        if not chunk:
            break
        buf += chunk.decode()
        while "\n\n" in buf:
            frame, _, buf = buf.partition("\n\n")
            for line in frame.splitlines():
                if line.startswith("data: "):
                    events.append(json.loads(line[6:]))
            if events and events[-1].get("done"):
                conn.close()
                return events
    conn.close()
    return events
