"""Async ingress: HTTP/SSE front door over a replica fleet.

Stdlib only (asyncio + json): the gateway is part of the serving
runtime, not a web-framework dependency.  One process owns N replicas,
a :class:`~.router.Router` placing requests by prefix affinity, and
(optionally) a :class:`~.controller.FleetController` resizing the
fleet against its SLO.  The HTTP layer streams tokens per request as
Server-Sent Events::

    POST /v1/generate        {"prompt": [1,2,3], "max_new_tokens": 16,
                              "tenant": "acme", "priority": "interactive"}
    -> 200 text/event-stream
       data: {"i": 0, "token": 42}
       ...
       data: {"done": true, "rid": 7, "usage": {...}}

Admission control happens BEFORE the scheduler ever sees a request:

- token-bucket rate limit per tenant (429; burst-tolerant, refilled on
  the injected clock);
- bounded in-flight queue per tenant (503 backpressure: a slow tenant
  queues against itself, not the fleet);
- priority classes ("interactive" < "batch") mapped onto
  ``Request.priority``, which the scheduler orders admission by.

Requests then flow through the SAME ``Scheduler``/``admission_plan``
interface and stamp the SAME ``serve.request_done`` spans as the
direct-engine path, so ``obs/live``, ``tadnn monitor`` and ``tadnn
report`` work unchanged on a gateway journal.

The :class:`Gateway` core is sync and clock-injected; the asyncio
server is a thin pump around it.  Tests and the chaos smoke drive
``Gateway.step()`` directly on virtual time — no sockets, no sleeps.
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque
from typing import Any, Callable, Sequence

from ...obs import journal as journal_mod
from ...obs.journal import Journal
from ..serve.scheduler import Request
from .controller import AutoscalePolicy, FleetController
from .router import NoHealthyReplica, Router

PRIORITY_CLASSES = {"interactive": 0, "batch": 1}


class GatewayError(RuntimeError):
    status = 500


class RateLimited(GatewayError):
    """Tenant exceeded its token-bucket rate (HTTP 429)."""
    status = 429


class Saturated(GatewayError):
    """Tenant's in-flight queue is full (HTTP 503 backpressure)."""
    status = 503


class TokenBucket:
    """Classic token bucket on an injected clock: ``rate_per_s``
    sustained, ``burst`` instantaneous."""

    def __init__(self, rate_per_s: float, burst: int, *,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self.clock = clock
        self.tokens = float(burst)
        self._last = clock()

    def try_take(self) -> bool:
        now = self.clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class Gateway:
    """Sync, clock-injected gateway core: admission control, routing,
    fleet stepping, elastic resize.  The asyncio server and the chaos
    smoke are both thin loops over ``submit``/``step``."""

    def __init__(self, replicas: Sequence, *,
                 journal: Journal | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 router: Router | None = None,
                 autoscale: AutoscalePolicy | None = None,
                 make_replica: Callable[[str], Any] | None = None,
                 rate_limit_per_s: float | None = None,
                 burst: int | None = None,
                 queue_limit: int = 64,
                 router_policy: str = "affinity",
                 step_costs: tuple[float, float] = (1e-3, 1e-3),
                 traffic_horizon_s: float = 8.0):
        if not replicas:
            raise ValueError("gateway needs at least one replica")
        self.clock = clock
        self.journal = (journal if journal is not None
                        else journal_mod.get_default())
        self.router = router or Router(
            replicas, block_size=replicas[0].block_size,
            policy=router_policy, clock=clock, journal=self.journal)
        self.make_replica = make_replica
        self._next_replica_idx = len(self.router.replicas)
        self.rate_limit_per_s = rate_limit_per_s
        self.burst = burst or (int(rate_limit_per_s * 2)
                               if rate_limit_per_s else 0)
        self.queue_limit = int(queue_limit)
        # (prefill_chunk_s, decode_step_s): the candidate-replay cost
        # model for the controller; SimReplica fleets pass the tick
        self.step_costs = step_costs
        self.traffic_horizon_s = float(traffic_horizon_s)
        self._buckets: dict[str, TokenBucket] = {}
        self._pending: dict[str, int] = {}       # tenant -> in flight
        self._meta: dict[int, dict] = {}         # rid -> bookkeeping
        # gateway-minted request ids: per-gateway, starting at 0, so a
        # virtual-clock scenario journals the SAME rids every run (the
        # scheduler's module-global counter is process-lifetime)
        self._next_rid = 0
        self._submits: deque = deque()           # (t, n_prompt, max_new, n_dec)
        self.n_accepted = 0
        self.n_rejected = 0
        self.n_done = 0
        self.controller = (FleetController(self, autoscale,
                                           journal=self.journal)
                           if autoscale is not None else None)
        if self.controller is not None:
            self.journal.subscribe(self.controller.offer)

    # -- admission -----------------------------------------------------------

    def _bucket(self, tenant: str) -> TokenBucket | None:
        if not self.rate_limit_per_s:
            return None
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = TokenBucket(
                self.rate_limit_per_s, self.burst, clock=self.clock)
        return b

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               tenant: str = "default",
               priority: int | str = "interactive",
               eos_id: int | None = None,
               n_decode: int | None = None) -> Request:
        """Admission-check, route, and queue one request.  Raises
        :class:`RateLimited` / :class:`Saturated` with the HTTP status
        the server maps them to; both are journaled so rejected load is
        visible in the report."""
        if isinstance(priority, str):
            if priority not in PRIORITY_CLASSES:
                raise ValueError(
                    f"unknown priority class {priority!r} "
                    f"(known: {sorted(PRIORITY_CLASSES)})")
            priority = PRIORITY_CLASSES[priority]
        # traffic is recorded at OFFER time, before admission: the
        # controller plans capacity against what clients are asking
        # for — planning against post-throttle throughput is the
        # classic autoscaler trap (a saturated fleet rejects its way
        # to a "healthy" accepted rate and never scales)
        self._submits.append((self.clock(), len(prompt),
                              int(max_new_tokens),
                              int(n_decode or max_new_tokens)))
        bucket = self._bucket(tenant)
        if bucket is not None and not bucket.try_take():
            self.n_rejected += 1
            self.journal.event("gateway.reject", kind="rate_limit",
                              tenant=tenant)
            raise RateLimited(f"tenant {tenant!r} over rate limit")
        if self._pending.get(tenant, 0) >= self.queue_limit:
            self.n_rejected += 1
            self.journal.event("gateway.reject", kind="backpressure",
                              tenant=tenant,
                              pending=self._pending[tenant])
            raise Saturated(
                f"tenant {tenant!r} has {self._pending[tenant]} "
                f"requests in flight (limit {self.queue_limit})")
        replica = self.router.route(prompt)
        rid = self._next_rid
        self._next_rid += 1
        req = replica.submit(prompt, max_new_tokens, eos_id=eos_id,
                             priority=int(priority), n_decode=n_decode,
                             rid=rid)
        self._pending[tenant] = self._pending.get(tenant, 0) + 1
        self._meta[req.rid] = {"tenant": tenant, "replica": replica,
                               "n_decode": n_decode, "req": req}
        self.n_accepted += 1
        self.journal.event("gateway.request", rid=req.rid,
                           tenant=tenant, priority=int(priority),
                           replica=replica.name, n_prompt=len(prompt))
        return req

    # -- serving loop --------------------------------------------------------

    def active_replicas(self) -> list:
        return [r for r in self.router.replicas
                if not r.retired and not r.draining]

    def n_active_replicas(self) -> int:
        return len(self.active_replicas())

    def idle(self) -> bool:
        return all(r.idle() for r in self.active_replicas())

    def step(self) -> list[Request]:
        """Advance every active replica one iteration; returns the
        requests that finished this step (pending counts released).
        The journal tap feeds the controller as records are written —
        a breach detected in this step's windows can resize the fleet
        before the next step."""
        finished: list[Request] = []
        for r in list(self.router.replicas):
            if r.retired:
                continue
            r.step()
            finished.extend(r.take_finished())
        for req in finished:
            meta = self._meta.pop(req.rid, None)
            if meta is not None:
                t = meta["tenant"]
                self._pending[t] = max(0, self._pending.get(t, 1) - 1)
            self.n_done += 1
        return finished

    def run_until_idle(self, *, max_steps: int = 100_000
                       ) -> list[Request]:
        out: list[Request] = []
        for _ in range(max_steps):
            if self.idle():
                break
            out.extend(self.step())
        return out

    # -- elastic resize ------------------------------------------------------

    def replica_shape(self) -> dict:
        """The active replicas' scheduling shape, for the controller's
        candidate replay (homogeneous fleet assumed)."""
        r = self.active_replicas()[0]
        return {
            "n_slots": r.n_slots,
            "block_size": r.block_size,
            "max_len": r.max_len,
            "admission": getattr(r, "admission", "reserve"),
            "prefill_chunk": getattr(r, "prefill_chunk", 32) or 32,
            "prefill_chunks_per_step": getattr(
                r, "prefill_chunks_per_step", 1),
            "prefix_cache": getattr(r, "prefix_cache", None) is not None,
            "prefill_chunk_s": self.step_costs[0],
            "decode_step_s": self.step_costs[1],
        }

    def traffic_snapshot(self) -> dict:
        """The measured arrival process over the trailing horizon —
        what the controller simulates candidate fleets against."""
        now = self.clock()
        horizon = self.traffic_horizon_s
        while self._submits and self._submits[0][0] < now - horizon:
            self._submits.popleft()
        subs = list(self._submits)
        if not subs:
            return {"rate_per_s": 0.0, "prompt_mean": 1, "max_new": 1,
                    "decode_mean": 1, "shared_prefix": 0}
        span = max(now - subs[0][0], 1e-9)
        return {
            "rate_per_s": len(subs) / span,
            "prompt_mean": sum(s[1] for s in subs) / len(subs),
            "max_new": max(s[2] for s in subs),
            "decode_mean": sum(s[3] for s in subs) / len(subs),
            "shared_prefix": 0,
        }

    def scale_to(self, n: int, *, reason: str = "manual") -> None:
        """Resize the active fleet to ``n`` replicas.

        Scale-out: the ``make_replica`` factory builds each new replica
        (an engine factory resolves the export cache there, so the new
        engine's decode/prefill executables load AOT-compiled instead
        of tracing — the prewarm that makes scale-out fast).  Scale-in:
        retire the youngest replicas, drain each through the
        scheduler's requeue path, forget its router claims, and
        resubmit its requests through the router."""
        n = max(1, int(n))
        while self.n_active_replicas() < n:
            if self.make_replica is None:
                self.journal.event("gateway.scale", kind="blocked",
                                   reason="no replica factory")
                break
            name = f"replica{self._next_replica_idx}"
            self._next_replica_idx += 1
            replica = self.make_replica(name)
            self.router.replicas.append(replica)
            self.journal.event(
                "gateway.scale", kind="out", replica=name,
                reason=reason, n_replicas=self.n_active_replicas(),
                prewarmed=bool(getattr(replica, "prewarmed", False)))
        while self.n_active_replicas() > n:
            victim = self.active_replicas()[-1]
            drained = victim.drain()
            self.router.forget(victim.name)
            self.journal.event(
                "gateway.scale", kind="in", replica=victim.name,
                reason=reason, requeued=len(drained),
                n_replicas=self.n_active_replicas())
            for req in drained:
                meta = self._meta.get(req.rid)
                target = self.router.route(req.prompt)
                target.resubmit(
                    req, n_decode=(meta or {}).get("n_decode"))
                if meta is not None:
                    meta["replica"] = target

    # -- summary -------------------------------------------------------------

    def summary(self) -> dict:
        prefix = [r.prefix_stats() for r in self.router.replicas]
        out = {
            "n_replicas": self.n_active_replicas(),
            "accepted": self.n_accepted,
            "rejected": self.n_rejected,
            "done": self.n_done,
            "router": self.router.stats(),
            "prefix_hit_tokens": sum(p["hit_tokens"] for p in prefix),
            "prefix_queries": sum(p["queries"] for p in prefix),
            "prefix_hit_requests": sum(p["hit_requests"]
                                       for p in prefix),
        }
        if self.controller is not None:
            out["controller"] = self.controller.stats()
        return out


# -- asyncio HTTP/SSE layer ---------------------------------------------------


def _sse(data: dict) -> bytes:
    return f"data: {json.dumps(data)}\n\n".encode()


def _http_response(status: int, body: dict) -> bytes:
    payload = json.dumps(body).encode()
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              429: "Too Many Requests",
              503: "Service Unavailable"}.get(status, "Error")
    return (f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(payload)}\r\n"
            f"Connection: close\r\n\r\n").encode() + payload


_SSE_HEADER = (b"HTTP/1.1 200 OK\r\n"
               b"Content-Type: text/event-stream\r\n"
               b"Cache-Control: no-cache\r\n"
               b"Connection: close\r\n\r\n")


class HttpIngress:
    """Asyncio server pumping one :class:`Gateway`.

    A background task steps the gateway whenever any replica has work
    and fans fresh tokens out to per-request asyncio queues; request
    handlers await their queue and write SSE frames.  Everything runs
    on one event loop — the gateway core is not thread-safe and never
    needs to be."""

    def __init__(self, gateway: Gateway, *, host: str = "127.0.0.1",
                 port: int = 0, poll_s: float = 0.005):
        self.gateway = gateway
        self.host = host
        self.port = port
        self.poll_s = poll_s
        self._server: asyncio.AbstractServer | None = None
        self._pump_task: asyncio.Task | None = None
        self._streams: dict[int, asyncio.Queue] = {}
        self._sent: dict[int, int] = {}
        self._stopping = False

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._pump_task = asyncio.create_task(self._pump())

    async def stop(self) -> None:
        self._stopping = True
        if self._pump_task is not None:
            await self._pump_task
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- pump ----------------------------------------------------------------

    async def _pump(self) -> None:
        while not self._stopping:
            if self.gateway.idle() and not self._streams:
                await asyncio.sleep(self.poll_s)
                continue
            finished = (self.gateway.step()
                        if not self.gateway.idle() else [])
            for rid, q in list(self._streams.items()):
                req = self.gateway._meta.get(rid, {}).get("req")
                if req is None:
                    req = next((r for r in finished if r.rid == rid),
                               None)
                if req is None:
                    continue
                sent = self._sent.get(rid, 0)
                # a preempted request regenerates from scratch: its
                # out_tokens shrank below what we already streamed —
                # greedy recompute reproduces the same ids, so wait
                # silently until it passes the high-water mark
                for i in range(sent, len(req.out_tokens)):
                    q.put_nowait({"i": i, "token": req.out_tokens[i]})
                self._sent[rid] = max(sent, len(req.out_tokens))
            for req in finished:
                q = self._streams.get(req.rid)
                if q is not None:
                    total = (req.t_done - req.t_submit
                             if req.t_done is not None else None)
                    q.put_nowait({
                        "done": True, "rid": req.rid,
                        "usage": {"n_prompt": req.n_prompt,
                                  "n_new": req.n_generated,
                                  "cached_tokens": req.cached_tokens,
                                  "preempted": req.preempted,
                                  "total_s": total}})
            await asyncio.sleep(0)

    # -- connection handling -------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            await self._handle_inner(reader, writer)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _handle_inner(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        request_line = (await reader.readline()).decode("latin-1")
        parts = request_line.split()
        if len(parts) < 2:
            return
        method, path = parts[0], parts[1]
        headers: dict[str, str] = {}
        while True:
            line = (await reader.readline()).decode("latin-1").strip()
            if not line:
                break
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        if method == "GET" and path == "/healthz":
            gw = self.gateway
            writer.write(_http_response(200, {
                "ok": True, **gw.summary()}))
            await writer.drain()
            return
        if method != "POST" or path != "/v1/generate":
            writer.write(_http_response(404, {"error": "not found"}))
            await writer.drain()
            return
        n = int(headers.get("content-length", 0))
        body = await reader.readexactly(n) if n else b"{}"
        try:
            payload = json.loads(body)
            prompt = [int(t) for t in payload["prompt"]]
            max_new = int(payload.get("max_new_tokens", 16))
        except (ValueError, KeyError, TypeError) as e:
            writer.write(_http_response(400, {"error": str(e)}))
            await writer.drain()
            return
        try:
            req = self.gateway.submit(
                prompt, max_new,
                tenant=str(payload.get("tenant", "default")),
                priority=payload.get("priority", "interactive"),
                eos_id=payload.get("eos_id"))
        except (RateLimited, Saturated) as e:
            writer.write(_http_response(e.status, {"error": str(e)}))
            await writer.drain()
            return
        except (NoHealthyReplica, ValueError) as e:
            writer.write(_http_response(503, {"error": str(e)}))
            await writer.drain()
            return
        q: asyncio.Queue = asyncio.Queue()
        self._streams[req.rid] = q
        self._sent[req.rid] = 0
        writer.write(_SSE_HEADER)
        await writer.drain()
        try:
            while True:
                item = await q.get()
                writer.write(_sse(item))
                await writer.drain()
                if item.get("done"):
                    break
        finally:
            self._streams.pop(req.rid, None)
            self._sent.pop(req.rid, None)


async def serve_forever(gateway: Gateway, *, host: str = "127.0.0.1",
                        port: int = 8080) -> None:
    """Run the ingress until cancelled (the CLI's --port mode)."""
    ingress = HttpIngress(gateway, host=host, port=port)
    await ingress.start()
    try:
        await asyncio.Event().wait()
    finally:
        await ingress.stop()


def sse_generate(host: str, port: int, payload: dict, *,
                 timeout: float = 60.0) -> list[dict]:
    """Blocking SSE client (stdlib http.client): POST a generate
    request, return every event frame.  Bench and tests drive real
    HTTP through this."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    body = json.dumps(payload)
    conn.request("POST", "/v1/generate", body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    if resp.status != 200:
        data = resp.read().decode()
        conn.close()
        raise GatewayError(f"HTTP {resp.status}: {data}")
    events: list[dict] = []
    buf = ""
    while True:
        chunk = resp.read(1024)
        if not chunk:
            break
        buf += chunk.decode()
        while "\n\n" in buf:
            frame, _, buf = buf.partition("\n\n")
            for line in frame.splitlines():
                if line.startswith("data: "):
                    events.append(json.loads(line[6:]))
            if events and events[-1].get("done"):
                conn.close()
                return events
    conn.close()
    return events
