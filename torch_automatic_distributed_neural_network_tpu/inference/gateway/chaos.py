"""Chaos-traffic autoscale scenario: the gateway's closed-loop proof.

A scripted traffic schedule — gentle mix, a mid-run flip to a heavy
mix (higher rate AND longer decodes), then back — drives a fleet of
:class:`~.router.SimReplica` under an autoscaling
:class:`~.ingress.Gateway`, everything on one virtual clock:

1. the flip saturates the fleet; measured p99 climbs through the SLO;
2. ``SLOMonitor`` journals ``slo.breach`` after its hysteresis count;
3. the controller replans from the measured mix via the serving
   replay and journals ``gateway.replan`` + ``gateway.scale`` events
   as it grows the fleet;
4. the grown fleet drains the backlog; windows go clean;
   ``slo.recover`` lands.

Because every timestamp, arrival, admission decision and journal
record derives from the injected clock and seeded mixes, running the
scenario twice produces an IDENTICAL event sequence — ``chaos_smoke``
runs it twice and diffs the normalized journals, which is the CI
gate's determinism assertion (no sleeps, no wall-clock reads, no
tolerance bands).
"""

from __future__ import annotations

import dataclasses
import json

from ...obs.journal import Journal
from ...tune.simulate import TrafficMix
from ...tune.slo import SLOSpec
from .controller import AutoscalePolicy
from .ingress import Gateway, GatewayError
from .router import SimReplica

# shared system-prompt head: identical across every request so the
# radix index (and the router's affinity map) has something to reuse
SHARED_PREFIX = 16


@dataclasses.dataclass(frozen=True)
class ChaosPhase:
    t0: float
    span_s: float
    mix: TrafficMix


def phases(scale: str = "smoke") -> list[ChaosPhase]:
    """The scripted schedule.  ``smoke`` is the CI scenario (2 -> ~8
    replicas); ``light`` is the faster tier-1 test variant."""

    def mix(rate, max_new, seed, n):
        return TrafficMix(rate_per_s=rate, n_requests=n,
                          prompt_mean=24, max_new=max_new,
                          decode_mean=max_new, jitter=0.0, seed=seed,
                          shared_prefix=SHARED_PREFIX)

    if scale == "gentle":
        # no flip: a healthy run whose journal must pass
        # ``tadnn monitor --replay --check`` with exit 0
        return [ChaosPhase(0.0, 4.0, mix(40.0, 8, 11, 200))]
    if scale == "light":
        return [
            ChaosPhase(0.0, 4.0, mix(40.0, 8, 11, 200)),
            ChaosPhase(4.0, 6.0, mix(240.0, 12, 12, 1700)),
            ChaosPhase(10.0, 6.0, mix(40.0, 8, 13, 280)),
        ]
    return [
        ChaosPhase(0.0, 6.0, mix(60.0, 8, 11, 420)),
        ChaosPhase(6.0, 10.0, mix(300.0, 16, 12, 3400)),
        ChaosPhase(16.0, 8.0, mix(60.0, 8, 13, 560)),
    ]


def arrivals(schedule: list[ChaosPhase], *, n_tenants: int = 8
             ) -> list[tuple[float, list[int], int, int, str]]:
    """Flatten the schedule into absolute-time submissions:
    ``(t, prompt, max_new, n_decode, tenant)``.  Prompts share a
    ``SHARED_PREFIX``-token head; tails are unique per request."""
    out: list[tuple[float, list[int], int, int, str]] = []
    uid = 0
    for phase in schedule:
        for arr, n_prompt, max_new, n_dec in phase.mix.sample(
                max_len=256):
            if arr > phase.span_s:
                break
            n_shared = min(SHARED_PREFIX, n_prompt - 1)
            prompt = ([1] * n_shared
                      + [100 + uid] * (n_prompt - n_shared))
            out.append((phase.t0 + arr, prompt, max_new, n_dec,
                        f"t{uid % n_tenants}"))
            uid += 1
    out.sort(key=lambda a: a[0])
    return out


def default_policy(slo_text: str = "p99_ms<=2500", *,
                   max_replicas: int = 8) -> AutoscalePolicy:
    return AutoscalePolicy(
        slo=SLOSpec.parse(slo_text), window_s=1.0,
        breach_after=2, recover_after=2, warmup_windows=1,
        min_replicas=1, max_replicas=max_replicas,
        cooldown_windows=3, scale_in_after=10_000)


def run_scenario(journal: Journal, *, clock: list[float] | None = None,
                 n_replicas: int = 2,
                 policy: AutoscalePolicy | None = None,
                 scale: str = "smoke", tick_s: float = 5e-3,
                 horizon_s: float = 90.0) -> dict:
    """One full pass of the scenario on a virtual clock; returns the
    gateway summary (the journal carries the event record).

    ``clock`` is a one-element list (the mutable time box) so the
    caller can hand the SAME virtual clock to the journal — the
    journal's ``t`` stamps must be virtual or the byte-for-byte
    determinism diff would see wall time."""
    policy = policy or default_policy()
    if clock is None:
        clock = [0.0]

    def now() -> float:
        return clock[0]

    def make(name: str) -> SimReplica:
        return SimReplica(name, n_slots=4, block_size=8, max_len=256,
                          prefill_chunk=8, clock=now, journal=journal)

    replicas = [make(f"replica{i}") for i in range(n_replicas)]
    gw = Gateway(replicas, journal=journal, clock=now,
                 autoscale=policy, make_replica=make, queue_limit=48,
                 step_costs=(tick_s, tick_s))
    plan = arrivals(phases(scale))
    i = 0
    while clock[0] < horizon_s and (i < len(plan) or not gw.idle()):
        t = clock[0]
        while i < len(plan) and plan[i][0] <= t:
            _, prompt, max_new, n_dec, tenant = plan[i]
            try:
                gw.submit(prompt, max_new, tenant=tenant, eos_id=0,
                          n_decode=n_dec)
            except GatewayError:
                pass  # counted by the gateway; journaled
            i += 1
        gw.step()
        clock[0] = t + tick_s
    if gw.controller is not None:
        gw.controller.finish()
    summary = gw.summary()
    summary["offered"] = len(plan)
    summary["virtual_s"] = clock[0]
    return summary


def _normalize(records: list[dict]) -> list[str]:
    """Canonical form for the determinism diff: drop the one
    legitimately nondeterministic field (wall time) and re-serialize.
    Everything else — virtual timestamps, decisions, counters — must
    match byte-for-byte across runs."""
    out = []
    for rec in records:
        out.append(json.dumps({k: v for k, v in rec.items()
                               if k != "wall"}, default=str))
    return out


def chaos_smoke(*, journal_path: str | None = None,
                n_replicas: int = 2, slo_text: str = "p99_ms<=2500",
                max_replicas: int = 8, scale: str = "smoke",
                autoscale: bool = True) -> dict:
    """Run the scenario TWICE (file-backed then in-memory journal),
    diff the normalized event sequences, and check the closed loop
    actually closed: breach -> replan -> scale -> recover, in order.

    Returns a summary dict with ``ok`` plus per-assertion booleans —
    the CLI smoke prints it as one JSON line and exits nonzero unless
    everything held."""
    policy = (default_policy(slo_text, max_replicas=max_replicas)
              if autoscale else None)

    def one(path: str | None) -> tuple[dict, list[dict]]:
        clock = [0.0]
        # the journal shares the scenario's virtual clock so record
        # ``t`` stamps are event-time, not wall-time — the whole point
        # of the twice-run diff below
        j = Journal(path, host0_only=False, clock=lambda: clock[0],
                    meta={"tool": "gateway-chaos"})
        with j:
            summary = run_scenario(j, clock=clock,
                                   n_replicas=n_replicas,
                                   policy=policy, scale=scale)
        records = (Journal.read(path) if path else list(j.records))
        return summary, records

    s1, r1 = one(journal_path)
    s2, r2 = one(None)
    seq1, seq2 = _normalize(r1), _normalize(r2)
    deterministic = seq1 == seq2

    def first_index(name: str) -> int:
        for idx, rec in enumerate(r1):
            if rec.get("name") == name:
                return idx
        return -1

    i_breach = first_index("slo.breach")
    i_replan = first_index("gateway.replan")
    i_scale = first_index("gateway.scale")
    i_recover = first_index("slo.recover")
    closed_loop = (0 <= i_breach <= i_replan <= i_scale
                   and i_scale <= i_recover) if autoscale else True
    ok = deterministic and closed_loop and s1["done"] > 0
    return {
        "ok": ok,
        "deterministic": deterministic,
        "closed_loop": closed_loop,
        "breach_at": i_breach, "replan_at": i_replan,
        "scale_at": i_scale, "recover_at": i_recover,
        "n_records": len(r1),
        "record_mismatch": (None if deterministic else next(
            (i for i, (a, b) in enumerate(zip(seq1, seq2)) if a != b),
            min(len(seq1), len(seq2)))),
        "names_seen": sorted({rec.get("name") for rec in r1
                              if rec.get("name")}),
        "run": s1,
    }
