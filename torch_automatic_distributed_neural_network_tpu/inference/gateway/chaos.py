"""Chaos-traffic autoscale scenario: the gateway's closed-loop proof.

A scripted traffic schedule — gentle mix, a mid-run flip to a heavy
mix (higher rate AND longer decodes), then back — drives a fleet of
:class:`~.router.SimReplica` under an autoscaling
:class:`~.ingress.Gateway`, everything on one virtual clock:

1. the flip saturates the fleet; measured p99 climbs through the SLO;
2. ``SLOMonitor`` journals ``slo.breach`` after its hysteresis count;
3. the controller replans from the measured mix via the serving
   replay and journals ``gateway.replan`` + ``gateway.scale`` events
   as it grows the fleet;
4. the grown fleet drains the backlog; windows go clean;
   ``slo.recover`` lands.

Because every timestamp, arrival, admission decision and journal
record derives from the injected clock and seeded mixes, running the
scenario twice produces an IDENTICAL event sequence — ``chaos_smoke``
runs it twice and diffs the normalized journals, which is the CI
gate's determinism assertion (no sleeps, no wall-clock reads, no
tolerance bands).
"""

from __future__ import annotations

import dataclasses
import json
import random

from ...obs.journal import Journal
from ...tune.simulate import TrafficMix
from ...tune.slo import SLOSpec
from .controller import AutoscalePolicy
from .fault import BreakerPolicy, HedgePolicy
from .ingress import Gateway, GatewayError
from .router import SimReplica

# shared system-prompt head: identical across every request so the
# radix index (and the router's affinity map) has something to reuse
SHARED_PREFIX = 16


@dataclasses.dataclass(frozen=True)
class ChaosPhase:
    t0: float
    span_s: float
    mix: TrafficMix


def phases(scale: str = "smoke") -> list[ChaosPhase]:
    """The scripted schedule.  ``smoke`` is the CI scenario (2 -> ~8
    replicas); ``light`` is the faster tier-1 test variant."""

    def mix(rate, max_new, seed, n):
        return TrafficMix(rate_per_s=rate, n_requests=n,
                          prompt_mean=24, max_new=max_new,
                          decode_mean=max_new, jitter=0.0, seed=seed,
                          shared_prefix=SHARED_PREFIX)

    if scale == "gentle":
        # no flip: a healthy run whose journal must pass
        # ``tadnn monitor --replay --check`` with exit 0
        return [ChaosPhase(0.0, 4.0, mix(40.0, 8, 11, 200))]
    if scale == "light":
        return [
            ChaosPhase(0.0, 4.0, mix(40.0, 8, 11, 200)),
            ChaosPhase(4.0, 6.0, mix(240.0, 12, 12, 1700)),
            ChaosPhase(10.0, 6.0, mix(40.0, 8, 13, 280)),
        ]
    return [
        ChaosPhase(0.0, 6.0, mix(60.0, 8, 11, 420)),
        ChaosPhase(6.0, 10.0, mix(300.0, 16, 12, 3400)),
        ChaosPhase(16.0, 8.0, mix(60.0, 8, 13, 560)),
    ]


def arrivals(schedule: list[ChaosPhase], *, n_tenants: int = 8
             ) -> list[tuple[float, list[int], int, int, str]]:
    """Flatten the schedule into absolute-time submissions:
    ``(t, prompt, max_new, n_decode, tenant)``.  Prompts share a
    ``SHARED_PREFIX``-token head; tails are unique per request."""
    out: list[tuple[float, list[int], int, int, str]] = []
    uid = 0
    for phase in schedule:
        for arr, n_prompt, max_new, n_dec in phase.mix.sample(
                max_len=256):
            if arr > phase.span_s:
                break
            n_shared = min(SHARED_PREFIX, n_prompt - 1)
            prompt = ([1] * n_shared
                      + [100 + uid] * (n_prompt - n_shared))
            out.append((phase.t0 + arr, prompt, max_new, n_dec,
                        f"t{uid % n_tenants}"))
            uid += 1
    out.sort(key=lambda a: a[0])
    return out


def default_policy(slo_text: str = "p99_ms<=2500", *,
                   max_replicas: int = 8) -> AutoscalePolicy:
    return AutoscalePolicy(
        slo=SLOSpec.parse(slo_text), window_s=1.0,
        breach_after=2, recover_after=2, warmup_windows=1,
        min_replicas=1, max_replicas=max_replicas,
        cooldown_windows=3, scale_in_after=10_000)


def run_scenario(journal: Journal, *, clock: list[float] | None = None,
                 n_replicas: int = 2,
                 policy: AutoscalePolicy | None = None,
                 scale: str = "smoke", tick_s: float = 5e-3,
                 horizon_s: float = 90.0) -> dict:
    """One full pass of the scenario on a virtual clock; returns the
    gateway summary (the journal carries the event record).

    ``clock`` is a one-element list (the mutable time box) so the
    caller can hand the SAME virtual clock to the journal — the
    journal's ``t`` stamps must be virtual or the byte-for-byte
    determinism diff would see wall time."""
    policy = policy or default_policy()
    if clock is None:
        clock = [0.0]

    def now() -> float:
        return clock[0]

    def make(name: str) -> SimReplica:
        return SimReplica(name, n_slots=4, block_size=8, max_len=256,
                          prefill_chunk=8, clock=now, journal=journal)

    replicas = [make(f"replica{i}") for i in range(n_replicas)]
    gw = Gateway(replicas, journal=journal, clock=now,
                 autoscale=policy, make_replica=make, queue_limit=48,
                 step_costs=(tick_s, tick_s))
    plan = arrivals(phases(scale))
    i = 0
    while clock[0] < horizon_s and (i < len(plan) or not gw.idle()):
        t = clock[0]
        while i < len(plan) and plan[i][0] <= t:
            _, prompt, max_new, n_dec, tenant = plan[i]
            try:
                gw.submit(prompt, max_new, tenant=tenant, eos_id=0,
                          n_decode=n_dec)
            except GatewayError:
                pass  # counted by the gateway; journaled
            i += 1
        gw.step()
        clock[0] = t + tick_s
    if gw.controller is not None:
        gw.controller.finish()
    summary = gw.summary()
    summary["offered"] = len(plan)
    summary["virtual_s"] = clock[0]
    return summary


# -- fleet fault scenario -----------------------------------------------------
#
# The second chaos tier: instead of flipping traffic, it breaks the
# FLEET — a seeded plan kills one replica mid-stream (heartbeat
# failover), wedges another (circuit breaker + hedging), and slows a
# third (hedging) — and the gate asserts that every accepted request
# still completes with a token stream bitwise-identical to a
# fault-free run of the same seed.  ``tadnn gateway --chaos`` in CI.


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scripted fault on the virtual clock."""

    t: float
    kind: str       # kill | stall | unstall | slow | restore
    replica: int    # index into the initial fleet
    factor: int = 1  # slow-down multiple (kind == "slow")


def fault_plan(seed: int, n_replicas: int) -> list[FaultEvent]:
    """The seeded fault schedule: one kill, one stall/unstall pair,
    one slow/restore pair, on DISTINCT victims, never replica0 (the
    fleet must keep at least one intact survivor so failover has
    somewhere to land).  Same seed -> same plan, byte-for-byte."""
    rng = random.Random(seed)
    victims = list(range(1, n_replicas))
    rng.shuffle(victims)
    events: list[FaultEvent] = []
    if victims:
        events.append(FaultEvent(
            round(rng.uniform(1.0, 2.0), 3), "kill", victims[0]))
    if len(victims) > 1:
        t = round(rng.uniform(0.6, 1.2), 3)
        events.append(FaultEvent(t, "stall", victims[1]))
        events.append(FaultEvent(
            round(t + rng.uniform(0.8, 1.2), 3), "unstall", victims[1]))
    if len(victims) > 2:
        t = round(rng.uniform(0.4, 0.8), 3)
        events.append(FaultEvent(t, "slow", victims[2], factor=64))
        events.append(FaultEvent(
            round(t + rng.uniform(1.0, 1.5), 3), "restore", victims[2]))
    events.sort(key=lambda e: (e.t, e.kind, e.replica))
    return events


def _apply_fault(ev: FaultEvent, replica: SimReplica,
                 journal: Journal) -> None:
    if ev.kind == "kill":
        replica.kill()
    elif ev.kind == "stall":
        replica.stalled = True
    elif ev.kind == "unstall":
        replica.stalled = False
    elif ev.kind == "slow":
        replica.slow_factor = max(1, int(ev.factor))
    elif ev.kind == "restore":
        replica.slow_factor = 1
    else:
        raise ValueError(f"unknown fault kind {ev.kind!r}")
    journal.event("chaos.fault", kind=ev.kind, replica=replica.name,
                  t_fault=ev.t, factor=ev.factor)


def run_fleet_scenario(journal: Journal, *,
                       clock: list[float] | None = None,
                       seed: int = 0, n_replicas: int = 4,
                       faults: bool = True,
                       prefix_cache: bool = True,
                       tick_s: float = 5e-3,
                       horizon_s: float = 30.0
                       ) -> tuple[dict, dict[int, list[int]]]:
    """One pass of the fleet fault scenario; returns ``(summary,
    streams)`` where ``streams`` maps every accepted rid to its
    exactly-once delivered token list (the gateway ledger).

    No rate limit and an effectively unbounded queue: BOTH the faulted
    and the fault-free run must accept the identical request set, or
    per-rid stream parity would be vacuous."""
    if clock is None:
        clock = [0.0]

    def now() -> float:
        return clock[0]

    def make(name: str) -> SimReplica:
        return SimReplica(name, n_slots=4, block_size=8, max_len=256,
                          prefill_chunk=8, prefix_cache=prefix_cache,
                          clock=now, journal=journal)

    replicas = [make(f"replica{i}") for i in range(n_replicas)]
    gw = Gateway(replicas, journal=journal, clock=now,
                 queue_limit=100_000,
                 heartbeat_s=tick_s * 10,
                 hedge=HedgePolicy(after_s=0.2,
                                   max_hedges_per_request=1),
                 breaker=BreakerPolicy(window_s=0.1,
                                       min_observations=10,
                                       failure_rate=0.5,
                                       open_s=0.3, clean_s=0.1),
                 step_costs=(tick_s, tick_s))
    plan = arrivals([ChaosPhase(0.0, 5.0, TrafficMix(
        rate_per_s=80.0, n_requests=400, prompt_mean=24, max_new=12,
        decode_mean=12, jitter=0.0, seed=seed,
        shared_prefix=SHARED_PREFIX))])
    fplan = fault_plan(seed, n_replicas) if faults else []
    expected: dict[int, int] = {}   # rid -> emulated true decode len
    i = f = 0
    while clock[0] < horizon_s and (
            i < len(plan) or f < len(fplan)
            or not gw.idle() or gw._meta):
        t = clock[0]
        while f < len(fplan) and fplan[f].t <= t:
            _apply_fault(fplan[f], replicas[fplan[f].replica], journal)
            f += 1
        while i < len(plan) and plan[i][0] <= t:
            _, prompt, max_new, n_dec, tenant = plan[i]
            try:
                req = gw.submit(prompt, max_new, tenant=tenant,
                                eos_id=0, n_decode=n_dec)
                expected[req.rid] = n_dec
            except GatewayError:
                pass
            i += 1
        gw.step()
        clock[0] = t + tick_s
    summary = gw.summary()
    summary["offered"] = len(plan)
    summary["virtual_s"] = clock[0]
    summary["n_faults"] = len(fplan)
    streams = {rid: gw.delivered(rid) for rid in expected}
    summary["complete"] = all(
        len(streams[rid]) == n_dec and streams[rid][-1] == 0
        for rid, n_dec in expected.items())
    return summary, streams


def fleet_chaos(*, journal_path: str | None = None, seed: int = 0,
                n_replicas: int = 4) -> dict:
    """The ``tadnn gateway --chaos`` CI gate.

    Three runs of the SAME seeded traffic: a fault-free baseline, a
    faulted run journaled to ``journal_path``, and a second faulted
    run in memory.  Holds iff

    - the two faulted runs journal identical normalized event
      sequences AND identical per-rid streams (determinism);
    - every accepted request completed, and each rid's delivered
      stream is bitwise-identical to the fault-free baseline's
      (failover/hedging lost and duplicated nothing);
    - at least one replica was killed while it held in-flight work
      (the kill really was mid-stream)."""

    def one(path: str | None, faults: bool
            ) -> tuple[dict, dict, list[dict]]:
        clock = [0.0]
        j = Journal(path, host0_only=False, clock=lambda: clock[0],
                    meta={"tool": "gateway-fleet-chaos"})
        with j:
            summary, streams = run_fleet_scenario(
                j, clock=clock, seed=seed, n_replicas=n_replicas,
                faults=faults)
        records = (Journal.read(path) if path else list(j.records))
        return summary, streams, records

    s0, st0, _ = one(None, False)
    s1, st1, r1 = one(journal_path, True)
    s2, st2, r2 = one(None, True)
    deterministic = (_normalize(r1) == _normalize(r2) and st1 == st2)
    parity = st1 == st0
    completed = bool(s1["complete"] and s1["done"] == s1["accepted"])
    killed_inflight = any(
        rec.get("name") == "gateway.failover"
        and rec.get("n_requeued", 0) > 0 for rec in r1)
    ok = (deterministic and parity and completed and killed_inflight
          and s0["complete"])
    return {
        "ok": ok,
        "deterministic": deterministic,
        "stream_parity": parity,
        "all_completed": completed,
        "killed_inflight": killed_inflight,
        "baseline_complete": s0["complete"],
        "seed": seed,
        "accepted": s1["accepted"],
        "failovers": s1["failovers"],
        "hedges": s1["hedges"],
        "hedge_wins": s1["hedge_wins"],
        "breakers": s1.get("breakers", {}),
        "n_records": len(r1),
        "fault_plan": [dataclasses.asdict(e)
                       for e in fault_plan(seed, n_replicas)],
        "run": s1,
    }


def _normalize(records: list[dict]) -> list[str]:
    """Canonical form for the determinism diff: drop the one
    legitimately nondeterministic field (wall time) and re-serialize.
    Everything else — virtual timestamps, decisions, counters — must
    match byte-for-byte across runs."""
    out = []
    for rec in records:
        out.append(json.dumps({k: v for k, v in rec.items()
                               if k != "wall"}, default=str))
    return out


def chaos_smoke(*, journal_path: str | None = None,
                n_replicas: int = 2, slo_text: str = "p99_ms<=2500",
                max_replicas: int = 8, scale: str = "smoke",
                autoscale: bool = True) -> dict:
    """Run the scenario TWICE (file-backed then in-memory journal),
    diff the normalized event sequences, and check the closed loop
    actually closed: breach -> replan -> scale -> recover, in order.

    Returns a summary dict with ``ok`` plus per-assertion booleans —
    the CLI smoke prints it as one JSON line and exits nonzero unless
    everything held."""
    policy = (default_policy(slo_text, max_replicas=max_replicas)
              if autoscale else None)

    def one(path: str | None) -> tuple[dict, list[dict]]:
        clock = [0.0]
        # the journal shares the scenario's virtual clock so record
        # ``t`` stamps are event-time, not wall-time — the whole point
        # of the twice-run diff below
        j = Journal(path, host0_only=False, clock=lambda: clock[0],
                    meta={"tool": "gateway-chaos"})
        with j:
            summary = run_scenario(j, clock=clock,
                                   n_replicas=n_replicas,
                                   policy=policy, scale=scale)
        records = (Journal.read(path) if path else list(j.records))
        return summary, records

    s1, r1 = one(journal_path)
    s2, r2 = one(None)
    seq1, seq2 = _normalize(r1), _normalize(r2)
    deterministic = seq1 == seq2

    def first_index(name: str) -> int:
        for idx, rec in enumerate(r1):
            if rec.get("name") == name:
                return idx
        return -1

    i_breach = first_index("slo.breach")
    i_replan = first_index("gateway.replan")
    i_scale = first_index("gateway.scale")
    i_recover = first_index("slo.recover")
    closed_loop = (0 <= i_breach <= i_replan <= i_scale
                   and i_scale <= i_recover) if autoscale else True
    ok = deterministic and closed_loop and s1["done"] > 0
    return {
        "ok": ok,
        "deterministic": deterministic,
        "closed_loop": closed_loop,
        "breach_at": i_breach, "replan_at": i_replan,
        "scale_at": i_scale, "recover_at": i_recover,
        "n_records": len(r1),
        "record_mismatch": (None if deterministic else next(
            (i for i, (a, b) in enumerate(zip(seq1, seq2)) if a != b),
            min(len(seq1), len(seq2)))),
        "names_seen": sorted({rec.get("name") for rec in r1
                              if rec.get("name")}),
        "run": s1,
    }
