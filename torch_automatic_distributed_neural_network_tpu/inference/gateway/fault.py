"""Fleet fault-tolerance policies: circuit breaking, hedging, degrade.

The gateway's failure model has three tiers, cheapest reaction first:

1. **Circuit breaker** (per replica) — a replica that heartbeats but
   makes no forward progress while loaded (a stall: wedged collective,
   livelocked host loop) is OPENED out of the routing set long before
   the autoscaler's SLO windows would notice.  The breaker is the
   classic three-state machine driven by a windowed failure rate: the
   gateway feeds one observation per serving step (progressed / did
   not), the window is pruned on the injected clock, and open →
   half-open → closed transitions are pure functions of (rate, time)
   so a chaos replay reproduces them byte-for-byte.
2. **Hedging** (per request) — a request that has made no token
   progress for ``HedgePolicy.after_s`` (queued too long behind a slow
   replica, or mid-decode on a stalled one) is speculatively
   re-dispatched to a second replica under the SAME rid.
   First-writer-wins: whichever copy finishes first resolves the
   request and the loser is cancelled; the ingress token cursor
   guarantees the merged stream is exactly-once regardless of which
   copy produced which token (greedy decode makes the copies
   content-identical).
3. **Degrade ladder** (fleet-wide) — when the fleet cannot scale its
   way out (sustained breach at ``max_replicas``, or capacity lost to
   failures), the controller walks the gateway down a deterministic
   ladder: disable speculation, tighten admission, shed priority
   classes LOWEST-first (batch before interactive).  Every transition
   journals ``gateway.degrade`` / ``gateway.restore`` so the shed
   history is auditable post-mortem (``tadnn doctor --gateway-dir``).

Everything here is host-side bookkeeping on an injected clock — no
device state, no wall-clock reads, no sleeps.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable


@dataclasses.dataclass(frozen=True)
class BreakerPolicy:
    """Per-replica circuit breaker knobs.

    An observation is one gateway step over a LOADED replica: ok when
    the replica advanced (steps counter moved), failure when it did
    not.  The breaker opens when at least ``min_observations`` land
    inside ``window_s`` and the failure fraction reaches
    ``failure_rate``; it half-opens after ``open_s`` and closes again
    after ``clean_s`` without a failure observation.
    """

    window_s: float = 0.25
    min_observations: int = 10
    failure_rate: float = 0.5
    open_s: float = 0.5
    clean_s: float = 0.25


class CircuitBreaker:
    """Three-state (closed/open/half_open) breaker on an injected clock."""

    def __init__(self, name: str, policy: BreakerPolicy, *,
                 clock: Callable[[], float] = time.monotonic,
                 journal=None):
        self.name = name
        self.policy = policy
        self.clock = clock
        self.journal = journal
        self.state = "closed"
        self._window: deque[tuple[float, bool]] = deque()
        self._opened_t: float | None = None
        self._last_failure_t: float | None = None
        self.n_opens = 0
        self.transitions: list[dict] = []

    def _set_state(self, state: str) -> None:
        if state == self.state:
            return
        rec = {"replica": self.name, "from": self.state, "to": state}
        self.state = state
        if state == "open":
            self.n_opens += 1
            self._opened_t = self.clock()
            self._window.clear()
        self.transitions.append(rec)
        if self.journal is not None:
            self.journal.event("gateway.breaker", **rec)

    def observe(self, ok: bool) -> None:
        """One loaded-replica step outcome; prunes the window, then
        applies the state machine."""
        now = self.clock()
        if not ok:
            self._last_failure_t = now
        pol = self.policy
        if self.state == "open":
            return  # open ignores traffic; only time can half-open it
        if self.state == "half_open":
            if not ok:
                self._set_state("open")
            return
        self._window.append((now, ok))
        while self._window and self._window[0][0] < now - pol.window_s:
            self._window.popleft()
        n = len(self._window)
        if n >= pol.min_observations:
            fails = sum(1 for _, o in self._window if not o)
            if fails / n >= pol.failure_rate:
                self._set_state("open")

    def tick(self) -> None:
        """Time-based transitions (call once per gateway step)."""
        now = self.clock()
        pol = self.policy
        if (self.state == "open" and self._opened_t is not None
                and now - self._opened_t >= pol.open_s):
            self._set_state("half_open")
        elif self.state == "half_open":
            last_fail = self._last_failure_t
            if last_fail is None or now - last_fail >= pol.clean_s:
                self._set_state("closed")

    def allow(self) -> bool:
        """May the router place NEW work here?  Half-open admits probe
        traffic — a success observation closes the breaker, a failure
        re-opens it."""
        return self.state != "open"


@dataclasses.dataclass(frozen=True)
class HedgePolicy:
    """Tail-hedging knobs: a request with no token progress for
    ``after_s`` is re-dispatched once to the least-loaded OTHER healthy
    replica; first writer wins and the loser is cancelled."""

    after_s: float = 0.25
    max_hedges_per_request: int = 1


# -- degrade ladder -----------------------------------------------------------
#
# Levels are cumulative and deterministic; shedding walks priority
# classes from the LOWEST (highest numeric value) up, never touching
# class 0 (interactive) until everything below it is gone.

#: level -> fraction of the configured per-tenant queue limit admitted
ADMISSION_FACTOR = {0: 1.0, 1: 0.5, 2: 0.5, 3: 0.25}

MAX_DEGRADE_LEVEL = 3


def shed_threshold(level: int, known_classes: list[int]) -> int | None:
    """The lowest priority VALUE rejected at this degrade level, or
    None when nothing is shed.

    Level 0 and 1 shed nothing (level 1 only disables speculation and
    tightens admission); from level 2 each further level sheds one
    more class from the bottom of ``known_classes``, never shedding
    class 0 — with the default {interactive: 0, batch: 1} table level
    2+ sheds batch and interactive always survives.
    """
    if level < 2:
        return None
    classes = sorted(set(known_classes))
    n_shed = min(level - 1, max(0, len(classes) - 1))
    if n_shed <= 0:
        return None
    return classes[len(classes) - n_shed]


def degrade_effects(level: int, known_classes: list[int]) -> dict:
    """The full knob set at a ladder level (journaled on transition)."""
    level = max(0, min(MAX_DEGRADE_LEVEL, int(level)))
    thr = shed_threshold(level, known_classes)
    return {
        "level": level,
        "speculation": level < 1,
        "admission_factor": ADMISSION_FACTOR[level],
        "shed_threshold": thr,
        "shed_classes": ([c for c in sorted(set(known_classes))
                          if thr is not None and c >= thr]),
    }
