"""Inference: KV-cached autoregressive decoding for the decoder families."""

from .decode import KVCache, SampleConfig, forward_cached, generate
from .quant import quantize_for_decode
from .speculative import speculative_generate

__all__ = ["KVCache", "SampleConfig", "forward_cached", "generate",
           "quantize_for_decode", "speculative_generate"]
