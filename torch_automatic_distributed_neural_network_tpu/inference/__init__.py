"""Inference: KV-cached autoregressive decoding for the decoder
families, plus the continuous-batching serving subsystem (`.serve`)."""

from .decode import KVCache, SampleConfig, forward_cached, generate
from .quant import dequantize_kv, quantize_for_decode, quantize_kv
from .speculative import speculative_generate

__all__ = ["KVCache", "SampleConfig", "dequantize_kv", "forward_cached",
           "generate", "quantize_for_decode", "quantize_kv",
           "speculative_generate"]
