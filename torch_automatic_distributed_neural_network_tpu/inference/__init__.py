"""Inference: KV-cached autoregressive decoding for the decoder families."""

from .decode import KVCache, SampleConfig, forward_cached, generate

__all__ = ["KVCache", "SampleConfig", "forward_cached", "generate"]
