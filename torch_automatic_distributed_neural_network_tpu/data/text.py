"""Text -> token-file bridge (component C13, the torch Dataset analog for
raw text corpora).

The reference world tokenizes with a HF tokenizer inside a torch Dataset;
here tokenization is a one-time OFFLINE step producing the native
loader's "TADN" flat token file (data/loader.py), so the training hot
path never touches Python string processing:

- :class:`ByteTokenizer` — dependency-free byte-level tokenizer
  (vocab = 256 bytes + BOS/EOS), always available (this environment has
  no network, so downloading a pretrained tokenizer may be impossible);
- :func:`load_tokenizer` — a ``transformers`` tokenizer when one is
  available locally (name/path), else the byte fallback;
- :func:`tokenize_file` — stream a UTF-8 text file into a token file in
  bounded memory; exposed as ``python -m <pkg> tokenize`` (cli.py).
"""

from __future__ import annotations

import sys
from typing import Any, Iterable

import numpy as np

from .loader import TokenFileWriter


class ByteTokenizer:
    """Byte-level tokenizer: ids 0..255 are raw bytes, 256 = BOS,
    257 = EOS.  Lossless on any input, no vocabulary files needed."""

    vocab_size = 258
    bos_id = 256
    eos_id = 257

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Iterable[int]) -> str:
        return bytes(i for i in ids if i < 256).decode(
            "utf-8", errors="replace"
        )


def load_tokenizer(name: str | None = None) -> Any:
    """A tokenizer with ``.encode(str) -> list[int]``.

    ``name`` = a ``transformers`` tokenizer name or local path; None (or
    'byte') = :class:`ByteTokenizer`.  Loading is attempted with
    ``local_files_only=True`` first — this environment has no egress, and
    failing fast beats a hanging download."""
    if name in (None, "byte"):
        return ByteTokenizer()
    from transformers import AutoTokenizer  # baked into the image

    try:
        return AutoTokenizer.from_pretrained(name, local_files_only=True)
    except Exception:
        return AutoTokenizer.from_pretrained(name)


def _encode(tok: Any, text: str) -> list[int]:
    """Encode WITHOUT special tokens: HF tokenizers default to inserting
    [CLS]/[SEP]/BOS per encode() call, which would corrupt the stream at
    every chunk boundary."""
    try:
        return tok.encode(text, add_special_tokens=False)
    except TypeError:
        return tok.encode(text)


def tokenize_file(
    input_path: str,
    output_path: str,
    *,
    tokenizer: Any | None = None,
    append_eos: bool = True,
    chunk_chars: int = 1 << 20,
    log: bool = True,
) -> int:
    """Stream ``input_path`` (UTF-8 text) into a TADN token file in
    bounded memory.

    Reads ``chunk_chars``-character chunks split at line boundaries (so
    multi-byte sequences and BPE merges never straddle a cut mid-line),
    encodes each WITHOUT per-chunk special tokens, and appends straight
    to the output file (TokenFileWriter patches the header count on
    close — no in-RAM concatenation).  Returns the token count.
    """
    tok = tokenizer if tokenizer is not None else ByteTokenizer()
    eos = getattr(tok, "eos_id", None)
    if eos is None:
        eos = getattr(tok, "eos_token_id", None)
    vocab = getattr(tok, "vocab_size", None)
    dtype = np.uint16 if (vocab is not None and vocab <= 2**16) else np.uint32
    with TokenFileWriter(output_path, dtype=dtype) as writer:
        with open(input_path, "r", encoding="utf-8", errors="replace") as f:
            buf = ""
            while True:
                chunk = f.read(chunk_chars)
                if not chunk:
                    break
                buf += chunk
                # split at the last newline; keep the tail for next chunk
                cut = buf.rfind("\n")
                if cut == -1:
                    continue
                writer.append(_encode(tok, buf[: cut + 1]))
                buf = buf[cut + 1:]
            if buf:
                writer.append(_encode(tok, buf))
        if append_eos and eos is not None:
            writer.append([eos])
        total = writer.n_tokens
    if log:
        print(f"tokenized {input_path} -> {output_path}: {total:,} tokens "
              f"(vocab {vocab if vocab is not None else '?'})",
              file=sys.stderr)
    return total
