"""Input pipelines: synthetic datasets + per-host sharded loaders (C13)."""
