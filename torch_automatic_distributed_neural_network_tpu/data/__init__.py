"""Input pipelines: synthetic datasets, the native token-file loader and
per-host sharded input (C13)."""

from .loader import TokenFileDataset, shard_for_host, write_token_file
from .synthetic import SyntheticClassification, SyntheticLM

__all__ = [
    "SyntheticClassification",
    "SyntheticLM",
    "TokenFileDataset",
    "shard_for_host",
    "write_token_file",
]
