"""Input pipelines: synthetic datasets, on-disk array datasets (MNIST idx
/ CIFAR-10 pickles / npy pairs), the native token-file loader and
per-host sharded input (C13)."""

from .arrays import (
    ArrayClassification,
    ArraySeq2Seq,
    classification_dataset,
    load_cifar10,
    load_mnist,
    load_seq2seq,
)
from .loader import TokenFileDataset, shard_for_host, write_token_file
from .text import ByteTokenizer, load_tokenizer, tokenize_file
from .synthetic import (
    SyntheticClassification,
    SyntheticLM,
    SyntheticMLM,
)
from .torch_adapter import TorchDatasetAdapter, TorchLoaderAdapter

__all__ = [
    "ArrayClassification",
    "ArraySeq2Seq",
    "classification_dataset",
    "load_cifar10",
    "load_mnist",
    "load_seq2seq",
    "SyntheticClassification",
    "SyntheticLM",
    "SyntheticMLM",
    "TokenFileDataset",
    "shard_for_host",
    "write_token_file",
    "ByteTokenizer",
    "load_tokenizer",
    "tokenize_file",
    "TorchDatasetAdapter",
    "TorchLoaderAdapter",
]
