"""Synthetic datasets standing in for the reference's example datasets
(MNIST / CIFAR-10 / WMT14 / LM corpora — component C13).

The build environment has no network, so example scripts default to
deterministic synthetic data with the real datasets' shapes; pass
``--data-dir`` to the examples to use real arrays if present on disk.
Batches are host-local numpy; `AutoDistribute.shard_batch` (or the jitted
step's in_shardings) places them onto the mesh.
"""

from __future__ import annotations

import numpy as np


class SyntheticClassification:
    """Deterministic image-classification stream (MNIST/CIFAR shaped).

    A fixed random linear teacher makes the task learnable so example
    loss curves actually decrease.
    """

    step_indexed = True  # Trainer protocol: .batch(i) is keyed by step

    def __init__(
        self,
        image_shape: tuple[int, ...] = (28, 28, 1),
        num_classes: int = 10,
        batch_size: int = 128,
        seed: int = 0,
    ):
        self.image_shape = image_shape
        self.num_classes = num_classes
        self.batch_size = batch_size
        self._rng = np.random.RandomState(seed)
        dim = int(np.prod(image_shape))
        self._teacher = np.random.RandomState(1234).randn(dim, num_classes) * 0.5

    def batch(self, step: int) -> dict:
        rng = np.random.RandomState(self._rng.randint(0, 2**31) if step is None
                                    else step + 1)
        x = rng.randn(self.batch_size, *self.image_shape).astype(np.float32)
        logits = x.reshape(self.batch_size, -1) @ self._teacher
        label = np.argmax(logits + 0.1 * rng.randn(*logits.shape), axis=-1)
        return {"x": x, "label": label.astype(np.int32)}

    def __iter__(self):
        step = 0
        while True:
            yield self.batch(step)
            step += 1


class SyntheticLM:
    """Deterministic token stream (GPT-2 / Llama shaped): a noisy copy task
    (next token depends on the previous one) so LM loss is reducible."""

    step_indexed = True  # Trainer protocol: .batch(i) is keyed by step

    def __init__(
        self,
        vocab_size: int = 32000,
        seq_len: int = 1024,
        batch_size: int = 8,
        seed: int = 0,
    ):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.seed = seed

    def batch(self, step: int) -> dict:
        rng = np.random.RandomState(self.seed + step + 1)
        first = rng.randint(0, self.vocab_size, size=(self.batch_size, 1))
        steps = rng.randint(0, 17, size=(self.batch_size, self.seq_len - 1))
        toks = np.concatenate(
            [first, np.cumsum(steps, axis=-1) + first], axis=-1
        ) % self.vocab_size
        return {"input_ids": toks.astype(np.int32)}

    def __iter__(self):
        step = 0
        while True:
            yield self.batch(step)
            step += 1


class SyntheticSeq2Seq:
    """Machine-translation shaped pairs (WMT14 stand-in): target is a
    deterministic transform (reverse + offset) of the source."""

    def __init__(
        self,
        vocab_size: int = 32000,
        src_len: int = 64,
        tgt_len: int = 64,
        batch_size: int = 64,
        seed: int = 0,
    ):
        self.vocab_size = vocab_size
        self.src_len = src_len
        self.tgt_len = tgt_len
        self.batch_size = batch_size
        self.seed = seed

    def batch(self, step: int) -> dict:
        rng = np.random.RandomState(self.seed + step + 1)
        src = rng.randint(
            2, self.vocab_size, size=(self.batch_size, self.src_len)
        )
        tgt = (src[:, ::-1] + 7) % self.vocab_size
        tgt = tgt[:, : self.tgt_len]
        return {
            "src": src.astype(np.int32),
            "tgt": tgt.astype(np.int32),
        }

    def __iter__(self):
        step = 0
        while True:
            yield self.batch(step)
            step += 1


class SyntheticMLM:
    """BERT-shaped masked-LM batches: the SyntheticLM cumsum stream with
    15% of positions masked out (80% [MASK], 10% random, 10% kept — the
    BERT recipe), labels carrying the original token at masked positions
    and -100 (ignore) elsewhere."""

    step_indexed = True

    def __init__(
        self,
        vocab_size: int = 30522,
        seq_len: int = 128,
        batch_size: int = 8,
        mask_token: int = 103,  # BERT's [MASK]
        mask_rate: float = 0.15,
        seed: int = 0,
    ):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.mask_token = mask_token
        self.mask_rate = mask_rate
        self.seed = seed

    def batch(self, step: int) -> dict:
        # same learnable cumsum stream as SyntheticLM (ONE recipe — the
        # LM and MLM streams must not silently diverge), masked on top
        toks = SyntheticLM(
            self.vocab_size, self.seq_len, self.batch_size, self.seed
        ).batch(step)["input_ids"]
        rng = np.random.RandomState(self.seed + step + 1)
        pick = rng.random(toks.shape) < self.mask_rate
        labels = np.where(pick, toks, -100)
        kind = rng.random(toks.shape)
        inputs = toks.copy()
        inputs[pick & (kind < 0.8)] = self.mask_token
        rand_pos = pick & (kind >= 0.8) & (kind < 0.9)
        inputs[rand_pos] = rng.randint(
            0, self.vocab_size, size=int(rand_pos.sum())
        )
        return {
            "input_ids": inputs.astype(np.int32),
            "labels": labels.astype(np.int32),
        }

    def __iter__(self):
        step = 0
        while True:
            yield self.batch(step)
            step += 1
