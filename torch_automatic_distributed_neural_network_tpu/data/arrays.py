"""On-disk array datasets for the CNN/MT examples (component C13).

The reference's examples train on MNIST / CIFAR-10 / WMT14
(BASELINE.json:7-9).  This environment has no network, so the example
scripts fall back to synthetic streams — but when real data IS on disk,
``--data-dir`` / ``run.data_dir`` loads it through here:

- **MNIST idx**: the canonical ``train-images-idx3-ubyte`` /
  ``train-labels-idx1-ubyte`` pair (optionally ``.gz``);
- **CIFAR-10 python pickles**: ``data_batch_1..5`` from the official
  ``cifar-10-batches-py`` tarball layout;
- **npy pairs**: generic ``x.npy``/``y.npy`` (classification) or
  ``src.npy``/``tgt.npy`` (seq2seq token ids) for pre-tokenized data.

Datasets are step-indexed (Trainer protocol: ``.batch(i)``): each epoch
draws a fresh deterministic permutation, so a resumed run sees exactly
the batches an uninterrupted run would have (elastic parity, SURVEY.md
§5).  LM token corpora use data/loader.py's TADN files instead.
"""

from __future__ import annotations

import gzip
import os
import pickle
from typing import Any

import numpy as np


def _epoch_order(n: int, epoch: int, seed: int) -> np.ndarray:
    return np.random.RandomState(seed + epoch).permutation(n)


class ArrayClassification:
    """Step-indexed batches over in-memory (x, y) arrays."""

    step_indexed = True

    def __init__(self, x: np.ndarray, y: np.ndarray, batch_size: int,
                 seed: int = 0):
        if len(x) != len(y):
            raise ValueError(f"x/y length mismatch: {len(x)} vs {len(y)}")
        if len(x) < batch_size:
            raise ValueError(
                f"dataset of {len(x)} rows < batch_size {batch_size}"
            )
        self.x = np.asarray(x)
        self.y = np.asarray(y, np.int32)
        self.batch_size = batch_size
        self.seed = seed
        self.batches_per_epoch = len(x) // batch_size

    def batch(self, step: int) -> dict:
        epoch, b = divmod(step, self.batches_per_epoch)
        order = _epoch_order(len(self.x), epoch, self.seed)
        rows = order[b * self.batch_size:(b + 1) * self.batch_size]
        return {"x": self.x[rows], "label": self.y[rows]}

    def __iter__(self):
        step = 0
        while True:
            yield self.batch(step)
            step += 1


class ArraySeq2Seq:
    """Step-indexed batches over (src, tgt) token-id arrays."""

    step_indexed = True

    def __init__(self, src: np.ndarray, tgt: np.ndarray, batch_size: int,
                 seed: int = 0):
        if len(src) != len(tgt):
            raise ValueError(
                f"src/tgt length mismatch: {len(src)} vs {len(tgt)}"
            )
        self.src = np.asarray(src, np.int32)
        self.tgt = np.asarray(tgt, np.int32)
        self.batch_size = batch_size
        self.seed = seed
        self.batches_per_epoch = len(src) // batch_size

    def batch(self, step: int) -> dict:
        epoch, b = divmod(step, self.batches_per_epoch)
        order = _epoch_order(len(self.src), epoch, self.seed)
        rows = order[b * self.batch_size:(b + 1) * self.batch_size]
        return {"src": self.src[rows], "tgt": self.tgt[rows]}

    def __iter__(self):
        step = 0
        while True:
            yield self.batch(step)
            step += 1


# ---------------------------------------------------------------------------
# Loaders
# ---------------------------------------------------------------------------


def _open_maybe_gz(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def _find(data_dir: str, *names: str) -> str | None:
    for name in names:
        for cand in (name, name + ".gz"):
            p = os.path.join(data_dir, cand)
            if os.path.exists(p):
                return p
    return None


def _read_idx(path: str) -> np.ndarray:
    """Parse an MNIST idx file (images magic 2051, labels magic 2049)."""
    with _open_maybe_gz(path) as f:
        raw = f.read()
    magic = int.from_bytes(raw[0:4], "big")
    ndim = magic & 0xFF
    dims = [
        int.from_bytes(raw[4 + 4 * i:8 + 4 * i], "big") for i in range(ndim)
    ]
    data = np.frombuffer(raw, np.uint8, offset=4 + 4 * ndim)
    return data.reshape(dims)


def load_mnist(data_dir: str, *, split: str = "train"):
    """(x [N,28,28,1] float32 in [0,1], y [N] int32) from ``data_dir``.

    Accepts npy pairs (``x_train.npy``/``y_train.npy``) or the canonical
    idx files.  Returns None if neither is present.
    """
    stem = "train" if split == "train" else "t10k"
    npy_x = _find(data_dir, f"x_{split}.npy")
    npy_y = _find(data_dir, f"y_{split}.npy")
    if npy_x and npy_y:
        x = np.load(npy_x).astype(np.float32)
        y = np.load(npy_y).astype(np.int32)
    else:
        ix = _find(data_dir, f"{stem}-images-idx3-ubyte",
                   f"{stem}-images.idx3-ubyte")
        iy = _find(data_dir, f"{stem}-labels-idx1-ubyte",
                   f"{stem}-labels.idx1-ubyte")
        if not (ix and iy):
            return None
        x = _read_idx(ix).astype(np.float32) / 255.0
        y = _read_idx(iy).astype(np.int32)
    if x.ndim == 3:
        x = x[..., None]
    if x.max() > 1.5:  # npy path may be raw 0..255
        x = x / 255.0
    return x.astype(np.float32), y


def load_cifar10(data_dir: str, *, split: str = "train"):
    """(x [N,32,32,3] float32 in [0,1], y [N] int32) from the official
    ``cifar-10-batches-py`` pickle layout (or a dir containing it), or
    npy pairs.  Returns None if absent."""
    npy_x = _find(data_dir, f"x_{split}.npy")
    npy_y = _find(data_dir, f"y_{split}.npy")
    if npy_x and npy_y:
        x = np.load(npy_x).astype(np.float32)
        if x.max() > 1.5:
            x = x / 255.0
        return x, np.load(npy_y).astype(np.int32)
    for root in (data_dir, os.path.join(data_dir, "cifar-10-batches-py")):
        names = (
            [f"data_batch_{i}" for i in range(1, 6)]
            if split == "train" else ["test_batch"]
        )
        if not all(os.path.exists(os.path.join(root, n)) for n in names):
            continue
        xs, ys = [], []
        for n in names:
            with open(os.path.join(root, n), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.append(np.asarray(d[b"labels"], np.int32))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.float32) / 255.0, np.concatenate(ys)
    return None


def load_seq2seq(data_dir: str, *, split: str = "train"):
    """(src [N,S] int32, tgt [N,T] int32) from pre-tokenized npy pairs
    (``src_train.npy``/``tgt_train.npy`` or ``src.npy``/``tgt.npy``).
    Returns None if absent."""
    s = _find(data_dir, f"src_{split}.npy", "src.npy")
    t = _find(data_dir, f"tgt_{split}.npy", "tgt.npy")
    if not (s and t):
        return None
    return np.load(s).astype(np.int32), np.load(t).astype(np.int32)


def classification_dataset(
    data_dir: str | None,
    loader,
    batch_size: int,
    *,
    fallback,
    seed: int = 0,
) -> Any:
    """``loader(data_dir)`` result as an ArrayClassification, or the
    synthetic ``fallback()`` when ``data_dir`` is empty/absent (with a
    console note either way)."""
    if data_dir:
        loaded = loader(data_dir)
        if loaded is not None:
            x, y = loaded
            print(f"data: {len(x)} examples from {data_dir}")
            return ArrayClassification(x, y, batch_size, seed=seed)
        print(f"data: nothing loadable in {data_dir!r}; using synthetic")
    return fallback()
