"""torch Dataset/DataLoader adapters — the data-side migration path.

The reference's users hold ``torch.utils.data`` pipelines (SURVEY.md C13:
torch ``DataLoader`` + ``DistributedSampler``).  Two adapters let them
keep those pipelines unchanged:

- :class:`TorchDatasetAdapter` wraps any map-style ``Dataset`` (anything
  with ``__len__`` + ``__getitem__``) into this framework's
  **step-indexed** protocol (``step_indexed = True``, ``.batch(i)``):
  deterministic per-epoch shuffling keyed by (seed, epoch), so a resumed
  run sees exactly the batches an uninterrupted run would have — the
  elastic-parity property the Trainer documents.  This replaces
  ``DistributedSampler`` outright: under the single-controller model
  every host materializes the same global batch and
  ``AutoDistribute.shard_batch`` / multi-host assembly splits it.
- :class:`TorchLoaderAdapter` wraps an iterable ``DataLoader`` (or any
  iterable of batches) as a plain iterable of host-numpy batches, for
  pipelines whose sampling/augmentation lives in the loader itself.

torch is imported lazily — the module is importable without torch
installed; instantiating an adapter is what requires it.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np


def to_numpy_tree(x: Any) -> Any:
    """torch tensors (recursively, through dict/list/tuple/namedtuple)
    -> numpy."""
    if hasattr(x, "detach"):  # torch tensor, no torch import needed
        return x.detach().cpu().numpy()
    if isinstance(x, dict):
        return {k: to_numpy_tree(v) for k, v in x.items()}
    if isinstance(x, tuple) and hasattr(x, "_fields"):  # namedtuple
        return type(x)(*(to_numpy_tree(v) for v in x))
    if isinstance(x, (list, tuple)):
        return type(x)(to_numpy_tree(v) for v in x)
    return x


def default_collate(items: Sequence[Any]) -> dict:
    """Stack per-example items into the framework's dict-batch shape.

    - dict items -> ``{key: stacked}``;
    - ``(x, y)`` tuples (the torch classification convention) ->
      ``{"x": ..., "label": ...}`` matching the CNN losses
      (training/losses.py);
    - single arrays -> ``{"x": ...}``.
    """
    first = to_numpy_tree(items[0])
    items = [to_numpy_tree(i) for i in items]
    if isinstance(first, dict):
        return {k: np.stack([i[k] for i in items]) for k in first}
    if isinstance(first, (list, tuple)):
        if len(first) != 2:
            raise ValueError(
                f"default_collate handles (x, y) pairs; got "
                f"{len(first)}-tuples — pass an explicit collate="
            )
        return {
            "x": np.stack([i[0] for i in items]),
            "label": np.stack([np.asarray(i[1]) for i in items]),
        }
    return {"x": np.stack(items)}


class TorchDatasetAdapter:
    """Map-style torch ``Dataset`` -> step-indexed batch source.

    ``batch(step)`` draws batch ``step % steps_per_epoch`` of epoch
    ``step // steps_per_epoch`` under a deterministic per-epoch
    permutation — stateless, so checkpoint resume replays the exact
    batch sequence (tests pin this).  Incomplete trailing batches are
    dropped (``drop_last`` semantics), matching DistributedSampler's
    default behavior.
    """

    step_indexed = True

    def __init__(
        self,
        dataset: Any,
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        collate: Callable[[Sequence[Any]], dict] | None = None,
    ):
        n = len(dataset)
        if batch_size > n:
            raise ValueError(f"batch_size {batch_size} > dataset size {n}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.collate = collate or default_collate
        self.steps_per_epoch = n // batch_size
        self._perm_cache: tuple[int, np.ndarray] | None = None

    def _perm(self, epoch: int) -> np.ndarray:
        from .arrays import _epoch_order

        n = len(self.dataset)
        if not self.shuffle:
            return np.arange(n)
        # regenerating a full permutation per batch is O(n) host work on
        # the hot data path; cache per epoch (still stateless: any
        # (seed, epoch) regenerates identically on resume)
        if self._perm_cache is None or self._perm_cache[0] != epoch:
            # same (seed, epoch) keying as the in-memory array sources,
            # so all step-indexed adapters share one determinism scheme
            self._perm_cache = (epoch, _epoch_order(n, epoch, self.seed))
        return self._perm_cache[1]

    def batch(self, step: int) -> dict:
        epoch, k = divmod(step, self.steps_per_epoch)
        idx = self._perm(epoch)[k * self.batch_size:(k + 1) * self.batch_size]
        return self.collate([self.dataset[int(j)] for j in idx])

    def __iter__(self):
        step = 0
        while True:
            yield self.batch(step)
            step += 1


class TorchLoaderAdapter:
    """Iterable ``DataLoader`` (or any batch iterable) -> iterable of
    host-numpy dict batches.  Re-iterable iff the wrapped loader is
    (DataLoaders are); tensors convert host-side, tuples map to the
    ``{"x", "label"}`` convention via :func:`default_collate`'s rules.
    """

    step_indexed = False

    def __init__(self, loader: Any):
        self.loader = loader

    def __iter__(self):
        for batch in self.loader:
            b = to_numpy_tree(batch)
            if isinstance(b, dict):
                yield b
            elif isinstance(b, (list, tuple)):
                if len(b) != 2:
                    raise ValueError(
                        f"TorchLoaderAdapter maps (x, y) pairs to "
                        f"{{'x', 'label'}}; got a {len(b)}-tuple — wrap "
                        f"your loader to yield dicts instead"
                    )
                yield {"x": np.asarray(b[0]), "label": np.asarray(b[1])}
            else:
                yield {"x": np.asarray(b)}
