"""Token-corpus data loader (component C13) with a native C++ fast path.

The reference rides torch ``DataLoader`` + ``DistributedSampler`` (C++
worker threads under the hood, SURVEY.md C13).  The TPU-native analog:

- a flat binary token-file format ("TADN" v1: header + little-endian
  uint16/uint32 tokens) written by :func:`write_token_file`;
- :class:`TokenFileDataset`, step-indexed (Trainer protocol) so elastic
  resume replays identical batches — window ``w`` of epoch ``e`` maps
  through a deterministic affine shuffle ``(a_e * w + c_e) % n_windows``
  seeded by splitmix64;
- a **native C++ backend** (native/tadnn_loader.cpp): mmap + background
  prefetch thread, compiled on demand with g++ and bound via ctypes.
  The pure-numpy fallback implements the identical determinism contract
  (bit-for-bit — tests/test_loader.py), so the backend is a pure speed
  choice;
- :func:`shard_for_host` for per-host input sharding under multi-host
  (each host loads only its rows, then
  ``jax.make_array_from_process_local_data`` assembles the global batch).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any

import numpy as np

_MAGIC = 0x4E444154  # "TADN"
_HEADER = np.dtype([
    ("magic", "<u4"), ("version", "<u4"), ("dtype_bytes", "<u4"),
    ("pad", "<u4"), ("n_tokens", "<u8"),
])

# the C++ source ships INSIDE the package (works from a wheel install);
# the repo-root native/ dir symlinks to it for the checkout layout
_REPO_NATIVE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
)


def _so_target(src: str) -> str:
    """Where to place the compiled .so: next to the source when that
    directory is writable (repo checkout / editable install), else a
    per-user cache dir (read-only site-packages wheel install), keyed
    on the source hash so caches from different installed versions
    never collide (the ABI/determinism contract may differ)."""
    d = os.path.dirname(src)
    if os.access(d, os.W_OK):
        return os.path.join(d, "libtadnn_loader.so")
    import hashlib

    with open(src, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.expanduser("~/.cache")), "tadnn")
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, f"libtadnn_loader-{tag}.so")

_MASK64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


class TokenFileWriter:
    """Streaming TADN v1 writer: append token chunks in bounded memory.

    Writes the header with a zero count up front, streams every
    ``append`` straight to disk, and patches ``n_tokens`` on close — so
    tokenizing a corpus much larger than RAM never concatenates it
    in-memory (data/text.py rides this).
    """

    def __init__(self, path: str, dtype=np.uint32):
        dtype = np.dtype(dtype)
        if dtype not in (np.dtype(np.uint16), np.dtype(np.uint32)):
            raise ValueError(f"TADN dtype must be uint16/uint32, got {dtype}")
        self._dtype = dtype
        self.n_tokens = 0
        self._f = open(path, "wb")
        self._write_header()

    def _write_header(self) -> None:
        header = np.zeros((), _HEADER)
        header["magic"] = _MAGIC
        header["version"] = 1
        header["dtype_bytes"] = self._dtype.itemsize
        header["n_tokens"] = self.n_tokens
        self._f.write(header.tobytes())

    def append(self, tokens) -> None:
        tokens = np.asarray(tokens).ravel()
        if tokens.size == 0:
            return
        lo, hi = int(tokens.min()), int(tokens.max())
        if lo < 0:
            raise ValueError("tokens must be non-negative")
        # batch() hands out int32 buffers (TPU-native token dtype); an
        # id >= 2^31 would silently wrap negative on read.
        limit = min(2**31, 2 ** (8 * self._dtype.itemsize))
        if hi >= limit:
            limit_str = "2**31" if limit == 2**31 else str(limit)
            raise ValueError(
                f"token id {hi} >= {limit_str} does not fit the file "
                f"dtype {self._dtype.name} / the loader's int32 batches"
            )
        self._f.write(tokens.astype(self._dtype).tobytes())
        self.n_tokens += int(tokens.size)

    def close(self) -> None:
        if self._f.closed:
            return
        self._f.seek(0)
        self._write_header()  # patch the real count
        self._f.close()

    def __enter__(self) -> "TokenFileWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def write_token_file(path: str, tokens: np.ndarray) -> None:
    """Write a TADN v1 token file; dtype picked from the token range."""
    tokens = np.asarray(tokens).ravel()
    dtype = np.uint16 if (
        tokens.size == 0 or int(tokens.max()) < 2**16) else np.uint32
    with TokenFileWriter(path, dtype=dtype) as w:
        w.append(tokens)


_build_lock = threading.Lock()
_lib: Any = None
_lib_failed = False


def _native_lib() -> Any | None:
    """Compile (once) and load the native loader; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_failed:
            return _lib
        src = os.path.join(_REPO_NATIVE, "tadnn_loader.cpp")
        try:
            # inside the try: an unwritable cache dir must mean
            # 'native unavailable' (numpy fallback), not a crash
            so = _so_target(src)
            if (
                not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)
            ):
                # compile to a private temp path, then atomically publish:
                # concurrent processes each build their own temp and the
                # last os.replace wins — no half-written .so is ever
                # visible (and so never cached by the mtime check)
                tmp = f"{so}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-pthread", src, "-o", tmp],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
            lib.tadnn_loader_open.restype = ctypes.c_void_p
            lib.tadnn_loader_open.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_uint64, ctypes.c_int,
            ]
            lib.tadnn_loader_n_windows.restype = ctypes.c_int64
            lib.tadnn_loader_n_windows.argtypes = [ctypes.c_void_p]
            lib.tadnn_loader_batch.restype = ctypes.c_int
            lib.tadnn_loader_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.tadnn_loader_close.argtypes = [ctypes.c_void_p]
            _lib = lib
        except (OSError, subprocess.SubprocessError):
            _lib_failed = True
    return _lib


class TokenFileDataset:
    """Step-indexed LM batches from a TADN token file.

    ``batch(i)`` -> ``{"input_ids": int32 [batch, seq_len+1]}`` — the
    ``seq_len+1`` window feeds next_token_loss's shift.  ``backend`` is
    'auto' (native if it builds, else numpy), 'native' (error if the C++
    loader is unavailable) or 'numpy'.
    """

    step_indexed = True  # Trainer protocol: .batch(i) is keyed by step

    def __init__(
        self,
        path: str,
        seq_len: int,
        batch_size: int,
        *,
        seed: int = 0,
        backend: str = "auto",
        prefetch: int = 4,
    ):
        if backend not in ("auto", "native", "numpy"):
            raise ValueError(
                f"backend must be 'auto', 'native' or 'numpy', got {backend!r}"
            )
        self.path = path
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.seed = seed & _MASK64

        header_arr = np.fromfile(path, dtype=_HEADER, count=1)
        if (
            header_arr.size != 1
            or header_arr[0]["magic"] != _MAGIC
            or header_arr[0]["version"] != 1
            or header_arr[0]["dtype_bytes"] not in (2, 4)
        ):
            raise ValueError(f"{path} is not a TADN v1 token file")
        header = header_arr[0]
        self.n_tokens = int(header["n_tokens"])
        self._dtype = np.uint16 if header["dtype_bytes"] == 2 else np.uint32
        if self.n_tokens < seq_len + 1:
            raise ValueError(
                f"{path}: {self.n_tokens} tokens < one window ({seq_len + 1})"
            )
        self.n_windows = (self.n_tokens - 1) // seq_len

        self._handle = None
        self._tokens = None
        lib = _native_lib() if backend in ("auto", "native") else None
        if lib is not None:
            self._handle = lib.tadnn_loader_open(
                path.encode(), seq_len, batch_size, self.seed, prefetch
            )
        if backend == "native" and not self._handle:
            raise RuntimeError("native loader unavailable (g++ build failed?)")
        if not self._handle:
            self._tokens = np.memmap(
                path, dtype=self._dtype, mode="r",
                offset=_HEADER.itemsize, shape=(self.n_tokens,),
            )

    @property
    def backend(self) -> str:
        return "native" if self._handle else "numpy"

    def _epoch_params(self, epoch: int) -> tuple[int, int]:
        s = _splitmix64(
            (self.seed ^ ((epoch * 0x5851F42D4C957F2D + 1) & _MASK64))
            & _MASK64
        )
        a = (_splitmix64(s) % self.n_windows) | 1
        while np.gcd(a, self.n_windows) != 1:
            a += 2
        a = a % self.n_windows or 1
        c = _splitmix64((s + 1) & _MASK64) % self.n_windows
        return a, c

    def _window_start(self, global_row: int) -> int:
        epoch, w = divmod(global_row, self.n_windows)
        a, c = self._epoch_params(epoch)
        return ((a * w + c) % self.n_windows) * self.seq_len

    def batch(self, step: int) -> dict:
        width = self.seq_len + 1
        # int32 buffer filled in place (tokens < 2^31, so the uint32 view
        # the native side writes through is layout-identical — no copy)
        out = np.empty((self.batch_size, width), np.int32)
        if self._handle:
            rc = _native_lib().tadnn_loader_batch(
                self._handle, step,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            )
            if rc != 0:
                raise RuntimeError(f"native loader failed at step {step}")
        else:
            for r in range(self.batch_size):
                start = self._window_start(step * self.batch_size + r)
                out[r] = self._tokens[start:start + width]
        if self._dtype is np.uint32 and out.min() < 0:
            # a uint32 id >= 2^31 wrapped negative through the int32 view
            # (file written by a foreign tool — write_token_file rejects
            # such ids at write time)
            raise ValueError(
                f"{self.path}: token id >= 2**31 at step {step} does not "
                "fit the loader's int32 batches"
            )
        return {"input_ids": out}

    def __iter__(self):
        step = 0
        while True:
            yield self.batch(step)
            step += 1

    def close(self) -> None:
        if self._handle:
            _native_lib().tadnn_loader_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def shard_for_host(batch: dict, *, process_index: int | None = None,
                   process_count: int | None = None) -> dict:
    """Slice a global batch to this host's rows (multi-host input path).

    Each host feeds its slice to
    ``jax.make_array_from_process_local_data`` (SURVEY.md C13); on one
    host this is the identity.
    """
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if pc == 1:
        return batch

    def slc(x):
        n = x.shape[0]
        if n % pc:
            raise ValueError(f"batch dim {n} not divisible by {pc} hosts")
        per = n // pc
        return x[pi * per:(pi + 1) * per]

    return {k: slc(v) for k, v in batch.items()}
