"""Pallas paged-attention decode kernel: block tables read in-kernel.

The serving decode step used to gather every slot's KV blocks into a
dense ``[S, max_len, kvH, hd]`` view (``kv_pool.gather_blocks``) before
stock attention — an O(slots x max_len) HBM materialization per layer
per token, the exact cost ROADMAP's "Serving path, phase 2" calls out.
This kernel eliminates it: the per-request block table is a
scalar-prefetch operand, so each grid step's ``index_map`` reads
``table[slot, j]`` and DMAs block ``j``'s page straight from the paged
pool into VMEM.  No dense view ever exists; HBM traffic is O(tokens
actually cached), the same bytes the pool stores.

Shape of the problem (one decode token per slot):

    q:      [S, Hq, hd]          one query per slot
    k/v:    [NB, bs, kvH, hd]    ONE layer of the paged pool
    tables: [S, MB] int32        block ids, null-padded (kv_pool)
    ctx:    [S] int32            keys 0..ctx inclusive are valid

Grid is ``(S, kvH, MB)`` with the block axis innermost ("arbitrary"
semantics): VMEM scratch carries flash-style online-softmax statistics
(running max / sum / accumulator, fp32) across a slot's blocks, exactly
the ``ops/flash_attention.py`` discipline.  GQA is native — each kv
head serves its ``Hq // kvH`` query group without materializing the
head broadcast.  Blocks past a slot's context (null-table padding) are
skipped at the grid level via the prefetched ``ctx``; a sliding window
additionally skips blocks entirely older than ``ctx - window``.

int8 KV (``inference/quant.quantize_kv``'s ``{"q", "scale"}`` leaves)
is dequantized ON LOAD, fused into the kernel: the int8 payload and its
per-(token, head) fp32 scales stream into VMEM and the multiply happens
right before the MXU dot — the dense bf16 form of a block never touches
HBM either.

CPU fallback follows ``flash_attention.py``: ``interpret=True`` (the
default off-TPU) runs the same kernel in the Pallas interpreter, so the
CPU-sim tests exercise the real kernel logic;
:func:`paged_attention_reference` is the pure-JAX oracle — it IS the
dense ``gather_blocks`` + ``xla_attention`` path the engine's
``attention_impl="dense"`` runs, which is what makes paged-vs-dense
parity a one-assert test.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -0.7 * float(np.finfo(np.float32).max)
_LANES = 128  # row stats stored lane-broadcast, as in flash_attention


def _default_interpret() -> bool:
    return jax.default_backend() == "cpu"


@dataclasses.dataclass(frozen=True)
class _Cfg:
    block_size: int
    group: int  # query heads per kv head (Hq // kvH)
    window: int | None
    quantized: bool
    interpret: bool


def _decode_kernel(*refs, cfg: _Cfg, scale: float):
    """One (slot, kv_head, block) grid step of paged decode attention."""
    tables_ref, ctx_ref = refs[0], refs[1]
    if cfg.quantized:
        (q_ref, kq_ref, ks_ref, vq_ref, vs_ref,
         o_ref, acc_ref, m_ref, l_ref) = refs[2:]
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs[2:]

    s = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)

    ctx = ctx_ref[s]
    start = j * cfg.block_size
    # a block is relevant iff it holds any key <= ctx (and, windowed,
    # any key newer than ctx - window) — the table's null padding sits
    # past ctx by construction, so padding blocks are skipped here
    relevant = start <= ctx
    if cfg.window is not None:
        relevant = jnp.logical_and(
            relevant, start + cfg.block_size - 1 > ctx - cfg.window)

    @pl.when(relevant)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32)  # [G, hd]
        if cfg.quantized:
            # dequantize-on-load: int8 payload x per-(token, head) scale,
            # fused right before the dot — the dense form never hits HBM
            k = kq_ref[0, :, 0].astype(jnp.float32) * ks_ref[0, :, 0]
            v = vq_ref[0, :, 0].astype(jnp.float32) * vs_ref[0, :, 0]
        else:
            k = k_ref[0, :, 0].astype(jnp.float32)  # [bs, hd]
            v = v_ref[0, :, 0].astype(jnp.float32)
        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G, bs]
        pos = start + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        valid = pos <= ctx
        if cfg.window is not None:
            valid = jnp.logical_and(valid, pos > ctx - cfg.window)
        sc = jnp.where(valid, sc, _NEG_BIG)

        m_prev = m_ref[:, :1]  # [G, 1] (lane-broadcast storage)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        m_new = jnp.maximum(m_new, _NEG_BIG / 2)
        p = jnp.exp(sc - m_new)  # [G, bs] fp32
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, hd]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nj - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def tensor_degree(mesh, axis: str = "tensor") -> int:
    """Size of ``axis`` in ``mesh`` (1 when absent or mesh is None)."""
    if mesh is None:
        return 1
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)


def paged_attention(
    q: jax.Array,
    k_pool,
    v_pool,
    tables: jax.Array,
    ctx_lens: jax.Array,
    *,
    window: int | None = None,
    interpret: bool | None = None,
    mesh=None,
    axis: str = "tensor",
) -> jax.Array:
    """Fused paged decode attention over one layer of the KV pool.

    ``q``: [S, Hq, hd] (one decode token per slot); ``k_pool``/``v_pool``:
    [NB, bs, kvH, hd] or the ``{"q": int8, "scale": fp32}`` quantized
    leaf; ``tables``: [S, MB] int32 null-padded block tables; ``ctx_lens``:
    [S] int32, keys ``0..ctx`` inclusive are attendable (the engine's
    decode-step convention: this step's key was just written at ``ctx``).
    Returns [S, Hq, hd] in ``q.dtype``.  The dense gathered view is never
    materialized — block pages stream VMEM-ward via the table prefetch.

    With ``mesh``, kv heads are partitioned over its ``axis`` (the
    ``cache_partition_spec`` rule: only when the head count divides the
    degree): the kernel runs per-shard under ``shard_map``, each device
    holding its head slice of the pool and computing its query group's
    attention — one server's pool HBM and attention FLOPs span the
    axis.  Heads are kv-major (``q.reshape(S, kvH, G, hd)``), so an
    even head split keeps every GQA group intact on one shard and the
    result needs no cross-device combine (attention is head-parallel).
    Tables and context lengths stay replicated — any slot may reference
    any block, exactly like the unsharded pool.
    """
    from ..inference.quant import kv_leaf_parts

    if interpret is None:
        interpret = _default_interpret()
    t = tensor_degree(mesh, axis)
    kvH_full = kv_leaf_parts(k_pool)[0].shape[2]
    if t > 1 and kvH_full % t == 0:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        heads = P(None, axis, None)        # [S, Hq, hd] on the head axis
        pool = P(None, None, axis, None)   # [NB, bs, kvH, hd] (+scales)
        local = functools.partial(
            _paged_attention_local, window=window, interpret=interpret)
        return shard_map(
            local, mesh=mesh,
            in_specs=(heads, pool, pool, P(None, None), P(None)),
            out_specs=heads, check_rep=False,
        )(q, k_pool, v_pool, tables, ctx_lens)
    return _paged_attention_local(
        q, k_pool, v_pool, tables, ctx_lens,
        window=window, interpret=interpret)


def _paged_attention_local(
    q: jax.Array,
    k_pool,
    v_pool,
    tables: jax.Array,
    ctx_lens: jax.Array,
    *,
    window: int | None,
    interpret: bool,
) -> jax.Array:
    """One device's (or the whole unsharded) kernel invocation — under
    ``shard_map`` the head axes arrive pre-sliced and the block tables
    replicated, so the body is identical either way."""
    from ..inference.quant import kv_leaf_parts

    k_arr, k_scale = kv_leaf_parts(k_pool)
    v_arr, v_scale = kv_leaf_parts(v_pool)
    quantized = k_scale is not None
    S, Hq, hd = q.shape
    NB, bs, kvH, _ = k_arr.shape
    MB = tables.shape[1]
    if Hq % kvH:
        raise ValueError(f"{Hq} query heads not a multiple of "
                         f"{kvH} kv heads")
    G = Hq // kvH
    cfg = _Cfg(block_size=bs, group=G, window=window,
               quantized=quantized, interpret=interpret)
    qg = q.reshape(S, kvH, G, hd)

    q_spec = pl.BlockSpec((1, 1, G, hd), lambda s, h, j, t, c: (s, h, 0, 0))
    # the table read: grid step (s, h, j) DMAs pool block table[s, j]
    kv_spec = pl.BlockSpec(
        (1, bs, 1, hd), lambda s, h, j, t, c: (t[s, j], 0, h, 0))
    scale_spec = pl.BlockSpec(
        (1, bs, 1, 1), lambda s, h, j, t, c: (t[s, j], 0, h, 0))
    if quantized:
        in_specs = [q_spec, kv_spec, scale_spec, kv_spec, scale_spec]
        operands = (qg, k_arr, k_scale, v_arr, v_scale)
    else:
        in_specs = [q_spec, kv_spec, kv_spec]
        operands = (qg, k_arr, v_arr)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, kvH, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, G, hd), lambda s, h, j, t, c: (s, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, hd), jnp.float32),
            pltpu.VMEM((G, _LANES), jnp.float32),
            pltpu.VMEM((G, _LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, cfg=cfg,
                          scale=1.0 / float(np.sqrt(hd))),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, kvH, G, hd), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), ctx_lens.astype(jnp.int32), *operands)
    return out.reshape(S, Hq, hd)


def paged_attention_reference(
    q: jax.Array,
    k_pool,
    v_pool,
    tables: jax.Array,
    ctx_lens: jax.Array,
    *,
    window: int | None = None,
    dtype=None,
) -> jax.Array:
    """Pure-JAX oracle: the dense decode path, verbatim.

    Gathers the block table into the dense view with
    ``kv_pool.gather_blocks`` (the engine's ``attention_impl="dense"``
    reference path) and runs ``xla_attention`` under the same
    ctx/window mask the engine builds — so kernel-vs-reference parity
    IS paged-vs-dense parity.
    """
    from ..inference.serve.kv_pool import gather_blocks
    from .attention import xla_attention

    if dtype is None:
        dtype = q.dtype
    kd = gather_blocks(k_pool, tables, dtype)
    vd = gather_blocks(v_pool, tables, dtype)
    key_idx = jnp.arange(kd.shape[1])[None, :]
    mask = key_idx <= ctx_lens[:, None]
    if window is not None:
        mask &= key_idx > ctx_lens[:, None] - window
    o = xla_attention(q[:, None], kd, vd, causal=False,
                      mask=mask[:, None, None, :])
    return o[:, 0]
