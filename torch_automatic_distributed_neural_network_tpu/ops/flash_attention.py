"""Pallas TPU flash attention (forward + backward kernels).

First-party block-streaming attention for the MXU (SURVEY.md §2.3: the
"native" tier on TPU is Pallas/Mosaic, not C++ we link ourselves).  The
reference's analog is torch.nn.functional.scaled_dot_product_attention
riding on cuDNN/flash CUDA kernels; here the kernel is implemented from
scratch:

- online-softmax streaming over K/V blocks -> O(seq) memory,
- fp32 accumulation, bf16-friendly inputs,
- causal masking with whole-block skipping (upper-triangle blocks are
  never computed),
- GQA (fewer K/V heads) by broadcast,
- arbitrary sequence lengths via padding + key masking,
- custom VJP with flash backward kernels (dq and dk/dv passes), so the
  attention matrix is never materialized in either direction.

Layout convention is BSHD [batch, seq, heads, head_dim]; internally the
kernels run on [batch*heads, seq, head_dim] with grid
(batch*heads, q_blocks, k_blocks) and VMEM scratch accumulators carried
across the innermost (arbitrary) grid dimension.

CPU fallback: ``interpret=True`` runs the same kernels in the Pallas
interpreter so every test exercises the real kernel logic on the 8-device
CPU sim (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _check_window

# jax 0.5 renamed pltpu.TPUCompilerParams -> CompilerParams; accept both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

_NEG_BIG = -0.7 * float(np.finfo(np.float32).max)
_LANES = 128  # TPU lane width: scratch row-stats are stored broadcast


@dataclasses.dataclass(frozen=True)
class _Cfg:
    causal: bool
    seq_q: int  # true (unpadded) lengths
    seq_k: int
    block_q: int
    block_k: int
    interpret: bool
    # sliding window (Mistral-style): attend iff q_pos - window < k_pos
    # <= q_pos.  None = full causal.  Requires causal=True.
    window: int | None = None


def _block_relevant(qi, ki, cfg: _Cfg):
    """Grid-level whole-block skip: True iff ANY (q, k) pair in the
    (qi, ki) tile can attend.  Causal skips above the diagonal; a
    sliding window additionally skips blocks entirely OLDER than
    q_block_start - window (window implies causal, enforced at entry)."""
    if not cfg.causal:
        return True
    ok = ki * cfg.block_k <= qi * cfg.block_q + cfg.block_q - 1
    if cfg.window is not None:
        ok = jnp.logical_and(
            ok,
            ki * cfg.block_k + cfg.block_k - 1 > qi * cfg.block_q - cfg.window,
        )
    return ok


def _pair_mask(q_pos, k_pos, cfg: _Cfg):
    """Element mask shared by forward and recompute: key padding,
    causality, sliding window."""
    mask = k_pos < cfg.seq_k
    if cfg.causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)
    if cfg.window is not None:
        mask = jnp.logical_and(mask, q_pos - k_pos < cfg.window)
    return mask


def _default_interpret() -> bool:
    return jax.default_backend() == "cpu"


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, cfg: _Cfg, scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)

    # skip blocks with no attendable pair (causal diagonal / window band)
    @pl.when(_block_relevant(qi, ki, cfg))
    def _block():
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]

        q_pos = qi * cfg.block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = ki * cfg.block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(_pair_mask(q_pos, k_pos, cfg), s, _NEG_BIG)

        m_prev = m_ref[:, :1]  # [bq, 1] (stored broadcast over lanes)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        # clamp so exp(_NEG_BIG - m) underflows to 0 for masked entries
        m_new = jnp.maximum(m_new, _NEG_BIG / 2)
        p = jnp.exp(s - m_new)  # [bq, bk] fp32
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, d]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # row stats are stored broadcast over the 128-lane dim (TPU tiling
        # forbids (1, block_q) blocks of a 2-D [bh, seq] array)
        lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _fwd(q, k, v, cfg: _Cfg):
    """q,k,v: [bh, S_pad, d] (padded).  Returns (o, lse) with lse fp32."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // cfg.block_q, sk // cfg.block_k
    scale = 1.0 / float(np.sqrt(d))
    kernel = functools.partial(_fwd_kernel, cfg=cfg, scale=scale)
    grid = (bh, nq, nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, cfg.block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, cfg.block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, cfg.block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, d), jnp.float32),
            pltpu.VMEM((cfg.block_q, _LANES), jnp.float32),
            pltpu.VMEM((cfg.block_q, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=cfg.interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------
#
# Standard flash backward split into two accumulation passes:
#   dkv pass: grid (bh, k_blocks, q_blocks) — fixed K/V block accumulates
#             dk, dv over visiting Q blocks.
#   dq  pass: grid (bh, q_blocks, k_blocks) — fixed Q block accumulates dq.
# Both recompute p = exp(s - lse) from the saved logsumexp; delta =
# rowsum(do * o) is precomputed outside the kernel.


def _recompute_p(q, k, qi, ki, lse, cfg: _Cfg, scale):
    """lse: [bq, 1] (sliced from the lane-broadcast stats)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [bq, bk]
    q_pos = qi * cfg.block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = ki * cfg.block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(_pair_mask(q_pos, k_pos, cfg), s, _NEG_BIG)
    return jnp.exp(s - lse)  # [bq, bk]


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, cfg: _Cfg, scale: float):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_block_relevant(qi, ki, cfg))
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0].astype(jnp.float32)
        p = _recompute_p(q, k, qi, ki, lse_ref[0][:, :1], cfg, scale)
        # dv += p^T do
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dp = do v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        # dk += ds^T q
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, cfg: _Cfg, scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(_block_relevant(qi, ki, cfg))
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0].astype(jnp.float32)
        p = _recompute_p(q, k, qi, ki, lse_ref[0][:, :1], cfg, scale)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd(cfg: _Cfg, res, do):
    return _bwd_impl(cfg, res, do, None)


def _bwd_stats(cfg: _Cfg, res, cot):
    """VJP for the (o, lse)-returning forward.  The lse cotangent folds
    into the delta term: dL/ds = p*(dp - delta) + p*dlse = p*(dp -
    (delta - dlse)), so the kernels run unchanged with an adjusted delta.
    """
    do, dlse_full = cot
    # dlse arrives in the lane-broadcast layout; callers slice one lane,
    # so summing over lanes recovers the row cotangent.
    dlse = jnp.sum(dlse_full.astype(jnp.float32), axis=-1)
    return _bwd_impl(cfg, res, do, dlse)


def _bwd_impl(cfg: _Cfg, res, do, dlse):
    q, k, v, o, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // cfg.block_q, sk // cfg.block_k
    scale = 1.0 / float(np.sqrt(d))
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))

    q_spec = pl.BlockSpec((1, cfg.block_q, d), lambda b, i, j: (b, i, 0))
    k_spec_kv = pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (b, i, 0))
    q_spec_kv = pl.BlockSpec((1, cfg.block_q, d), lambda b, i, j: (b, j, 0))
    row_kv = pl.BlockSpec((1, cfg.block_q, _LANES), lambda b, i, j: (b, j, 0))
    k_spec_q = pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (b, j, 0))
    row_q = pl.BlockSpec((1, cfg.block_q, _LANES), lambda b, i, j: (b, i, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg=cfg, scale=scale),
        grid=(bh, nk, nq),
        in_specs=[q_spec_kv, k_spec_kv, k_spec_kv, q_spec_kv, row_kv, row_kv],
        out_specs=[k_spec_kv, k_spec_kv],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_k, d), jnp.float32),
            pltpu.VMEM((cfg.block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=cfg.interpret,
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg=cfg, scale=scale),
        grid=(bh, nq, nk),
        in_specs=[q_spec, k_spec_q, k_spec_q, q_spec, row_q, row_q],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((cfg.block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=cfg.interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp core on folded [bh, S, d] arrays
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_core(q, k, v, cfg: _Cfg):
    o, _ = _fwd(q, k, v, cfg)
    return o


def _flash_core_fwd(q, k, v, cfg: _Cfg):
    o, lse = _fwd(q, k, v, cfg)
    return o, (q, k, v, o, lse)


_flash_core.defvjp(_flash_core_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_core_stats(q, k, v, cfg: _Cfg):
    """Like _flash_core but also returns the lane-broadcast logsumexp —
    the merge statistic ring attention needs (parallel/ring.py)."""
    return _fwd(q, k, v, cfg)


def _flash_core_stats_fwd(q, k, v, cfg: _Cfg):
    o, lse = _fwd(q, k, v, cfg)
    return (o, lse), (q, k, v, o, lse)


_flash_core_stats.defvjp(_flash_core_stats_fwd, _bwd_stats)


# ---------------------------------------------------------------------------
# Public BSHD entry point
# ---------------------------------------------------------------------------


def _pad_to(x, target, dim):
    pad = target - x.shape[dim]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[dim] = (0, pad)
    return jnp.pad(x, widths)


# Per-seq (block_q, block_k) fwd+bwd winners, measured on a live v5e
# (BENCH_NOTES.md round-5 `mode=attention sweep=1`: 36.1% HW util @ 8k
# with 512x2048 vs 29.2% for the old 1024x1024 default; 40.3% @ 16k with
# 1024x1024; 25.2% @ 2k with 512x2048).  2048-wide q blocks, and
# bq>=1024 x bk>=1024 combinations beyond these, exceed the compile
# helper's VMEM budget and fail to compile.
_MEASURED_BLOCKS = {
    2048: (512, 2048),
    8192: (512, 2048),
    16384: (1024, 1024),
}


def default_blocks(seq_k: int) -> tuple[int, int]:
    """Measured per-seq block defaults (nearest swept seq_k wins)."""
    key = min(_MEASURED_BLOCKS, key=lambda sw: abs(sw - seq_k))
    return _MEASURED_BLOCKS[key]


def _prep_bshd(q, k, v, causal, block_q, block_k, interpret,
               window=None):
    """Shared BSHD preprocessing: GQA broadcast, fold to [B*H, S, D], pad
    to block multiples.  Returns (qf, kf, vf, cfg, (b, hq, sq, d))."""
    _check_window(window, causal)
    if interpret is None:
        interpret = _default_interpret()
    b, sq, hq, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if block_q is None or block_k is None:
        dq, dk = default_blocks(sk)
        block_q = dq if block_q is None else block_q
        block_k = dk if block_k is None else block_k
    if hk != hq:
        assert hq % hk == 0, (hq, hk)
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)
    if causal and sq != sk:
        raise NotImplementedError(
            "causal flash attention requires seq_q == seq_k"
        )

    block_q = min(block_q, max(sq, 1))
    block_k = min(block_k, max(sk, 1))
    sq_pad = -(-sq // block_q) * block_q
    sk_pad = -(-sk // block_k) * block_k
    cfg = _Cfg(causal=causal, seq_q=sq, seq_k=sk, block_q=block_q,
               block_k=block_k, interpret=interpret, window=window)

    def fold(x):  # BSHD -> [B*H, S, D]
        x = jnp.swapaxes(x, 1, 2)
        return x.reshape(b * hq, x.shape[2], d)

    qf = _pad_to(fold(q), sq_pad, 1)
    kf = _pad_to(fold(k), sk_pad, 1)
    vf = _pad_to(fold(v), sk_pad, 1)
    return qf, kf, vf, cfg, (b, hq, sq, d)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: int | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention over BSHD tensors [batch, seq, heads, head_dim].

    Numerically matches :func:`..attention.xla_attention` (the oracle the
    tests compare against) while never materializing the [S, S] score
    matrix.  K/V may have fewer heads (GQA) — broadcast to Q's head count.

    ``window`` (requires ``causal=True``) is Mistral-style sliding-window
    attention: position q attends keys in ``(q - window, q]``.  Blocks
    entirely outside the band are skipped at the grid level (fwd AND both
    bwd passes), so compute scales O(S * window) instead of O(S^2 / 2).

    Block defaults resolve per-sequence from a live-v5e sweep
    (:func:`default_blocks`; BENCH_NOTES.md round-5 block sweep):
    512x2048 up to seq 8k, 1024x1024 at 16k+.  2048-wide q blocks
    exceed the VMEM budget and fail to compile.
    """
    qf, kf, vf, cfg, (b, hq, sq, d) = _prep_bshd(
        q, k, v, causal, block_q, block_k, interpret, window
    )
    of = _flash_core(qf, kf, vf, cfg)
    of = of[:, :sq]
    o = of.reshape(b, hq, sq, d)
    return jnp.swapaxes(o, 1, 2)


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Flash attention returning ``(o, lse)`` — ``o`` as BSHD, ``lse``
    [batch, heads, seq] fp32 logsumexp of each row's scores.

    The lse output is what makes per-block results mergeable: ring
    attention (parallel/ring.py) combines normalized block outputs as
    ``sum_i o_i * exp(lse_i - logaddexp_i(lse_i))``.  Gradients flow
    through both outputs (the lse cotangent folds into the kernels'
    delta term).
    """
    qf, kf, vf, cfg, (b, hq, sq, d) = _prep_bshd(
        q, k, v, causal, block_q, block_k, interpret
    )
    of, lse_f = _flash_core_stats(qf, kf, vf, cfg)
    o = jnp.swapaxes(of[:, :sq].reshape(b, hq, sq, d), 1, 2)
    lse = lse_f[:, :sq, 0].reshape(b, hq, sq)
    return o, lse
