"""Attention kernels with a single dispatch surface.

Implementations:

- ``xla``   — plain jnp einsum attention; XLA fuses it well for moderate
              sequence lengths and it runs everywhere (CPU sim included).
- ``chunked`` — query-block scan over the same einsum math with fp32
              online numerics and per-block rematerialization: peak
              score memory O(block_q * S) instead of O(S^2), pure XLA,
              runs everywhere and takes explicit masks.  The auto path
              uses it for long sequences whenever the Pallas kernel
              can't run (non-TPU backends, explicit masks) — it is what
              keeps long-seq memfit numbers honest off-TPU.
- ``flash`` — Pallas block-streaming attention (ops/flash_attention.py),
              O(seq) memory, MXU-tiled; TPU only.
- ``ring``  — context-parallel ring attention (parallel/ring.py): KV blocks
              rotate around the ``seq`` mesh axis via ppermute with
              online-softmax accumulation (SURVEY.md §3.4).

Models call :func:`attention` and the parallel plan decides the impl; the
CPU-sim tests exercise every impl against the ``xla`` oracle.

Shapes follow the TPU-friendly convention [batch, seq, heads, head_dim]
(BSHD) — keeps the trailing two dims MXU-tileable after the head fold.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np

Impl = Literal["xla", "chunked", "flash", "ring", "auto"]

# auto-dispatch floor for the chunked path off-TPU: below this the full
# S^2 score tensor is small enough that the plain einsum fuses better
CHUNKED_MIN_SEQ = 1024


def _check_window(window, causal):
    """Shared by every attention entry point: a window only makes sense
    as a causal band, and window < 1 would mask EVERY key — with the
    finite mask bias that yields a UNIFORM softmax over all positions
    (an acausality leak), not an error, so reject it up front."""
    if window is None:
        return
    if not causal:
        raise ValueError("window= requires causal=True (the sliding "
                         "window is a causal band)")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def _mask_bias(scores_dtype, mask):
    big_neg = jnp.finfo(scores_dtype).min * 0.5
    return jnp.where(mask, 0.0, big_neg).astype(scores_dtype)


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: int | None = None,
    mask: jax.Array | None = None,
    softmax_dtype=jnp.float32,
) -> jax.Array:
    """Reference einsum attention.  q,k,v: [B, S, H, D] (k,v may have fewer
    heads for GQA — broadcast over query groups)."""
    _check_window(window, causal)
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    if hk != hq:
        assert hq % hk == 0, (hq, hk)
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)
    scale = 1.0 / np.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(softmax_dtype) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        if window is not None:
            # sliding band: q attends keys in (q - window, q]
            causal_mask &= jnp.triu(
                jnp.ones((sq, sk), bool), k=sk - sq - window + 1)
        scores = scores + _mask_bias(scores.dtype, causal_mask[None, None])
    if mask is not None:
        # mask: [B, 1|H, Q|1, K] boolean, True = attend
        scores = scores + _mask_bias(scores.dtype, mask)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def chunked_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: int | None = None,
    mask: jax.Array | None = None,
    block_q: int = 256,
    softmax_dtype=jnp.float32,
) -> jax.Array:
    """Memory-efficient einsum attention: lax.scan over query blocks.

    Numerically identical to :func:`xla_attention` (same fp32 softmax,
    same GQA broadcast, same mask conventions) but the [B,H,S,S] score
    tensor never materializes — each scan step holds [B,H,block_q,S],
    and ``jax.checkpoint`` on the block recomputes scores in the
    backward instead of stashing them per block.  This is the flash
    algorithm's memory shape in pure XLA, so it runs on any backend and
    supports explicit masks (which the Pallas kernel does not).
    """
    _check_window(window, causal)
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    if hk != hq:
        assert hq % hk == 0, (hq, hk)
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)
    block_q = min(block_q, sq)
    n_blocks = -(-sq // block_q)
    pad = n_blocks * block_q - sq
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if mask is not None and mask.shape[2] > 1:
            # keep mask rows aligned with padded q rows (a fully-False
            # row yields a uniform softmax via the finite mask bias; the
            # row's output is sliced off below)
            mask = jnp.pad(mask, ((0, 0), (0, 0), (0, pad), (0, 0)))
    q_blocks = q.reshape(b, n_blocks, block_q, hq, d).swapaxes(0, 1)
    scale = 1.0 / np.sqrt(d)
    k_pos = jnp.arange(sk)

    @jax.checkpoint
    def block(q_i, start):
        scores = jnp.einsum("bqhd,bkhd->bhqk", q_i, k).astype(
            softmax_dtype) * scale
        if causal:
            # global q position p attends key positions <= p + (sk - sq)
            q_pos = start + jnp.arange(block_q)
            allow = k_pos[None, :] <= q_pos[:, None] + (sk - sq)
            if window is not None:
                allow &= (k_pos[None, :]
                          > q_pos[:, None] + (sk - sq) - window)
            scores = scores + _mask_bias(scores.dtype, allow[None, None])
        if mask is not None:
            m = mask
            if m.shape[2] > 1:  # [B, 1|H, Q, K]: slice this block's rows
                m = jax.lax.dynamic_slice_in_dim(m, start, block_q, axis=2)
            scores = scores + _mask_bias(scores.dtype, m)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)

    def body(_, inp):
        q_i, start = inp
        return None, block(q_i, start)

    _, out = jax.lax.scan(
        body, None, (q_blocks, jnp.arange(n_blocks) * block_q))
    out = out.swapaxes(0, 1).reshape(b, n_blocks * block_q, hq, d)
    return out[:, :sq]


def _flash_ok(q: jax.Array, k: jax.Array, mask) -> bool:
    """Auto-dispatch gate for the Pallas flash kernel: TPU backend, no
    explicit mask, a sequence long enough that block streaming wins.
    Measured on the v5e (bench.py mode=attention, BENCH_NOTES.md): flash
    beats the einsum path 20x at seq 512, 87x at 2048, 43x at 8192
    (fwd+bwd, causal, 16 heads x d128) — 512 is a conservative floor set
    by the kernel's block size, not the perf crossover."""
    if mask is not None:
        return False
    if q.shape[1] < 512 or q.shape[1] != k.shape[1]:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: int | None = None,
    mask: jax.Array | None = None,
    impl: Impl = "auto",
) -> jax.Array:
    """Dispatching attention entry point used by all models.

    With impl='auto': if the ambient ParallelContext has a nontrivial
    ``seq`` axis, context parallelism kicks in — Ulysses when the local
    head count divides the cp degree (cheapest: two all_to_alls), ring
    attention otherwise (SURVEY.md §5 long-context tiers).  Without a
    context (or cp=1): plain XLA attention.

    ``window`` (requires ``causal=True``) is Mistral-style sliding-window
    attention, supported natively by the xla/chunked/flash paths (the
    flash kernel skips out-of-band blocks at the grid level).
    """
    from ..parallel import context as pctx

    _check_window(window, causal)

    ctx = pctx.current()
    cp = ctx.seq_degree if ctx is not None else 1

    if impl == "auto" and ctx is not None and ctx.attn_impl:
        impl = ctx.attn_impl
    if impl == "auto":
        if cp > 1:
            if ctx.seq_impl in ("ring", "ulysses"):
                impl = ctx.seq_impl  # user override via AutoDistribute
            else:
                tp = ctx.degrees.get(ctx.head_axis, 1)
                local_heads = q.shape[2] // max(tp, 1)
                seq = q.shape[1]
                if local_heads % cp == 0 and seq <= 8192:
                    impl = "ulysses"
                else:
                    impl = "ring"
        elif _flash_ok(q, k, mask):
            impl = "flash"
        elif q.shape[1] >= CHUNKED_MIN_SEQ and q.shape[1] == k.shape[1]:
            # long sequence but the Pallas kernel can't run (non-TPU
            # backend or explicit mask): O(block*S) memory via the
            # query-block scan instead of the S^2 einsum
            impl = "chunked"
        else:
            impl = "xla"

    if impl == "xla":
        return xla_attention(q, k, v, causal=causal, window=window,
                             mask=mask)
    if impl == "chunked":
        return chunked_attention(q, k, v, causal=causal, window=window,
                                 mask=mask)
    if impl == "flash":
        from .flash_attention import flash_attention

        if mask is not None:
            raise NotImplementedError(
                "flash attention does not take explicit masks (causal only)"
            )
        if ctx is not None and cp > 1:
            raise NotImplementedError(
                "flash attention cannot span a sharded sequence axis — "
                "use impl='ring' or 'ulysses' (or 'auto') under context "
                "parallelism"
            )
        if ctx is not None and (ctx.present_batch_axes
                                or ctx.degrees.get(ctx.head_axis, 1) > 1):
            # Inside a GSPMD-jitted step on a nontrivial mesh the Mosaic
            # custom call is not partitionable — run it under shard_map
            # over the batch (and head, under TP) axes, which is exact:
            # attention is independent per batch element and per head.
            from ..utils.jax_compat import shard_map
            from jax.sharding import PartitionSpec as P

            tp = ctx.degrees.get(ctx.head_axis, 1)
            head_axis = ctx.head_axis if tp > 1 else None
            if tp > 1 and q.shape[2] % tp:
                # head count indivisible by the tensor degree — the
                # einsum path under GSPMD is the safe fallback
                return xla_attention(q, k, v, causal=causal, window=window)
            if k.shape[2] != q.shape[2]:
                # GQA: broadcast K/V heads first so all three operands
                # shard evenly on the head axis (n_kv_heads may not
                # divide the tensor degree)
                rep = q.shape[2] // k.shape[2]
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            spec = P(ctx.batch_spec_entry(), None, head_axis, None)
            fn = shard_map(
                functools.partial(flash_attention, causal=causal,
                                  window=window),
                mesh=ctx.mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                check_vma=False,
            )
            return fn(q, k, v)
        return flash_attention(q, k, v, causal=causal, window=window)
    if impl in ("ring", "ulysses"):
        if ctx is None or cp <= 1:
            # degenerate: no seq axis -> plain attention is identical,
            # and the xla path handles window/mask natively — so a
            # single-chip run of a windowed model must not hit the
            # cp-only NotImplementedErrors below
            return xla_attention(q, k, v, causal=causal, window=window,
                                 mask=mask)
        if mask is not None:
            raise NotImplementedError(
                f"{impl} attention does not take explicit masks (causal only)"
            )
        if window is not None:
            raise NotImplementedError(
                "sliding-window attention is not yet supported under "
                "context parallelism (ring/ulysses) — train windowed "
                "models with dp/fsdp/tp, or drop seq_parallel"
            )
        head_axis = (
            ctx.head_axis if ctx.degrees.get(ctx.head_axis, 1) > 1 else None
        )
        from jax.sharding import PartitionSpec as P

        batch_spec = P(ctx.batch_spec_entry())
        if impl == "ring":
            from ..parallel.ring import ring_attention_sharded

            return ring_attention_sharded(
                q, k, v, ctx.mesh, causal=causal, axis_name=ctx.seq_axis,
                batch_spec=batch_spec, head_axis=head_axis,
            )
        from ..parallel.ulysses import ulysses_attention_sharded

        return ulysses_attention_sharded(
            q, k, v, ctx.mesh, causal=causal, axis_name=ctx.seq_axis,
            batch_spec=batch_spec, head_axis=head_axis,
        )
    raise ValueError(f"Unknown attention impl {impl!r}")
