"""TPU-tuned ops: attention (XLA + Pallas), checkpoint policies, layers."""
