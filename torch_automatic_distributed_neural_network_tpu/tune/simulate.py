"""Fleet-scale what-if planner: joint train × serve × survive predictions
for hypothetical TPU fleets, without touching a chip.

``tadnn simulate`` sweeps topologies (``topology.parse_topology`` SKU
spellings, optionally expanded over slice counts) crossed with every
plan the tuner would enumerate (``tune/space.py``) and, per candidate,
joins four independently-shipped models into one prediction:

- **training**: roofline MFU / step time from ``tune/cost.py`` (with
  any measured overlap correction), per-device HBM headroom from the
  same sharding-aware memory math the tuner prunes with;
- **serving**: KV-pool capacity from ``analysis.serve_lint`` and
  throughput / p99 / occupancy / preemptions from a discrete-event
  replay of the REAL ``scheduler.py`` — the replay drives an actual
  :class:`Scheduler` on virtual time, mirroring ``ServeEngine.step``'s
  phase order exactly, so the predicted admission behavior is the
  shipped policy, not a model of it;
- **survival**: probability the fleet's preemption rate exhausts the
  ``RestartPolicy`` rolling-window restart budget over the mission
  (``training.resilience.survival_probability``).

Candidates are ranked by an operator SLO (``tune/slo.py``), sweeps are
cached through ``tune/cache.py``, and everything journals ``simulate.*``
events for ``tadnn report``.  Every future real bench record becomes a
falsification test of these predictions (``report --check-simulate``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Sequence

import numpy as np

from .. import planner
from .. import topology as topo_mod
from ..inference.serve.kv_pool import BlockAllocator, blocks_for_tokens
from ..inference.serve.prefix_cache import PrefixCache
from ..inference.serve.scheduler import Request, Scheduler
from ..obs import journal as obs_journal
from ..training.resilience import survival_probability
from . import cache as cache_mod
from . import cost as cost_mod
from . import space as space_mod
from .slo import SLOSpec, rank as slo_rank

# Matmul efficiency assumed by the analytic serving-time model — same
# knob the training roofline uses.
_EFFICIENCY = cost_mod._EFFICIENCY


@dataclasses.dataclass(frozen=True)
class TrafficMix:
    """Parameterized serving traffic for the discrete-event replay.

    ``rate_per_s`` draws seeded exponential inter-arrivals; prompt and
    decode lengths are drawn uniformly within ``±jitter`` of their
    means (``jitter=0`` makes the mix fully deterministic, which the
    analytic tests rely on).  ``decode_mean`` is the EXPECTED tokens
    before EOS — the replay emits EOS there, so ``max_new`` is the
    budget, not the typical length, exactly like production traffic.

    ``shared_prefix`` models prefix-heavy production traffic: every
    request's prompt opens with that many IDENTICAL tokens (a system
    prompt / few-shot preamble), the rest unique per request.  With
    ``replay_serve(prefix_cache=True)`` the replay's radix index then
    prices the redundant-prefill savings; with the cache off the knob
    changes nothing (content never affects timing there).
    """

    rate_per_s: float = 16.0
    n_requests: int = 64
    prompt_mean: int = 128
    max_new: int = 128
    decode_mean: int | None = None
    jitter: float = 0.5
    seed: int = 0
    shared_prefix: int = 0

    @classmethod
    def parse(cls, text: str | None) -> "TrafficMix":
        """Parse ``"rate=16,n=64,prompt=128,max_new=128,decode=96"``."""
        if not text or not text.strip():
            return cls()
        alias = {"rate": "rate_per_s", "n": "n_requests",
                 "prompt": "prompt_mean", "decode": "decode_mean",
                 "shared": "shared_prefix"}
        fields = {f.name: f.type for f in dataclasses.fields(cls)}
        kwargs: dict[str, Any] = {}
        for clause in text.split(","):
            clause = clause.strip()
            if not clause:
                continue
            name, sep, raw = clause.partition("=")
            if not sep:
                raise ValueError(
                    f"traffic clause {clause!r} is not name=value")
            name = alias.get(name.strip(), name.strip())
            if name not in fields:
                raise ValueError(
                    f"unknown traffic field {name!r}; known: "
                    f"{', '.join(sorted(set(fields) | set(alias)))}")
            val = float(raw)
            kwargs[name] = (val if name in ("rate_per_s", "jitter")
                            else int(val))
        return cls(**kwargs)

    def sample(self, *, max_len: int
               ) -> list[tuple[float, int, int, int]]:
        """Seeded request list: ``(arrival_s, n_prompt, max_new,
        n_decode)`` tuples, clamped to the engine's ``max_len``."""
        rng = np.random.RandomState(self.seed)
        t = 0.0
        out: list[tuple[float, int, int, int]] = []
        decode_mean = self.decode_mean or self.max_new

        def draw(mean: int) -> int:
            if self.jitter <= 0:
                return max(1, int(mean))
            lo = max(1, int(mean * (1.0 - self.jitter)))
            hi = max(lo, int(mean * (1.0 + self.jitter)))
            return int(rng.randint(lo, hi + 1))

        for _ in range(max(1, self.n_requests)):
            if self.rate_per_s > 0:
                t += float(rng.exponential(1.0 / self.rate_per_s))
            n_prompt = min(draw(self.prompt_mean), max(1, max_len - 1))
            max_new = min(int(self.max_new), max_len - n_prompt)
            max_new = max(1, max_new)
            n_decode = max(1, min(draw(decode_mean), max_new))
            out.append((t, n_prompt, max_new, n_decode))
        return out


def replay_serve(
    requests: Sequence[tuple[float, int, int, int]],
    *,
    n_slots: int = 8,
    block_size: int = 16,
    max_len: int = 256,
    num_blocks: int | None = None,
    admission: str = "reserve",
    prefill_chunk: int | None = 32,
    prefill_chunks_per_step: int = 1,
    spec_lookahead: int = 0,
    decode_step_s: float = 1e-3,
    prefill_chunk_s: float = 1e-3,
    disaggregate: bool = False,
    kv_ship_s: float = 0.0,
    dcn_step_s: float = 0.0,
    prefix_cache: bool = False,
    shared_prefix: int = 0,
    max_steps: int = 200_000,
) -> dict:
    """Discrete-event replay of the serving scheduler on virtual time.

    Drives a REAL :class:`Scheduler` + :class:`BlockAllocator` (the
    clock injected, nothing else changed) through the exact phase order
    of ``ServeEngine.step``: evict finished → admit/start-prefill →
    advance one chunk per planned slot → grow/preempt (optimistic) →
    decode every running slot → occupancy accrual.  Token *values* are
    emulated (EOS exactly at each request's ``n_decode``); token
    *timing* comes from the supplied per-step costs, so the output is
    the policy's admission/preemption/occupancy behavior priced in
    seconds.

    ``disaggregate`` mirrors the engine's split-slice mode: every
    prefilling slot advances each step (no chunks-per-step cap), each
    finished prefill pays ``kv_ship_s`` to hand its KV blocks to the
    decode slice (``Scheduler.record_ship`` accounting, same counters
    the live engine accrues), and a step's wall time is
    ``max(prefill_side, decode_side)`` — the slices run concurrently —
    instead of their sum.  ``dcn_step_s`` prices per-decode-step
    cross-slice collectives (a tp group spanning slices); it is added
    on the decode side in both modes.

    ``prefix_cache`` drives a REAL :class:`PrefixCache` (the engine's
    radix index, same eviction and admission interplay): prompts are
    synthesized as ``shared_prefix`` identical tokens plus a unique
    per-request suffix, each finished prefill publishes its full
    prompt blocks, and a later request's matched prefix skips those
    chunks — so the replay PRICES the hit rate instead of assuming one.
    """
    if prefix_cache and not prefill_chunk:
        raise ValueError(
            "prefix_cache=True requires chunked prefill (the replay "
            "mirrors the engine's contract)")
    clock = [0.0]
    if num_blocks is None:
        num_blocks = n_slots * blocks_for_tokens(max_len, block_size) + 1
    alloc = BlockAllocator(num_blocks)
    pc = (PrefixCache(block_size=block_size, allocator=alloc,
                      clock=lambda: clock[0])
          if prefix_cache else None)
    sched = Scheduler(
        n_slots=n_slots, allocator=alloc, block_size=block_size,
        admission=admission, spec_lookahead=spec_lookahead,
        prefix_cache=pc, clock=lambda: clock[0])
    chunk = (math.gcd(min(int(prefill_chunk), max_len), max_len)
             if prefill_chunk else None)

    pending = sorted(requests)  # by arrival
    n_decode_of: dict[int, int] = {}
    prefill_pos: dict[int, int] = {}
    done: list[Request] = []
    next_arrival = 0

    def emit(req: Request) -> None:
        # EOS (0) exactly at the request's true decode length, 1 else —
        # finished() then trips on the same (max_new | eos) rule the
        # engine uses
        eos_at = n_decode_of[req.rid]
        req.out_tokens.append(0 if req.n_generated + 1 >= eos_at else 1)
        # virtual-time token stamp: consecutive diffs are the replay's
        # predicted inter-token latencies, same field the engine fills
        req.token_walls.append(clock[0])

    steps = 0
    occ_sum = 0.0
    prefill_busy = 0.0
    decode_busy = 0.0
    while steps < max_steps:
        # arrivals due by now join the queue (bench-style all-up-front
        # submission is just every arrival at t=0)
        while (next_arrival < len(pending)
               and pending[next_arrival][0] <= clock[0] + 1e-12):
            arr, n_prompt, max_new, n_dec = pending[next_arrival]
            # shared-prefix content: the radix index matches on token
            # ids, so the shared head must be identical and the tail
            # unique per request (cache off: content is timing-inert)
            n_shared = max(0, min(int(shared_prefix), int(n_prompt) - 1))
            prompt = ([1] * n_shared
                      + [2 + next_arrival] * (int(n_prompt) - n_shared))
            req = Request(prompt=prompt,
                          max_new_tokens=int(max_new), eos_id=0)
            req.t_submit = float(arr)
            n_decode_of[req.rid] = int(n_dec)
            sched.submit(req)
            next_arrival += 1
        if next_arrival >= len(pending) and sched.idle():
            break

        # -- one ServeEngine.step(), phase for phase ---------------------
        progressed = False
        for s in range(n_slots):
            req = sched.slots[s]
            if (req is not None and req.state == "running"
                    and req.finished()):
                done.append(sched.evict(s))
                progressed = True
        step_pf_s = 0.0
        step_dec_s = 0.0

        def ship(slot: int, req: Request) -> float:
            # disaggregated: finished prefill pays the block handoff
            # into the decode slice (engine: pool.ship_prefill)
            if not disaggregate:
                return 0.0
            sched.record_ship(
                slot, blocks_for_tokens(req.n_prompt, block_size))
            return kv_ship_s

        for slot, req in sched.admit():
            progressed = True
            if chunk is None:
                step_pf_s += prefill_chunk_s  # one full prompt forward
                step_pf_s += ship(slot, req)
                emit(req)  # single-shot prefill: first token now
                req.t_first_token = clock[0]
                if req.finished():
                    done.append(sched.evict(slot))
            else:
                req.state = "prefilling"
                # a prefix-cache hit starts the cursor after the
                # matched blocks — the skipped chunks are the savings
                prefill_pos[req.rid] = req.cached_tokens
        budget = None if disaggregate else prefill_chunks_per_step
        for slot, req in sched.prefill_plan(budget):
            pos = prefill_pos[req.rid]
            pos += min(chunk, req.n_prompt - pos)
            prefill_pos[req.rid] = pos
            step_pf_s += prefill_chunk_s
            progressed = True
            if pos >= req.n_prompt:
                del prefill_pos[req.rid]
                step_pf_s += ship(slot, req)
                if pc is not None:
                    # publish full prompt blocks (engine: at commit /
                    # KV-ship time)
                    n_pub = req.n_prompt // block_size
                    pc.insert(req.prompt[:n_pub * block_size],
                              req.blocks[:n_pub])
                emit(req)
                req.t_first_token = clock[0]
                req.state = "running"
                if req.finished():
                    done.append(sched.evict(slot))
        for victim in sched.grow_for_step():
            prefill_pos.pop(victim.rid, None)
            progressed = True
        if sched.n_decoding:
            for req in sched.slots:
                if req is not None and req.state == "running":
                    emit(req)
            step_dec_s += decode_step_s + dcn_step_s
            progressed = True
        steps += 1
        occ_sum += sched.n_active / n_slots
        prefill_busy += step_pf_s
        decode_busy += step_dec_s
        # one chip serializes the phases; distinct slices overlap them
        step_s = (max(step_pf_s, step_dec_s) if disaggregate
                  else step_pf_s + step_dec_s)
        clock[0] += step_s

        if not progressed:
            if next_arrival < len(pending):
                # queue drained before the next arrival: jump to it
                clock[0] = max(clock[0], pending[next_arrival][0])
            else:
                break  # wedged (pool too small to ever admit) — report

    totals = [r.t_done - r.t_submit for r in done if r.t_done is not None]
    waits = [r.t_admit - r.t_submit for r in done if r.t_admit is not None]
    ttfts = [r.t_first_token - r.t_submit for r in done
             if r.t_first_token is not None]
    itls = [b - a for r in done
            for a, b in zip(r.token_walls, r.token_walls[1:])]
    new_tokens = sum(r.n_generated for r in done)
    wall = clock[0]
    return {
        "steps": steps,
        "n_requests": len(requests),
        "n_finished": len(done),
        "stalled": len(done) < len(requests),
        "new_tokens": int(new_tokens),
        "wall_s": wall,
        "tokens_per_s": (new_tokens / wall) if wall > 0 else 0.0,
        "mean_occupancy": (occ_sum / steps) if steps else 0.0,
        "preemptions": int(sched.n_preemptions),
        "disaggregate": bool(disaggregate),
        "prefill_busy_s": prefill_busy,
        "decode_busy_s": decode_busy,
        "kv_ships": int(sched.n_kv_ships),
        "shipped_blocks": int(sched.shipped_blocks),
        "p50_s": float(np.percentile(totals, 50)) if totals else None,
        "p99_s": float(np.percentile(totals, 99)) if totals else None,
        "p99_admission_wait_s": (float(np.percentile(waits, 99))
                                 if waits else None),
        "ttft_p50_s": float(np.percentile(ttfts, 50)) if ttfts else None,
        "ttft_p99_s": float(np.percentile(ttfts, 99)) if ttfts else None,
        "itl_p50_s": float(np.percentile(itls, 50)) if itls else None,
        "itl_p99_s": float(np.percentile(itls, 99)) if itls else None,
        "prefix_cache": bool(prefix_cache),
        **({"prefix_queries": pc.queries,
            "prefix_hit_requests": pc.hit_requests,
            "prefix_hit_tokens": pc.hit_tokens,
            "prefix_hit_rate": (
                pc.hit_tokens
                / max(1, sum(int(r[1]) for r in requests))),
            "prefix_evicted_blocks": pc.evicted_blocks}
           if pc is not None else {}),
    }


def replay_bench_record(extra: Mapping[str, Any]) -> dict:
    """Replay a recorded SERVE_BENCH config against the current
    scheduler policy — the ``--check-simulate`` falsification path.

    Per-request decode lengths are not recorded, only the total; the
    replay spreads ``new_tokens`` evenly across the streams (the
    max-occupancy reading of the total — measured occupancy with
    staggered EOS lengths sits a little below it).  Step costs come
    from the record's measured breakdown.
    """
    streams = int(extra["streams"])
    total_new = int(extra.get("new_tokens") or
                    streams * int(extra["max_new"]))
    base, rem = divmod(total_new, streams)
    lens = [base + (1 if i < rem else 0) for i in range(streams)]
    prompt = int(extra["prompt_len"])
    max_new = int(extra["max_new"])
    bd = extra.get("breakdown") or {}
    requests = [(0.0, prompt, max_new, max(1, lens[i]))
                for i in range(streams)]
    result = replay_serve(
        requests,
        n_slots=int(extra["slots"]),
        block_size=int(extra["block_size"]),
        # max_len joined the recorded extra after r03; 64 is the bench
        # default it ran with
        max_len=int(extra.get("max_len") or 64),
        admission=str(extra.get("admission") or "reserve"),
        prefill_chunk=extra.get("prefill_chunk"),
        spec_lookahead=int(extra.get("speculative") or 0),
        decode_step_s=float(bd.get("decode_step_ms") or 1.0) * 1e-3,
        prefill_chunk_s=float(bd.get("prefill_chunk_ms") or 1.0) * 1e-3,
        # r04+ records carry the engine mode; the in-process bench ships
        # blocks at HBM speed, so no extra kv_ship_s term here
        disaggregate=bool(extra.get("disaggregate")),
        # r05+ records carry the prefix-cache mix; the replay reprices
        # the recorded hit rate instead of trusting it
        prefix_cache=bool(extra.get("prefix_cache")),
        shared_prefix=int(extra.get("shared_prefix") or 0),
    )
    obs_journal.event("simulate.replay", source="bench_record", **{
        k: result[k] for k in ("steps", "new_tokens", "tokens_per_s",
                               "mean_occupancy", "preemptions")})
    return result


@dataclasses.dataclass(frozen=True)
class SimulatePolicy:
    """Knobs of the what-if sweep; hashed into the cache key (plain
    JSON-able values only), so any change re-simulates instead of
    replaying a stale report."""

    # training search space (tune/space.py)
    grad_accums: tuple[int, ...] = (1, 2, 4, 8)
    max_tensor: int = 8
    state_factor: float = 4.0
    batch_items: int | None = None
    safety: float = space_mod.MEMORY_SAFETY
    zero1: bool = True
    # measured comm/compute overlap (0..1) correcting the training
    # roofline — from `tadnn trace` via cost.overlap_from_trace, wired
    # through `tadnn simulate --trace-journal` / --measured-overlap
    measured_overlap: float | None = None
    # topology expansion: an un-sliced SKU ("v5p-16") is swept over
    # these slice counts (kept where they divide the chip count)
    slicings: tuple[int, ...] = (1, 2, 4, 8, 16)
    # serving deployment shape (engine defaults)
    admissions: tuple[str, ...] = ("reserve", "optimistic")
    slots: int = 8
    block_size: int = 16
    max_len: int = 256
    prefill_chunk: int | None = 32
    spec_lookahead: int = 0
    # disaggregated prefill/decode serving replicas (engine
    # --disaggregate): prefill on its own slice, KV blocks shipped over
    # DCN on multislice fleets, step wall = max(prefill, decode)
    disaggregate: bool = False
    quant_kv: bool = False
    # cross-request prefix caching (engine --prefix-cache): the replay
    # drives the real radix index over TrafficMix.shared_prefix traffic
    prefix_cache: bool = False
    adapters: int = 0
    adapter_rank: int = 8
    # measured per-step costs override the analytic serving-time model
    decode_step_ms: float | None = None
    prefill_chunk_ms: float | None = None
    # restart-budget survival (training.resilience.RestartPolicy math);
    # the preemption rate is PER HOST per hour — big fleets fail more
    preemption_rate_per_h: float = 0.0
    mission_hours: float = 24.0
    max_restarts: int = 2
    restart_window_s: float = 3600.0
    top_k: int = 10
    use_cache: bool = True


def expand_topologies(
    specs: Sequence[str], slicings: Sequence[int]
) -> list[tuple[str, topo_mod.Topology]]:
    """Parse sweep targets; a spec without an explicit ``xN`` slicing
    fans out over every slice count in ``slicings`` that divides its
    chip count (slicing changes which collectives ride DCN, so it is a
    real degree of freedom, not a spelling detail)."""
    out: list[tuple[str, topo_mod.Topology]] = []
    for spec in specs:
        if "x" in spec.partition("-")[2]:
            out.append((spec, topo_mod.parse_topology(spec)))
            continue
        base = topo_mod.parse_topology(spec)
        n = base.num_devices
        for s in sorted(set(int(s) for s in slicings)):
            if s < 1 or n % s:
                continue
            label = spec if s == 1 else f"{base.device_kind}-{n // s}x{s}"
            out.append((label, topo_mod.parse_topology(label)))
    return out


def _serving_times(chip: topo_mod.ChipSpec, *, params_bytes: int,
                   kv_bytes_per_step: float, prefill_flops_chunk: float,
                   tensor: int) -> tuple[float, float]:
    """Analytic (decode_step_s, prefill_chunk_s) for one tp-group
    serving replica: decode is HBM-bound (weights + KV read per step),
    prefill is the max of its FLOPs and the same weight read."""
    read = params_bytes / max(1, tensor) + kv_bytes_per_step
    decode = read / (chip.hbm_bytes_per_s * _EFFICIENCY)
    pf_compute = prefill_flops_chunk / max(1, tensor) / (
        chip.flops_per_s * _EFFICIENCY)
    pf_mem = (params_bytes / max(1, tensor)
              / (chip.hbm_bytes_per_s * _EFFICIENCY))
    return decode, max(pf_compute, pf_mem)


def _params_bytes(abstract_params: Any) -> int:
    import jax

    return int(sum(
        math.prod(tuple(getattr(leaf, "shape", ())) or (1,))
        * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        for leaf in jax.tree.leaves(abstract_params)))


def simulate(
    abstract_params: Any,
    topo_specs: Sequence[str],
    *,
    model_cfg: Any = None,
    rules: Sequence[planner.Rule] = planner.TRANSFORMER_RULES,
    policy: SimulatePolicy | None = None,
    traffic: TrafficMix | None = None,
    slo: SLOSpec | None = None,
    cache_path: str | None = None,
) -> dict:
    """Run the full what-if sweep; returns the ranked report dict.

    ``model_cfg`` (a transformer config with n_layers/kv_heads/head_dim,
    e.g. ``model.cfg``) sizes the serving KV pool; without one the
    serving terms are None and serving SLO clauses read as violations.
    Pure shape math + virtual-time replay — device-free by construction.
    """
    policy = policy or SimulatePolicy()
    traffic = traffic or TrafficMix()
    slo = slo or SLOSpec()
    key = cache_mod.cache_key(
        cache_mod.params_signature(abstract_params),
        {"specs": sorted(topo_specs)},
        {"sim": dataclasses.asdict(policy),
         "traffic": dataclasses.asdict(traffic),
         "slo": dataclasses.asdict(slo)},
    )
    if policy.use_cache:
        rec = cache_mod.lookup(key, path=cache_path)
        if rec and rec.get("predictions"):
            obs_journal.event("simulate.cache_hit", key=key,
                              n_candidates=len(rec["predictions"]))
            return {**rec, "cache": "hit", "key": key}
        obs_journal.event("simulate.cache_miss", key=key)

    params_bytes = _params_bytes(abstract_params)
    requests = traffic.sample(max_len=policy.max_len)
    replay_memo: dict[tuple, dict] = {}
    serve_memo: dict[tuple, dict | None] = {}
    predictions: list[dict] = []

    topos = expand_topologies(topo_specs, policy.slicings)
    # enumeration depends only on device count + chip kind, not slicing
    # — reuse kept plans across the slice variants of one fleet size
    plans_memo: dict[tuple, list] = {}
    for label, topo in topos:
        pk = (topo.num_devices, topo.device_kind)
        if pk not in plans_memo:
            kept, _pruned = space_mod.enumerate_candidates(
                abstract_params, topo, rules=rules,
                grad_accums=policy.grad_accums,
                max_tensor=policy.max_tensor,
                state_factor=policy.state_factor,
                batch_items=policy.batch_items, safety=policy.safety,
                zero1=policy.zero1)
            plans_memo[pk] = kept
        chip = topo.chip
        survival = survival_probability(
            rate_per_hour=policy.preemption_rate_per_h * topo.num_hosts,
            mission_hours=policy.mission_hours,
            max_restarts=policy.max_restarts,
            window_s=policy.restart_window_s)
        for cand in plans_memo[pk]:
            est = cost_mod.score(
                abstract_params, topo, cand, rules=rules,
                state_factor=policy.state_factor,
                batch_items=policy.batch_items, safety=policy.safety,
                measured_overlap=policy.measured_overlap)
            mem = est.breakdown["memory"]
            headroom = chip.hbm_bytes - mem["total_bytes"]
            mfu = (est.breakdown["flops_per_device"] / est.step_time_s
                   / chip.flops_per_s) if est.step_time_s > 0 else 0.0
            tensor = cand.full_degrees().get("tensor", 1)

            serve_est = None
            if model_cfg is not None:
                from ..analysis.serve_lint import serve_estimate

                sk = (chip, tensor)  # pool capacity is per chip kind
                if sk not in serve_memo:
                    _f, serve_memo[sk] = serve_estimate(
                        model_cfg, budget=chip.hbm_bytes,
                        block_size=policy.block_size,
                        max_len=policy.max_len, streams=policy.slots,
                        quant_kv=policy.quant_kv,
                        params_bytes=params_bytes // max(1, tensor),
                        adapters=policy.adapters or None,
                        adapter_rank=policy.adapter_rank,
                        prefix_cache=policy.prefix_cache,
                        expected_hit_rate=(
                            min(0.95, traffic.shared_prefix
                                / max(1, traffic.prompt_mean))
                            if policy.prefix_cache else 0.0),
                        degrees={"tensor": tensor})
                serve_est = serve_memo[sk]

            for adm in policy.admissions:
                pred: dict[str, Any] = {
                    "topology": label,
                    "num_devices": topo.num_devices,
                    "num_slices": topo.num_slices,
                    "num_hosts": topo.num_hosts,
                    "plan": cand.label(),
                    "strategy": cand.strategy,
                    "mesh": cand.degrees_dict,
                    "grad_accum": cand.grad_accum,
                    "zero1": bool(cand.zero1),
                    "admission": adm,
                    "step_time_s": est.step_time_s,
                    "mfu": round(mfu, 4),
                    "fits": est.fits,
                    "hbm_headroom_bytes": int(headroom),
                    "hbm_headroom_frac": round(
                        headroom / chip.hbm_bytes, 4),
                    "survival": round(survival, 4),
                    "tok_s_per_chip": None,
                    "p99_s": None,
                    "p99_admission_wait_s": None,
                    "mean_occupancy": None,
                    "preemptions": None,
                    "serve": serve_est,
                }
                if serve_est is not None and serve_est["max_streams"] > 0:
                    slots = min(policy.slots, serve_est["max_streams"])
                    kv_tok = (2 * model_cfg.n_layers
                              * model_cfg.kv_heads
                              * model_cfg.head_dim
                              * (1 if policy.quant_kv else 2))
                    if policy.decode_step_ms is not None:
                        dec_s = policy.decode_step_ms * 1e-3
                        pf_s = (policy.prefill_chunk_ms
                                or policy.decode_step_ms) * 1e-3
                    else:
                        dec_s, pf_s = _serving_times(
                            chip, params_bytes=params_bytes,
                            kv_bytes_per_step=(kv_tok * slots
                                               * policy.max_len / 2
                                               / max(1, tensor)),
                            prefill_flops_chunk=(
                                2.0 * (params_bytes / 2)
                                * (policy.prefill_chunk or
                                   traffic.prompt_mean)),
                            tensor=tensor)
                    # multi-slice serving tax (measured step costs came
                    # from single-slice runs, so these apply either way):
                    # a tp group wider than one slice pays two DCN
                    # all-reduces of the [slots, d_model] activations
                    # per layer per decode step
                    dcn_s = 0.0
                    ship_s = 0.0
                    if topo.is_multislice:
                        d = getattr(model_cfg, "d_model",
                                    model_cfg.kv_heads
                                    * model_cfg.head_dim)
                        if tensor > topo.devices_per_slice:
                            step_bytes = (2 * model_cfg.n_layers
                                          * slots * d * 2)
                            dcn_s = (step_bytes / chip.dcn_bytes_per_s
                                     + 2 * model_cfg.n_layers
                                     * chip.dcn_latency_s)
                        if policy.disaggregate:
                            # a finished prompt's KV crosses slices
                            ship_s = (kv_tok * traffic.prompt_mean
                                      / max(1, tensor)
                                      / chip.dcn_bytes_per_s
                                      + chip.dcn_latency_s)
                    rk = (adm, slots, serve_est["num_blocks"],
                          round(dec_s, 9), round(pf_s, 9),
                          policy.disaggregate, policy.prefix_cache,
                          round(ship_s, 9), round(dcn_s, 9))
                    if rk not in replay_memo:
                        replay_memo[rk] = replay_serve(
                            requests, n_slots=slots,
                            block_size=policy.block_size,
                            max_len=policy.max_len,
                            num_blocks=serve_est["num_blocks"],
                            admission=adm,
                            prefill_chunk=policy.prefill_chunk,
                            spec_lookahead=policy.spec_lookahead,
                            decode_step_s=dec_s, prefill_chunk_s=pf_s,
                            disaggregate=policy.disaggregate,
                            kv_ship_s=ship_s, dcn_step_s=dcn_s,
                            prefix_cache=policy.prefix_cache,
                            shared_prefix=traffic.shared_prefix)
                        obs_journal.event(
                            "simulate.replay", admission=adm,
                            slots=slots, decode_step_ms=dec_s * 1e3,
                            disaggregate=policy.disaggregate,
                            dcn_step_ms=dcn_s * 1e3,
                            kv_ship_ms=ship_s * 1e3,
                            **{k: replay_memo[rk][k] for k in
                               ("steps", "tokens_per_s",
                                "mean_occupancy", "preemptions",
                                "stalled", "kv_ships")})
                    rep = replay_memo[rk]
                    pred.update(
                        tok_s_per_chip=round(
                            rep["tokens_per_s"] / max(1, tensor), 3),
                        fleet_tok_s=round(
                            rep["tokens_per_s"] / max(1, tensor)
                            * topo.num_devices, 1),
                        p99_s=rep["p99_s"],
                        p99_admission_wait_s=rep["p99_admission_wait_s"],
                        mean_occupancy=round(rep["mean_occupancy"], 4),
                        preemptions=rep["preemptions"],
                        replay_stalled=rep["stalled"])
                predictions.append(pred)

    ranked = slo_rank(predictions, slo)
    obs_journal.event(
        "simulate.sweep", key=key, n_topologies=len(topos),
        n_candidates=len(ranked), n_replays=len(replay_memo),
        n_slo_ok=sum(1 for p in ranked if p["slo_ok"]))
    for i, p in enumerate(ranked[:8]):
        obs_journal.event("simulate.candidate", rank=i, **{
            k: p[k] for k in (
                "topology", "plan", "admission", "mfu", "step_time_s",
                "hbm_headroom_frac", "tok_s_per_chip", "p99_s",
                "survival", "slo_ok", "slo_violations")})
    report = {
        "predictions": ranked[:policy.top_k] if policy.top_k else ranked,
        "n_candidates": len(ranked),
        "n_slo_ok": sum(1 for p in ranked if p["slo_ok"]),
        "topologies": [label for label, _ in topos],
        "traffic": dataclasses.asdict(traffic),
        "slo": dataclasses.asdict(slo),
    }
    if ranked:
        win = ranked[0]
        obs_journal.event("simulate.decision", key=key, **{
            k: win[k] for k in (
                "topology", "plan", "admission", "slo_ok",
                "slo_violations", "mfu", "tok_s_per_chip", "p99_s",
                "hbm_headroom_frac", "survival")})
    if policy.use_cache:
        try:
            cache_mod.store(key, report, path=cache_path)
        except OSError:
            pass  # read-only HOME etc. — the sweep still worked
    return {**report, "cache": "miss" if policy.use_cache else "off",
            "key": key}
