"""Analytic roofline step-time model for ranking tuner candidates.

Per candidate: build the *abstract* plan (the planner's pure functions
accept a degrees mapping in place of a Mesh), take its
``expected_collective_bytes``, and combine three roofline terms with
per-link numbers from :class:`topology.ChipSpec`:

- compute: 6 * params * items FLOPs (fwd 2PN + bwd 4PN; +1/3 re-forward
  under remat), spread over all devices — or a caller-supplied FLOPs
  count from ``utils.profiling.compiled_cost`` when one exists;
- comms: the planner's ring-formula wire bytes per category, each
  riding ICI or DCN depending on whether its mesh axis crosses slices
  (``topology.hybrid_factorization``), plus per-hop link latency — the
  multihost/multislice penalty;
- HBM: parameter + optimizer-state + activation traffic against the
  chip's HBM bandwidth.

step_time = max(compute, hbm) + comms + latency.  The absolute numbers
are coarse; what the tuner needs is the *ordering*, and the ordering is
driven by terms the model does capture (dp's 2(n-1)/n allreduce vs
ZeRO-3's 3(n-1)/n gather+scatter, DCN vs ICI, memory fit).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import numpy as np

from .. import planner
from .. import topology as topo_mod
from .space import Candidate, DEFAULT_BATCH_ITEMS, candidate_memory, hbm_budget

# Which mesh axes each comm category of expected_collective_bytes rides.
_CATEGORY_AXES = {
    "grad_allreduce": ("data", "expert"),
    "param_allgather": ("fsdp",),
    "grad_reduce_scatter": ("fsdp",),
    "zero1_grad_reduce_scatter": ("data",),
    "zero1_param_allgather": ("data",),
}

# Fraction of peak the analytic model assumes achievable (matmul
# efficiency / collective overlap are not modeled per-op).
_EFFICIENCY = 0.5


@dataclasses.dataclass
class CostEstimate:
    """A candidate with its modeled step time and full breakdown."""

    candidate: Candidate
    step_time_s: float
    fits: bool
    breakdown: dict

    def to_json(self) -> dict:
        return {
            "strategy": self.candidate.strategy,
            "mesh": self.candidate.degrees_dict,
            "grad_accum": self.candidate.grad_accum,
            "zero1": bool(self.candidate.zero1),
            "step_time_ms": round(self.step_time_s * 1e3, 4),
            "fits": self.fits,
            "breakdown": self.breakdown,
        }


def _param_count(abstract_params: Any) -> int:
    import jax

    return sum(
        math.prod(getattr(leaf, "shape", ()) or (1,))
        for leaf in jax.tree.leaves(abstract_params)
    )


def _dcn_axes(topo: topo_mod.Topology, degrees: dict) -> set[str]:
    """Mesh axes whose collectives cross slices (ride DCN)."""
    if not topo.is_multislice:
        return set()
    fact = topo_mod.hybrid_factorization(degrees, topo.num_slices)
    if fact is None:
        # flat-mesh fallback: every nontrivial axis may cross DCN
        return {ax for ax, d in degrees.items() if d > 1}
    _, dcn_shape = fact
    return {
        ax for ax, d in zip(topo_mod.MESH_AXES, dcn_shape) if d > 1
    }


def score(
    abstract_params: Any,
    topo: topo_mod.Topology,
    cand: Candidate,
    *,
    rules: Sequence[planner.Rule] = planner.TRANSFORMER_RULES,
    state_factor: float = 4.0,
    batch_items: int | None = None,
    grad_dtype: Any = np.float32,
    flops_total: float | None = None,
    safety: float | None = None,
    act_profile: dict | None = None,
    measured_overlap: float | None = None,
) -> CostEstimate:
    """Roofline step-time estimate for one candidate.

    ``flops_total`` overrides the analytic 6*P*N FLOPs estimate with a
    measured one (``utils.profiling.compiled_cost``) when the caller
    has compiled the real step; ``act_profile`` swaps the activation
    heuristic for the liveness profile (``space.candidate_memory``).

    ``measured_overlap`` corrects the model's worst-case comm term with
    a measured exposed-collective fraction from a trace
    (``obs.trace.exposed_fraction``): the model charges every wire byte
    as serial time, but XLA hides part of it behind compute, so a
    traced run can feed back "only 30% was exposed" and the comm term
    shrinks to match.  Clamped to [0, 1]; None keeps the worst case.
    """
    chip = topo.chip
    degrees = cand.full_degrees()
    items = batch_items or DEFAULT_BATCH_ITEMS
    remat = cand.strategy in ("fsdp", "tp_fsdp", "ep_fsdp")

    specs = planner.param_spec_tree(
        abstract_params, degrees, cand.strategy, rules
    )
    zero1 = bool(cand.zero1) and degrees.get("data", 1) > 1
    # abstract plan: mesh is the degrees mapping, which every planner
    # pure function accepts (topology.mesh_degrees)
    plan = planner.ShardPlan(
        mesh=degrees,
        strategy=cand.strategy,
        param_specs=specs,
        batch_spec=planner.batch_partition_spec(degrees),
        remat=remat,
        zero1=zero1,
        opt_spec_tree=(planner.zero1_spec_tree(abstract_params, degrees,
                                               specs) if zero1 else None),
    )
    comm = planner.expected_collective_bytes(
        plan, abstract_params,
        grad_dtype=grad_dtype, grad_accum=cand.grad_accum,
    )

    pcount = _param_count(abstract_params)
    flops = flops_total if flops_total else 6.0 * pcount * items
    if remat:
        flops *= 4.0 / 3.0  # one extra forward in backward
    compute_s = flops / topo.num_devices / (chip.flops_per_s * _EFFICIENCY)

    dcn = _dcn_axes(topo, degrees)
    comm_s = 0.0
    latency_s = 0.0
    comm_detail: dict[str, dict] = {}
    for cat, vals in comm["per_device"].items():
        wire = float(vals["wire_bytes"])
        if not wire:
            continue
        axes = [a for a in _CATEGORY_AXES.get(cat, ())
                if degrees.get(a, 1) > 1]
        on_dcn = any(a in dcn for a in axes)
        bw = chip.dcn_bytes_per_s if on_dcn else chip.ici_bytes_per_s
        lat = chip.dcn_latency_s if on_dcn else chip.ici_latency_s
        hops = max(
            (degrees.get(a, 1) for a in axes), default=topo.num_devices
        ) - 1
        t = wire / bw
        l = hops * lat * cand.grad_accum
        comm_s += t
        latency_s += l
        comm_detail[cat] = {
            "wire_bytes": int(wire),
            "link": "dcn" if on_dcn else "ici",
            "s": t + l,
        }

    mem = candidate_memory(
        abstract_params, cand, state_factor=state_factor,
        batch_items=items, rules=rules, remat=remat,
        act_profile=act_profile,
    )
    # fwd+bwd read params twice, optimizer reads+writes state once each
    hbm_traffic = (4.0 * mem["param_bytes"] + 2.0 * mem["state_bytes"]
                   + 2.0 * mem["activation_bytes"])
    hbm_s = hbm_traffic / chip.hbm_bytes_per_s

    budget = hbm_budget(topo) if safety is None else int(
        safety * chip.hbm_bytes)
    fits = mem["total_bytes"] <= budget
    if measured_overlap is not None:
        # latency (per-hop setup) cannot be hidden; only the wire time
        # scales with how much of the collective was actually exposed
        comm_s *= min(1.0, max(0.0, measured_overlap))
    step = max(compute_s, hbm_s) + comm_s + latency_s
    return CostEstimate(
        candidate=cand,
        step_time_s=step,
        fits=fits,
        breakdown={
            "compute_ms": round(compute_s * 1e3, 4),
            "comm_ms": round(comm_s * 1e3, 4),
            "latency_ms": round(latency_s * 1e3, 4),
            "hbm_ms": round(hbm_s * 1e3, 4),
            "comm": comm_detail,
            "memory": mem,
            "hbm_budget_bytes": budget,
            "remat": remat,
            "flops_per_device": flops / topo.num_devices,
            "flops_source": "measured" if flops_total else "analytic_6PN",
            **({"measured_overlap": round(
                min(1.0, max(0.0, measured_overlap)), 4)}
               if measured_overlap is not None else {}),
        },
    )


def overlap_from_trace(trace_steps: Sequence[dict]) -> float | None:
    """Measured exposed-collective fraction over ``trace.step`` records
    (journal dicts or ``obs.trace.attribute`` output) — the value to
    feed back as ``score(measured_overlap=...)``.  None when the trace
    saw no collectives."""
    from ..obs.trace import exposed_fraction

    return exposed_fraction(trace_steps)


def rank(
    abstract_params: Any,
    topo: topo_mod.Topology,
    candidates: Sequence[Candidate],
    **kwargs,
) -> list[CostEstimate]:
    """Score every candidate and sort best-first (fitting plans always
    rank above non-fitting ones, then by modeled step time)."""
    ests = [score(abstract_params, topo, c, **kwargs) for c in candidates]
    ests.sort(key=lambda e: (not e.fits, e.step_time_s))
    return ests
