"""Persistent tuning cache: (param signature, topology fingerprint,
policy) -> winning plan, as append-only JSONL.

Default location ``~/.cache/tadnn/tune_cache.jsonl``; override with the
``TADNN_TUNE_CACHE`` env var (point different jobs at different files,
or at /dev/null-ish paths in hermetic CI).  Append-only with
last-match-wins semantics — concurrent writers at worst duplicate a
line, they never corrupt a decision.

The key hashes everything a decision depends on, so any change —
different model shapes, different device count/kind/slicing, different
search policy — misses cleanly instead of replaying a stale plan.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Mapping

import jax

from .. import planner
from .. import topology as topo_mod

_ENV = "TADNN_TUNE_CACHE"
_DEFAULT = "~/.cache/tadnn/tune_cache.jsonl"


def cache_path(path: str | None = None) -> str:
    return os.path.expanduser(path or os.environ.get(_ENV) or _DEFAULT)


def params_signature(abstract_params: Any) -> str:
    """Stable digest of the abstract param tree (paths, shapes, dtypes)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(abstract_params)
    entries = sorted(
        (
            planner.path_str(keypath),
            list(getattr(leaf, "shape", ()) or ()),
            str(getattr(leaf, "dtype", "float32")),
        )
        for keypath, leaf in flat
    )
    digest = hashlib.sha256(
        json.dumps(entries, sort_keys=True).encode()
    )
    return digest.hexdigest()[:16]


def topology_fingerprint(topo: topo_mod.Topology) -> dict:
    fp = {
        "num_devices": topo.num_devices,
        "num_hosts": topo.num_hosts,
        "platform": topo.platform,
        "device_kind": topo.device_kind,
        "num_slices": topo.num_slices,
    }
    if topo.chip_override is not None:
        # what-if sweeps may override interconnect numbers per topology
        # (topology.parse_topology dcn_* args) — a swept variant must
        # never replay a decision cached under the datasheet chip
        fp["chip_override"] = dataclasses.asdict(topo.chip_override)
    return fp


def cache_key(
    signature: str, topo_fp: Mapping, policy: Mapping | Any
) -> str:
    if dataclasses.is_dataclass(policy) and not isinstance(policy, type):
        policy = dataclasses.asdict(policy)
    blob = json.dumps(
        {"params": signature, "topology": dict(topo_fp),
         "policy": {k: list(v) if isinstance(v, tuple) else v
                    for k, v in dict(policy).items()}},
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def lookup(key: str, path: str | None = None) -> dict | None:
    """Latest cached record for ``key``, or None."""
    p = cache_path(path)
    if not os.path.isfile(p):
        return None
    hit = None
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn concurrent write — skip the line
            if rec.get("key") == key:
                hit = rec.get("record")
    return hit


def store(key: str, record: Mapping, path: str | None = None) -> str:
    """Append a decision; returns the file written."""
    p = cache_path(path)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    with open(p, "a") as f:
        f.write(json.dumps({"key": key, "record": dict(record)}) + "\n")
    return p
