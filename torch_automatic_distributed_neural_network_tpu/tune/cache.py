"""Persistent tuning cache: (param signature, topology fingerprint,
policy) -> winning plan, as append-only JSONL.

Default location ``~/.cache/tadnn/tune_cache.jsonl``; override with the
``TADNN_TUNE_CACHE`` env var (point different jobs at different files,
or at /dev/null-ish paths in hermetic CI).  Append-only with
last-match-wins semantics — concurrent writers at worst duplicate a
line, they never corrupt a decision.

The key hashes everything a decision depends on, so any change —
different model shapes, different device count/kind/slicing, different
search policy — misses cleanly instead of replaying a stale plan.

Size cap: append-only means unbounded growth on long-lived machines.
``TADNN_TUNE_CACHE_MAX_BYTES`` (same contract as the journal's
``TADNN_JOURNAL_MAX_BYTES``, default off) caps the file: when an
append crosses the cap, :func:`compact_jsonl` rewrites it keeping only
the LAST record per key (the record ``lookup`` would return anyway),
then sheds oldest-first if still over.  The export subsystem's
executable index (``export/cache.py``) shares this exact compaction.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Mapping

import jax

from .. import planner
from .. import topology as topo_mod

_ENV = "TADNN_TUNE_CACHE"
_ENV_MAX = "TADNN_TUNE_CACHE_MAX_BYTES"
_DEFAULT = "~/.cache/tadnn/tune_cache.jsonl"


def cache_path(path: str | None = None) -> str:
    return os.path.expanduser(path or os.environ.get(_ENV) or _DEFAULT)


def _env_max_bytes() -> int:
    try:
        return int(os.environ.get(_ENV_MAX, "0"))
    except ValueError:
        return 0


def params_signature(abstract_params: Any) -> str:
    """Stable digest of the abstract param tree (paths, shapes, dtypes)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(abstract_params)
    entries = sorted(
        (
            planner.path_str(keypath),
            list(getattr(leaf, "shape", ()) or ()),
            str(getattr(leaf, "dtype", "float32")),
        )
        for keypath, leaf in flat
    )
    digest = hashlib.sha256(
        json.dumps(entries, sort_keys=True).encode()
    )
    return digest.hexdigest()[:16]


def topology_fingerprint(topo: topo_mod.Topology) -> dict:
    fp = {
        "num_devices": topo.num_devices,
        "num_hosts": topo.num_hosts,
        "platform": topo.platform,
        "device_kind": topo.device_kind,
        "num_slices": topo.num_slices,
    }
    if topo.chip_override is not None:
        # what-if sweeps may override interconnect numbers per topology
        # (topology.parse_topology dcn_* args) — a swept variant must
        # never replay a decision cached under the datasheet chip
        fp["chip_override"] = dataclasses.asdict(topo.chip_override)
    return fp


def cache_key(
    signature: str, topo_fp: Mapping, policy: Mapping | Any
) -> str:
    if dataclasses.is_dataclass(policy) and not isinstance(policy, type):
        policy = dataclasses.asdict(policy)
    blob = json.dumps(
        {"params": signature, "topology": dict(topo_fp),
         "policy": {k: list(v) if isinstance(v, tuple) else v
                    for k, v in dict(policy).items()}},
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def lookup(key: str, path: str | None = None) -> dict | None:
    """Latest cached record for ``key``, or None."""
    p = cache_path(path)
    if not os.path.isfile(p):
        return None
    hit = None
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn concurrent write — skip the line
            if rec.get("key") == key:
                hit = rec.get("record")
    return hit


def store(key: str, record: Mapping, path: str | None = None,
          max_bytes: int | None = None) -> str:
    """Append a decision; returns the file written.

    ``max_bytes`` caps the file via :func:`compact_jsonl` (None reads
    ``TADNN_TUNE_CACHE_MAX_BYTES``; 0 disables — callers with their own
    compaction schedule, like the export index, pass 0).
    """
    p = cache_path(path)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    with open(p, "a") as f:
        f.write(json.dumps({"key": key, "record": dict(record)}) + "\n")
    cap = _env_max_bytes() if max_bytes is None else max_bytes
    if cap:
        try:
            over = os.path.getsize(p) >= cap
        except OSError:
            over = False
        if over:
            compact_jsonl(p, max_bytes=cap)
    return p


def compact_jsonl(path: str, max_bytes: int = 0) -> dict:
    """Dedup-compact an append-only keyed JSONL file in place.

    Keeps the LAST record per key (last-match-wins semantics preserved
    bit-for-bit: every surviving key still resolves to the same record
    ``lookup`` returned before), ordered by last occurrence; torn lines
    are dropped.  If the result still exceeds ``max_bytes`` (when
    nonzero), oldest entries are shed first.  Atomic (tmp +
    ``os.replace``), so a concurrent reader sees either generation,
    never a torn file.  Returns compaction stats.
    """
    if not os.path.isfile(path):
        return {"before_bytes": 0, "after_bytes": 0, "kept": 0,
                "dropped": 0}
    before = os.path.getsize(path)
    last: dict[str, str] = {}  # key -> raw line, in last-occurrence order
    total = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn write — compaction discards it
            if not isinstance(rec, dict) or rec.get("key") is None:
                continue
            total += 1
            last.pop(rec["key"], None)  # re-insert at the end
            last[rec["key"]] = line
    lines = list(last.values())
    dropped = total - len(lines)
    if max_bytes:
        size = sum(len(ln) + 1 for ln in lines)
        while lines and size > max_bytes:
            size -= len(lines[0]) + 1
            lines.pop(0)
            dropped += 1
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for ln in lines:
            f.write(ln + "\n")
    os.replace(tmp, path)
    return {"before_bytes": before, "after_bytes": os.path.getsize(path),
            "kept": len(lines), "dropped": dropped}
