"""Candidate search space for the parallelism autotuner.

Enumerates every plan the framework could actually build on this
topology: divisor splits of the device count across strategies
(dp / fsdp / tp_fsdp / ep / ep_fsdp) x tensor degree x grad-accum
choice x ZeRO-1 optimizer-state sharding (for meshes with a nontrivial
data axis), then prunes by a per-device memory-fit estimate — params +
grads + optimizer state through the planner's real ``param_spec_tree``
sharding math (so indivisible dims that stay replicated are charged
correctly) plus a coarse activation estimate.

Everything here is pure shape math: candidates are scored on a degrees
*mapping*, never a built ``Mesh`` (topology.mesh_degrees accepts both),
so enumeration works for hypothetical topologies in unit tests.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import planner
from .. import topology as topo_mod

# Per-device items assumed when the caller gives no batch: enough that
# compute (not fixed overhead) dominates the analytic step time.
DEFAULT_BATCH_ITEMS = 4096

# Fraction of HBM a candidate's state + activations may claim (matches
# the spirit of core.AutoDistribute's search-ladder safety margin).
MEMORY_SAFETY = 0.9

# Activation shrink under gradient checkpointing: only boundary
# activations are stored, the rest recomputed in backward.
REMAT_ACT_FACTOR = 0.25


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the search space: a strategy, its mesh-axis degrees
    (only non-trivial axes listed, ordered like MESH_AXES), a
    grad-accumulation choice, and whether the optimizer state is
    ZeRO-1-sharded over the data axis."""

    strategy: str
    degrees: tuple[tuple[str, int], ...]
    grad_accum: int = 1
    zero1: bool = False

    @property
    def degrees_dict(self) -> dict[str, int]:
        return dict(self.degrees)

    def full_degrees(self) -> dict[str, int]:
        """Degrees over every canonical axis (unlisted axes -> 1)."""
        d = dict(self.degrees)
        return {ax: int(d.get(ax, 1)) for ax in topo_mod.MESH_AXES}

    def label(self) -> str:
        mesh = "x".join(f"{ax}{n}" for ax, n in self.degrees if n > 1)
        s = f"{self.strategy}[{mesh or '1'}]"
        if self.zero1:
            s += "+z1"
        if self.grad_accum > 1:
            s += f"/ga{self.grad_accum}"
        return s


def _degrees_key(strategy: str, degrees: dict[str, int],
                 zero1: bool = False) -> tuple:
    return (strategy,
            tuple(sorted((a, n) for a, n in degrees.items() if n > 1)),
            bool(zero1))


def _as_candidate(strategy: str, degrees: dict[str, int],
                  grad_accum: int, zero1: bool = False) -> Candidate:
    ordered = tuple(
        (ax, int(degrees[ax]))
        for ax in topo_mod.MESH_AXES
        if degrees.get(ax, 1) >= 1 and ax in degrees
    )
    return Candidate(strategy=strategy, degrees=ordered,
                     grad_accum=grad_accum, zero1=zero1)


def estimate_batch_items(batch: Any) -> int:
    """Items per global step implied by a sample batch: tokens for LM
    batches ([B, S] integer ids), leading-dim rows otherwise."""
    best = 1
    for leaf in jax.tree.leaves(batch):
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape:
            continue
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        if np.issubdtype(dtype, np.integer) and len(shape) >= 2:
            best = max(best, int(shape[0]) * int(shape[1]))
        else:
            best = max(best, int(shape[0]))
    return best


def _model_width(abstract_params: Any) -> int:
    """Modal trailing dim of matrix params — a d_model estimate."""
    counts: dict[int, int] = {}
    for leaf in jax.tree.leaves(abstract_params):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) >= 2:
            counts[int(shape[-1])] = counts.get(int(shape[-1]), 0) + 1
    if not counts:
        return 1
    return max(counts, key=lambda k: (counts[k], k))


def activation_bytes(
    abstract_params: Any,
    items_per_device: float,
    *,
    itemsize: int = 4,
    remat: bool = False,
) -> int:
    """Coarse per-device activation estimate.

    Every matmul writes one activation row per item; total activation
    elements per item ~ param_count / d_model (exact for a stack of
    square-ish matmuls, order-of-magnitude elsewhere — which is all a
    fit *estimate* needs).
    """
    param_count = sum(
        math.prod(getattr(leaf, "shape", ()) or (1,))
        for leaf in jax.tree.leaves(abstract_params)
    )
    per_item = param_count / max(1, _model_width(abstract_params))
    est = itemsize * items_per_device * per_item
    return int(est * (REMAT_ACT_FACTOR if remat else 1.0))


def _profiled_activation_bytes(
    act_profile: dict,
    items_per_device: float,
    *,
    remat: bool,
    param_frac: float,
) -> int:
    """Per-device transient bytes from a liveness profile
    (``analysis.mem_lint`` via ``AutoDistribute.activation_profile``):
    the traced batch-proportional term rescales linearly to this
    candidate's items/device, param-shaped transients (grads, optimizer
    temporaries) scale with the candidate's average param shard
    fraction, the remainder is charged in full."""
    key = "remat" if (remat and act_profile.get("remat")) else "noremat"
    prof = act_profile.get(key) or act_profile.get("noremat") or {}
    n0 = max(1, int(act_profile.get("batch_items") or 1))
    est = (
        prof.get("batch_bytes", 0) * (items_per_device / n0)
        + prof.get("param_like_bytes", 0) * param_frac
        + prof.get("other_bytes", 0)
    )
    return int(est)


def candidate_memory(
    abstract_params: Any,
    cand: Candidate,
    *,
    state_factor: float = 4.0,
    batch_items: int | None = None,
    rules: Sequence[planner.Rule] = planner.TRANSFORMER_RULES,
    remat: bool = True,
    act_profile: dict | None = None,
) -> dict:
    """Per-device memory estimate for a candidate, via the planner's own
    spec assignment (replicated-because-indivisible dims are charged in
    full, exactly as GSPMD would lay them out).

    With ``act_profile`` (a liveness profile of the *real* traced step)
    the activation term comes from measured liveness intervals rescaled
    to this candidate; without it, from the coarse param-count
    heuristic (:func:`activation_bytes`).
    """
    degrees = cand.full_degrees()
    specs = planner.param_spec_tree(
        abstract_params, degrees, cand.strategy, rules
    )
    spec_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    leaves = jax.tree.leaves(abstract_params)

    def sharded_bytes(spec_flat):
        acc = 0.0
        for spec, leaf in zip(spec_flat, leaves):
            shape = tuple(getattr(leaf, "shape", ()))
            itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
            nbytes = (math.prod(shape) if shape else 1) * itemsize
            frac = 1
            for ax in planner.spec_axes(spec):
                frac *= degrees.get(ax, 1)
            acc += nbytes / max(1, frac)
        return acc

    total_b = float(sum(
        (math.prod(tuple(getattr(leaf, "shape", ())) or (1,)))
        * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        for leaf in leaves
    ))
    param_b = sharded_bytes(spec_leaves)
    if cand.zero1 and degrees.get("data", 1) > 1:
        # split state_factor: params + grads stay at the param sharding
        # (factor capped at 2), optimizer moments (the remainder — exact
        # 2.0 for uniform fp32 adam, conservative for mixed-precision
        # factors) are charged at the zero1 opt-spec sharding instead
        opt_tree = planner.zero1_spec_tree(abstract_params, degrees, specs)
        opt_leaves = jax.tree.leaves(
            opt_tree, is_leaf=lambda x: isinstance(x, P))
        moment_factor = max(0.0, state_factor - 2.0)
        state_b = (min(state_factor, 2.0) * param_b
                   + moment_factor * sharded_bytes(opt_leaves))
    else:
        state_b = state_factor * param_b
    batch_deg = math.prod(
        degrees.get(a, 1) for a in ("data", "fsdp", "expert")
    )
    items = (batch_items or DEFAULT_BATCH_ITEMS) / max(1, batch_deg)
    items /= max(1, cand.grad_accum)
    if act_profile:
        act_b = _profiled_activation_bytes(
            act_profile, items, remat=remat,
            param_frac=param_b / max(1.0, total_b))
    else:
        act_b = activation_bytes(abstract_params, items, remat=remat)
    return {
        "param_bytes": int(param_b),
        "state_bytes": int(state_b),
        "activation_bytes": int(act_b),
        "total_bytes": int(state_b + act_b),
        "profiled": bool(act_profile),
    }


def hbm_budget(topo: topo_mod.Topology, safety: float = MEMORY_SAFETY) -> int:
    return int(safety * topo.chip.hbm_bytes)


def enumerate_candidates(
    abstract_params: Any,
    topo: topo_mod.Topology,
    *,
    rules: Sequence[planner.Rule] = planner.TRANSFORMER_RULES,
    grad_accums: Sequence[int] = (1,),
    max_tensor: int = 8,
    state_factor: float = 4.0,
    batch_items: int | None = None,
    safety: float = MEMORY_SAFETY,
    act_profile: dict | None = None,
    zero1: bool = True,
) -> tuple[list[Candidate], list[tuple[Candidate, str]]]:
    """(kept, pruned) candidates for this model on this topology.

    ``kept`` passes the per-device memory-fit estimate; ``pruned``
    carries a human-readable reason per dropped candidate so the CLI
    can show *why* the space shrank.
    """
    n = topo.num_devices
    raw: list[tuple[str, dict[str, int]]] = []
    seen: set = set()

    def add(strategy: str, degrees: dict[str, int]) -> None:
        key = _degrees_key(strategy, degrees)
        if key not in seen and math.prod(degrees.values()) == n:
            seen.add(key)
            raw.append((strategy, degrees))

    divisors = [d for d in range(1, n + 1) if n % d == 0]
    add("dp", {"data": n})
    if n > 1:
        add("fsdp", {"fsdp": n})
    if planner.tp_applicable(abstract_params, rules):
        for t in divisors:
            if 2 <= t <= max_tensor and n // t >= 2:
                add("tp_fsdp", {"tensor": t, "fsdp": n // t})
    e_count = planner.detect_expert_count(abstract_params)
    if e_count:
        g = math.gcd(n, e_count)
        for e in divisors:
            if e >= 2 and g % e == 0:
                add("ep", {"expert": e, "data": n // e})
                if n // e >= 2:
                    add("ep_fsdp", {"expert": e, "fsdp": n // e})

    budget = hbm_budget(topo, safety)
    kept: list[Candidate] = []
    pruned: list[tuple[Candidate, str]] = []
    for strategy, degrees in raw:
        # a nontrivial data axis admits a ZeRO-1 variant: same mesh,
        # optimizer moments sharded over 'data' (arxiv 2004.13336)
        z1_opts = ((False, True) if zero1 and degrees.get("data", 1) > 1
                   else (False,))
        for ga, z1 in ((g, z) for g in grad_accums for z in z1_opts):
            cand = _as_candidate(strategy, degrees, int(ga), zero1=z1)
            mem = candidate_memory(
                abstract_params, cand, state_factor=state_factor,
                batch_items=batch_items, rules=rules,
                act_profile=act_profile,
            )
            if mem["total_bytes"] > budget:
                kind = "liveness" if act_profile else "heuristic"
                pruned.append((cand, (
                    f"memory: ~{mem['total_bytes'] / 2**30:.2f} GiB "
                    f"(state {mem['state_bytes'] / 2**30:.2f} + act "
                    f"{mem['activation_bytes'] / 2**30:.2f}, {kind}) "
                    f"> budget {budget / 2**30:.2f} GiB")))
            else:
                kept.append(cand)
    return kept, pruned
