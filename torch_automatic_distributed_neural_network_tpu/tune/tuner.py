"""Tuner orchestration: enumerate -> score -> (optionally measure) ->
cache, with every decision journaled.

Entry point is :func:`tune`; ``planner.make_plan(strategy='tuned')``
calls it and builds the winning mesh, so ``AutoDistribute(...,
strategy='tuned')`` and ``Trainer`` get autotuned plans with no other
changes.  Journal event names (all picked up by ``tadnn report``):

- ``tune.cache_hit`` / ``tune.cache_miss`` — persistent-cache probe
- ``tune.fallback`` — degenerate space, heuristic ``auto`` answer used
- ``tune.candidate`` — one per ranked candidate (top 8), with the full
  cost breakdown
- ``tune.decision`` — the winner and why
- ``tune.trial`` spans / ``tune.trial.result`` — measured calibration
  (tune/measure.py)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

from .. import planner
from .. import topology as topo_mod
from ..obs import journal as obs_journal
from . import cache as cache_mod
from . import cost as cost_mod
from . import space as space_mod


@dataclasses.dataclass(frozen=True)
class TunePolicy:
    """Knobs of the search; hashed into the cache key, so changing any
    of them re-tunes instead of replaying a stale decision."""

    grad_accums: tuple[int, ...] = (1,)
    max_tensor: int = 8
    state_factor: float = 4.0
    # items (tokens for LM batches) per global optimizer step; None ->
    # space.DEFAULT_BATCH_ITEMS
    batch_items: int | None = None
    safety: float = space_mod.MEMORY_SAFETY
    top_k: int = 3
    use_cache: bool = True
    # Enumerate ZeRO-1 optimizer-state-sharding variants (space.py adds
    # a zero1=True twin of every candidate with a nontrivial data axis).
    # Part of the frozen dataclass, so it hashes into the cache key —
    # a cached plain-dp decision can never shadow a dp+zero1 search.
    zero1: bool = True
    # Liveness activation profile of the real traced step
    # (AutoDistribute.activation_profile / analysis.mem_lint) — swaps
    # the coarse activation heuristic for measured liveness intervals
    # in memory pruning and ranking.  A plain JSON-able dict, so it
    # hashes into the cache key like every other knob: a changed model
    # graph re-tunes.
    act_profile: Any = None


@dataclasses.dataclass
class TuneResult:
    strategy: str
    degrees: dict[str, int]
    grad_accum: int
    ranked: list  # list[cost.CostEstimate]; empty on cache hit/fallback
    source: str  # 'cost_model' | 'cache' | 'fallback'
    key: str
    zero1: bool = False  # winner shards optimizer state over 'data'


def tune(
    abstract_params: Any,
    topo: topo_mod.Topology | None = None,
    *,
    rules: Sequence[planner.Rule] = planner.TRANSFORMER_RULES,
    policy: TunePolicy | None = None,
    cache_path: str | None = None,
) -> TuneResult:
    """Pick (strategy, mesh degrees, grad_accum) for this model on this
    topology.  Pure shape math — no device arrays are built, so it runs
    before any mesh exists."""
    topo = topo or topo_mod.detect()
    policy = policy or TunePolicy()
    key = cache_mod.cache_key(
        cache_mod.params_signature(abstract_params),
        cache_mod.topology_fingerprint(topo),
        policy,
    )

    if policy.use_cache:
        rec = cache_mod.lookup(key, path=cache_path)
        if rec and rec.get("strategy"):
            obs_journal.event(
                "tune.cache_hit", key=key, strategy=rec["strategy"],
                mesh=rec.get("degrees"), grad_accum=rec.get("grad_accum", 1),
                zero1=bool(rec.get("zero1", False)),
                step_time_ms=rec.get("step_time_ms"),
            )
            return TuneResult(
                strategy=rec["strategy"],
                degrees={k: int(v) for k, v in
                         (rec.get("degrees") or {}).items()},
                grad_accum=int(rec.get("grad_accum", 1)),
                ranked=[], source="cache", key=key,
                zero1=bool(rec.get("zero1", False)),
            )
        obs_journal.event("tune.cache_miss", key=key)

    kept, pruned = space_mod.enumerate_candidates(
        abstract_params, topo, rules=rules,
        grad_accums=policy.grad_accums, max_tensor=policy.max_tensor,
        state_factor=policy.state_factor, batch_items=policy.batch_items,
        safety=policy.safety, act_profile=policy.act_profile,
        zero1=policy.zero1,
    )
    if topo.num_devices == 1 or len(kept) <= 1:
        # Degenerate space (single chip, or pruning left at most one
        # survivor): nothing to rank — the auto heuristic is the answer.
        strategy, degrees = planner.choose_strategy(
            abstract_params, topo, rules, state_factor=policy.state_factor
        )
        obs_journal.event(
            "tune.fallback",
            reason=(f"degenerate space: {topo.num_devices} device(s), "
                    f"{len(kept)} candidate(s) after pruning"),
            strategy=strategy, mesh=dict(degrees), key=key,
        )
        return TuneResult(
            strategy=strategy, degrees=dict(degrees), grad_accum=1,
            ranked=[], source="fallback", key=key,
        )

    ranked = cost_mod.rank(
        abstract_params, topo, kept, rules=rules,
        state_factor=policy.state_factor, batch_items=policy.batch_items,
        safety=policy.safety, act_profile=policy.act_profile,
    )
    for i, est in enumerate(ranked[:8]):
        obs_journal.event("tune.candidate", rank=i, **est.to_json())
    win = ranked[0]
    decision = {
        "strategy": win.candidate.strategy,
        "degrees": win.candidate.degrees_dict,
        "grad_accum": win.candidate.grad_accum,
        "zero1": bool(win.candidate.zero1),
        "step_time_ms": round(win.step_time_s * 1e3, 4),
        "fits": win.fits,
    }
    obs_journal.event(
        "tune.decision", source="cost_model", key=key,
        n_candidates=len(kept), n_pruned=len(pruned),
        breakdown=win.breakdown, **decision,
    )
    if policy.use_cache:
        try:
            cache_mod.store(key, decision, path=cache_path)
        except OSError:
            pass  # read-only HOME etc. — tuning still worked
    return TuneResult(
        strategy=win.candidate.strategy,
        degrees=win.candidate.degrees_dict,
        grad_accum=win.candidate.grad_accum,
        ranked=ranked, source="cost_model", key=key,
        zero1=bool(win.candidate.zero1),
    )
