"""Cost-model-driven parallelism autotuner.

Turns plan selection from a single hand-written heuristic
(``planner.choose_strategy``) into enumerate -> score -> (optionally)
measure -> cache:

- :mod:`.space` — candidate mesh factorizations x strategy x tensor
  degree x grad-accum, pruned by a per-device memory-fit estimate
- :mod:`.cost` — analytic roofline step-time model (FLOPs, the
  planner's collective-bytes estimate over per-link ICI/DCN bandwidth,
  HBM pressure)
- :mod:`.measure` — optional compile-and-time of the top-k candidates
  (real train step; works on the CPU sim)
- :mod:`.cache` — persistent JSONL decisions under ``~/.cache/tadnn/``
  (``TADNN_TUNE_CACHE`` overrides)

Use it implicitly with ``AutoDistribute(..., strategy='tuned')`` /
``make_plan(strategy='tuned')``, or explicitly via :func:`tune` and the
``tadnn tune`` CLI.  Decisions, cost breakdowns, and measured trials
are journaled (``tune.*`` events) so ``tadnn report`` shows why a plan
was chosen.
"""

from . import cache, cost, measure, space
from .cost import CostEstimate, rank, score
from .space import Candidate, enumerate_candidates, estimate_batch_items
from .tuner import TunePolicy, TuneResult, tune

__all__ = [
    "Candidate",
    "CostEstimate",
    "TunePolicy",
    "TuneResult",
    "cache",
    "cost",
    "enumerate_candidates",
    "estimate_batch_items",
    "measure",
    "rank",
    "score",
    "space",
    "tune",
]
