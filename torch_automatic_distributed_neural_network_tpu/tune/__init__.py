"""Cost-model-driven parallelism autotuner.

Turns plan selection from a single hand-written heuristic
(``planner.choose_strategy``) into enumerate -> score -> (optionally)
measure -> cache:

- :mod:`.space` — candidate mesh factorizations x strategy x tensor
  degree x grad-accum, pruned by a per-device memory-fit estimate
- :mod:`.cost` — analytic roofline step-time model (FLOPs, the
  planner's collective-bytes estimate over per-link ICI/DCN bandwidth,
  HBM pressure)
- :mod:`.measure` — optional compile-and-time of the top-k candidates
  (real train step; works on the CPU sim)
- :mod:`.cache` — persistent JSONL decisions under ``~/.cache/tadnn/``
  (``TADNN_TUNE_CACHE`` overrides)

Use it implicitly with ``AutoDistribute(..., strategy='tuned')`` /
``make_plan(strategy='tuned')``, or explicitly via :func:`tune` and the
``tadnn tune`` CLI.  Decisions, cost breakdowns, and measured trials
are journaled (``tune.*`` events) so ``tadnn report`` shows why a plan
was chosen.

The fleet-scale what-if layer composes these with the serving and
resilience models:

- :mod:`.simulate` — sweep hypothetical topologies x plans, predict
  MFU / HBM headroom / serving tok/s + p99 (discrete-event replay of
  the real scheduler) / restart survival (``tadnn simulate``)
- :mod:`.slo` — operator SLO specs the sweep ranks against
"""

from . import cache, cost, measure, simulate, slo, space
from .cost import CostEstimate, rank, score
from .simulate import SimulatePolicy, TrafficMix, replay_serve
from .slo import SLOSpec
from .space import Candidate, enumerate_candidates, estimate_batch_items
from .tuner import TunePolicy, TuneResult, tune

__all__ = [
    "Candidate",
    "CostEstimate",
    "SLOSpec",
    "SimulatePolicy",
    "TrafficMix",
    "TunePolicy",
    "TuneResult",
    "cache",
    "cost",
    "enumerate_candidates",
    "estimate_batch_items",
    "measure",
    "rank",
    "replay_serve",
    "score",
    "simulate",
    "slo",
    "space",
    "tune",
]
