"""Operator SLO specs for the what-if planner (``tadnn simulate``).

An :class:`SLOSpec` is the contract a candidate fleet plan must meet:
minimum serving throughput per chip, maximum p99 latency, minimum
per-device HBM headroom, minimum probability of surviving the mission
without exhausting the restart budget.  Candidates are ranked SLO-first
— every plan that meets the spec beats every plan that misses it, and
among the misses fewer violations rank higher — so the top of the
report is always the cheapest plan that actually keeps the promise,
not the fastest plan that quietly blows the latency budget.

Specs are spelled compactly on the command line::

    tadnn simulate --slo "tok_s_chip>=40,p99_ms<=2500,headroom>=0.1,survival>=0.9"
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

# CLI field -> (attr, comparator, value transform).  Latencies are
# spelled in ms on the command line (operators think in ms) but stored
# in seconds like every other latency in the codebase.
_FIELDS = {
    "tok_s_chip": ("min_tok_s_per_chip", ">=", 1.0),
    "p99_ms": ("max_p99_s", "<=", 1e-3),
    "headroom": ("min_hbm_headroom_frac", ">=", 1.0),
    "survival": ("min_survival", ">=", 1.0),
    # p99 time-to-first-token / inter-token latency: predicted by the
    # serve replay and measured live per window by obs/slo_monitor
    "ttft_ms": ("max_ttft_p99_s", "<=", 1e-3),
    "itl_ms": ("max_itl_p99_s", "<=", 1e-3),
}


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Thresholds a candidate plan must meet; None means "don't care"."""

    min_tok_s_per_chip: float | None = None
    max_p99_s: float | None = None
    min_hbm_headroom_frac: float | None = None
    min_survival: float | None = None
    max_ttft_p99_s: float | None = None
    max_itl_p99_s: float | None = None

    @classmethod
    def parse(cls, text: str | None) -> "SLOSpec":
        """Parse ``"tok_s_chip>=40,p99_ms<=2500,headroom>=0.1"``.

        Unknown fields or comparators raise ValueError loudly — a typo
        in an SLO must never silently relax the contract.
        """
        if not text or not text.strip():
            return cls()
        kwargs: dict[str, float] = {}
        for clause in text.split(","):
            clause = clause.strip()
            if not clause:
                continue
            for op in (">=", "<="):
                if op in clause:
                    name, _, raw = clause.partition(op)
                    break
            else:
                raise ValueError(
                    f"SLO clause {clause!r} has no >= or <= comparator")
            name = name.strip()
            if name not in _FIELDS:
                raise ValueError(
                    f"unknown SLO field {name!r}; known: "
                    f"{', '.join(sorted(_FIELDS))}")
            attr, want_op, scale = _FIELDS[name]
            if op != want_op:
                raise ValueError(
                    f"SLO field {name!r} takes {want_op}, not {op}")
            kwargs[attr] = float(raw) * scale
        return cls(**kwargs)

    def evaluate(self, pred: Mapping[str, Any]
                 ) -> tuple[bool, list[str]]:
        """Check a candidate prediction; returns (ok, violations).

        A threshold whose metric is missing from the prediction counts
        as a violation (e.g. an SLO demanding serving throughput from a
        model family the serve estimator cannot size) — absence of
        evidence is not compliance.
        """
        violations: list[str] = []

        def check(value, bound, greater: bool, label: str) -> None:
            if bound is None:
                return
            if value is None:
                violations.append(f"{label}: no prediction")
            elif (value < bound) if greater else (value > bound):
                violations.append(
                    f"{label}: {value:.4g} vs required "
                    f"{'>=' if greater else '<='} {bound:.4g}")

        check(pred.get("tok_s_per_chip"), self.min_tok_s_per_chip,
              True, "tok_s_chip")
        check(pred.get("p99_s"), self.max_p99_s, False, "p99_s")
        check(pred.get("hbm_headroom_frac"), self.min_hbm_headroom_frac,
              True, "headroom")
        check(pred.get("survival"), self.min_survival, True, "survival")
        check(pred.get("ttft_p99_s"), self.max_ttft_p99_s,
              False, "ttft_p99_s")
        check(pred.get("itl_p99_s"), self.max_itl_p99_s,
              False, "itl_p99_s")
        if not pred.get("fits", True):
            violations.append("memory: plan does not fit in HBM")
        return (not violations, violations)


def rank_key(pred: Mapping[str, Any]) -> tuple:
    """Sort key over evaluated predictions: SLO-passing plans first,
    then fewest violations, then highest serving throughput per chip,
    then fastest training step."""
    return (
        not pred.get("slo_ok", False),
        len(pred.get("slo_violations", ())),
        -(pred.get("tok_s_per_chip") or 0.0),
        pred.get("step_time_s", float("inf")),
    )


def rank(preds: list[dict], spec: SLOSpec) -> list[dict]:
    """Evaluate ``spec`` over each prediction (annotating ``slo_ok`` /
    ``slo_violations`` in place) and return them ranked best-first."""
    for p in preds:
        ok, violations = spec.evaluate(p)
        p["slo_ok"] = ok
        p["slo_violations"] = violations
    return sorted(preds, key=rank_key)
