"""Measured calibration: compile-and-time the top-k tuner candidates.

The analytic model ranks cheaply; this module answers "but is it
actually faster?" by running a short real microbenchmark of the actual
train step per candidate — init, warmup (compile), then a few timed
steps.  Works on the CPU host-platform sim (CI) exactly as on TPU.

Every trial is journaled as an obs span (``tune.trial``) with the
measured milliseconds and, when the backend exposes it, the XLA
cost-analysis FLOPs of the compiled step
(``utils.profiling.compiled_cost``) — so ``tadnn report`` can show the
trials next to the analytic decision.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence

import jax

from ..obs import journal as obs_journal
from .space import Candidate


def time_step(ad, state, batch, *, warmup: int = 1, iters: int = 3) -> float:
    """Median wall-clock seconds of ``ad.step`` after warmup."""
    for _ in range(max(1, warmup)):
        state, _ = ad.step(state, batch)
    jax.block_until_ready(state.params)
    times = []
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        state, _ = ad.step(state, batch)
        jax.block_until_ready(state.params)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def measure_candidates(
    candidates: Sequence[Candidate],
    make_ad: Callable[[Candidate], Any],
    rng: jax.Array,
    sample_batch: Any,
    *,
    warmup: int = 1,
    iters: int = 3,
) -> list[dict]:
    """Run the microbenchmark for each candidate.

    ``make_ad(candidate)`` must return a fresh ``AutoDistribute``
    configured for that candidate (strategy + mesh built from its
    degrees + its grad_accum).  A candidate that fails to build or OOMs
    is reported with its error instead of aborting the sweep — the
    analytic ranking already called it plausible; measurement is where
    reality gets a veto.
    """
    results: list[dict] = []
    for cand in candidates:
        fields = {
            "candidate": cand.label(),
            "strategy": cand.strategy,
            "mesh": cand.degrees_dict,
            "grad_accum": cand.grad_accum,
        }
        with obs_journal.span("tune.trial", **fields):
            entry = dict(fields)
            try:
                ad = make_ad(cand)
                state = ad.init(rng, sample_batch)
                step_s = time_step(
                    ad, state, sample_batch, warmup=warmup, iters=iters
                )
                entry["step_time_s"] = step_s
                entry["step_time_ms"] = round(step_s * 1e3, 3)
                flops = _compiled_flops(ad, state, sample_batch)
                if flops is not None:
                    entry["compiled_flops"] = flops
            except Exception as e:  # noqa: BLE001 — a veto, not a crash
                entry["error"] = f"{type(e).__name__}: {e}"
            obs_journal.event("tune.trial.result", **entry)
            results.append(entry)
    return results


def _compiled_flops(ad, state, batch) -> float | None:
    """XLA cost-analysis FLOPs of the compiled step, if the backend
    exposes them (utils.profiling.compiled_cost; AOT, hits the jit
    cache so no recompile)."""
    try:
        from ..utils import profiling

        cost = profiling.compiled_cost(ad._step_fn, state, batch) or {}
        flops = cost.get("flops")
        return float(flops) if flops else None
    except Exception:
        return None
