"""Command-line launcher (component C9, SURVEY.md §1 L1).

The reference launches one process per GPU via ``torchrun``/``mp.spawn``
(BASELINE.json:5).  Single-controller JAX needs no per-device spawn: one
process per *host* drives every local chip, so the launcher's job shrinks
to multi-host initialization + convenience commands::

    python -m torch_automatic_distributed_neural_network_tpu devices
    python -m torch_automatic_distributed_neural_network_tpu run train.py [args...]
    python -m torch_automatic_distributed_neural_network_tpu profile train.py --logdir /tmp/tb [args...]
    python -m torch_automatic_distributed_neural_network_tpu bench [--ops allreduce,allgather] [--sizes 1048576,...]

(`tadnn` works as the module name too.)  ``run`` calls
``jax.distributed.initialize()`` first when a multi-host environment is
detected (coordinator address in env), then executes the script in
__main__ — the torchrun analog with no rank bookkeeping.
"""

from __future__ import annotations

import argparse
import json
import os
import runpy
import sys


def _maybe_init_distributed() -> None:
    """Initialize the multi-host runtime when the env asks for it."""
    import jax

    if (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
        or int(os.environ.get("TADNN_NUM_PROCESSES", "1")) > 1
    ):
        from . import topology

        topology.initialize_distributed()
        if jax.process_index() == 0:
            print(
                f"distributed: {jax.process_count()} processes, "
                f"{jax.device_count()} devices"
            )


def cmd_devices(args: argparse.Namespace) -> int:
    import jax

    from . import topology

    topo = topology.detect()
    print(f"process {jax.process_index()}/{jax.process_count()}")
    print(f"devices: {topo.num_devices} x {topo.device_kind}")
    print(f"local devices: {len(jax.local_devices())}")
    print(f"multihost: {topo.is_multihost}  multislice: {topo.is_multislice}")
    if args.json:
        print(json.dumps({
            "num_devices": topo.num_devices,
            "device_kind": topo.device_kind,
            "process_count": jax.process_count(),
        }))
    return 0


def _run_script(script: str, script_args: list[str]) -> int:
    if script_args and script_args[0] == "--":
        script_args = script_args[1:]
    sys.argv = [script, *script_args]
    sys.path.insert(0, os.path.dirname(os.path.abspath(script)) or ".")
    runpy.run_path(script, run_name="__main__")
    return 0


def cmd_run(args: argparse.Namespace) -> int:
    if not os.environ.get("TADNN_NO_COMPILE_CACHE"):
        from .topology import enable_compilation_cache

        enable_compilation_cache()
    _maybe_init_distributed()
    return _run_script(args.script, args.script_args)


def cmd_profile(args: argparse.Namespace) -> int:
    """Run a script under a jax.profiler trace (TensorBoard-viewable)."""
    import jax

    _maybe_init_distributed()
    os.makedirs(args.logdir, exist_ok=True)
    with jax.profiler.trace(args.logdir):
        rc = _run_script(args.script, args.script_args)
    print(f"profile trace written to {args.logdir}")
    return rc


def cmd_bench(args: argparse.Namespace) -> int:
    """Collectives microbenchmark (allreduce bus-bw is a BASELINE metric)."""
    from .parallel.collectives import bench_sweep

    ops = args.ops.split(",")
    sizes = [int(s) for s in args.sizes.split(",")]
    for r in bench_sweep(sizes=sizes, ops=ops, axis=args.axis):
        print(json.dumps(r.to_json()))
    return 0


def _family_setup(args: argparse.Namespace):
    """(model, loss_fn, sample_batch) for the model-zoo CLI commands
    (fit, tune, check --memory) from --family/--size/--seq/--batch."""
    import numpy as np

    from .models import GPT2, MLP, Bert, Llama, MoE, ViT
    from .training import (
        blockwise_next_token_loss,
        masked_lm_loss,
        moe_next_token_loss,
        next_token_loss,
        softmax_xent_loss,
    )

    if args.family == "mlp":
        # the bench model: --size is the comma-separated layer widths,
        # --seq the (square) input image side
        feats = tuple(
            int(x) for x in (args.size or "1024,1024,10").split(","))
        side = args.seq or 28
        model = MLP(features=feats)
        sample = {
            "x": np.zeros((args.batch, side * side), np.float32),
            "label": np.zeros((args.batch,), np.int32),
        }
        return model, softmax_xent_loss, sample
    family = {"gpt2": GPT2, "llama": Llama, "moe": MoE,
              "bert": Bert, "vit": ViT}[args.family]
    size = args.size or {"gpt2": "1p3b", "llama": "8b", "moe": "test",
                         "bert": "large", "vit": "large"}[args.family]
    blockwise = getattr(args, "loss", "full") == "blockwise"
    if args.family == "vit":
        side = args.seq or 224  # --seq is the image side for ViT
        model = family(size, image_size=side)
        loss = softmax_xent_loss
        sample = {"x": np.zeros((args.batch, side, side, 3), np.float32),
                  "label": np.zeros((args.batch,), np.int32)}
    else:
        seq = args.seq or 1024
        model = family(size, max_seq_len=seq)
        if args.family == "bert":
            loss = masked_lm_loss
            sample = {
                "input_ids": np.zeros((args.batch, seq), np.int32),
                "labels": np.full((args.batch, seq), -100, np.int32),
            }
        else:
            if blockwise:
                loss = blockwise_next_token_loss()
            else:
                loss = (moe_next_token_loss if args.family == "moe"
                        else next_token_loss)
            sample = {
                "tokens": np.zeros((args.batch, seq + 1), np.int32),
            }
    return model, loss, sample


def cmd_fit(args: argparse.Namespace) -> int:
    """Will this model fit? Abstract-shapes AOT compile + XLA memory
    analysis (AutoDistribute.compile_report) — nothing materialized, so
    it answers for models far larger than this host.  One JSON line per
    measured candidate."""
    import jax

    import optax

    from . import AutoDistribute

    if args.loss == "blockwise" and args.family in ("bert", "vit"):
        # blockwise CE is a CAUSAL next-token loss; silently running it
        # on an encoder would fit-report a graph no real config trains
        print(json.dumps({"error": "--loss blockwise is next-token "
                          "(causal); bert uses masked LM, vit uses "
                          "classification"}))
        return 1
    model, loss, sample = _family_setup(args)
    ad = AutoDistribute(
        model,
        optimizer=optax.adamw(1e-4),
        loss_fn=loss,
        strategy=args.strategy,
        precision=args.precision,
    )
    if args.strategy == "search":
        ad.build_plan(jax.random.key(0), sample)
        entries = ad.search_report or [
            {"strategy": ad.plan.strategy, "note": "1-device no-op"}
        ]
    else:
        report = ad.compile_report(jax.random.key(0), sample)
        peak = report and report.get("per_device_peak_bytes")
        if not peak:
            print(json.dumps({"error": "backend exposes no analysis"}))
            return 1
        # same budget the search ladder measures against
        budget = AutoDistribute.hbm_fit_budget(
            jax.devices()[0].device_kind
        )
        entries = [{
            "strategy": ad.plan.strategy,
            "peak_bytes": peak,
            "budget_bytes": int(budget),
            "fits": peak <= budget,
            "flops": report.get("flops"),
            "memory": report.get("memory"),
        }]
    for e in entries:
        pb = e.get("peak_bytes")
        if pb:
            e["peak_gib"] = round(pb / 2**30, 3)
        print(json.dumps(e))
    chosen = ad.plan.strategy if ad.plan is not None else None
    print(json.dumps({"chosen_strategy": chosen,
                      "mesh": _mesh_degrees_or_none(ad)}))
    return 0


def cmd_tune(args: argparse.Namespace) -> int:
    """Rank candidate parallelism plans with the tune/ cost model (and
    optionally measure the top-k with a real microbenchmark), printing
    the per-candidate cost breakdown the decision was made from."""
    import jax
    import optax

    from . import AutoDistribute, topology, tune

    if getattr(args, "simulate", None):
        # tune --simulate v5p-64[,v5e-256] == tadnn simulate over those
        # fleets with this tune invocation's model/search knobs
        args.topology = [t.strip() for t in args.simulate.split(",")
                        if t.strip()]
        return cmd_simulate(args)

    model, loss, sample = _family_setup(args)
    ad = AutoDistribute(model, optimizer=optax.adamw(1e-4), loss_fn=loss,
                        precision=args.precision)
    rng = jax.random.key(0)
    abstract_vars = jax.eval_shape(ad._init_variables, rng, sample)
    abstract, _ = ad._split_variables(abstract_vars)

    topo = topology.detect()
    act_profile = None
    try:
        act_profile = ad.activation_profile(rng, sample)
    except Exception:  # profile is advisory — rank on the heuristic
        act_profile = None
    policy = tune.TunePolicy(
        grad_accums=tuple(int(g) for g in args.grad_accums.split(",")),
        top_k=args.top_k,
        batch_items=tune.estimate_batch_items(sample),
        use_cache=not args.no_cache,
        act_profile=act_profile,
        zero1=not args.no_zero1,
    )
    result = tune.tune(abstract, topo, policy=policy)
    ranked = result.ranked
    if not ranked:  # cache hit or fallback — re-rank locally for display
        kept, _ = tune.enumerate_candidates(
            abstract, topo, grad_accums=policy.grad_accums,
            max_tensor=policy.max_tensor, state_factor=policy.state_factor,
            batch_items=policy.batch_items, safety=policy.safety,
            act_profile=policy.act_profile, zero1=policy.zero1,
        )
        ranked = tune.rank(abstract, topo, kept,
                           state_factor=policy.state_factor,
                           batch_items=policy.batch_items,
                           safety=policy.safety,
                           act_profile=policy.act_profile) if kept else []

    measured: dict[str, float] = {}
    if args.measure and ranked:
        def make_ad(cand):
            return AutoDistribute(
                model, optimizer=optax.adamw(1e-4), loss_fn=loss,
                strategy=cand.strategy,
                mesh=topology.build_mesh(**cand.degrees_dict),
                grad_accum=cand.grad_accum, precision=args.precision,
            )

        trials = tune.measure.measure_candidates(
            [e.candidate for e in ranked[:args.top_k]], make_ad, rng, sample,
        )
        measured = {t["candidate"]: t.get("step_time_ms")
                    for t in trials if t.get("step_time_ms")}

    if args.json:
        for i, est in enumerate(ranked):
            row = {"rank": i, **est.to_json()}
            if est.candidate.label() in measured:
                row["measured_ms"] = measured[est.candidate.label()]
            print(json.dumps(row))
        print(json.dumps({
            "chosen_strategy": result.strategy, "mesh": result.degrees,
            "grad_accum": result.grad_accum, "zero1": result.zero1,
            "source": result.source,
            "cache_key": result.key,
        }))
        return 0

    print(f"devices: {topo.num_devices} x {topo.device_kind}  "
          f"candidates: {len(ranked)}  source: {result.source}")
    hdr = (f"{'rank':>4} {'strategy':<9} {'mesh':<24} {'ga':>2} "
           f"{'step_ms':>9} {'compute':>8} {'comm':>8} {'hbm':>8} "
           f"{'mem_gib':>8} fit")
    if measured:
        hdr += f" {'measured':>9}"
    print(hdr)
    for i, est in enumerate(ranked):
        b = est.breakdown
        mesh = "x".join(f"{a}{n}" for a, n in est.candidate.degrees if n > 1)
        strat = est.candidate.strategy + (
            "+z1" if est.candidate.zero1 else "")
        line = (f"{i:>4} {strat:<9} {mesh or '1':<24} "
                f"{est.candidate.grad_accum:>2} "
                f"{est.step_time_s * 1e3:>9.3f} {b['compute_ms']:>8.3f} "
                f"{b['comm_ms']:>8.3f} {b['hbm_ms']:>8.3f} "
                f"{b['memory']['total_bytes'] / 2**30:>8.2f} "
                f"{'y' if est.fits else 'N'}")
        m = measured.get(est.candidate.label())
        if measured:
            line += f" {m:>9.3f}" if m is not None else f" {'-':>9}"
        print(line)
    print(f"chosen: {result.strategy}{'+z1' if result.zero1 else ''} "
          f"{result.degrees} "
          f"grad_accum={result.grad_accum} ({result.source}; "
          f"cache {tune.cache.cache_path()})")
    return 0


def cmd_simulate(args: argparse.Namespace) -> int:
    """Fleet-scale what-if planner: sweep hypothetical topologies x
    parallelism plans and rank the joint prediction (training MFU/step
    time, HBM headroom, serving tok/s + p99 from a virtual-time replay
    of the real scheduler, restart-budget survival) against an operator
    SLO.  Pure shape math + discrete-event simulation — device-free."""
    import jax
    import optax

    from . import AutoDistribute, tune
    from .obs import Journal, set_default

    jnl = Journal(getattr(args, "journal", None))
    set_default(jnl)
    model, loss, sample = _family_setup(args)
    ad = AutoDistribute(model, optimizer=optax.adamw(1e-4), loss_fn=loss,
                        precision=args.precision)
    rng = jax.random.key(0)
    abstract_vars = jax.eval_shape(ad._init_variables, rng, sample)
    abstract, _ = ad._split_variables(abstract_vars)
    # transformer families carry a cfg that sizes the serving KV pool;
    # without one (mlp) the serving columns are simply absent
    model_cfg = getattr(model, "cfg", None)

    specs = args.topology or ["v5p-16"]
    measured_overlap = getattr(args, "measured_overlap", None)
    trace_journal = getattr(args, "trace_journal", None)
    if measured_overlap is None and trace_journal:
        # feed a real `tadnn trace` capture back into the roofline:
        # trace.step records carry collective_s / exposed_collective_s,
        # and their exposed fraction IS cost.score's measured_overlap
        from .tune import cost as cost_mod

        steps = []
        with open(trace_journal) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec.get("name") == "trace.step":
                    steps.append(rec)
        measured_overlap = cost_mod.overlap_from_trace(steps)
        if measured_overlap is None:
            print(f"simulate: {trace_journal} has no trace.step records "
                  "with collective time; ignoring --trace-journal",
                  file=sys.stderr)
    try:
        traffic = tune.TrafficMix.parse(getattr(args, "traffic", None))
        slo = tune.SLOSpec.parse(getattr(args, "slo", None))
        adm_raw = getattr(args, "admissions", None) or "reserve,optimistic"
        admissions = tuple(
            a.strip() for a in adm_raw.split(",") if a.strip())
        policy = tune.SimulatePolicy(
            grad_accums=tuple(
                int(g) for g in
                str(getattr(args, "grad_accums", None)
                    or "1,2,4,8").split(",")),
            batch_items=tune.estimate_batch_items(sample),
            admissions=admissions,
            slots=int(getattr(args, "slots", None) or 8),
            block_size=int(getattr(args, "block_size", None) or 16),
            max_len=int(getattr(args, "max_len", None) or 256),
            prefill_chunk=(int(getattr(args, "prefill_chunk", None) or 32)
                           or None),
            disaggregate=bool(getattr(args, "disaggregate", False)),
            prefix_cache=bool(getattr(args, "prefix_cache", False)),
            measured_overlap=measured_overlap,
            preemption_rate_per_h=float(
                getattr(args, "preemption_rate", None) or 0.0),
            mission_hours=float(
                getattr(args, "mission_hours", None) or 24.0),
            top_k=int(getattr(args, "top_k", None) or 10),
            use_cache=not getattr(args, "no_cache", False),
        )
        report = tune.simulate.simulate(
            abstract, specs, model_cfg=model_cfg, policy=policy,
            traffic=traffic, slo=slo)
    except ValueError as e:
        # unknown SKU / malformed traffic / malformed SLO — loud + clean
        print(f"simulate: {e}", file=sys.stderr)
        return 2

    out_path = getattr(args, "out", None)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2)
    if getattr(args, "json", False):
        print(json.dumps(report))
        return 0

    preds = report["predictions"]
    print(f"simulated {report['n_candidates']} candidates over "
          f"{len(report['topologies'])} topologies "
          f"({report['n_slo_ok']} meet the SLO; cache {report['cache']})")
    print(f"{'rank':>4} {'topology':<12} {'plan':<26} {'adm':<10} "
          f"{'mfu':>6} {'step_ms':>9} {'hdroom':>7} {'tok/s/c':>8} "
          f"{'p99_ms':>8} {'occ':>5} {'pre':>4} {'surv':>6} slo")
    for i, p in enumerate(preds):
        p99 = (f"{p['p99_s'] * 1e3:>8.1f}" if p.get("p99_s") is not None
               else f"{'-':>8}")
        tok = (f"{p['tok_s_per_chip']:>8.1f}"
               if p.get("tok_s_per_chip") is not None else f"{'-':>8}")
        occ = (f"{p['mean_occupancy']:>5.2f}"
               if p.get("mean_occupancy") is not None else f"{'-':>5}")
        pre = (f"{p['preemptions']:>4d}"
               if p.get("preemptions") is not None else f"{'-':>4}")
        print(f"{i:>4} {p['topology']:<12} {p['plan']:<26} "
              f"{p['admission']:<10} {p['mfu']:>6.3f} "
              f"{p['step_time_s'] * 1e3:>9.3f} "
              f"{p['hbm_headroom_frac']:>7.2%} {tok} {p99} {occ} {pre} "
              f"{p['survival']:>6.3f} "
              f"{'ok' if p['slo_ok'] else ';'.join(p['slo_violations'])}")
    if getattr(args, "journal", None):
        print(f"journal written to {args.journal} (render with "
              f"`tadnn report {args.journal}`)")
    return 0


def _mesh_degrees_or_none(ad):
    from . import topology as topo_mod

    return (dict(topo_mod.mesh_degrees(ad.plan.mesh))
            if ad.plan is not None else None)


def cmd_trace(args: argparse.Namespace) -> int:
    """Profile real steps on the live backend: a device-timeline capture
    (obs/trace) attributed into per-step compute / collective / exposed
    collective time and measured MFU, plus the measured-vs-modeled
    collective-bytes crosscheck (compiled HLO vs
    planner.expected_collective_bytes).

    Two modes: a model-zoo config (--family et al., default the bench
    mlp) traced in-process, or a training script (``tadnn trace
    train.py``) run with TADNN_TRACE_EVERY_N exported so the Trainer
    instruments itself every Nth step.
    """
    if args.target and args.target.endswith(".py"):
        os.environ.setdefault("TADNN_TRACE_EVERY_N", str(args.every))
        if args.journal:
            os.environ.setdefault("TADNN_JOURNAL", args.journal)
        _maybe_init_distributed()
        return _run_script(args.target, args.script_args)
    if args.target:
        print(f"trace target must be a .py script (got {args.target}); "
              "omit it to trace a --family config", file=sys.stderr)
        return 2

    import jax
    import optax

    from . import AutoDistribute
    from .obs import Journal, set_default
    from .obs import comms as obs_comms
    from .obs import trace as obs_trace
    from .training.metrics import transformer_step_flops

    jnl = Journal(args.journal)  # path=None -> in-memory sink
    set_default(jnl)
    model, loss, sample = _family_setup(args)
    ad = AutoDistribute(model, optimizer=optax.adamw(1e-4), loss_fn=loss,
                        strategy=args.strategy, precision=args.precision)
    rng = jax.random.key(0)
    state = ad.init(rng, sample)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    tokens = args.batch * ((args.seq or 1024)
                           if args.family in ("gpt2", "llama", "moe", "bert")
                           else 1)
    flops = transformer_step_flops(n_params, tokens)

    # warm the compile outside the capture — the first dispatch would
    # profile XLA, not the step
    state, m = ad.step(state, sample)
    jax.block_until_ready(m)
    state, recs = obs_trace.trace_steps(
        ad.step, state, sample, steps=args.steps, first_step=1,
        logdir=args.logdir, flops_per_step=flops, journal=jnl,
    )
    measured = obs_trace.measured_collective_bytes(ad, rng, sample)
    est = obs_comms.comm_profile(ad, rng, sample)
    xc = obs_trace.crosscheck_collectives(
        measured, est.get("per_device") or {},
        grad_accum=ad._grad_accum, journal=jnl,
    )
    jnl.close()

    if args.json:
        for r in recs:
            print(json.dumps(r))
        for c in xc:
            print(json.dumps(c))
        return 0
    print(f"traced {len(recs)} step(s) on {jax.device_count()} x "
          f"{jax.devices()[0].device_kind}  (strategy "
          f"{ad.plan.strategy}, {n_params:,} params)")
    for r in recs:
        line = (f"  step {r['step']}: wall {r['wall_s'] * 1e3:8.2f}ms  "
                f"compute {r['compute_s'] * 1e3:8.2f}ms  "
                f"collective {r['collective_s'] * 1e3:7.2f}ms  "
                f"exposed {r['exposed_collective_s'] * 1e3:7.2f}ms")
        if r.get("measured_mfu") is not None:
            line += f"  mfu {r['measured_mfu']:.2%}"
        print(line)
    frac = obs_trace.exposed_fraction(recs)
    if frac is not None:
        print(f"exposed collective fraction: {frac:.1%} "
              "(communication the schedule failed to hide)")
    for c in xc:
        print(f"  {c['category']}: measured {c['measured_bytes']:,} B "
              f"vs modeled {c['modeled_bytes']:,} B  "
              f"ratio {c['ratio']}"
              + ("" if c["within_2x"] else "  !! outside 2x band"))
    if args.journal:
        print(f"journal written to {args.journal} (render with "
              f"`tadnn report {args.journal}`)")
    return 0


def cmd_report(args: argparse.Namespace) -> int:
    """Summarize a finished (or crashed) run from its on-disk artifacts:
    journal JSONL + MetricsLogger JSONL.  Pure file parsing — no jax
    import, so it works on a machine with no accelerator runtime.
    ``--check`` instead runs the bench freshness guard; ``--merge``
    joins per-host journals first (obs/aggregate)."""
    from .obs import report as obs_report

    if args.check:
        code, msgs = obs_report.check_bench(
            args.target, bench_path=args.bench,
            last_good_path=args.last_good)
        for m in msgs:
            # per-message verdict: with two trajectories (BENCH + SERVE)
            # one can be fresh while the other fails the aggregate code
            print(("ok   " if ": fresh" in m else "FAIL ") + m)
        return code
    if getattr(args, "check_simulate", False):
        code, msgs = obs_report.check_simulate(args.target)
        for m in msgs:
            print(("ok   " if "within 2x" in m else "FAIL ") + m)
        return code
    if args.merge:
        from .obs import aggregate

        try:
            merged = aggregate.merge_run(args.target)
            print(f"merged per-host journals -> {merged}")
        except (FileNotFoundError, NotADirectoryError, OSError) as e:
            print(f"--merge: {e}", file=sys.stderr)
            return 1
    rep = obs_report.generate(args.target, args.metrics)
    if args.json:
        print(json.dumps(rep))
    else:
        print(obs_report.format_report(rep))
    return 0


def cmd_monitor(args: argparse.Namespace) -> int:
    """Continuous SLO monitor over a serving journal (obs/slo_monitor):
    fold ``serve.*`` events into rolling event-time windows, evaluate
    the ``--slo`` spec per window with hysteresis, journal
    ``slo.breach`` / ``slo.recover`` incidents, and optionally compare
    measured throughput against the simulate replay's prediction for a
    committed bench record (``--drift``).  ``--follow`` tails a live
    journal; the default deterministically replays a finished one —
    with ``--check`` the exit code is the CI gate (nonzero on any
    breach or out-of-band planner drift).  Pure file parsing unless
    ``--drift`` is given — no accelerator needed."""
    from .obs import slo_monitor as slm
    from .obs.journal import Journal
    from .tune.slo import SLOSpec

    if args.follow and args.replay:
        print("monitor: --follow and --replay are mutually exclusive",
              file=sys.stderr)
        return 2
    try:
        spec = SLOSpec.parse(args.slo)
    except ValueError as e:
        print(f"monitor: {e}", file=sys.stderr)
        return 2
    if not args.follow and not os.path.isfile(args.journal):
        # --follow accepts a not-yet-created journal (a gateway starts
        # its monitor before first traffic): Journal.follow polls for
        # the file under --idle-timeout instead of raising
        print(f"monitor: no journal at {args.journal}", file=sys.stderr)
        return 2
    policy = slm.MonitorPolicy(
        slo=spec, window_s=args.window,
        breach_after=args.breach_after,
        recover_after=args.recover_after,
        n_chips=args.chips, warmup_windows=args.warmup_windows)
    drift_extra = None
    if args.drift:
        with open(args.drift) as f:
            rec = json.load(f)
        # a full bench record or a bare extra dict both work
        drift_extra = rec.get("extra") or rec
    # incidents land in their own sink: --replay must never append to
    # the (possibly committed) journal it is reading
    with Journal(args.incident_journal, host0_only=False,
                 meta={"tool": "monitor",
                       "source": args.journal}) as sink:
        records = (Journal.follow(args.journal,
                                  idle_timeout=args.idle_timeout)
                   if args.follow else Journal.read(args.journal))
        summary = slm.monitor_records(
            records, policy, journal=sink, drift_extra=drift_extra)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f)
    if args.json:
        print(json.dumps(summary))
    else:
        print(slm.format_summary(summary))
    if args.check:
        drift_bad = ((summary.get("drift") or {}).get("within_band")
                     is False)
        return 1 if (summary["breaches"] or drift_bad) else 0
    return 0


def cmd_doctor(args: argparse.Namespace) -> int:
    """Verify a checkpoint directory's integrity and print the fallback
    chain restore_or_init would walk.  Exit 0 when at least one step is
    restorable, 1 otherwise (corrupt-only or empty directory).

    ``--launch-dir`` switches to launch supervision health (training/
    launch.py): per-host last-seen heartbeats, restart-budget
    consumption, and which host broke the cohort.  ``--gateway-dir``
    is the serving twin: a fleet post-mortem from a gateway journal —
    per-replica heartbeats, failovers, hedge record, breaker/degrade
    history, and which replica broke the cohort."""
    from .training import resilience

    if getattr(args, "launch_dir", None):
        from .training import launch as launch_mod

        doc = launch_mod.launch_doctor(args.launch_dir)
        if args.json:
            print(json.dumps(doc))
        else:
            print(launch_mod.format_launch_doctor(doc))
        return 1 if doc.get("ok") is False else 0
    if getattr(args, "gateway_dir", None):
        from .inference.gateway import doctor as gw_doctor

        doc = gw_doctor.gateway_doctor(args.gateway_dir)
        if args.json:
            print(json.dumps(doc))
        else:
            print(gw_doctor.format_gateway_doctor(doc))
        return 1 if doc.get("ok") is False else 0
    if not args.directory:
        print("doctor: a checkpoint directory, --launch-dir or "
              "--gateway-dir is required",
              file=sys.stderr)
        return 2
    from .training import shards

    # sharded-format dirs (training/shards.py) carry a meta.json per
    # step; verify those through the per-host shard chain instead
    sharded = any(
        os.path.isfile(os.path.join(args.directory, str(s), "meta.json"))
        for s in resilience.list_steps(args.directory)
    )
    report = (shards.verify_directory(args.directory) if sharded
              else resilience.verify_directory(args.directory))
    if args.json:
        print(json.dumps(report))
    else:
        print(resilience.format_doctor(report))
    return 0 if report["healthy"] else 1


def cmd_launch(args: argparse.Namespace) -> int:
    """Elastic multihost launch (training/launch.py): spawn + supervise
    N simulated-mesh workers with sharded async checkpoints, cohort
    restart under the RestartPolicy budget, and seeded chaos.

    ``--smoke`` runs the acceptance pair — a clean run and a chaos run
    (one SIGKILL) — and exits nonzero unless the chaos run resumes to
    **bitwise-identical** per-step losses."""
    from .training import resilience
    from .training.launch import LaunchConfig, Launcher

    chaos = None
    if args.kill_host_at or args.tear_shard_at or args.partition_journal_at:
        chaos = resilience.ChaosPlan(
            seed=args.seed,
            sigkill_at=tuple(args.kill_host_at or ()),
            shard_tear_at=tuple(args.tear_shard_at or ()),
            journal_partition_at=tuple(args.partition_journal_at or ()),
            chaos_host=args.chaos_host,
        )

    def make_cfg(launch_dir: str, chaos_plan) -> LaunchConfig:
        return LaunchConfig(
            launch_dir=launch_dir, hosts=args.hosts,
            local_devices=args.local_devices, steps=args.steps,
            ckpt_every=args.ckpt_every, strategy=args.strategy,
            zero1=args.zero1, seed=args.seed,
            max_restarts=args.max_restarts, elastic=args.elastic,
            watchdog_s=args.watchdog_s, chaos=chaos_plan,
            heartbeat_interval_s=args.heartbeat_interval_s,
            export_cache=getattr(args, "export_cache", None),
        )

    if args.smoke:
        # acceptance pair: uninterrupted oracle, then the same seeded
        # run with one SIGKILL mid-step — per-step losses must match
        # bitwise after the resume
        if chaos is None:
            chaos = resilience.ChaosPlan(
                seed=args.seed, sigkill_at=(max(args.ckpt_every + 1, 3),),
                chaos_host=args.chaos_host)
        clean = Launcher(make_cfg(
            os.path.join(args.launch_dir, "clean"), None)).run()
        chaotic = Launcher(make_cfg(
            os.path.join(args.launch_dir, "chaos"), chaos)).run()
        parity = (clean.get("ok") and chaotic.get("ok")
                  and clean.get("losses") == chaotic.get("losses"))
        out = {
            "ok": bool(parity),
            "clean_ok": clean.get("ok"),
            "chaos_ok": chaotic.get("ok"),
            "parity": bool(clean.get("losses")
                           and clean.get("losses") == chaotic.get("losses")),
            "restarts_used": chaotic.get("restarts_used"),
            "final_loss": chaotic.get("final_loss"),
            "world": chaotic.get("world"),
            "merged_journal": chaotic.get("merged_journal"),
            "launch_dir": args.launch_dir,
        }
        if not chaotic.get("ok"):
            out["error"] = chaotic.get("error")
        print(json.dumps(out))
        return 0 if out["ok"] else 1

    result = Launcher(make_cfg(args.launch_dir, chaos)).run()
    if args.json:
        print(json.dumps(result))
    else:
        if result["ok"]:
            print(f"launch ok: world={result['world']} "
                  f"rounds={result['rounds']} "
                  f"restarts={result['restarts_used']} "
                  f"final_step={result['final_step']} "
                  f"final_loss={result['final_loss']}")
            if result.get("merged_journal"):
                print(f"merged journal: {result['merged_journal']}")
        else:
            print(f"launch FAILED: {result.get('error')}", file=sys.stderr)
    return 0 if result["ok"] else 1


def _fmt_mem_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.2f} GiB"


def _print_memory_report(report: dict) -> None:
    rows = [
        ("params", report.get("params_bytes")),
        ("optimizer", report.get("optimizer_bytes")),
        ("model_state", report.get("model_state_bytes")),
        ("batch", report.get("batch_bytes")),
        ("activations", report.get("activation_bytes")),
        ("peak", report.get("peak_bytes")),
        ("budget", report.get("budget_bytes")),
    ]
    mesh = "x".join(f"{a}{n}" for a, n in
                    sorted((report.get("degrees") or {}).items()))
    strat = str(report.get("strategy"))
    if report.get("zero1"):
        strat += "+zero1"
    print(f"memory estimate (static, per device; strategy "
          f"{strat}, mesh {mesh or '1'}, "
          f"grad_accum {report.get('grad_accum')}, "
          f"remat {'on' if report.get('remat') else 'off'}):")
    for name, val in rows:
        if name == "model_state" and not val:
            continue
        print(f"  {name:<12} {_fmt_mem_bytes(val):>12}")
    comp = report.get("compiled") or {}
    peak_c = comp.get("per_device_peak_bytes")
    if peak_c:
        print(f"  {'xla peak':<12} {_fmt_mem_bytes(peak_c):>12}  "
              f"(static/compiled {report.get('static_over_compiled')}x)")
    elif comp.get("error"):
        print(f"  xla peak: unavailable ({comp['error']})")


def cmd_check(args: argparse.Namespace) -> int:
    """Static analyzer (analysis/): source lint over the repo's Python
    by default; ``--preflight FILE`` adds plan + graph lint driven by
    the file's ``tadnn_check()`` dict; ``--memory`` builds a model-zoo
    config (--family/--batch/--strategy) and runs the liveness
    peak-HBM estimator against ``--budget``.  Exit 1 on error-severity
    findings; with ``--strict`` also on warnings."""
    from . import analysis

    if args.rules:
        if getattr(args, "journal", False):
            # the generated journal event reference: the registry as a
            # markdown table (the README's "Telemetry contracts" docs)
            from .obs import schema as obs_schema

            print(obs_schema.registry_markdown())
            return 0
        for r in analysis.RULES.values():
            print(f"{r.code}  {r.layer:<6} {r.severity:<5} {r.title}")
        return 0
    findings: list = []
    if not args.no_source:
        from .analysis import async_lint, source_lint

        findings += source_lint.lint_paths(args.paths or None)
        findings += async_lint.lint_paths(args.paths or None)
    if args.preflight:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_tadnn_check_target", args.preflight)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        hook = getattr(mod, "tadnn_check", None)
        if hook is None:
            print(f"{args.preflight} does not define tadnn_check()",
                  file=sys.stderr)
            return 2
        hook_spec = dict(hook())
        if args.pl005_bytes is not None:
            hook_spec.setdefault("big_leaf_bytes", args.pl005_bytes)
        findings += analysis.check_spec(hook_spec)
    mem_report = None
    if args.memory:
        import jax
        import optax

        from . import AutoDistribute

        model, loss, sample = _family_setup(args)
        ad = AutoDistribute(
            model, optimizer=optax.adamw(1e-4), loss_fn=loss,
            strategy=args.strategy, precision=args.precision,
            grad_accum=args.grad_accum, zero1=args.zero1,
        )
        mem_findings, mem_report = analysis.memory_check(
            ad, sample, rng=jax.random.key(0), budget=args.budget,
            headroom=args.headroom, big_leaf_bytes=args.pl005_bytes,
            compiled=not args.no_compiled,
        )
        findings += mem_findings
    serve_est = None
    serve_trace_stats = None
    if getattr(args, "serving", False):
        if args.family not in ("gpt2", "llama", "moe"):
            print("check --serving needs a decoder family "
                  "(--family gpt2|llama|moe)", file=sys.stderr)
            return 2
        import jax
        import jax.numpy as jnp

        from .analysis import serve_lint

        model, _, _ = _family_setup(args)
        cfg = model.cfg
        abstract = jax.eval_shape(
            lambda r: model.init(
                r, jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32)),
            jax.random.key(0))
        params_bytes = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(abstract))
        kwargs = {}
        if args.headroom is not None:
            kwargs["headroom"] = args.headroom
        serve_tp = int(getattr(args, "serve_tp", 1) or 1)
        if serve_tp > 1:
            # per-shard accounting: KV heads + adapter b factors split,
            # params charged per shard like the engine lays them out
            kwargs["degrees"] = {"tensor": serve_tp}
            params_bytes //= serve_tp
        s_findings, serve_est = serve_lint.serve_estimate(
            cfg, budget=args.budget,
            block_size=args.serve_block_size,
            max_len=args.serve_max_len or args.seq or 256,
            streams=args.serve_streams,
            quant_kv=args.serve_quant_kv,
            attention_impl=args.serve_attention_impl,
            adapters=args.serve_adapters,
            adapter_rank=args.serve_adapter_rank,
            quant_adapters=args.serve_quant_adapters,
            prefix_cache=bool(getattr(args, "serve_prefix_cache", False)),
            expected_hit_rate=float(
                getattr(args, "serve_prefix_hit_rate", None) or 0.0),
            params_bytes=params_bytes, **kwargs)
        findings += s_findings
        if getattr(args, "trace_serve", False):
            from .analysis import serve_trace

            variables = model.init(
                jax.random.key(0),
                jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32))
            t_findings, serve_trace_stats = serve_trace.serve_trace_check(
                model, variables,
                n_slots=4,
                max_len=min(args.serve_max_len or 64, cfg.max_seq_len),
                block_size=min(args.serve_block_size, 8),
                quant_kv=args.serve_quant_kv,
                attention_impl=args.serve_attention_impl,
            )
            findings += t_findings
    protocol_results = None
    if getattr(args, "protocol", False):
        from .analysis import protocol as protocol_mod

        p_findings, p_results = protocol_mod.run_protocol_check(
            scope=args.scope,
            counterexample_dir=args.counterexample_dir,
        )
        findings += p_findings
        protocol_results = [
            {"model": r.model, "scope": r.scope, "states": r.states,
             "transitions": r.transitions, "depth": r.depth,
             "frontier_peak": r.frontier_peak,
             "wall_s": round(r.wall_s, 3), "complete": r.complete,
             "violations": len(r.counterexamples)}
            for r in p_results]
    journal_stats = None
    if getattr(args, "journal", False) or getattr(args, "journal_file",
                                                  None):
        from .analysis import journal_lint
        from .obs import journal as obs_journal

        journal_stats = {}
        if getattr(args, "journal", False):
            j_findings, journal_stats = journal_lint.lint_paths(
                args.paths or None)
            findings += j_findings
            obs_journal.event(
                "lint.journal",
                kinds_emitted=journal_stats.get("kinds_emitted", 0),
                kinds_known=journal_stats.get("kinds_known", 0),
                sites=journal_stats.get("sites", 0),
                dynamic_sites=journal_stats.get("dynamic_sites", 0),
                coverage=journal_stats.get("coverage", 1.0),
                findings=len(j_findings))
        audits = {}
        for jf in (getattr(args, "journal_file", None) or ()):
            a_findings, a_stats = journal_lint.audit_journal(jf)
            findings += a_findings
            audits[jf] = {**a_stats, "findings": len(a_findings)}
        if audits:
            journal_stats = {**journal_stats, "audited": audits}
    try:
        findings = analysis.filter_ignored(findings, args.ignore or ())
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    analysis.journal_findings(findings, phase="check")
    summary = analysis.summarize(findings)
    if args.json:
        out = {"findings": [f.to_json() for f in findings],
               "summary": summary}
        if mem_report is not None:
            out["memory"] = mem_report
        if serve_est is not None:
            out["serve_estimate"] = serve_est
        if serve_trace_stats is not None:
            out["serve_trace"] = serve_trace_stats
        if protocol_results is not None:
            out["protocol"] = protocol_results
        if journal_stats is not None:
            out["journal"] = journal_stats
        print(json.dumps(out))
    else:
        for f in findings:
            print(f.format())
        if mem_report is not None:
            _print_memory_report(mem_report)
        if serve_est is not None:
            ws = serve_est.get("decode_workspace_bytes", 0)
            print(f"serve estimate: {serve_est['max_streams']} "
                  f"concurrent stream(s) of {serve_est['max_len']} "
                  f"tokens ({serve_est['num_blocks']} blocks x "
                  f"{serve_est['block_size']}, "
                  f"{'int8' if serve_est['quant_kv'] else 'bf16'} KV, "
                  f"{serve_est.get('attention_impl', 'paged')} decode"
                  + (f", {ws // 1024} KiB gather workspace" if ws
                     else "")
                  + (f", adapter pool {serve_est['n_adapters']}x "
                     f"r{serve_est['adapter_rank']} "
                     f"{'int8' if serve_est['quant_adapters'] else 'f32'} "
                     f"({serve_est['adapter_pool_bytes'] // 1024} KiB)"
                     if serve_est.get("n_adapters") else "") + ")")
            if serve_est.get("prefix_cache"):
                print(f"  prefix cache: index metadata "
                      f"{serve_est['prefix_index_bytes'] // 1024} KiB; "
                      f"at {serve_est['expected_hit_rate']:.0%} hit rate "
                      f"~{serve_est['effective_max_streams']} effective "
                      f"stream(s) (shared prefix blocks counted once)")
        if serve_trace_stats is not None:
            for tag, st in serve_trace_stats.items():
                print(f"serve trace [{tag}]: {st['eqns']} eqn(s), "
                      f"{st['collectives']} collective(s)")
        if protocol_results is not None:
            for r in protocol_results:
                print(f"protocol [{r['model']}]: {r['states']} states / "
                      f"{r['transitions']} transitions explored to depth "
                      f"{r['depth']} in {r['wall_s']}s "
                      f"({'complete' if r['complete'] else 'TRUNCATED'}"
                      f", {r['violations']} violation(s))")
        if journal_stats is not None and journal_stats.get("sites"):
            print(f"journal contract: {journal_stats['kinds_emitted']} "
                  f"event kind(s) across {journal_stats['sites']} "
                  f"emission site(s) "
                  f"(+{journal_stats['dynamic_sites']} dynamic), "
                  f"registry coverage "
                  f"{journal_stats['coverage']:.0%} of "
                  f"{journal_stats['kinds_known']} declared kind(s)")
        if journal_stats is not None:
            for jf, st in (journal_stats.get("audited") or {}).items():
                print(f"journal audit [{jf}]: {st['records']} record(s)"
                      + (f", {st['torn']} torn" if st["torn"] else "")
                      + f", {st['findings']} finding(s)")
        print(f"tadnn check: {summary['errors']} error(s), "
              f"{summary['warnings']} warning(s)")
    return analysis.exit_code(findings, strict=args.strict)


def cmd_serve(args: argparse.Namespace) -> int:
    """Continuous-batching serving loop (inference/serve): build a
    decoder, spin up the paged-KV ServeEngine, drive it with N seeded
    streams and print one JSON summary line.  ``--smoke`` pins the tiny
    CI configuration (test-size model, 8 streams, CPU-friendly); a
    ``--journal`` path makes the per-request spans renderable by
    ``tadnn report`` (serving section: p50/p99 latency, goodput, slot
    occupancy)."""
    import time

    import numpy as np

    if args.smoke:
        # the CI smoke contract: tiny model, 8 simulated streams — keep
        # in sync with tests/test_serve.py and .github/workflows/ci.yml
        args.family, args.size = "gpt2", "test"
        args.streams = args.streams or 8
        args.max_len = args.max_len or 64
        args.block_size = args.block_size or 8
        args.max_new = args.max_new or 12
        args.prompt_len = args.prompt_len or 10
        args.slots = args.slots or 4
    if args.family not in ("gpt2", "llama", "moe"):
        print(f"tadnn serve needs a decoder family (gpt2/llama/moe), "
              f"got {args.family!r}", file=sys.stderr)
        return 2
    import jax
    import jax.numpy as jnp

    from .inference.serve import ServeEngine, random_adapter
    from .models import GPT2, Llama, MoE
    from .obs.journal import Journal

    family = {"gpt2": GPT2, "llama": Llama, "moe": MoE}[args.family]
    size = args.size or "test"
    max_len = args.max_len or 256
    vocab = args.vocab or (128 if size == "test" else None)
    overrides = {"max_seq_len": max_len, "dtype": jnp.float32,
                 "remat": False}
    if vocab:
        overrides["vocab_size"] = vocab
    model = family(size, **overrides)
    cfg = model.cfg
    rs = np.random.RandomState(args.seed)
    prompt_len = args.prompt_len or 10
    sample_tokens = jnp.asarray(
        rs.randint(1, cfg.vocab_size, size=(1, prompt_len)), jnp.int32)
    variables = model.init(jax.random.key(1), sample_tokens)

    lora_spec = None
    n_adapters = int(getattr(args, "adapters", 0) or 0)
    if n_adapters:
        from .training.lora import LoraSpec

        lora_spec = LoraSpec(rank=args.adapter_rank)

    mesh = None
    serve_tp = int(getattr(args, "serve_tp", 1) or 1)
    if serve_tp > 1:
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < serve_tp:
            print(f"--serve-tp {serve_tp} needs {serve_tp} devices but "
                  f"only {len(devs)} are visible (CPU sim: "
                  "XLA_FLAGS=--xla_force_host_platform_device_count=N)",
                  file=sys.stderr)
            return 2
        mesh = Mesh(np.array(devs[:serve_tp]), ("tensor",))

    with Journal(args.journal, host0_only=False,
                 meta={"tool": "serve"}) as jnl:
        eng = ServeEngine(
            model, variables,
            n_slots=args.slots or 4,
            max_len=max_len,
            block_size=args.block_size or 16,
            quant_kv=args.quant_kv,
            attention_impl=args.attention_impl,
            prefill_chunk=args.prefill_chunk or None,
            admission=args.admission,
            lora_spec=lora_spec,
            # +1: slot 0 is the identity adapter
            n_adapters=n_adapters + 1 if n_adapters else 8,
            quant_adapters=args.quant_adapters,
            speculative=args.speculative,
            mesh=mesh,
            disaggregate=bool(getattr(args, "disaggregate", False)),
            prefix_cache=bool(getattr(args, "prefix_cache", False)),
            journal=jnl,
        )
        for i in range(n_adapters):
            eng.register_adapter(
                f"tenant{i}",
                random_adapter(variables["params"], lora_spec,
                               seed=args.seed + 100 + i))
        streams = args.streams or 8
        shared_len = max(0, min(
            int(getattr(args, "shared_prefix", 0) or 0), prompt_len - 1))
        shared = (rs.randint(1, cfg.vocab_size, size=(shared_len,))
                  if shared_len else None)
        for j in range(streams):
            prompt = rs.randint(1, cfg.vocab_size, size=(prompt_len,))
            if shared is not None:
                prompt = np.concatenate([shared, prompt[shared_len:]])
            eng.submit([int(t) for t in prompt],
                       max_new_tokens=args.max_new or 12, eos_id=0,
                       adapter=(f"tenant{j % n_adapters}"
                                if n_adapters else None))
        t0 = time.monotonic()
        done = eng.run()
        wall = time.monotonic() - t0
        totals = sorted((r.t_done or 0.0) - r.t_submit for r in done)
        new_tokens = sum(r.n_generated for r in done)

        def pct(vals, q):
            import math as _m

            return (vals[min(len(vals) - 1,
                             max(0, _m.ceil(q * len(vals)) - 1))]
                    if vals else None)

        summary = {
            "family": args.family, "size": size,
            "streams": streams, "slots": eng.n_slots,
            "n_requests": len(done),
            "new_tokens": new_tokens,
            "wall_s": round(wall, 4),
            "tokens_per_s": round(new_tokens / max(wall, 1e-9), 2),
            "p50_latency_s": pct(totals, 0.50),
            "p99_latency_s": pct(totals, 0.99),
            "mean_occupancy": (round(eng.mean_occupancy, 4)
                               if eng.mean_occupancy is not None
                               else None),
            "preemptions": eng.scheduler.n_preemptions,
            "quant_kv": args.quant_kv,
            "attention_impl": eng.attention_impl,
            "prefill_chunk": eng.prefill_chunk,
            "adapters": n_adapters,
            "adapter_rank": lora_spec.rank if lora_spec else None,
            "quant_adapters": bool(args.quant_adapters and n_adapters),
            "adapter_hit_rate": (
                round(eng.adapter_pool.allocator.hit_rate, 4)
                if eng.adapter_pool is not None else None),
            "speculative": eng.speculative,
            "spec_accept_rate": (
                round(eng.spec_accepted / eng.spec_drafted, 4)
                if eng.spec_drafted else None),
            "disaggregate": eng.disaggregate,
            "prefix_cache": eng.prefix_cache is not None,
            "prefix_hit_rate": (
                round(eng.prefix_cached_tokens
                      / max(1, sum(r.n_prompt for r in done)), 4)
                if eng.prefix_cache is not None else None),
            "prefix_hit_requests": (eng.prefix_hits
                                    if eng.prefix_cache is not None
                                    else None),
            "prefix_saved_chunks": (eng.prefix_saved_chunks
                                    if eng.prefix_cache is not None
                                    else None),
            "cow_forks": (eng.cow_forks
                          if eng.prefix_cache is not None else None),
            "tp": serve_tp,
            "kv_ships": eng.pool.n_transfers,
            "shipped_blocks": eng.pool.transferred_blocks,
            "shipped_bytes": eng.pool.transferred_bytes,
            "prefill_busy_s": round(eng.prefill_busy_s, 4),
            "decode_busy_s": round(eng.decode_busy_s, 4),
            "overlapped_wall_s": round(eng.overlapped_wall_s, 4),
            "journal": args.journal,
        }
    print(json.dumps(summary))
    if args.smoke and len(done) != streams:
        print(f"smoke: expected {streams} finished requests, got "
              f"{len(done)}", file=sys.stderr)
        return 1
    return 0


def cmd_gateway(args: argparse.Namespace) -> int:
    """Online serving gateway (inference/gateway): multi-replica
    ingress with prefix-affinity routing and the closed-loop SLO
    autoscaler.

    ``--smoke`` runs the virtual-clock chaos scenario twice (traffic
    flip → SLO breach → replan → scale-out → recover) and checks the
    two journals are byte-identical — the CI gate.  ``--chaos`` runs
    the FLEET fault scenario (seeded replica kill/stall/slow) and
    passes only if every accepted request completes with a token
    stream bitwise-identical to a fault-free replay, deterministically
    across two runs.  ``--port`` starts a real asyncio HTTP/SSE server
    over ``--replicas`` tiny engines (the ``tadnn serve --smoke``
    model) for interactive use.
    """
    from .inference.gateway import chaos_smoke, fleet_chaos

    if getattr(args, "chaos", False):
        out = fleet_chaos(
            journal_path=args.journal,
            seed=args.seed,
            n_replicas=max(4, args.replicas))
        print(json.dumps(out))
        if not out["ok"]:
            for flag in ("deterministic", "stream_parity",
                         "all_completed", "killed_inflight",
                         "baseline_complete"):
                if not out[flag]:
                    print(f"gateway chaos: {flag} check failed",
                          file=sys.stderr)
            return 1
        return 0
    if args.smoke:
        out = chaos_smoke(
            journal_path=args.journal,
            n_replicas=args.replicas,
            slo_text=args.slo,
            max_replicas=args.max_replicas,
            scale=args.scale,
            autoscale=args.autoscale)
        print(json.dumps(out))
        if not out["ok"]:
            for flag in ("deterministic", "closed_loop"):
                if not out[flag]:
                    print(f"gateway smoke: {flag} check failed",
                          file=sys.stderr)
            return 1
        return 0
    if not args.port:
        print("tadnn gateway needs --smoke, --chaos or --port",
              file=sys.stderr)
        return 2

    import asyncio

    import jax
    import jax.numpy as jnp
    import numpy as np

    from .inference.gateway import (
        AutoscalePolicy, EngineReplica, Gateway, serve_forever)
    from .inference.serve import ServeEngine
    from .models import GPT2
    from .obs.journal import Journal
    from .tune.slo import SLOSpec

    model = GPT2("test", max_seq_len=args.max_len, vocab_size=128,
                 dtype=jnp.float32, remat=False)
    rs = np.random.RandomState(args.seed)
    sample = jnp.asarray(rs.randint(1, 128, size=(1, 10)), jnp.int32)
    variables = model.init(jax.random.key(1), sample)

    with Journal(args.journal, host0_only=False,
                 meta={"tool": "gateway"}) as jnl:
        def make(name: str) -> EngineReplica:
            eng = ServeEngine(model, variables, n_slots=args.slots,
                              max_len=args.max_len, block_size=8,
                              prefix_cache=True, journal=jnl)
            return EngineReplica(name, eng)

        replicas = [make(f"replica{i}") for i in range(args.replicas)]
        policy = (AutoscalePolicy(slo=SLOSpec.parse(args.slo))
                  if args.autoscale else None)
        gw = Gateway(replicas, journal=jnl, autoscale=policy,
                     make_replica=make if args.autoscale else None,
                     rate_limit_per_s=args.rate_limit,
                     queue_limit=args.queue_limit)
        print(json.dumps({"listening": True, "host": args.host,
                          "port": args.port,
                          "replicas": args.replicas,
                          "autoscale": bool(args.autoscale),
                          "journal": args.journal}))
        try:
            asyncio.run(serve_forever(gw, host=args.host,
                                      port=args.port))
        except KeyboardInterrupt:
            pass
    return 0


def cmd_export(args: argparse.Namespace) -> int:
    """AOT export (export/ subsystem): compile the training step —
    and, with ``--serve``, the serving decode/prefill traces — ahead of
    time, serialize the executables into the content-addressed export
    cache, and print one result line per executable.  Any later
    ``Trainer``/``ServeEngine`` start on the same fingerprint (same
    shapes, plan, topology, jax/XLA version) then deserializes in
    milliseconds instead of recompiling.  ``--worlds N,M`` prewarms
    simulated N-device topologies in subprocesses (the elastic
    launcher's shrink candidates); ``--verify`` audits which cache
    entries would load here/now and which are stale."""
    from .export import cache as export_cache_mod
    from .obs import journal as obs_journal_mod

    cache = export_cache_mod.resolve(args.cache or True)

    if getattr(args, "gc", False):
        from .obs.journal import Journal

        days = getattr(args, "max_age_days", None)
        days = 30.0 if days is None else float(days)
        with Journal(args.journal, host0_only=False,
                     meta={"tool": "export"}) as jnl:
            with obs_journal_mod.as_default(jnl):
                stats = cache.gc(days * 86400.0)
        if args.json:
            print(json.dumps({"cache": cache.root, **stats}))
        else:
            kb = stats["payload_bytes_freed"] // 1024
            print(f"export cache: {cache.root}")
            print(f"  gc: dropped {stats['dropped']}/{stats['scanned']} "
                  f"entries not hit in {days:g} day(s) "
                  f"({kb} KiB of payloads freed, {stats['kept']} kept)")
        return 0

    if args.verify:
        report = cache.verify()
        if args.json:
            print(json.dumps({"cache": cache.root, "entries": report}))
        else:
            print(f"export cache: {cache.root}")
            if not report:
                print("  (empty)")
            for e in report:
                mark = "live " if e["live"] else "STALE"
                kb = (e.get("payload_bytes") or 0) // 1024
                line = (f"  [{mark}] {e.get('kind') or '?':<14} "
                        f"{e['key'][:16]}  {kb} KiB")
                if e.get("reason"):
                    line += f"  ({e['reason']})"
                print(line)
        return 0

    if args.worlds:
        # fan out over simulated device counts: each child exports the
        # same spec on an N-device CPU mesh, landing N-keyed entries in
        # the shared cache — exactly what an elastic shrink will ask for
        import subprocess

        from .training.launch import _sim_env

        worlds = [int(w) for w in args.worlds.split(",") if w.strip()]
        base = [sys.executable, "-m",
                "torch_automatic_distributed_neural_network_tpu", "export",
                "--family", args.family, "--batch", str(args.batch),
                "--strategy", args.strategy,
                "--precision", args.precision,
                "--cache", cache.root, "--json"]
        if args.size:
            base += ["--size", args.size]
        if args.seq:
            base += ["--seq", str(args.seq)]
        if args.serve:
            base.append("--serve")
        ok = True
        for w in worlds:
            env = _sim_env(w)
            env["TADNN_EXPORT_CACHE"] = cache.root
            proc = subprocess.run(base, env=env, capture_output=True,
                                  text=True)
            if proc.returncode != 0:
                ok = False
                print(json.dumps({"world": w, "error": "export failed",
                                  "rc": proc.returncode,
                                  "stderr": proc.stderr[-500:]}))
                continue
            for line in proc.stdout.splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                rec["world"] = w
                print(json.dumps(rec))
        return 0 if ok else 1

    import jax
    import optax

    from . import AutoDistribute
    from .obs.journal import Journal

    results: list[dict] = []
    with Journal(args.journal, host0_only=False,
                 meta={"tool": "export"}) as jnl:
        with obs_journal_mod.as_default(jnl):
            if args.preflight:
                # user-authored spec: the file's tadnn_export() returns
                # {model, loss_fn, sample_batch[, optimizer, **ad_kwargs]}
                # — export the REAL training program, not a zoo preset
                import importlib.util

                spec = importlib.util.spec_from_file_location(
                    "_tadnn_export_target", args.preflight)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                hook = getattr(mod, "tadnn_export", None)
                if hook is None:
                    print(f"{args.preflight} does not define "
                          f"tadnn_export()", file=sys.stderr)
                    return 2
                d = dict(hook())
                model = d.pop("model")
                loss = d.pop("loss_fn")
                sample = d.pop("sample_batch")
                optimizer = d.pop("optimizer", None) or optax.adamw(1e-4)
                kwargs = {"strategy": args.strategy,
                          "precision": args.precision}
                kwargs.update(d)
            else:
                model, loss, sample = _family_setup(args)
                optimizer = optax.adamw(1e-4)
                kwargs = {"strategy": args.strategy,
                          "precision": args.precision}
            ad = AutoDistribute(model, optimizer=optimizer, loss_fn=loss,
                                grad_accum=args.grad_accum,
                                zero1=args.zero1, **kwargs)
            results.append(ad.export_step(jax.random.key(0), sample,
                                          cache=cache))
            if args.serve:
                if args.family not in ("gpt2", "llama", "moe"):
                    print("export --serve needs a decoder family "
                          "(--family gpt2|llama|moe)", file=sys.stderr)
                    return 2
                import jax.numpy as jnp

                from .inference.serve import ServeEngine
                from .models import GPT2, Llama, MoE

                family = {"gpt2": GPT2, "llama": Llama,
                          "moe": MoE}[args.family]
                size = args.size or "test"
                max_len = args.max_len or 64
                vocab = args.vocab or (128 if size == "test" else None)
                overrides = {"max_seq_len": max_len, "dtype": jnp.float32,
                             "remat": False}
                if vocab:
                    overrides["vocab_size"] = vocab
                smodel = family(size, **overrides)
                variables = smodel.init(jax.random.key(1),
                                        jnp.zeros((1, 8), jnp.int32))
                eng = ServeEngine(
                    smodel, variables, n_slots=args.slots or 4,
                    max_len=max_len, block_size=args.block_size or 8,
                    prefill_chunk=args.prefill_chunk or 32,
                    journal=jnl, export_cache=cache)
                results.extend(eng.export_info)
    rc = 0
    for r in results:
        if r.get("source") == "error":
            rc = 1
        if args.json:
            print(json.dumps(r))
        else:
            wall = (f"deserialized in {r['deserialize_s'] * 1e3:.1f} ms"
                    if r.get("source") == "hit"
                    else f"compiled in {r.get('compile_s', 0.0):.2f} s"
                    if r.get("source") == "compile" else "FAILED")
            kb = (r.get("payload_bytes") or 0) // 1024
            print(f"{r.get('kind', '?'):<14} {r.get('source', '?'):<8} "
                  f"{wall}  ({kb} KiB, key {r.get('key', '?')[:16]})")
    if not args.json:
        print(f"export cache: {cache.root}")
    return rc


def cmd_tokenize(args: argparse.Namespace) -> int:
    """Text -> TADN token file (data/text.py)."""
    from .data.text import load_tokenizer, tokenize_file

    tokenize_file(
        args.input,
        args.output,
        tokenizer=load_tokenizer(args.tokenizer),
        append_eos=not args.no_eos,
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tadnn",
        description="TPU-native automatic-distribution launcher",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("devices", help="print device topology")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_devices)

    p = sub.add_parser("run", help="launch a training script "
                                   "(initializes multi-host if configured)")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("profile", help="run a script under jax.profiler")
    p.add_argument("script")
    p.add_argument("--logdir", default="/tmp/tadnn_profile")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("bench", help="collectives microbenchmark")
    p.add_argument("--ops", default="allreduce,allgather,reduce_scatter")
    p.add_argument("--sizes", default=str(64 * 2**20))
    p.add_argument("--axis", default="data")
    p.set_defaults(fn=cmd_bench)

    p = sub.add_parser(
        "fit",
        help="will this model fit? abstract AOT compile + XLA memory "
             "analysis per device; with --strategy search, walks the "
             "escalation ladder and reports every candidate",
    )
    p.add_argument("--family", default="gpt2",
                   choices=("mlp", "gpt2", "llama", "moe", "bert", "vit"))
    p.add_argument("--size", default=None,
                   help="model size preset; default per family "
                        "(gpt2: 1p3b, llama: 8b, moe: test, bert: large, "
                        "vit: large); for vit, --seq is the image side; "
                        "for mlp, comma-separated layer widths")
    p.add_argument("--seq", type=int, default=None,
                   help="sequence length (default 1024); for vit, the "
                        "image side (default 224)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--strategy", default="search")
    p.add_argument("--precision", default="mixed")
    p.add_argument("--loss", default="full", choices=("full", "blockwise"),
                   help="blockwise = vocab-blockwise CE (never "
                        "materializes [B,S,V] logits; big-vocab models "
                        "fit far smaller)")
    p.set_defaults(fn=cmd_fit)

    p = sub.add_parser(
        "tune",
        help="rank candidate parallelism plans for a model-zoo config "
             "with the analytic cost model (tune/); --measure also "
             "compiles and times the top-k on the real train step",
    )
    p.add_argument("--family", default="gpt2",
                   choices=("mlp", "gpt2", "llama", "moe", "bert", "vit"))
    p.add_argument("--size", default=None,
                   help="model size preset; default per family "
                        "(gpt2: 1p3b, llama: 8b, moe: test, bert: large, "
                        "vit: large); for vit, --seq is the image side; "
                        "for mlp, comma-separated layer widths")
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--precision", default="fp32")
    p.add_argument("--top-k", type=int, default=3,
                   help="candidates to measure with --measure")
    p.add_argument("--grad-accums", default="1",
                   help="comma-separated grad-accumulation choices to "
                        "include in the search space")
    p.add_argument("--measure", action="store_true",
                   help="compile + time the top-k candidates (journaled "
                        "as tune.trial spans)")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the persistent tuning cache "
                        "(~/.cache/tadnn/, TADNN_TUNE_CACHE)")
    p.add_argument("--no-zero1", action="store_true",
                   help="drop the ZeRO-1 optimizer-state-sharding "
                        "variants from the search space (changes the "
                        "cache key)")
    p.add_argument("--simulate", default=None, metavar="TOPOS",
                   help="run the fleet-scale what-if sweep over these "
                        "comma-separated SKUs (e.g. v5p-64,v5e-256) "
                        "instead of tuning the local topology — "
                        "shorthand for `tadnn simulate`")
    p.add_argument("--traffic", default=None, help=argparse.SUPPRESS)
    p.add_argument("--slo", default=None, help=argparse.SUPPRESS)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_tune)

    p = sub.add_parser(
        "simulate",
        help="fleet-scale what-if planner: sweep hypothetical TPU "
             "fleets (v5p-1024, v5e-256x4, ...) x parallelism plans "
             "and rank the joint MFU/HBM/serving/survival prediction "
             "against an operator SLO — device-free, runs anywhere",
    )
    p.add_argument("--topology", action="append", default=None,
                   metavar="SKU",
                   help="fleet to sweep, as <kind>-<chips> or "
                        "<kind>-<chips_per_slice>x<slices> (repeatable; "
                        "default v5p-16; un-sliced specs fan out over "
                        "slice counts)")
    p.add_argument("--family", default="gpt2",
                   choices=("mlp", "gpt2", "llama", "moe", "bert", "vit"))
    p.add_argument("--size", default=None,
                   help="model size preset (default per family; serving "
                        "predictions need a transformer family)")
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--precision", default="fp32")
    p.add_argument("--traffic", default=None,
                   help="serving traffic mix, e.g. "
                        "'rate=16,n=64,prompt=128,max_new=128,decode=96"
                        ",jitter=0.5,shared=0,seed=0' (rate in req/s; "
                        "shared = leading prompt tokens common to every "
                        "request, for --prefix-cache)")
    p.add_argument("--slo", default=None,
                   help="SLO spec, e.g. 'tok_s_chip>=40,p99_ms<=2500,"
                        "headroom>=0.1,survival>=0.9'")
    p.add_argument("--grad-accums", default="1,2,4,8",
                   dest="grad_accums",
                   help="comma-separated grad-accumulation choices in "
                        "the training search space")
    p.add_argument("--admissions", default="reserve,optimistic",
                   help="comma-separated admission policies to sweep")
    p.add_argument("--slots", type=int, default=8,
                   help="decode slots per serving replica")
    p.add_argument("--block-size", type=int, default=16,
                   dest="block_size")
    p.add_argument("--max-len", type=int, default=256, dest="max_len")
    p.add_argument("--prefill-chunk", type=int, default=32,
                   dest="prefill_chunk",
                   help="chunked-prefill size (0 = single-shot prefill)")
    p.add_argument("--disaggregate", action="store_true",
                   help="simulate disaggregated prefill/decode serving "
                        "replicas: prefill on its own slice, KV blocks "
                        "shipped over DCN on multislice fleets, step "
                        "wall = max(prefill, decode)")
    p.add_argument("--prefix-cache", action="store_true",
                   dest="prefix_cache",
                   help="price cross-request prefix reuse in the replay "
                        "(a real PrefixCache over the virtual pool); "
                        "pair with a shared= term in --traffic, e.g. "
                        "'prompt=128,shared=112' — needs --prefill-chunk")
    p.add_argument("--measured-overlap", type=float, default=None,
                   dest="measured_overlap", metavar="FRAC",
                   help="measured exposed-collective fraction (0..1) "
                        "correcting the training roofline "
                        "(cost.score measured_overlap)")
    p.add_argument("--trace-journal", default=None, dest="trace_journal",
                   metavar="JSONL",
                   help="journal from `tadnn trace` to derive "
                        "--measured-overlap from its trace.step records "
                        "(cost.overlap_from_trace)")
    p.add_argument("--preemption-rate", type=float, default=0.0,
                   dest="preemption_rate",
                   help="preemptions per HOST per hour for the "
                        "restart-budget survival model")
    p.add_argument("--mission-hours", type=float, default=24.0,
                   dest="mission_hours")
    p.add_argument("--top-k", type=int, default=10,
                   help="ranked candidates to keep in the report")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the persistent sweep cache "
                        "(~/.cache/tadnn/, TADNN_TUNE_CACHE)")
    p.add_argument("--journal", default=None,
                   help="journal JSONL to write simulate.* events to")
    p.add_argument("--out", default=None,
                   help="write the full JSON report to this file "
                        "(the CI artifact path)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_simulate)

    p = sub.add_parser(
        "trace",
        help="profile real steps: device-timeline capture with per-step "
             "compute/collective/exposed attribution + measured MFU, "
             "and a measured-vs-modeled collective-bytes crosscheck; "
             "pass a .py script to run it with TADNN_TRACE_EVERY_N "
             "exported",
    )
    p.add_argument("target", nargs="?", default=None,
                   help="training script to instrument (script mode); "
                        "omit to trace a --family config in-process. "
                        "trace options go BEFORE the script; everything "
                        "after it is passed to the script: "
                        "tadnn trace --every 8 train.py -- --steps 100")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    p.add_argument("--steps", type=int, default=3,
                   help="instrumented steps to capture (config mode)")
    p.add_argument("--every", type=int, default=10,
                   help="script mode: trace every Nth step "
                        "(TADNN_TRACE_EVERY_N)")
    p.add_argument("--logdir", default=None,
                   help="profiler logdir (default: a fresh temp dir)")
    p.add_argument("--journal", default=None,
                   help="journal JSONL to write trace.step / "
                        "trace.collective events to")
    p.add_argument("--json", action="store_true")
    p.add_argument("--family", default="mlp",
                   choices=("mlp", "gpt2", "llama", "moe", "bert", "vit"),
                   help="model to trace in config mode (default: the "
                        "bench mlp)")
    p.add_argument("--size", default=None,
                   help="model size preset; for mlp, comma-separated "
                        "layer widths (default 1024,1024,10)")
    p.add_argument("--seq", type=int, default=None,
                   help="sequence length; for mlp/vit, the input image "
                        "side")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--strategy", default="dp",
                   help="sharding strategy (default dp — the bench "
                        "config, which has collectives on >1 device)")
    p.add_argument("--precision", default="fp32")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "report",
        help="summarize a run's journal + metrics JSONL: compiles/"
             "recompiles, goodput breakdown, expected + measured comm "
             "bytes, trace attribution, incidents (works offline; no "
             "accelerator needed)",
    )
    p.add_argument("target",
                   help="run directory (searched for journal.merged."
                        "jsonl / journal.jsonl / metrics.jsonl) or a "
                        "journal file path")
    p.add_argument("--metrics", default=None,
                   help="explicit MetricsLogger JSONL path")
    p.add_argument("--json", action="store_true")
    p.add_argument("--check", action="store_true",
                   help="bench freshness guard: exit nonzero when the "
                        "latest BENCH_r*.json is stale-marked/missing "
                        "or its headline regressed >10%% vs "
                        "BENCH_LAST_GOOD.json")
    p.add_argument("--bench", default=None,
                   help="explicit bench record path for --check "
                        "(default: newest BENCH_r*.json in target)")
    p.add_argument("--last-good", default=None, dest="last_good",
                   help="explicit BENCH_LAST_GOOD.json path for --check")
    p.add_argument("--merge", action="store_true",
                   help="merge per-host journals in the target directory "
                        "into journal.merged.jsonl before reporting")
    p.add_argument("--check-simulate", action="store_true",
                   dest="check_simulate",
                   help="crosscheck the simulator against reality: "
                        "replay the newest SERVE_BENCH record's config "
                        "through the what-if serve replay and fail when "
                        "prediction and measurement disagree by >2x")
    p.set_defaults(fn=cmd_report)

    p = sub.add_parser(
        "monitor",
        help="continuous SLO monitor over a serving journal: rolling "
             "TTFT/ITL/latency windows, slo.breach/slo.recover "
             "incidents with hysteresis, planner drift vs the serve "
             "replay (works offline; no accelerator needed)",
    )
    p.add_argument("journal", help="serving journal JSONL to monitor")
    p.add_argument("--slo", default=None,
                   help='spec over window aggregates, e.g. '
                        '"p99_ms<=2500,ttft_ms<=2000,itl_ms<=100" '
                        "(tune/slo fields; empty = report only)")
    p.add_argument("--window", type=float, default=5.0,
                   help="window width in event-time seconds")
    p.add_argument("--replay", action="store_true",
                   help="deterministically replay the journal from the "
                        "start (the default mode, spelled out)")
    p.add_argument("--follow", action="store_true",
                   help="tail a concurrently-appending journal instead "
                        "of replaying a finished one")
    p.add_argument("--idle-timeout", type=float, default=30.0,
                   dest="idle_timeout",
                   help="--follow: stop after this many seconds with "
                        "no new records")
    p.add_argument("--breach-after", type=int, default=2,
                   dest="breach_after",
                   help="consecutive violating windows before a breach "
                        "incident (hysteresis)")
    p.add_argument("--recover-after", type=int, default=2,
                   dest="recover_after",
                   help="consecutive clean windows before recovery")
    p.add_argument("--warmup-windows", type=int, default=1,
                   dest="warmup_windows",
                   help="leading traffic windows reported but not "
                        "SLO-evaluated (they carry the jit compiles; "
                        "same discipline as bench_serve's warm phase)")
    p.add_argument("--chips", type=int, default=1,
                   help="chip count for tok_s_chip evaluation")
    p.add_argument("--drift", default=None,
                   help="SERVE_BENCH_r*.json record: compare measured "
                        "throughput against the simulate replay's "
                        "prediction and flag >2x planner drift")
    p.add_argument("--incident-journal", default=None,
                   dest="incident_journal",
                   help="append slo.breach/slo.recover/simulate.drift "
                        "events to this JSONL (renderable by tadnn "
                        "report)")
    p.add_argument("--out", default=None,
                   help="write the full monitor summary JSON here")
    p.add_argument("--json", action="store_true")
    p.add_argument("--check", action="store_true",
                   help="exit nonzero on any breach or out-of-band "
                        "drift — the CI gate")
    p.set_defaults(fn=cmd_monitor)

    p = sub.add_parser(
        "serve",
        help="continuous-batching serving loop (paged KV cache, "
             "iteration-level scheduler); --smoke pins the tiny CI "
             "configuration",
    )
    p.add_argument("--smoke", action="store_true",
                   help="CI smoke: test-size model, 8 streams, CPU-ok")
    p.add_argument("--family", default="gpt2",
                   help="decoder family: gpt2 | llama | moe")
    p.add_argument("--size", default=None,
                   help="model preset (default: test)")
    p.add_argument("--vocab", type=int, default=None,
                   help="vocab override (default 128 for test size)")
    p.add_argument("--streams", type=int, default=None,
                   help="number of concurrent request streams")
    p.add_argument("--slots", type=int, default=None,
                   help="decode slots (batch width of the jitted step)")
    p.add_argument("--max-len", type=int, default=None, dest="max_len",
                   help="max tokens per request (prompt + generated)")
    p.add_argument("--max-new", type=int, default=None, dest="max_new",
                   help="max generated tokens per request")
    p.add_argument("--prompt-len", type=int, default=None,
                   dest="prompt_len")
    p.add_argument("--block-size", type=int, default=None,
                   dest="block_size", help="KV pool block size (tokens)")
    p.add_argument("--quant-kv", action="store_true", dest="quant_kv",
                   help="int8 KV blocks (inference/quant.quantize_kv)")
    p.add_argument("--attention-impl", default="paged",
                   choices=("paged", "dense"), dest="attention_impl",
                   help="decode attention: fused paged kernel "
                        "(ops/paged_attention) or the dense "
                        "gather_blocks reference path")
    p.add_argument("--prefill-chunk", type=int, default=32,
                   dest="prefill_chunk",
                   help="chunked-prefill chunk size (0 = legacy "
                        "single-shot prefill)")
    p.add_argument("--admission", default="reserve",
                   choices=("reserve", "optimistic"),
                   help="block admission policy (scheduler.py)")
    p.add_argument("--adapters", type=int, default=0,
                   help="serve N seeded LoRA tenants round-robin through "
                        "the paged adapter pool (serve/adapters.py); "
                        "0 = base model only")
    p.add_argument("--adapter-rank", type=int, default=8,
                   dest="adapter_rank", help="LoRA rank of the tenants")
    p.add_argument("--quant-adapters", action="store_true",
                   dest="quant_adapters",
                   help="int8 adapter factors "
                        "(quant.quantize_lora_factor)")
    p.add_argument("--speculative", type=int, nargs="?", const=4,
                   default=0, metavar="K",
                   help="speculative decoding with K n-gram draft "
                        "tokens per step (bare flag = 4; greedy only)")
    p.add_argument("--disaggregate", action="store_true",
                   help="disaggregated prefill/decode: prefill runs as "
                        "a dedicated worker loop (uncapped chunks per "
                        "step), finished KV blocks ship into decode "
                        "slots through the pool, and decode steps no "
                        "longer interleave prefill; token-identical to "
                        "colocated")
    p.add_argument("--prefix-cache", action="store_true",
                   dest="prefix_cache",
                   help="cross-request prefix reuse: radix-index full "
                        "prompt blocks by chained content hash; admitted "
                        "requests skip prefill over their cached prefix "
                        "(copy-on-write blocks, token-identical to "
                        "cache-off; needs --prefill-chunk)")
    p.add_argument("--shared-prefix", type=int, default=0,
                   dest="shared_prefix", metavar="N",
                   help="draw the first N prompt tokens once and share "
                        "them across every stream (the traffic shape "
                        "--prefix-cache exploits; capped at "
                        "prompt_len - 1)")
    p.add_argument("--serve-tp", type=int, default=1, dest="serve_tp",
                   metavar="N",
                   help="tensor-parallel degree: shard KV-pool / "
                        "adapter-pool heads and the paged decode kernel "
                        "over the first N devices (kv_heads % N == 0 "
                        "to shard the kernel)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--journal", default=None,
                   help="journal path for serve.* spans "
                        "(tadnn report renders them)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "export",
        help="AOT-compile the train step (and --serve decode/prefill "
             "traces) and serialize the executables into the export "
             "cache, so later starts deserialize instead of "
             "recompiling; --verify audits live vs stale entries",
    )
    p.add_argument("--family", default="gpt2",
                   choices=("mlp", "gpt2", "llama", "moe", "bert", "vit"))
    p.add_argument("--size", default=None,
                   help="model size preset (default per family)")
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--strategy", default="auto")
    p.add_argument("--precision", default="fp32")
    p.add_argument("--grad-accum", type=int, default=1,
                   dest="grad_accum")
    p.add_argument("--zero1", action="store_true")
    p.add_argument("--loss", default="full", choices=("full", "blockwise"))
    p.add_argument("--preflight", default=None, metavar="FILE",
                   help="export the file's tadnn_export() spec "
                        "({model, loss_fn, sample_batch[, optimizer, "
                        "ad kwargs]}) instead of a --family preset")
    p.add_argument("--serve", action="store_true",
                   help="also export the serving decode + prefill-chunk "
                        "traces (decoder families only)")
    p.add_argument("--worlds", default=None, metavar="N,M,...",
                   help="prewarm simulated N-device topologies in "
                        "subprocesses (the elastic launcher's shrink "
                        "candidates)")
    p.add_argument("--cache", default=None,
                   help="export cache dir (default: TADNN_EXPORT_CACHE "
                        "or ~/.cache/tadnn/executables)")
    p.add_argument("--verify", action="store_true",
                   help="report which cache entries would load on this "
                        "host/version (live) and which are stale")
    p.add_argument("--gc", action="store_true",
                   help="garbage-collect by last-hit age: drop entries "
                        "not deserialized within --max-age-days, delete "
                        "their payloads and rewrite the index (every "
                        "cache hit refreshes an entry's age)")
    p.add_argument("--max-age-days", type=float, default=30.0,
                   dest="max_age_days", metavar="N",
                   help="--gc retention window in days (default 30)")
    p.add_argument("--slots", type=int, default=None,
                   help="--serve: decode slots")
    p.add_argument("--max-len", type=int, default=None, dest="max_len",
                   help="--serve: max tokens per request")
    p.add_argument("--block-size", type=int, default=None,
                   dest="block_size", help="--serve: KV block size")
    p.add_argument("--prefill-chunk", type=int, default=32,
                   dest="prefill_chunk")
    p.add_argument("--vocab", type=int, default=None)
    p.add_argument("--journal", default=None,
                   help="journal path for export.* events "
                        "(tadnn report renders them)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser(
        "doctor",
        help="verify a checkpoint directory (per-leaf integrity "
             "manifests, resilience.py) and print the fallback chain; "
             "exits nonzero when no step is restorable",
    )
    p.add_argument("directory", nargs="?", default=None,
                   help="CheckpointManager or sharded-checkpoint directory")
    p.add_argument("--launch-dir", default=None,
                   help="report launch supervision health instead "
                        "(per-host heartbeats, restart budget, which "
                        "host broke the cohort)")
    p.add_argument("--gateway-dir", default=None,
                   help="fleet post-mortem from a gateway journal "
                        "(dir or .jsonl): per-replica heartbeats, "
                        "failovers, hedge wins/losses, breaker and "
                        "degrade history, who broke the cohort; exits "
                        "nonzero when accepted requests were lost")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser(
        "launch",
        help="elastic multihost launcher: spawn + supervise N "
             "simulated-mesh workers with async sharded checkpoints, "
             "cohort restart on death/hang, and seeded chaos "
             "(training/launch.py); --smoke runs the kill-and-resume "
             "bitwise-parity acceptance pair",
    )
    p.add_argument("--launch-dir", required=True,
                   help="run directory (heartbeats, shards, journals)")
    p.add_argument("--hosts", type=int, default=2)
    p.add_argument("--local-devices", type=int, default=4)
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--ckpt-every", type=int, default=2)
    p.add_argument("--strategy", default="auto",
                   help="worker strategy ('auto' re-plans per cohort)")
    p.add_argument("--zero1", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-restarts", type=int, default=2)
    p.add_argument("--elastic", action="store_true",
                   help="shrink the cohort after a host death instead "
                        "of respawning at full size")
    p.add_argument("--watchdog-s", type=float, default=120.0,
                   help="no heartbeat step-progress within this = hung")
    p.add_argument("--heartbeat-interval-s", type=float, default=0.5)
    p.add_argument("--kill-host-at", type=int, action="append",
                   help="SIGKILL the chaos host when its heartbeat "
                        "reaches this step (repeatable)")
    p.add_argument("--tear-shard-at", type=int, action="append",
                   help="tear the chaos host's shard of the newest "
                        "committed step at this step (repeatable)")
    p.add_argument("--partition-journal-at", type=int, action="append",
                   help="partition the chaos host's journal at this "
                        "step (repeatable)")
    p.add_argument("--chaos-host", type=int, default=0)
    p.add_argument("--export-cache", default=None, dest="export_cache",
                   help="AOT executable cache dir shared by the cohort: "
                        "workers go cache-first on the step compile and "
                        "elastic shrink worlds are prewarmed in the "
                        "background (tadnn export)")
    p.add_argument("--smoke", action="store_true",
                   help="clean + one-SIGKILL chaos pair; exit nonzero "
                        "unless resumed losses match bitwise")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_launch)

    p = sub.add_parser(
        "check",
        help="static analyzer: source lint over the repo (plan/graph "
             "lint with --preflight FILE, liveness peak-HBM + dtype "
             "lint with --memory); exit 1 on errors, with --strict "
             "also on warnings",
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to source-lint (default: the "
                        "package, tests, examples and top-level scripts)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--strict", action="store_true",
                   help="warnings also fail (exit 1)")
    p.add_argument("--rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--preflight", default=None, metavar="FILE",
                   help="python file defining tadnn_check() -> dict with "
                        "keys among plan/abstract_params/param_specs/"
                        "batch_spec/degrees/strategy/fn/args/static_args/"
                        "budget; runs plan + graph + mem + dtype lint "
                        "on it")
    p.add_argument("--no-source", action="store_true",
                   help="skip the source lint (only --preflight/--memory "
                        "layers)")
    p.add_argument("--memory", action="store_true",
                   help="trace a model-zoo config (--family et al.) and "
                        "predict its per-device peak HBM against "
                        "--budget (ML001 error when it would OOM)")
    p.add_argument("--budget", default=None,
                   help="HBM budget for --memory, e.g. '16GiB' "
                        "(default: the detected chip's ChipSpec)")
    p.add_argument("--headroom", type=float, default=None,
                   help="warn (ML002) when the predicted peak is within "
                        "this fraction of the budget (default 0.1)")
    p.add_argument("--no-compiled", action="store_true",
                   help="skip the XLA compiled_cost cross-check (stay "
                        "fully device-free / trace-only)")
    p.add_argument("--ignore", action="append", default=[],
                   metavar="CODE",
                   help="suppress findings with this rule code "
                        "(repeatable) — the plan/graph/mem/dtype analog "
                        "of '# tadnn: lint-ok(CODE)'")
    p.add_argument("--pl005-bytes", type=int, default=None,
                   help="PL005 'large replicated leaf' byte threshold "
                        "(default: the rule table's, 64 MiB)")
    p.add_argument("--family", default="mlp",
                   choices=("mlp", "gpt2", "llama", "moe", "bert", "vit"),
                   help="model for --memory (default: the bench mlp)")
    p.add_argument("--size", default=None,
                   help="model size preset; for mlp, comma-separated "
                        "layer widths (default 1024,1024,10)")
    p.add_argument("--seq", type=int, default=None,
                   help="sequence length; for mlp/vit, the input image "
                        "side (mlp default 28)")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--strategy", default="fsdp",
                   help="sharding strategy for --memory (default fsdp)")
    p.add_argument("--precision", default="fp32")
    p.add_argument("--grad-accum", type=int, default=1)
    p.add_argument("--serving", action="store_true",
                   help="serving capacity lint (analysis/serve_lint): "
                        "predict max concurrent KV streams under "
                        "--budget for --family/--size; ML004/ML005")
    p.add_argument("--serve-streams", type=int, default=None,
                   dest="serve_streams",
                   help="requested concurrency (fewer fitting = ML005)")
    p.add_argument("--serve-block-size", type=int, default=16,
                   dest="serve_block_size")
    p.add_argument("--serve-max-len", type=int, default=None,
                   dest="serve_max_len",
                   help="tokens per stream (default: --seq or 256)")
    p.add_argument("--serve-quant-kv", action="store_true",
                   dest="serve_quant_kv", help="int8 KV pool")
    p.add_argument("--serve-attention-impl", default="paged",
                   choices=("paged", "dense"),
                   dest="serve_attention_impl",
                   help="decode path to budget: dense charges the "
                        "per-step gather workspace, paged charges 0")
    p.add_argument("--serve-adapters", type=int, default=None,
                   dest="serve_adapters",
                   help="size the multi-tenant LoRA adapter pool "
                        "(N tenants + identity slot 0); charged against "
                        "the HBM budget, ML006 when it alone pushes "
                        "streams to zero")
    p.add_argument("--serve-adapter-rank", type=int, default=8,
                   dest="serve_adapter_rank")
    p.add_argument("--serve-quant-adapters", action="store_true",
                   dest="serve_quant_adapters",
                   help="int8 adapter factors (~quarter the pool)")
    p.add_argument("--serve-tp", type=int, default=1, dest="serve_tp",
                   metavar="N",
                   help="budget the serving estimate per TP shard "
                        "(degrees={'tensor': N}): KV-pool heads, "
                        "adapter b factors and params all charge "
                        "per-device, so ML004/ML005/ML006 judge the "
                        "sharded deployment")
    p.add_argument("--serve-prefix-cache", action="store_true",
                   dest="serve_prefix_cache",
                   help="charge the prefix-reuse radix index metadata "
                        "and report effective concurrency when shared "
                        "prefixes dedupe KV blocks")
    p.add_argument("--serve-prefix-hit-rate", type=float, default=0.0,
                   dest="serve_prefix_hit_rate", metavar="FRAC",
                   help="expected fraction [0,1) of prompt tokens served "
                        "from the prefix cache (sizes "
                        "effective_max_streams; default 0)")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1 for --memory: shard optimizer moments "
                        "over the data axis (the per-chip optimizer row "
                        "drops ~DP-fold)")
    p.add_argument("--trace-serve", action="store_true",
                   dest="trace_serve",
                   help="with --serving: build a ServeEngine on the "
                        "family config and run graph + dtype lint over "
                        "its decode/prefill jaxprs (trace-only, the "
                        "PR-14 eval_shape AOT operands)")
    p.add_argument("--protocol", action="store_true",
                   help="explicit-state model check of the serving "
                        "control plane (allocator / scheduler / prefix "
                        "cache / gateway): BFS over all event "
                        "interleavings at --scope, PC0xx findings with "
                        "minimized replayable counterexamples")
    p.add_argument("--scope", type=int, default=1,
                   help="protocol small-scope level (default 1: 2 "
                        "replicas, 3 requests, 4+ blocks; 2 widens "
                        "requests/windows — slower, exponentially "
                        "larger space)")
    p.add_argument("--counterexample-dir", default=None, metavar="DIR",
                   dest="counterexample_dir",
                   help="write minimized counterexamples as replayable "
                        "JSON event scripts into DIR (replay via "
                        "analysis.protocol.replay_script)")
    p.add_argument("--journal", action="store_true",
                   help="journal telemetry contract lint (JL00x): "
                        "resolve every event emission/consumption site "
                        "against the obs/schema.py registry; with "
                        "--rules, print the registry as a markdown "
                        "event reference instead")
    p.add_argument("--journal-file", action="append", default=None,
                   metavar="FILE", dest="journal_file",
                   help="audit a committed/artifact JSONL journal "
                        "record-by-record against the event schema "
                        "registry (repeatable)")
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser(
        "gateway",
        help="online serving gateway: multi-replica SSE ingress with "
             "prefix-affinity routing and a closed-loop SLO "
             "autoscaler; --smoke replays the chaos scenario twice "
             "and asserts byte-identical journals",
    )
    p.add_argument("--smoke", action="store_true",
                   help="run the virtual-clock chaos autoscale "
                        "scenario (breach → replan → scale → recover) "
                        "twice and verify determinism; exit 1 on any "
                        "failed check")
    p.add_argument("--chaos", action="store_true",
                   help="run the fleet fault scenario (seeded replica "
                        "kill/stall/slow mid-stream) and assert every "
                        "accepted request completes with tokens "
                        "bitwise-identical to a fault-free replay, "
                        "deterministically across two runs")
    p.add_argument("--replicas", type=int, default=2,
                   help="initial fleet size (--chaos default: 4)")
    p.add_argument("--max-replicas", type=int, default=8,
                   dest="max_replicas",
                   help="autoscaler ceiling (smoke: the scale-out "
                        "target under the traffic flip)")
    p.add_argument("--autoscale", action="store_true",
                   help="enable the closed-loop SLO autoscaler")
    p.add_argument("--slo", default="p99_ms<=2500",
                   help="SLO spec the monitor/autoscaler enforce "
                        "(tune/slo grammar, e.g. 'p99_ms<=2500,"
                        "ttft_p99_ms<=1000')")
    p.add_argument("--scale", default="smoke",
                   choices=["smoke", "light", "gentle"],
                   help="chaos scenario size (light = fast tier-1 "
                        "variant; gentle = no traffic flip)")
    p.add_argument("--journal", default=None,
                   help="journal JSONL path (smoke: run 1's journal, "
                        "the CI artifact; --port: the live journal "
                        "tadnn monitor can follow)")
    p.add_argument("--port", type=int, default=0,
                   help="start a real HTTP/SSE ingress on this port "
                        "(POST /v1/generate, GET /healthz)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--slots", type=int, default=4,
                   help="serving slots per replica (--port mode)")
    p.add_argument("--max-len", type=int, default=64, dest="max_len",
                   help="per-replica context length (--port mode)")
    p.add_argument("--rate-limit", type=float, default=None,
                   dest="rate_limit", metavar="R",
                   help="per-tenant sustained requests/s "
                        "(token bucket; default unlimited)")
    p.add_argument("--queue-limit", type=int, default=64,
                   dest="queue_limit",
                   help="per-tenant in-flight cap before 503 "
                        "backpressure")
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(fn=cmd_gateway)

    p = sub.add_parser(
        "tokenize",
        help="tokenize a UTF-8 text file into a native TADN token file "
             "(data/loader.py) for the LM examples",
    )
    p.add_argument("input")
    p.add_argument("output")
    p.add_argument("--tokenizer", default="byte",
                   help="'byte' (offline, vocab 258) or a transformers "
                        "tokenizer name/path (tried local-first)")
    p.add_argument("--no-eos", action="store_true")
    p.set_defaults(fn=cmd_tokenize)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
