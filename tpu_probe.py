"""Stdlib-only driver-path helpers: backend probe + CPU-sim child env.

The tunneled axon TPU backend can hang ``jax.devices()`` indefinitely when
the tunnel is down (observed 2026-07-29: 24-minute hang, then
'UNAVAILABLE: TPU backend setup/compile error') — and the hang is inside a
C call, so no in-process alarm/signal can break it.  The only safe probe
is a SUBPROCESS with a timeout.  This module is shared by ``bench.py``,
``__graft_entry__.py`` and ``utils/simenv.py`` and must stay stdlib-only:
it runs on the driver's parent path where importing jax (and thereby
risking backend init) is exactly the hang vector being guarded against.
"""

from __future__ import annotations

import os
import subprocess
import sys


def probe_backend(timeout_s: int = 300) -> str | None:
    """Initialize the JAX backend in a subprocess with a timeout.

    Returns an error string when the backend is unreachable, None when it
    is fine (or when the process is already forced onto the CPU platform,
    which never hangs).
    """
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return None
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return f"backend init hung > {timeout_s}s (tunnel down?)"
    if proc.returncode != 0:
        return proc.stderr.strip().splitlines()[-1][:300] if (
            proc.stderr.strip()) else f"backend init rc={proc.returncode}"
    return None


def cpu_sim_env(
    n_devices: int,
    base: dict | None = None,
    *,
    extra_pythonpath: tuple[str, ...] = (),
) -> dict:
    """Environment for a child process on ``n_devices`` simulated CPU
    devices: drop the axon sitecustomize from PYTHONPATH (it forces the
    TPU platform at interpreter start), force JAX_PLATFORMS=cpu, and set
    the virtual device count in XLA_FLAGS (replacing any existing count
    flag).  ``extra_pythonpath`` entries are prepended (e.g. the repo
    root for test workers)."""
    env = dict(os.environ if base is None else base)
    paths = [
        p for p in (
            *extra_pythonpath,
            *env.get("PYTHONPATH", "").split(os.pathsep),
        ) if p and "axon" not in p
    ]
    if paths:
        env["PYTHONPATH"] = os.pathsep.join(paths)
    else:
        env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={n_devices}"]
    )
    return env
