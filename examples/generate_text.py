"""Autoregressive generation demo: KV-cached decode on the GPT-2 family.

With random init the output is noise; the point is the decode path and
its throughput — one compiled prefill + a single-program lax.scan decode
loop (inference/decode.py).

Usage::

    python examples/generate_text.py model.size=small run.new_tokens=64
    python examples/generate_text.py run.quant=int8       # int8 weights
    python examples/generate_text.py run.speculative=1    # draft+verify
"""

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from torch_automatic_distributed_neural_network_tpu.inference import (
    SampleConfig,
    generate,
)
from torch_automatic_distributed_neural_network_tpu.models import GPT2
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    size: str = "small"
    vocab_size: int = 50257


@dataclasses.dataclass(frozen=True)
class RunCfg:
    batch_size: int = 4
    prompt_len: int = 32
    new_tokens: int = 64
    temperature: float = 0.8
    top_k: int = 40
    top_p: float = 1.0  # nucleus sampling; 1.0 = off
    eos_id: int = -1  # >= 0: rows finalize after emitting this token
    # 'none' -> plain single-program decode; any planner strategy
    # ('tp', 'tp_fsdp', 'fsdp', 'dp') -> plan-aware sharded decode
    # (AutoDistribute.generate: sharded params, KV cache on the mesh)
    strategy: str = "none"
    quant: str = "none"  # 'int8': weight-only quantized decode
    # 1: greedy speculative decoding (batch 1, temperature ignored) —
    # a 1-layer draft proposes, the full model verifies; output is
    # bit-identical to plain greedy decoding of the full model
    speculative: int = 0
    spec_k: int = 4


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    run: RunCfg = RunCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    r = cfg.run
    if r.quant not in ("none", "int8"):
        raise SystemExit(f"unknown run.quant={r.quant!r}; "
                         "supported: none, int8")
    if r.speculative and (r.strategy != "none" or r.quant != "none"
                          or r.eos_id >= 0):
        # a silently-dropped flag would attribute the tok/s line to a
        # config that never ran
        raise SystemExit("run.speculative=1 is plain greedy decode: it "
                         "does not compose with run.strategy / "
                         "run.quant / run.eos_id")
    # speculative rounds need k+1 positions of headroom past the last
    # emitted token; build the model ONCE with the right table size
    seq_budget = r.prompt_len + r.new_tokens + (
        r.spec_k + 1 if r.speculative else 0)
    batch = 1 if r.speculative else r.batch_size
    r = dataclasses.replace(r, batch_size=batch)
    model = GPT2(cfg.model.size, vocab_size=cfg.model.vocab_size,
                 max_seq_len=seq_budget)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(
            0, cfg.model.vocab_size, size=(batch, r.prompt_len)),
        jnp.int32,
    )
    variables = model.init(jax.random.key(0), prompt)
    eos = r.eos_id if r.eos_id >= 0 else None
    sample = SampleConfig(temperature=r.temperature, top_k=r.top_k,
                          top_p=r.top_p)

    if r.speculative:
        from torch_automatic_distributed_neural_network_tpu.inference import (
            speculative_generate,
        )

        draft = GPT2(cfg.model.size, vocab_size=cfg.model.vocab_size,
                     max_seq_len=seq_budget, n_layers=1)
        dv = draft.init(jax.random.key(7), prompt)
        gen = jax.jit(lambda v, p, k: speculative_generate(
            model, v, draft, dv, p, max_new_tokens=r.new_tokens,
            k=r.spec_k))
    elif r.strategy != "none":
        import optax

        import torch_automatic_distributed_neural_network_tpu as tad
        from torch_automatic_distributed_neural_network_tpu.training import (
            next_token_loss,
        )

        ad = tad.AutoDistribute(
            model, optimizer=optax.sgd(0.1), loss_fn=next_token_loss,
            strategy=r.strategy,
        )
        ad.build_plan(
            jax.random.key(0),
            {"input_ids": np.zeros(
                (r.batch_size, r.prompt_len + 1), np.int32)},
        )
        print(f"plan: strategy={ad.plan.strategy} "
              f"mesh={tad.mesh_degrees(ad.plan.mesh)}")
        gen = lambda v, p, k: ad.generate(
            v, p, max_new_tokens=r.new_tokens, sample=sample, rng=k,
            eos_id=eos, quant=None if r.quant == "none" else r.quant)
    else:
        if r.quant == "int8":
            from torch_automatic_distributed_neural_network_tpu.inference import (  # noqa: E501
                quantize_for_decode,
            )

            variables = quantize_for_decode(variables)
        gen = jax.jit(lambda v, p, k: generate(
            model, v, p, max_new_tokens=r.new_tokens, sample=sample, rng=k,
            eos_id=eos))
    # fence with a host readback: on the tunneled TPU, block_until_ready
    # does not synchronize (see bench.py readback_overhead_s)
    t0 = time.perf_counter()
    out = np.asarray(gen(variables, prompt, jax.random.key(1)))
    print(f"compile + first generate: {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    out = np.asarray(gen(variables, prompt, jax.random.key(2)))
    dt = time.perf_counter() - t0
    total_new = r.batch_size * r.new_tokens
    print(f"generated {total_new} tokens in {dt*1e3:.0f}ms "
          f"({total_new/dt:,.0f} tok/s)")
    print("sample token ids:", np.asarray(out[0, r.prompt_len:])[:16])


if __name__ == "__main__":
    main()
