"""Train GPT-2 with an automatic tensor-parallel shard plan.

The reference's fourth example config (BASELINE.json:10): "GPT-2 1.3B with
auto tensor-parallel shard plan".  The planner picks tp_fsdp automatically
when the model doesn't fit replicated; force a strategy with
``parallel.strategy=...``.

Usage::

    python examples/train_gpt2.py model.size=small run.steps=100
    python examples/train_gpt2.py model.size=1p3b parallel.strategy=tp_fsdp
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data.synthetic import SyntheticLM
from torch_automatic_distributed_neural_network_tpu.models import GPT2, gpt2_config
from torch_automatic_distributed_neural_network_tpu.training import (
    MetricsLogger,
    Trainer,
    TrainerConfig,
    next_token_loss,
    transformer_step_flops,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    size: str = "small"
    seq_len: int = 512
    vocab_size: int = 50257


@dataclasses.dataclass(frozen=True)
class DataCfg:
    path: str = ""  # TADN token file (data/loader.py); "" = synthetic


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 50
    batch_size: int = 8
    lr: float = 3e-4
    log_every: int = 10
    metrics_path: str = ""
    ckpt_dir: str = ""
    ckpt_every: int = 0
    # restarts allowed per rolling hour (needs ckpt_dir); <0 = no recovery
    max_restarts: int = -1
    anomaly_rollback: bool = False  # loss NaN/spike -> restore + skip batch


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "auto"
    seq: int = 1  # context-parallel degree (ring/Ulysses attention)
    pipe: int = 1  # pipeline stages (1 = no pipeline)
    microbatches: int = 8
    schedule: str = "cond"  # cond | dense | 1f1b (parallel/pipeline.py)


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    data: DataCfg = DataCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    print(f"devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    mcfg = gpt2_config(
        cfg.model.size, vocab_size=cfg.model.vocab_size,
        max_seq_len=cfg.model.seq_len,
    )
    if cfg.data.path:
        from torch_automatic_distributed_neural_network_tpu.data import (
            TokenFileDataset,
        )

        data = TokenFileDataset(
            cfg.data.path, seq_len=cfg.model.seq_len,
            batch_size=cfg.run.batch_size,
        )
        print(f"data: {cfg.data.path} ({data.n_tokens:,} tokens, "
              f"{data.backend} backend)")
    else:
        data = SyntheticLM(
            vocab_size=mcfg.vocab_size, seq_len=cfg.model.seq_len + 1,
            batch_size=cfg.run.batch_size,
        )
    ad = tad.AutoDistribute(
        GPT2(cfg.model.size, vocab_size=cfg.model.vocab_size,
             max_seq_len=cfg.model.seq_len),
        optimizer=optax.adamw(cfg.run.lr),
        loss_fn=next_token_loss,
        strategy=cfg.parallel.strategy,
        seq_parallel=cfg.parallel.seq,
        pipeline_stages=cfg.parallel.pipe,
        microbatches=cfg.parallel.microbatches,
        pipeline_schedule=cfg.parallel.schedule,
    )

    tokens_per_step = cfg.run.batch_size * cfg.model.seq_len
    ad.build_plan(jax.random.key(0), data.batch(0))
    # 6ND fwd+bwd; remat recomputes the forward -> 8ND of hardware FLOPs
    flops_mult = 8.0 / 6.0 if ad.plan.remat else 1.0
    metrics = MetricsLogger(
        cfg.run.metrics_path or None,
        items_name="tokens",
        flops_per_step=transformer_step_flops(mcfg.num_params(),
                                              tokens_per_step) * flops_mult,
        console_every=cfg.run.log_every,
    )
    ckpt = None
    if cfg.run.ckpt_dir:
        from torch_automatic_distributed_neural_network_tpu.training import (
            CheckpointManager,
        )

        ckpt = CheckpointManager(cfg.run.ckpt_dir)
    anomaly = None
    if cfg.run.anomaly_rollback:
        from torch_automatic_distributed_neural_network_tpu.training import (
            AnomalyConfig,
        )

        anomaly = AnomalyConfig()
    trainer = Trainer(
        ad,
        TrainerConfig(steps=cfg.run.steps, log_every=cfg.run.log_every,
                      ckpt_every=cfg.run.ckpt_every, anomaly=anomaly),
        metrics=metrics,
        ckpt=ckpt,
        items_per_step=tokens_per_step,
        run_config=cfglib.to_dict(cfg),
    )
    if cfg.run.max_restarts >= 0:
        from torch_automatic_distributed_neural_network_tpu.training import (
            RestartPolicy,
            run_with_recovery,
        )

        # step-indexed data + restore_or_init make fit() re-entrant: each
        # retry resumes from the newest intact checkpoint
        state = run_with_recovery(
            lambda: trainer.fit(data),
            policy=RestartPolicy(max_restarts=cfg.run.max_restarts,
                                 window_s=3600.0),
        )
    else:
        state = trainer.fit(data)  # step-indexed: resume replays batches
    print(f"plan: {ad.plan.strategy} mesh={tad.mesh_degrees(ad.plan.mesh)} "
          f"params={mcfg.num_params()/1e6:.0f}M final_step={int(state.step)}")


if __name__ == "__main__":
    main()
