"""Train a Vision Transformer on (synthetic) CIFAR-10-shaped data.

Extends the reference's CNN example set with the image-transformer
bridge (models/vit.py): patch-unfold + Dense onto the MXU, then the
same scanned encoder core every other family uses — so
dp/fsdp/tp/tp_fsdp all apply unchanged.

Usage::

    python examples/train_vit.py run.steps=100
    python examples/train_vit.py model.size=base model.image_size=224 \
        parallel.strategy=fsdp
    python examples/train_vit.py data.dir=/path/to/cifar-10-batches-py
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data import (
    classification_dataset,
    load_cifar10,
)
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticClassification,
)
from torch_automatic_distributed_neural_network_tpu.models import ViT
from torch_automatic_distributed_neural_network_tpu.training import (
    MetricsLogger,
    Trainer,
    TrainerConfig,
    softmax_xent_loss,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    size: str = "test"  # test | base | large (models/vit.py)
    image_size: int = 32
    patch_size: int = 8
    num_classes: int = 10


@dataclasses.dataclass(frozen=True)
class DataCfg:
    dir: str = ""  # cifar-10-batches-py dir; "" = synthetic teacher


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 50
    batch_size: int = 64
    lr: float = 3e-3
    log_every: int = 10
    metrics_path: str = ""


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "auto"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    data: DataCfg = DataCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    print(f"devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    model = ViT(cfg.model.size, image_size=cfg.model.image_size,
                patch_size=cfg.model.patch_size,
                num_classes=cfg.model.num_classes)
    shape = (cfg.model.image_size, cfg.model.image_size, 3)
    data = classification_dataset(
        cfg.data.dir, load_cifar10, cfg.run.batch_size,
        fallback=lambda: SyntheticClassification(
            image_shape=shape, num_classes=cfg.model.num_classes,
            batch_size=cfg.run.batch_size,
        ),
    )
    ad = tad.AutoDistribute(
        model,
        optimizer=optax.adamw(cfg.run.lr),
        loss_fn=softmax_xent_loss,
        strategy=cfg.parallel.strategy,
    )
    ad.build_plan(jax.random.key(0), data.batch(0))
    metrics = MetricsLogger(
        cfg.run.metrics_path or None,
        items_name="images",
        console_every=cfg.run.log_every,
    )
    trainer = Trainer(
        ad,
        TrainerConfig(steps=cfg.run.steps, log_every=cfg.run.log_every),
        metrics=metrics,
        items_per_step=cfg.run.batch_size,
        run_config=cfglib.to_dict(cfg),
    )
    state = trainer.fit(data)
    print(f"plan: {ad.plan.strategy} mesh={tad.mesh_degrees(ad.plan.mesh)} "
          f"params={model.cfg.num_params()/1e6:.1f}M "
          f"final_step={int(state.step)}")


if __name__ == "__main__":
    main()
