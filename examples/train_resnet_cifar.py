"""Train ResNet-50 on (synthetic) CIFAR-10, data-parallel.

The reference's second example config (BASELINE.json:8): "ResNet-50 /
CIFAR-10 data-parallel (DDP allreduce -> XLA allreduce)".  Headline metric:
images/sec/chip.

Usage::

    python examples/train_resnet_cifar.py run.steps=100 run.batch_size=256
    python examples/train_resnet_cifar.py model.arch=thin   # CPU-sim scale
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data import (
    classification_dataset,
    load_cifar10,
)
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticClassification,
)
from torch_automatic_distributed_neural_network_tpu.models import (
    ResNet18Thin,
    ResNet50,
)
from torch_automatic_distributed_neural_network_tpu.training import (
    MetricsLogger,
    Trainer,
    TrainerConfig,
    softmax_xent_loss_mutable,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    arch: str = "resnet50"  # resnet50 | thin


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 50
    batch_size: int = 128
    lr: float = 0.1
    log_every: int = 10
    metrics_path: str = ""
    # dir with cifar-10-batches-py pickles or x_train/y_train.npy;
    # synthetic fallback when empty/absent
    data_dir: str = ""


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "dp"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    print(f"devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    if cfg.model.arch == "thin":
        model = ResNet18Thin(num_classes=10)
        image_shape = (16, 16, 3)
    else:
        model = ResNet50(num_classes=10, small_inputs=True)
        image_shape = (32, 32, 3)
    data = classification_dataset(
        cfg.run.data_dir, load_cifar10, cfg.run.batch_size,
        fallback=lambda: SyntheticClassification(
            image_shape=image_shape, num_classes=10,
            batch_size=cfg.run.batch_size,
        ),
    )
    if not isinstance(data, SyntheticClassification) and (
        data.x.shape[1:] != image_shape
    ):
        raise SystemExit(
            f"loaded images {data.x.shape[1:]} do not match the model's "
            f"expected {image_shape} (arch={cfg.model.arch})"
        )
    ad = tad.AutoDistribute(
        model,
        optimizer=optax.sgd(cfg.run.lr, momentum=0.9),
        loss_fn=softmax_xent_loss_mutable,
        strategy=cfg.parallel.strategy,
    )
    metrics = MetricsLogger(
        cfg.run.metrics_path or None,
        items_name="images",
        console_every=cfg.run.log_every,
    )
    trainer = Trainer(
        ad,
        TrainerConfig(steps=cfg.run.steps, log_every=cfg.run.log_every),
        metrics=metrics,
        items_per_step=cfg.run.batch_size,
        run_config=cfglib.to_dict(cfg),
    )
    trainer.fit(iter(data))
    print(f"plan: {ad.plan.strategy} mesh={tad.mesh_degrees(ad.plan.mesh)}")


if __name__ == "__main__":
    main()
