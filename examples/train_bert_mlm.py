"""Pretrain a BERT encoder on masked-LM (synthetic stream by default).

Completes the transformer example set (SURVEY.md C12) with the
encoder-only family: bidirectional attention, post-norm, segment
embeddings, the HF-layout MLM head — all on the shared scanned core,
so every strategy (dp/fsdp/tp/tp_fsdp) works unchanged.

Usage::

    python examples/train_bert_mlm.py run.steps=100
    python examples/train_bert_mlm.py model.size=base parallel.strategy=fsdp
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticMLM,
)
from torch_automatic_distributed_neural_network_tpu.models import Bert
from torch_automatic_distributed_neural_network_tpu.training import (
    MetricsLogger,
    Trainer,
    TrainerConfig,
    masked_lm_loss,
    transformer_step_flops,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    size: str = "test"  # test | base | large (models/bert.py)
    seq_len: int = 128
    vocab_size: int = 30522


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 50
    batch_size: int = 8
    lr: float = 1e-4
    log_every: int = 10
    metrics_path: str = ""


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "auto"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    print(f"devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    model = Bert(cfg.model.size, vocab_size=cfg.model.vocab_size,
                 max_seq_len=cfg.model.seq_len)
    mcfg = model.cfg  # ONE config: reported params/MFU = trained model
    data = SyntheticMLM(
        vocab_size=cfg.model.vocab_size, seq_len=cfg.model.seq_len,
        batch_size=cfg.run.batch_size,
    )
    ad = tad.AutoDistribute(
        model,
        optimizer=optax.adamw(cfg.run.lr),
        loss_fn=masked_lm_loss,
        strategy=cfg.parallel.strategy,
    )
    tokens_per_step = cfg.run.batch_size * cfg.model.seq_len
    ad.build_plan(jax.random.key(0), data.batch(0))
    metrics = MetricsLogger(
        cfg.run.metrics_path or None,
        items_name="tokens",
        flops_per_step=transformer_step_flops(
            mcfg.num_params(), tokens_per_step),
        console_every=cfg.run.log_every,
    )
    trainer = Trainer(
        ad,
        TrainerConfig(steps=cfg.run.steps, log_every=cfg.run.log_every),
        metrics=metrics,
        items_per_step=tokens_per_step,
        run_config=cfglib.to_dict(cfg),
    )
    state = trainer.fit(data)
    print(f"plan: {ad.plan.strategy} mesh={tad.mesh_degrees(ad.plan.mesh)} "
          f"params={mcfg.num_params()/1e6:.1f}M final_step={int(state.step)}")


if __name__ == "__main__":
    main()
