"""Migrate from the torch reference in one script: HF/torch weights in,
TPU-sharded finetuning + generation out.

The workflow a reference user follows to switch (README "Migrating from
torch"): build or load a transformers model (any GPT-2/Llama/Mixtral
checkpoint; this example constructs one offline so it runs with zero
network), import its weights into this framework's parameter tree, keep
the torch Dataset too (data/torch_adapter.py), and hand both to
``AutoDistribute``.

Two sources:

- ``model.source=hf`` (default): a transformers checkpoint via
  ``import_hf_gpt2`` — the curated-architecture path.
- ``model.source=torch``: a HAND-WRITTEN ``torch.nn.Module`` (defined
  below, attention and all) converted by ``models.from_torch`` — the
  reference's "AutoDistribute(model) runs an unmodified nn.Module"
  promise (BASELINE.json:5), with no HF involvement.

Run (CPU sim)::

    env -u PYTHONPATH JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/finetune_from_torch.py run.steps=30
    # or the hand-written torch model:
    ... examples/finetune_from_torch.py model.source=torch run.steps=30

With a real checkpoint directory::

    python examples/finetune_from_torch.py model.path=/path/to/hf_gpt2
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

from torch_automatic_distributed_neural_network_tpu import AutoDistribute
from torch_automatic_distributed_neural_network_tpu.data import (
    TorchDatasetAdapter,
)
from torch_automatic_distributed_neural_network_tpu.models import (
    import_hf_gpt2,
)
from torch_automatic_distributed_neural_network_tpu.training import (
    Trainer,
    TrainerConfig,
    next_token_loss,
    next_token_loss_mutable,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    source: str = "hf"  # 'hf' | 'torch' (hand-written nn.Module below)
    path: str = ""  # HF checkpoint dir; "" = build a small random one
    seq_len: int = 64


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 30
    batch_size: int = 16
    lr: float = 1e-4
    log_every: int = 10


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "auto"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


class TokenDataset:
    """A torch-style map Dataset of token windows (stands in for the
    user's own torch.utils.data pipeline)."""

    def __init__(self, vocab: int, seq_len: int, n: int = 2048):
        rng = np.random.RandomState(0)
        first = rng.randint(0, vocab, (n, 1))
        steps = rng.randint(0, 7, (n, seq_len))
        self._tok = (np.concatenate(
            [first, np.cumsum(steps, -1) + first], -1
        ) % vocab).astype(np.int32)

    def __len__(self):
        return len(self._tok)

    def __getitem__(self, i):
        return {"tokens": self._tok[i]}


def build_handwritten_torch_lm(vocab: int, seq: int):
    """An ordinary from-scratch torch LM — nothing framework-specific.
    ``from_torch`` traces it (attention, mask buffer, weight plumbing)
    and converts the weights; this is the path a user with their own
    torch codebase takes."""
    import torch
    import torch.nn as tnn

    class HandWrittenLM(tnn.Module):
        def __init__(self, d=128, heads=4):
            super().__init__()
            self.emb = tnn.Embedding(vocab, d)
            self.pos = tnn.Parameter(torch.randn(1, seq, d) * 0.02)
            self.ln1 = tnn.LayerNorm(d)
            self.qkv = tnn.Linear(d, 3 * d)
            self.proj = tnn.Linear(d, d)
            self.ln2 = tnn.LayerNorm(d)
            self.mlp_up = tnn.Linear(d, 4 * d)
            self.mlp_down = tnn.Linear(4 * d, d)
            self.ln_f = tnn.LayerNorm(d)
            self.head = tnn.Linear(d, vocab, bias=False)
            self.heads = heads
            self.register_buffer(
                "mask", torch.tril(torch.ones(seq, seq)))

        def forward(self, idx):
            b, t = idx.size(0), idx.size(1)
            x = self.emb(idx) + self.pos[:, :t]
            h = self.ln1(x)
            q, k, v = self.qkv(h).chunk(3, dim=-1)
            hd = q.size(-1) // self.heads
            q = q.view(b, t, self.heads, hd).transpose(1, 2)
            k = k.view(b, t, self.heads, hd).transpose(1, 2)
            v = v.view(b, t, self.heads, hd).transpose(1, 2)
            att = torch.matmul(q, k.transpose(-2, -1)) / (hd ** 0.5)
            att = att.masked_fill(self.mask[:t, :t] == 0, float("-inf"))
            att = torch.softmax(att, dim=-1)
            o = torch.matmul(att, v).transpose(1, 2).contiguous()
            x = x + self.proj(o.view(b, t, -1))
            h = self.ln2(x)
            x = x + self.mlp_down(torch.nn.functional.gelu(self.mlp_up(h)))
            return self.head(self.ln_f(x))

    torch.manual_seed(0)
    return HandWrittenLM()


def main() -> None:
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))

    if cfg.model.source == "torch":
        from torch_automatic_distributed_neural_network_tpu.models import (
            from_torch,
        )

        net = build_handwritten_torch_lm(512, cfg.model.seq_len)
        model, variables = from_torch(net)
        n_params = sum(p.numel() for p in net.parameters())
        print(f"bridged hand-written torch LM: {n_params/1e6:.1f}M params")
    else:
        import transformers

        if cfg.model.path:
            hf = transformers.GPT2LMHeadModel.from_pretrained(cfg.model.path)
        else:
            # offline stand-in for a real checkpoint
            hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
                vocab_size=512, n_positions=cfg.model.seq_len,
                n_embd=128, n_layer=4, n_head=2,
            ))
        model, variables = import_hf_gpt2(hf)
        print(f"imported: {model.cfg.n_layers}L d={model.cfg.d_model} "
              f"vocab={model.cfg.vocab_size}")

    bridged = cfg.model.source == "torch"
    data = TorchDatasetAdapter(
        TokenDataset(512 if bridged else model.cfg.vocab_size,
                     cfg.model.seq_len),
        batch_size=cfg.run.batch_size,
    )
    ad = AutoDistribute(
        model,
        optimizer=optax.adamw(cfg.run.lr),
        loss_fn=next_token_loss_mutable if bridged else next_token_loss,
        strategy=cfg.parallel.strategy,
        init_fn=lambda rng, batch: variables,  # imported weights
    )
    trainer = Trainer(
        ad, TrainerConfig(steps=cfg.run.steps,
                          log_every=cfg.run.log_every),
    )
    state = trainer.fit(data)
    print(f"plan: {ad.plan.strategy} "
          f"mesh={dict(zip(ad.plan.mesh.axis_names, ad.plan.mesh.devices.shape))} "
          f"final_step={int(state.step)}")

    if bridged:
        # greedy sampling needs the framework's decode cache — the
        # bridged graph is a straight re-execution of the torch forward,
        # so sample by full-context argmax instead
        import jax.numpy as jnp

        toks = np.asarray(data.batch(0)["tokens"][:1, :8])
        for _ in range(8):
            logits = model.apply(
                {"params": state.params, **state.model_state},
                jnp.asarray(toks))
            nxt = np.asarray(logits)[:, -1].argmax(-1)[:, None]
            toks = np.concatenate([toks, nxt], axis=1)
        print("generated ids:", toks[0].tolist())
    else:
        # greedy sample from the finetuned weights
        prompt = data.batch(0)["tokens"][:1, :8]
        out = ad.generate(state, prompt, max_new_tokens=16)
        print("generated ids:", np.asarray(out)[0].tolist())


if __name__ == "__main__":
    main()
