"""Migrate from the torch reference in one script: HF/torch weights in,
TPU-sharded finetuning + generation out.

The workflow a reference user follows to switch (README "Migrating from
torch"): build or load a transformers model (any GPT-2/Llama/Mixtral
checkpoint; this example constructs one offline so it runs with zero
network), import its weights into this framework's parameter tree, keep
the torch Dataset too (data/torch_adapter.py), and hand both to
``AutoDistribute``.

Run (CPU sim)::

    env -u PYTHONPATH JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/finetune_from_torch.py run.steps=30

With a real checkpoint directory::

    python examples/finetune_from_torch.py model.path=/path/to/hf_gpt2
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

from torch_automatic_distributed_neural_network_tpu import AutoDistribute
from torch_automatic_distributed_neural_network_tpu.data import (
    TorchDatasetAdapter,
)
from torch_automatic_distributed_neural_network_tpu.models import (
    import_hf_gpt2,
)
from torch_automatic_distributed_neural_network_tpu.training import (
    Trainer,
    TrainerConfig,
    next_token_loss,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    path: str = ""  # HF checkpoint dir; "" = build a small random one
    seq_len: int = 64


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 30
    batch_size: int = 16
    lr: float = 1e-4
    log_every: int = 10


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "auto"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


class TokenDataset:
    """A torch-style map Dataset of token windows (stands in for the
    user's own torch.utils.data pipeline)."""

    def __init__(self, vocab: int, seq_len: int, n: int = 2048):
        rng = np.random.RandomState(0)
        first = rng.randint(0, vocab, (n, 1))
        steps = rng.randint(0, 7, (n, seq_len))
        self._tok = (np.concatenate(
            [first, np.cumsum(steps, -1) + first], -1
        ) % vocab).astype(np.int32)

    def __len__(self):
        return len(self._tok)

    def __getitem__(self, i):
        return {"tokens": self._tok[i]}


def main() -> None:
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))

    import transformers

    if cfg.model.path:
        hf = transformers.GPT2LMHeadModel.from_pretrained(cfg.model.path)
    else:
        # offline stand-in for a real checkpoint
        hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
            vocab_size=512, n_positions=cfg.model.seq_len,
            n_embd=128, n_layer=4, n_head=2,
        ))
    model, variables = import_hf_gpt2(hf)
    print(f"imported: {model.cfg.n_layers}L d={model.cfg.d_model} "
          f"vocab={model.cfg.vocab_size}")

    data = TorchDatasetAdapter(
        TokenDataset(model.cfg.vocab_size, cfg.model.seq_len),
        batch_size=cfg.run.batch_size,
    )
    ad = AutoDistribute(
        model,
        optimizer=optax.adamw(cfg.run.lr),
        loss_fn=next_token_loss,
        strategy=cfg.parallel.strategy,
        init_fn=lambda rng, batch: variables,  # imported weights
    )
    trainer = Trainer(
        ad, TrainerConfig(steps=cfg.run.steps,
                          log_every=cfg.run.log_every),
    )
    state = trainer.fit(data)
    print(f"plan: {ad.plan.strategy} "
          f"mesh={dict(zip(ad.plan.mesh.axis_names, ad.plan.mesh.devices.shape))} "
          f"final_step={int(state.step)}")

    # greedy sample from the finetuned weights
    prompt = data.batch(0)["tokens"][:1, :8]
    out = ad.generate(state, prompt, max_new_tokens=16)
    print("generated ids:", np.asarray(out)[0].tolist())


if __name__ == "__main__":
    main()
