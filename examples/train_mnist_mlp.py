"""Train a 3-layer MLP on (synthetic) MNIST with AutoDistribute.

The reference's first example config (BASELINE.json:7): single-process
no-op on 1 device, DP on many.  Run::

    python examples/train_mnist_mlp.py --steps 50 --strategy auto

On a single chip this exercises the AutoDistribute no-op path; on an
8-device CPU sim (JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8) it runs 8-way DP.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data import (
    classification_dataset,
    load_mnist,
)
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticClassification,
)
from torch_automatic_distributed_neural_network_tpu.models import MLP
from torch_automatic_distributed_neural_network_tpu.training import (
    softmax_xent_loss,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--strategy", default="auto",
                   choices=["auto", "tuned", "dp", "fsdp", "tp", "tp_fsdp"])
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--data-dir", default="",
                   help="dir with MNIST idx files or x_train/y_train.npy; "
                        "falls back to synthetic when empty/absent")
    p.add_argument("--out-dir", default="",
                   help="write run artifacts (journal.jsonl + "
                        "metrics.jsonl) here and train via Trainer; "
                        "inspect afterwards with `tadnn report <dir>`")
    args = p.parse_args()

    print(f"devices: {jax.device_count()} x {jax.devices()[0].device_kind}")
    data = classification_dataset(
        args.data_dir, load_mnist, args.batch_size,
        fallback=lambda: SyntheticClassification(batch_size=args.batch_size),
    )
    ad = tad.AutoDistribute(
        MLP(features=(512, 256, 10)),
        optimizer=optax.sgd(args.lr),
        loss_fn=softmax_xent_loss,
        strategy=args.strategy,
    )
    if args.out_dir:
        return run_observed(args, data, ad)
    state = ad.init(jax.random.key(0), data.batch(0))
    print(f"plan: strategy={ad.plan.strategy} "
          f"mesh={tad.mesh_degrees(ad.plan.mesh)}")

    t0 = time.perf_counter()
    for i in range(args.steps):
        state, metrics = ad.step(state, data.batch(i))
        if i % args.log_every == 0 or i == args.steps - 1:
            print(
                f"step {i:4d}  loss {float(metrics['loss']):.4f}  "
                f"acc {float(metrics['accuracy']):.3f}"
            )
    dt = time.perf_counter() - t0
    imgs = args.steps * args.batch_size
    print(f"{imgs / dt:.0f} images/sec total "
          f"({imgs / dt / jax.device_count():.0f} /chip incl. compile)")


def run_observed(args, data, ad):
    """--out-dir path: same training via Trainer, leaving journal.jsonl +
    metrics.jsonl behind for `tadnn report`."""
    from torch_automatic_distributed_neural_network_tpu.obs import Journal
    from torch_automatic_distributed_neural_network_tpu.training import (
        MetricsLogger,
        Trainer,
        TrainerConfig,
    )

    os.makedirs(args.out_dir, exist_ok=True)
    journal = Journal(os.path.join(args.out_dir, "journal.jsonl"))
    metrics = MetricsLogger(os.path.join(args.out_dir, "metrics.jsonl"),
                            items_name="images")
    trainer = Trainer(
        ad,
        TrainerConfig(steps=args.steps, log_every=args.log_every),
        metrics=metrics,
        items_per_step=args.batch_size,
        journal=journal,
    )
    trainer.fit(data)
    journal.close()
    gp = trainer.goodput or {}
    if gp.get("fractions"):
        print("goodput: " + "  ".join(
            f"{k} {v:.1%}" for k, v in gp["fractions"].items()))
    print(f"artifacts in {args.out_dir} — summarize with: "
          f"python -m torch_automatic_distributed_neural_network_tpu "
          f"report {args.out_dir}")


if __name__ == "__main__":
    main()
