"""Train a Llama-style decoder with FSDP auto-shard + gradient checkpointing.

The reference's fifth example config (BASELINE.json:11): "Llama-3-8B
FSDP-style auto-shard + grad checkpoint on v5p-64".  The planner's fsdp
strategy shards every param over the fsdp axis (ZeRO-3), optimizer state
inherits the shards, and remat is on by default.

Usage::

    python examples/train_llama_fsdp.py model.size=1b run.steps=20
    python examples/train_llama_fsdp.py model.size=test   # CPU-sim scale
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data.synthetic import SyntheticLM
from torch_automatic_distributed_neural_network_tpu.models import Llama, llama_config
from torch_automatic_distributed_neural_network_tpu.training import (
    MetricsLogger,
    Trainer,
    TrainerConfig,
    next_token_loss,
    transformer_step_flops,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    size: str = "8b"
    seq_len: int = 2048


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 20
    batch_size: int = 8  # divides the 8-device CPU sim and any 2^k slice
    lr: float = 3e-4
    log_every: int = 5
    metrics_path: str = ""
    ckpt_dir: str = ""
    ckpt_every: int = 0


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "fsdp"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    print(f"devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    mcfg = llama_config(cfg.model.size, max_seq_len=cfg.model.seq_len)
    data = SyntheticLM(
        vocab_size=mcfg.vocab_size, seq_len=cfg.model.seq_len + 1,
        batch_size=cfg.run.batch_size,
    )
    ad = tad.AutoDistribute(
        Llama(cfg.model.size, max_seq_len=cfg.model.seq_len),
        optimizer=optax.adamw(cfg.run.lr),
        loss_fn=next_token_loss,
        strategy=cfg.parallel.strategy,
    )
    tokens_per_step = cfg.run.batch_size * cfg.model.seq_len
    ad.build_plan(jax.random.key(0), data.batch(0))
    flops_mult = 8.0 / 6.0 if ad.plan.remat else 1.0
    metrics = MetricsLogger(
        cfg.run.metrics_path or None,
        items_name="tokens",
        flops_per_step=transformer_step_flops(
            mcfg.num_params(), tokens_per_step) * flops_mult,
        console_every=cfg.run.log_every,
    )
    ckpt = None
    if cfg.run.ckpt_dir:
        from torch_automatic_distributed_neural_network_tpu.training import (
            CheckpointManager,
        )

        ckpt = CheckpointManager(cfg.run.ckpt_dir)
    trainer = Trainer(
        ad,
        TrainerConfig(steps=cfg.run.steps, log_every=cfg.run.log_every,
                      ckpt_every=cfg.run.ckpt_every),
        metrics=metrics,
        ckpt=ckpt,
        items_per_step=tokens_per_step,
        run_config=cfglib.to_dict(cfg),
    )
    trainer.fit(iter(data))
    print(f"plan: {ad.plan.strategy} mesh={tad.mesh_degrees(ad.plan.mesh)} "
          f"params={mcfg.num_params()/1e9:.2f}B")


if __name__ == "__main__":
    main()
