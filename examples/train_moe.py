"""Train a Mixtral-style MoE LM with automatic expert parallelism.

EP is brief-mandated (SURVEY.md §2.2 — no reference config exercises it;
the reference zoo is dense, BASELINE.json:7-11).  The planner detects the
expert banks and puts the expert dim on its own mesh axis; GSPMD emits
the dispatch/combine all_to_all over ICI.

Usage::

    python examples/train_moe.py model.size=nano run.steps=100
    python examples/train_moe.py parallel.strategy=ep_fsdp
"""

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data.synthetic import SyntheticLM
from torch_automatic_distributed_neural_network_tpu.models import MoE, moe_config
from torch_automatic_distributed_neural_network_tpu.training import (
    MetricsLogger,
    moe_next_token_loss,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    size: str = "nano"
    seq_len: int = 512
    vocab_size: int = 32000


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 50
    batch_size: int = 8
    lr: float = 3e-4
    log_every: int = 10
    metrics_path: str = ""


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "auto"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    print(f"devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    mcfg = moe_config(cfg.model.size, vocab_size=cfg.model.vocab_size,
                      max_seq_len=cfg.model.seq_len)
    print(f"MoE {cfg.model.size}: {mcfg.num_params()/1e6:.0f}M total / "
          f"{mcfg.active_params()/1e6:.0f}M active params, "
          f"{mcfg.n_experts} experts top-{mcfg.top_k}")
    data = SyntheticLM(vocab_size=mcfg.vocab_size,
                       seq_len=cfg.model.seq_len + 1,
                       batch_size=cfg.run.batch_size)
    ad = tad.AutoDistribute(
        MoE(cfg.model.size, vocab_size=cfg.model.vocab_size,
            max_seq_len=cfg.model.seq_len),
        optimizer=optax.adamw(cfg.run.lr),
        loss_fn=moe_next_token_loss,
        strategy=cfg.parallel.strategy,
    )
    state = ad.init(jax.random.key(0), data.batch(0))
    print(f"plan: {ad.plan.strategy} mesh={tad.mesh_degrees(ad.plan.mesh)}")

    logger = MetricsLogger(
        cfg.run.metrics_path or None, items_name="tokens",
        console_every=cfg.run.log_every,
    )
    tokens_per_step = cfg.run.batch_size * cfg.model.seq_len
    for i in range(cfg.run.steps):
        logger.start_step()
        state, m = ad.step(state, data.batch(i))
        logger.log_step(i + 1, m, tokens_per_step)
    logger.close()


if __name__ == "__main__":
    main()
