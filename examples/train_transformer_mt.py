"""Train a transformer-base MT model (synthetic WMT14-shaped data).

The reference's third example config (BASELINE.json:9): "Transformer-base
MT / WMT14 en-de (bucketed DDP path)".  On TPU the bucketed-allreduce
overlap is XLA's latency-hiding scheduler's job — this config is plain DP
and the collectives microbench (bench.py --collectives) quantifies overlap.

Usage::

    python examples/train_transformer_mt.py run.steps=50
    python examples/train_transformer_mt.py model.size=test   # CPU-sim scale
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data import (
    ArraySeq2Seq,
    load_seq2seq,
)
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticSeq2Seq,
)
from torch_automatic_distributed_neural_network_tpu.models import TransformerMT
from torch_automatic_distributed_neural_network_tpu.training import (
    MetricsLogger,
    Trainer,
    TrainerConfig,
    seq2seq_loss,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    size: str = "base"
    src_len: int = 64
    tgt_len: int = 64
    vocab_size: int = 32000


@dataclasses.dataclass(frozen=True)
class RunCfg:
    steps: int = 50
    batch_size: int = 64
    lr: float = 1e-3
    log_every: int = 10
    metrics_path: str = ""
    # dir with src[_train].npy / tgt[_train].npy token ids;
    # synthetic WMT14-shaped fallback when empty/absent
    data_dir: str = ""


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "dp"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    print(f"devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    vocab = 512 if cfg.model.size == "test" else cfg.model.vocab_size
    model = TransformerMT(cfg.model.size, vocab_size=vocab,
                          max_seq_len=max(cfg.model.src_len, cfg.model.tgt_len))
    loaded = load_seq2seq(cfg.run.data_dir) if cfg.run.data_dir else None
    if loaded is not None:
        src, tgt = loaded
        print(f"data: {len(src)} pairs from {cfg.run.data_dir}")
        data = ArraySeq2Seq(src, tgt, cfg.run.batch_size)
    else:
        if cfg.run.data_dir:
            print(f"data: nothing loadable in {cfg.run.data_dir!r}; "
                  "using synthetic")
        data = SyntheticSeq2Seq(
            vocab_size=vocab, src_len=cfg.model.src_len,
            tgt_len=cfg.model.tgt_len, batch_size=cfg.run.batch_size,
        )
    ad = tad.AutoDistribute(
        model,
        optimizer=optax.adam(cfg.run.lr),
        loss_fn=seq2seq_loss,
        strategy=cfg.parallel.strategy,
    )
    metrics = MetricsLogger(
        cfg.run.metrics_path or None,
        items_name="tokens",
        console_every=cfg.run.log_every,
    )
    trainer = Trainer(
        ad,
        TrainerConfig(steps=cfg.run.steps, log_every=cfg.run.log_every),
        metrics=metrics,
        items_per_step=cfg.run.batch_size * cfg.model.tgt_len,
        run_config=cfglib.to_dict(cfg),
    )
    trainer.fit(iter(data))
    print(f"plan: {ad.plan.strategy} mesh={tad.mesh_degrees(ad.plan.mesh)}")


if __name__ == "__main__":
    main()
