"""LoRA fine-tuning: adapt a frozen checkpoint with low-rank factors.

The migration workflow this demos: bring weights (import_hf_* /
from_torch / a checkpoint), freeze them, train rank-r adapters on the
attention/MLP kernels — optimizer state exists ONLY for the adapters
(the Adam m+v for the base never allocates), and `merge_lora` folds the
result back into plain weights for export or full-speed serving.

Without a checkpoint handy, the script stands one up by briefly
pretraining on the synthetic stream, then LoRA-continues from it.

Usage::

    python examples/finetune_lora.py run.steps=50 lora.rank=16
    python examples/finetune_lora.py parallel.strategy=fsdp
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import optax

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticLM,
)
from torch_automatic_distributed_neural_network_tpu.models import GPT2
from torch_automatic_distributed_neural_network_tpu.training import (
    LoraSpec,
    LoraTarget,
    lora_init_fn,
    lora_loss,
    lora_optimizer,
    merge_lora,
    next_token_loss,
)
from torch_automatic_distributed_neural_network_tpu.utils import config as cfglib


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    size: str = "test"
    seq_len: int = 64
    vocab_size: int = 512


@dataclasses.dataclass(frozen=True)
class LoraCfg:
    rank: int = 16
    alpha: float = 32.0


@dataclasses.dataclass(frozen=True)
class RunCfg:
    pretrain_steps: int = 30  # stand-in for "load a checkpoint"
    steps: int = 40
    batch_size: int = 16
    lr: float = 3e-3
    log_every: int = 10


@dataclasses.dataclass(frozen=True)
class ParallelCfg:
    strategy: str = "auto"


@dataclasses.dataclass(frozen=True)
class Cfg:
    model: ModelCfg = ModelCfg()
    lora: LoraCfg = LoraCfg()
    run: RunCfg = RunCfg()
    parallel: ParallelCfg = ParallelCfg()


def main():
    cfg: Cfg = cfglib.apply_overrides(Cfg(), sys.argv[1:])
    print(cfglib.to_json(cfg))
    model = GPT2(cfg.model.size, vocab_size=cfg.model.vocab_size,
                 max_seq_len=cfg.model.seq_len)
    data = SyntheticLM(vocab_size=cfg.model.vocab_size,
                       seq_len=cfg.model.seq_len + 1,
                       batch_size=cfg.run.batch_size)

    # "the checkpoint": a briefly full-trained base
    ad0 = tad.AutoDistribute(model, optimizer=optax.adamw(cfg.run.lr),
                             loss_fn=next_token_loss, strategy="dp")
    state = ad0.init(jax.random.key(0), data.batch(0))
    for i in range(cfg.run.pretrain_steps):
        state, m = ad0.step(state, data.batch(i))
    print(f"base checkpoint ready: loss {float(m['loss']):.4f}")
    base = jax.device_get(state.params)

    spec = LoraSpec(rank=cfg.lora.rank, alpha=cfg.lora.alpha,
                    targets=(LoraTarget(r"q_proj/kernel", 1, 2),
                             LoraTarget(r"v_proj/kernel", 1, 2),
                             LoraTarget(r"up_proj/kernel", 1, 1)))
    ad = tad.AutoDistribute(
        model,
        optimizer=lora_optimizer(optax.adamw(cfg.run.lr)),
        loss_fn=lora_loss(next_token_loss, spec),
        init_fn=lora_init_fn(base, spec),
        strategy=cfg.parallel.strategy,
    )
    st = ad.init(jax.random.key(2), data.batch(0))
    n_base = sum(x.size for x in jax.tree.leaves(st.params["base"]))
    n_lora = sum(x.size for x in jax.tree.leaves(st.params["lora"]))
    n_opt = sum(x.size for x in jax.tree.leaves(st.opt_state)
                if hasattr(x, "size"))
    print(f"base {n_base:,} params (frozen)  adapters {n_lora:,} "
          f"({100 * n_lora / n_base:.2f}%)  opt state {n_opt:,} leaves "
          "(adapters only)")
    start = cfg.run.pretrain_steps
    for i in range(start, start + cfg.run.steps):
        st, m = ad.step(st, data.batch(i))
        if (i - start) % cfg.run.log_every == 0:
            print(f"step {i - start:4d}  loss {float(m['loss']):.4f}")
    print(f"final loss {float(m['loss']):.4f}  "
          f"plan={ad.plan.strategy} mesh={tad.mesh_degrees(ad.plan.mesh)}")
    merged = merge_lora(st.params["base"], st.params["lora"], spec)
    del merged  # ready for export_hf_* / full-speed serving
    print("adapters merged back into plain weights (export-ready)")


if __name__ == "__main__":
    main()
