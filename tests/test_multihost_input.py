"""Multi-host input assembly (SURVEY.md C13; VERDICT r1 missing #4).

On a real multi-host slice each host holds only its row-slice of the
global batch; ``AutoDistribute.shard_batch``/``step`` assemble global
arrays via ``jax.make_array_from_process_local_data``.  A single process
cannot run a real multi-host world, so these tests pin (1) the slice
partition (every host's rows concatenate back to the global batch in
order), (2) the assembly dispatch with a mocked process world, (3) the
1-host identity path.
"""

import jax
import numpy as np
import optax
import pytest

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu import core as core_mod
from torch_automatic_distributed_neural_network_tpu.data import shard_for_host
from torch_automatic_distributed_neural_network_tpu.data.synthetic import SyntheticLM
def test_host_slices_partition_the_global_batch():
    global_batch = {"input_ids": np.arange(32 * 5).reshape(32, 5)}
    for pc in (1, 2, 4, 8):
        slices = [
            shard_for_host(global_batch, process_index=pi, process_count=pc)
            for pi in range(pc)
        ]
        rebuilt = np.concatenate([s["input_ids"] for s in slices], axis=0)
        np.testing.assert_array_equal(rebuilt, global_batch["input_ids"])


def test_indivisible_batch_rejected():
    with pytest.raises(ValueError, match="not divisible"):
        shard_for_host({"x": np.zeros((10, 3))}, process_index=0,
                       process_count=4)


def test_one_host_shard_batch_is_device_put(devices8):
    from torch_automatic_distributed_neural_network_tpu.models import GPT2
    from torch_automatic_distributed_neural_network_tpu.training import (
        next_token_loss,
    )

    data = SyntheticLM(vocab_size=64, seq_len=9, batch_size=8)
    ad = tad.AutoDistribute(
        GPT2("test", vocab_size=64, max_seq_len=8),
        optimizer=optax.sgd(1e-2), loss_fn=next_token_loss, strategy="dp",
    )
    ad.init(jax.random.key(0), data.batch(0))
    out = ad.shard_batch(data.batch(0))
    leaf = out["input_ids"]
    assert isinstance(leaf, jax.Array)
    assert leaf.sharding == ad.plan.batch_sharding()
    np.testing.assert_array_equal(np.asarray(leaf), data.batch(0)["input_ids"])
    # idempotent: an already-sharded leaf passes through by identity
    again = ad.shard_batch(out)
    assert again["input_ids"] is leaf


def test_multihost_assembly_dispatch(devices8, monkeypatch):
    """With a mocked 4-process world, shard_batch must route every numpy
    leaf through make_array_from_process_local_data with the plan's batch
    sharding and this host's slice."""
    from torch_automatic_distributed_neural_network_tpu.models import GPT2
    from torch_automatic_distributed_neural_network_tpu.training import (
        next_token_loss,
    )

    data = SyntheticLM(vocab_size=64, seq_len=9, batch_size=8)
    ad = tad.AutoDistribute(
        GPT2("test", vocab_size=64, max_seq_len=8),
        optimizer=optax.sgd(1e-2), loss_fn=next_token_loss, strategy="dp",
    )
    ad.init(jax.random.key(0), data.batch(0))

    global_batch = data.batch(1)
    local = shard_for_host(global_batch, process_index=2, process_count=4)
    calls = []

    def fake_assemble(sharding, local_data, **kw):
        calls.append((sharding, np.asarray(local_data)))
        return jax.device_put(local_data)  # stand-in global array

    monkeypatch.setattr(core_mod.jax, "process_count", lambda: 4)
    monkeypatch.setattr(
        core_mod.jax, "make_array_from_process_local_data", fake_assemble
    )
    ad.shard_batch(local)
    assert len(calls) == 1
    sharding, local_data = calls[0]
    assert sharding == ad.plan.batch_sharding()
    np.testing.assert_array_equal(local_data, local["input_ids"])
    assert local_data.shape[0] == global_batch["input_ids"].shape[0] // 4
