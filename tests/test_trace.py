"""Runtime tracing tests (obs/trace, obs/aggregate + journal hardening
and the bench freshness guard): profiler capture + attribution on a real
CPU-sim step, measured-vs-modeled collective bytes, multihost journal
merge with seeded skew, report rendering, `tadnn report --check` exit
codes, journal rotation and the torn-line reader."""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu import (
    cli,
    topology,
    tune,
)
from torch_automatic_distributed_neural_network_tpu.models import MLP
from torch_automatic_distributed_neural_network_tpu.obs import (
    Journal,
    aggregate,
)
from torch_automatic_distributed_neural_network_tpu.obs import (
    comms as obs_comms,
)
from torch_automatic_distributed_neural_network_tpu.obs import (
    journal as obs_journal,
)
from torch_automatic_distributed_neural_network_tpu.obs import (
    report as obs_report,
)
from torch_automatic_distributed_neural_network_tpu.obs import (
    trace as obs_trace,
)
from torch_automatic_distributed_neural_network_tpu.training import (
    softmax_xent_loss,
)


def toy_batch(seed=0, batch=16, dim=8, classes=10):
    rng = np.random.RandomState(seed)
    return {
        "x": jnp.asarray(rng.randn(batch, dim), jnp.float32),
        "label": jnp.asarray(rng.randint(0, classes, size=(batch,))),
    }


def make_ad(strategy="dp", **kw):
    return tad.AutoDistribute(
        MLP(features=(32, 16, 10)),
        optimizer=optax.sgd(0.1),
        loss_fn=softmax_xent_loss,
        strategy=strategy,
        **kw,
    )


# ------------------------------------------------- pure interval math


def test_union_merges_overlaps():
    u = obs_trace._union([(0, 10), (5, 15), (20, 30), (30, 31)])
    assert u == [(0, 15), (20, 31)]
    assert obs_trace._total(u) == 26


def test_overlap_of_unions():
    a = obs_trace._union([(0, 10), (20, 30)])
    b = obs_trace._union([(5, 25)])
    assert obs_trace._overlap(a, b) == 5 + 5


def test_attribute_synthetic_exposed_math():
    # window [0, 100)us; compute [0, 60); collective [40, 80):
    # collective 40us, 20 hidden behind compute, 20 exposed
    parsed = {
        "steps": [{"step": 7, "ts": 0, "dur": 100}],
        "ops": [
            {"name": "fusion.1", "ts": 0, "dur": 60, "tid": 1},
            {"name": "all-reduce-start.2", "ts": 40, "dur": 40, "tid": 2},
        ],
    }
    (rec,) = obs_trace.attribute(parsed)
    assert rec["step"] == 7
    assert rec["wall_s"] == pytest.approx(100e-6)
    assert rec["compute_s"] == pytest.approx(60e-6)
    assert rec["collective_s"] == pytest.approx(40e-6)
    assert rec["exposed_collective_s"] == pytest.approx(20e-6)
    assert rec["collectives"] == {"all-reduce": pytest.approx(40e-6)}


def test_attribute_clips_ops_to_window():
    parsed = {
        "steps": [{"step": 0, "ts": 50, "dur": 50}],
        "ops": [{"name": "all-gather.9", "ts": 0, "dur": 80, "tid": 1}],
    }
    (rec,) = obs_trace.attribute(parsed)
    # only the [50, 80) slice of the op lands inside the step
    assert rec["collective_s"] == pytest.approx(30e-6)
    assert rec["collective_s"] <= rec["wall_s"]


def test_exposed_fraction_bounds_and_none():
    assert obs_trace.exposed_fraction([]) is None
    assert obs_trace.exposed_fraction(
        [{"collective_s": 0.0, "exposed_collective_s": 0.0}]) is None
    f = obs_trace.exposed_fraction(
        [{"collective_s": 1.0, "exposed_collective_s": 0.25}])
    assert f == pytest.approx(0.25)


# ------------------------------------------- HLO collective byte parse


def test_hlo_collective_bytes_parses_definitions():
    text = """
  %all-reduce.3 = f32[1024,256]{1,0} all-reduce(f32[1024,256]{1,0} %add.5), replica_groups={}
  %ag.1 = bf16[8,4]{1,0} all-gather-start(bf16[1,4]{1,0} %p), dimensions={0}
  %done.2 = f32[1024,256]{1,0} all-reduce-done(f32[1024,256]{1,0} %all-reduce.3)
  %fusion.7 = f32[512]{0} fusion(f32[512]{0} %x), kind=kLoop
"""
    out = obs_trace.hlo_collective_bytes(text)
    assert out["all-reduce"]["count"] == 1  # -done must NOT double-count
    assert out["all-reduce"]["payload_bytes"] == 1024 * 256 * 4
    assert out["all-gather"]["count"] == 1
    assert out["all-gather"]["payload_bytes"] == 8 * 4 * 2
    assert "fusion" not in out


def test_hlo_collective_bytes_tuple_shape():
    text = "%rs = (f32[64]{0}, u32[]) reduce-scatter(f32[512]{0} %g)"
    out = obs_trace.hlo_collective_bytes(text)
    assert out["reduce-scatter"]["payload_bytes"] == 64 * 4 + 4


# ------------------------------------- real capture on the 8-device sim


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory, devices8):
    """One profiler capture of 3 real dp steps, plus the HLO/planner
    collective-bytes crosscheck, journaled to a run directory."""
    out = tmp_path_factory.mktemp("tracerun")
    ad = make_ad("dp")
    batch = toy_batch()
    rng = jax.random.key(0)
    state = ad.init(rng, batch)
    state, m = ad.step(state, batch)  # warm the compile outside capture
    jax.block_until_ready(m)
    jnl = Journal(str(out / "journal.jsonl"))
    state, recs = obs_trace.trace_steps(
        ad.step, state, batch, steps=3, first_step=1,
        logdir=str(out / "profile"), flops_per_step=1e6, journal=jnl,
    )
    measured = obs_trace.measured_collective_bytes(ad, rng, batch)
    with obs_journal.as_default(jnl):
        est = obs_comms.comm_profile(ad, rng, batch)
    xc = obs_trace.crosscheck_collectives(
        measured, est["per_device"], journal=jnl)
    jnl.close()
    return {"dir": str(out), "recs": recs, "measured": measured,
            "est": est, "xc": xc}


def test_capture_produces_per_step_attribution(traced_run):
    recs = traced_run["recs"]
    assert [r["step"] for r in recs] == [1, 2, 3]
    for r in recs:
        assert r["wall_s"] > 0
        assert r["n_ops"] > 0  # the window contains device work (fenced)
        assert 0 <= r["compute_s"] <= r["wall_s"] + 1e-9
        assert 0 <= r["collective_s"] <= r["wall_s"] + 1e-9
        assert r["exposed_collective_s"] <= r["collective_s"] + 1e-9
        assert r["measured_mfu"] > 0


def test_capture_sees_dp_collectives(traced_run):
    # dp on 8 devices all-reduces grads: the timeline must show it
    assert any(r["collective_s"] > 0 for r in traced_run["recs"])
    assert any("all-reduce" in (r.get("collectives") or {})
               for r in traced_run["recs"])


def test_trace_journal_events(traced_run):
    events = Journal.read(os.path.join(traced_run["dir"], "journal.jsonl"))
    steps = [e for e in events if e.get("name") == "trace.step"]
    assert len(steps) == 3
    assert all(e.get("trace", "").endswith(".json.gz") for e in steps)
    colls = [e for e in events if e.get("name") == "trace.collective"]
    assert colls


def test_measured_vs_modeled_within_2x(traced_run):
    xc = {c["category"]: c for c in traced_run["xc"]}
    ar = xc["grad_allreduce"]
    assert ar["measured_bytes"] > 0 and ar["modeled_bytes"] > 0
    assert ar["within_2x"]
    # on the bench config the planner's ring math matches the
    # executable payload exactly
    assert ar["ratio"] == pytest.approx(1.0, rel=0.05)


def test_exposed_fraction_from_real_trace(traced_run):
    f = obs_trace.exposed_fraction(traced_run["recs"])
    assert f is None or 0.0 <= f <= 1.0


def test_report_renders_trace_sections(traced_run):
    rep = obs_report.generate(traced_run["dir"])
    assert rep["trace"]["n_steps"] == 3
    assert rep["trace"]["mean_wall_s"] > 0
    tc = {e["category"]: e for e in rep["trace_collectives"]}
    assert tc["grad_allreduce"]["within_2x"]
    text = obs_report.format_report(rep)
    assert "trace:" in text
    assert "exposed-comm crosscheck" in text


# --------------------------------------------- trainer instrumentation


def test_trainer_trace_every_n(tmp_path, devices8):
    from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
        SyntheticClassification,
    )
    from torch_automatic_distributed_neural_network_tpu.training import (
        Trainer,
        TrainerConfig,
    )

    jnl = Journal(str(tmp_path / "journal.jsonl"))
    trainer = Trainer(
        make_ad("dp"),
        TrainerConfig(steps=5, log_every=0, trace_every_n=3,
                      trace_dir=str(tmp_path / "profile"),
                      preflight=False),
        journal=jnl,
    )
    trainer.fit(SyntheticClassification(batch_size=16))
    jnl.close()
    events = Journal.read(str(tmp_path / "journal.jsonl"))
    steps = [e for e in events if e.get("name") == "trace.step"]
    # steps=5 from start=0: only i=3 matches (i != start, (i-start)%3==0)
    assert [e["step"] for e in steps] == [3]
    # the traced step's wall time lands in the trace bucket, not goodput
    assert trainer.goodput["seconds"]["trace"] > 0
    assert trainer.goodput["seconds"]["step"] > 0


def test_trainer_trace_failure_falls_back(tmp_path, devices8, monkeypatch):
    from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
        SyntheticClassification,
    )
    from torch_automatic_distributed_neural_network_tpu.training import (
        Trainer,
        TrainerConfig,
    )

    def boom(*a, **k):
        raise RuntimeError("no profiler here")

    monkeypatch.setattr(obs_trace, "trace_steps", boom)
    jnl = Journal(None)
    trainer = Trainer(
        make_ad("dp"),
        TrainerConfig(steps=4, log_every=0, trace_every_n=2,
                      preflight=False),
        journal=jnl,
    )
    trainer.fit(SyntheticClassification(batch_size=16))  # must not raise
    errs = [e for e in jnl.records if e.get("name") == "trace.error"]
    assert errs and "no profiler here" in errs[0]["error"]


# --------------------------------------------------- multihost merging


def _write_host_journal(path, host, wall_s, n=4):
    j = Journal(str(path), host0_only=False, meta={"host": host})
    for k in range(n):
        j.event("trace.step", step=k, wall_s=wall_s)
    j.close()


def test_multihost_merge_and_skew(tmp_path):
    # seeded skew: host 1 is 30% slower than host 0
    _write_host_journal(tmp_path / "journal.host0.jsonl", 0, 0.010)
    _write_host_journal(tmp_path / "journal.host1.jsonl", 1, 0.013)
    merged_path = aggregate.merge_run(str(tmp_path))
    assert merged_path.endswith("journal.merged.jsonl")
    records = Journal.read(merged_path)
    assert {r["host"] for r in records} == {0, 1}
    walls = [r.get("wall") or 0.0 for r in records]
    assert walls == sorted(walls)  # interleaved on the shared clock
    skew = aggregate.host_skew(records)
    assert skew["n_hosts"] == 2
    assert skew["per_host"][0]["mean"] == pytest.approx(0.010)
    assert skew["per_host"][1]["mean"] == pytest.approx(0.013)
    assert skew["skew_fraction"] == pytest.approx(0.3, rel=1e-6)
    # a re-merge must not ingest the merged file itself
    assert len(Journal.read(aggregate.merge_run(str(tmp_path)))) == \
        len(records)


def test_report_prefers_merged_journal_and_shows_hosts(tmp_path, capsys):
    _write_host_journal(tmp_path / "journal.host0.jsonl", 0, 0.010)
    _write_host_journal(tmp_path / "journal.host1.jsonl", 1, 0.015)
    rc = cli.main(["report", str(tmp_path), "--merge"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "journal.merged.jsonl" in out
    assert "hosts: 2" in out
    assert "straggler" in out  # 50% skew > the 10% callout threshold


def test_host_skew_needs_two_hosts():
    assert aggregate.host_skew(
        [{"name": "trace.step", "host": 0, "wall_s": 0.01}]) is None


# ------------------------------------------------- journal hardening


def test_journal_rotation_caps_file(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path, max_bytes=600)
    for k in range(40):
        j.event("tick", k=k, pad="x" * 40)
    j.close()
    assert j.rotations >= 1
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path + ".1") < 1200  # capped, not unbounded
    records = Journal.read(path)
    assert any(r.get("name") == "journal.rotated" for r in records)


def test_journal_rotation_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TADNN_JOURNAL_MAX_BYTES", "500")
    j = Journal(str(tmp_path / "j.jsonl"))
    assert j._max_bytes == 500
    j.close()


def test_reader_skips_torn_lines_with_one_warning(tmp_path):
    path = str(tmp_path / "torn.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "event", "name": "a"}) + "\n")
        f.write('{"kind": "event", "name": "b", "tr\n')  # torn mid-write
        f.write("42\n")  # non-dict JSON is torn too
        f.write(json.dumps({"kind": "event", "name": "c"}) + "\n")
    with pytest.warns(UserWarning, match="2 torn/corrupt"):
        records = Journal.read(path)
    assert [r["name"] for r in records] == ["a", "c"]
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second read: silent
        assert len(Journal.read(path)) == 2


# ----------------------------------------- bench freshness guard (CLI)


def _write_round(d, n, rec, wrapped=True):
    payload = {"n": n, "cmd": "python bench.py", "rc": 0, "tail": "",
               "parsed": rec} if wrapped else rec
    p = os.path.join(d, f"BENCH_r{n:02d}.json")
    with open(p, "w") as f:
        json.dump(payload, f)
    return p


def _write_last_good(d, metric, value):
    with open(os.path.join(d, "BENCH_LAST_GOOD.json"), "w") as f:
        json.dump({"gpt2": {
            "result": {"metric": metric, "value": value,
                       "unit": "tokens/s/chip", "vs_baseline": 1.0,
                       "extra": {}},
            "measured_utc": "2026-07-31T01:04:15Z",
            "device_kind": "TPU v5 lite",
        }}, f)


def test_check_fresh_record_passes(tmp_path):
    _write_last_good(str(tmp_path), "gpt2_tokens", 1000.0)
    _write_round(str(tmp_path), 6, {"metric": "gpt2_tokens",
                                    "value": 980.0, "unit": "t/s"})
    code, msgs = obs_report.check_bench(str(tmp_path))
    assert code == 0 and "fresh" in msgs[0]
    assert cli.main(["report", str(tmp_path), "--check"]) == 0


def test_check_stale_record_fails(tmp_path):
    _write_last_good(str(tmp_path), "gpt2_tokens", 1000.0)
    _write_round(str(tmp_path), 6, {
        "metric": "gpt2_backend_unreachable", "value": 0.0,
        "status": "backend_unreachable", "stale": True, "stale_of": "r02",
    })
    code, msgs = obs_report.check_bench(str(tmp_path))
    assert code == 1
    assert "stale" in msgs[0] and "r02" in msgs[0]
    assert cli.main(["report", str(tmp_path), "--check"]) == 1


def test_check_picks_newest_round(tmp_path):
    _write_last_good(str(tmp_path), "gpt2_tokens", 1000.0)
    _write_round(str(tmp_path), 5, {"metric": "gpt2_tokens",
                                    "value": 990.0, "unit": "t/s"})
    _write_round(str(tmp_path), 6, {"metric": "gpt2_unmeasurable_backend_down",
                                    "value": 0.0})
    code, msgs = obs_report.check_bench(str(tmp_path))
    assert code == 1 and "unmeasurable" in msgs[0]


def test_check_regression_fails(tmp_path):
    _write_last_good(str(tmp_path), "gpt2_tokens", 1000.0)
    _write_round(str(tmp_path), 6, {"metric": "gpt2_tokens",
                                    "value": 850.0, "unit": "t/s"})
    code, msgs = obs_report.check_bench(str(tmp_path))
    assert code == 1 and "regressed" in msgs[0]
    # within the 10% band is fine
    _write_round(str(tmp_path), 7, {"metric": "gpt2_tokens",
                                    "value": 901.0, "unit": "t/s"})
    code, _ = obs_report.check_bench(str(tmp_path))
    assert code == 0


def test_check_missing_record_fails(tmp_path):
    code, msgs = obs_report.check_bench(str(tmp_path))
    assert code == 1 and "no bench record" in msgs[0]


def test_check_unwrapped_record_too(tmp_path):
    # bench stdout saved directly (no driver wrapper) still checks
    _write_round(str(tmp_path), 6, {"metric": "m", "value": 5.0},
                 wrapped=False)
    code, _ = obs_report.check_bench(str(tmp_path))
    assert code == 0


def test_repo_current_round_is_flagged_stale():
    # the committed r05 artifact IS the backend-unreachable case the
    # guard exists for — it must fail the check until a live round lands
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not any(f.startswith("BENCH_r") for f in os.listdir(repo)):
        pytest.skip("no committed bench rounds")
    code, msgs = obs_report.check_bench(repo)
    assert code == 1


# ----------------------------------------------- cost-model feedback


def test_cost_measured_overlap_shrinks_comm():
    params = {"big": {"kernel": np.zeros((512, 512), np.float32)}}
    topo = topology.Topology(num_devices=8, num_hosts=1,
                             platform="tpu", device_kind="v5p")
    cand = tune.Candidate("dp", (("data", 8),))
    base = tune.cost.score(params, topo, cand)
    fed = tune.cost.score(params, topo, cand, measured_overlap=0.25)
    assert fed.step_time_s < base.step_time_s
    assert fed.breakdown["measured_overlap"] == 0.25
    # fully-hidden comms: only latency remains of the comm terms
    hidden = tune.cost.score(params, topo, cand, measured_overlap=0.0)
    assert hidden.step_time_s <= fed.step_time_s


def test_overlap_from_trace_roundtrip():
    f = tune.cost.overlap_from_trace(
        [{"collective_s": 2.0, "exposed_collective_s": 1.0}])
    assert f == pytest.approx(0.5)
