"""Checkpoint/resume tests (SURVEY.md §5): sharded save/restore, resume
continuity, and restore-to-a-different-mesh (resharding)."""

import jax
import numpy as np
import optax
import pytest

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.models import MLP
from torch_automatic_distributed_neural_network_tpu.training import (
    CheckpointManager,
    Trainer,
    TrainerConfig,
    abstract_state_for,
    softmax_xent_loss,
)


def batch(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "x": rng.randn(16, 8).astype(np.float32),
        "label": rng.randint(0, 10, size=(16,)).astype(np.int32),
    }


def make_ad(strategy="dp", devices=None):
    return tad.AutoDistribute(
        MLP(features=(32, 10)),
        optimizer=optax.adam(1e-2),
        loss_fn=softmax_xent_loss,
        strategy=strategy,
        devices=devices,
    )


def data_stream():
    i = 0
    while True:
        yield batch(i)
        i += 1


def test_save_restore_roundtrip(devices8, tmp_path):
    ad = make_ad("dp")
    state = ad.init(jax.random.key(0), batch())
    for i in range(3):
        state, _ = ad.step(state, batch(i))
    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(3, state, config={"lr": 1e-2})
    ckpt.wait()

    ad2 = make_ad("dp")
    abstract = abstract_state_for(ad2, jax.random.key(0), batch())
    restored = ckpt.restore(abstract)
    assert int(restored.step) == 3
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ckpt.restore_config() == {"lr": 1e-2}
    ckpt.close()


def test_resume_continues_identically(devices8, tmp_path):
    """Train 6 straight vs train 3 + resume + 3: identical final params."""
    ad = make_ad("dp")
    state = ad.init(jax.random.key(0), batch())
    for i in range(6):
        state, _ = ad.step(state, batch(i))
    straight = jax.tree.leaves(state.params)

    ckpt_dir = str(tmp_path / "resume")
    ad1 = make_ad("dp")
    s1 = ad1.init(jax.random.key(0), batch())
    for i in range(3):
        s1, _ = ad1.step(s1, batch(i))
    ckpt = CheckpointManager(ckpt_dir)
    ckpt.save(3, s1)
    ckpt.close()

    ad2 = make_ad("dp")
    ckpt2 = CheckpointManager(ckpt_dir)
    abstract = abstract_state_for(ad2, jax.random.key(0), batch())
    s2 = ckpt2.restore(abstract)
    ad2._compile_step(abstract, ad2.state_shardings(abstract))
    for i in range(3, 6):
        s2, _ = ad2.step(s2, batch(i))
    resumed = jax.tree.leaves(s2.params)
    for a, b in zip(straight, resumed):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    ckpt2.close()


def test_reshard_on_restore(devices8, tmp_path):
    """Checkpoint written on an 8-way DP mesh restores onto a 2x4 fsdp/tp
    mesh (elastic-resume path)."""
    ad = make_ad("dp")
    state = ad.init(jax.random.key(0), batch())
    state, _ = ad.step(state, batch())
    ckpt = CheckpointManager(str(tmp_path / "reshard"))
    ckpt.save(1, state)
    ckpt.wait()

    ad2 = make_ad("fsdp")
    abstract = abstract_state_for(ad2, jax.random.key(0), batch())
    restored = ckpt.restore(abstract)
    d = tad.mesh_degrees(ad2.plan.mesh)
    assert d["fsdp"] == 8
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored leaves actually carry the new sharding
    leaves = jax.tree.leaves(restored.params)
    assert any(not l.sharding.is_fully_replicated for l in leaves)
    ckpt.close()


def test_trainer_with_checkpointing(devices8, tmp_path):
    ckpt_dir = str(tmp_path / "trainer")
    ad = make_ad("dp")
    trainer = Trainer(
        ad,
        TrainerConfig(steps=4, log_every=0, ckpt_every=2),
        ckpt=CheckpointManager(ckpt_dir),
        run_config={"note": "test"},
    )
    state = trainer.fit(data_stream())
    assert int(state.step) == 4

    # a new trainer resumes from step 4 and finishes instantly
    ad2 = make_ad("dp")
    trainer2 = Trainer(
        ad2,
        TrainerConfig(steps=4, log_every=0, ckpt_every=2),
        ckpt=CheckpointManager(ckpt_dir),
    )
    state2 = trainer2.fit(data_stream())
    assert int(state2.step) == 4
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(state2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
