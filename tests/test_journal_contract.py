"""Telemetry contract tests (ISSUE 20): the event schema registry
(obs/schema.py), the JL001–JL007 producer/consumer lint
(analysis/journal_lint.py), runtime enforcement
(``Journal(validate=True)`` / ``TADNN_JOURNAL_VALIDATE``), the
journal-file auditor, and round-trip validation of journals produced
by live smoke runs.  The lint self-validates via the planted-mutation
harness, PR-19 style."""

import json

import pytest

from torch_automatic_distributed_neural_network_tpu import analysis
from torch_automatic_distributed_neural_network_tpu.analysis import (
    journal_lint,
)
from torch_automatic_distributed_neural_network_tpu.obs import schema
from torch_automatic_distributed_neural_network_tpu.obs.journal import (
    Journal,
)


# -- registry ----------------------------------------------------------------

def test_rules_table_has_all_jl_codes():
    for code in ("JL001", "JL002", "JL003", "JL004", "JL005", "JL006",
                 "JL007"):
        assert code in analysis.RULES
        assert analysis.RULES[code].layer == "journal"


def test_every_typespec_in_registry_is_well_formed():
    # check_value raises ValueError on an unknown spec string — probing
    # every declared spec proves the registry parses end to end
    for s in schema.REGISTRY.values():
        for spec in s.fields().values():
            schema.check_value(None, spec)


def test_alias_resolution_and_names_for():
    assert schema.canonical("serve.request") == "serve.request_done"
    assert schema.canonical("serve.step") == "serve.step"
    assert schema.names_for("serve.request_done") == (
        "serve.request_done", "serve.request")
    # an alias resolves to the canonical schema
    assert schema.get("serve.request") is schema.get("serve.request_done")
    assert schema.get("no.such.kind") is None


def test_registry_markdown_lists_kinds_and_aliases():
    md = schema.registry_markdown()
    assert "| `serve.request_done` | 2 |" in md
    assert "`serve.request`" in md  # the alias note
    assert "`gateway.replan`" in md


def test_check_value_type_grammar():
    assert schema.check_value(3, "int")
    assert not schema.check_value(True, "int")  # bool is not an int
    assert schema.check_value(3, "float")  # JSON loses int/float
    assert not schema.check_value("3", "float")
    assert schema.check_value(None, "str?")
    assert not schema.check_value(None, "str")
    assert schema.check_value([1], "list")
    assert schema.check_value({}, "dict")
    assert schema.check_value(object(), "any")
    with pytest.raises(ValueError):
        schema.check_value(1, "complex128")


# -- record validation (the runtime half) ------------------------------------

def _rec(name, **fields):
    return {"kind": "event", "name": name, "t": 0.0, "wall": 0.0,
            "depth": 0, **fields}


def test_validate_record_clean():
    assert schema.validate_record(
        _rec("serve.preempt", rid=3, n_regenerate=2)) == []


def test_validate_record_unknown_kind_jl001():
    codes = [c for c, _ in schema.validate_record(_rec("serve.bogus"))]
    assert codes == ["JL001"]


def test_validate_record_missing_required_jl002():
    codes = [c for c, _ in schema.validate_record(
        _rec("serve.preempt", rid=3))]
    assert codes == ["JL002"]


def test_validate_record_type_mismatch_jl003():
    codes = [c for c, _ in schema.validate_record(
        _rec("serve.preempt", rid="three", n_regenerate=2))]
    assert codes == ["JL003"]


def test_validate_record_undeclared_field_jl004():
    codes = [c for c, _ in schema.validate_record(
        _rec("serve.preempt", rid=3, n_regenerate=2, slot=1))]
    assert codes == ["JL004"]


def test_validate_record_open_schema_tolerates_extras():
    assert schema.validate_record(
        _rec("tune.decision", key="k", source="measured",
             anything_else={"deep": 1})) == []


def test_validate_record_deprecated_alias_jl007():
    codes = [c for c, _ in schema.validate_record(
        _rec("serve.request", rid=1, n_prompt=1, n_new=1, queue_s=0.0,
             total_s=0.1, tokens_per_s=10.0, preempted=0, ttft_s=0.05,
             itl_s=[]))]
    assert codes == ["JL007"]


def test_validate_record_kind_collision_is_payload():
    # payload fields named ``kind`` overwrite the journal's own
    # event/span discriminator (the established on-disk format); the
    # schema must check them as payload, not strip them as base fields
    rec = _rec("serve.prefix", rid=1, n_blocks=2)
    rec["kind"] = "publish"
    assert schema.validate_record(rec) == []
    rec["kind"] = 7  # and still type-check them
    assert [c for c, _ in schema.validate_record(rec)] == ["JL003"]


# -- static lint: per-rule fixtures ------------------------------------------

def _lint(src, **kw):
    findings, _ = journal_lint.lint_sources([("<t>", src)], **kw)
    return [f.code for f in findings]


def test_jl001_unknown_kind_positive_and_negative():
    assert _lint('def f(j): j.event("serve.bogus", x=1)') == ["JL001"]
    assert _lint(
        'def f(j): j.event("serve.preempt", rid=1, n_regenerate=2)') == []


def test_jl002_missing_required_field():
    assert _lint('def f(j): j.event("serve.preempt", rid=1)') == ["JL002"]
    # a **splat may supply anything: the site is not checkable
    assert _lint(
        'def f(j, kw): j.event("serve.preempt", rid=1, **kw)') == []


def test_jl003_literal_type_mismatch():
    assert _lint('def f(j): j.event("serve.preempt", rid="x", '
                 'n_regenerate=2)') == ["JL003"]


def test_jl004_undeclared_field_closed_vs_open():
    assert _lint('def f(j): j.event("serve.preempt", rid=1, '
                 'n_regenerate=2, extra=1)') == ["JL004"]
    assert _lint('def f(j): j.event("tune.decision", key="k", '
                 'source="s", extra=1)') == []


def test_jl005_dead_optional_field_full_scan_only():
    src = ('def f(j): j.event("gateway.hedge", kind="fire", rid=1, '
           'primary="a", replica="b")')
    assert _lint(src, full_scan=True) == ["JL005"]  # winner never emitted
    assert _lint(src, full_scan=False) == []


def test_jl006_consumer_reads_undeclared_field():
    src = (
        "def f(events):\n"
        '    xs = [e for e in events if e.get("name") == "serve.step"]\n'
        '    return [e.get("occupancyy") for e in xs]\n')
    assert _lint(src) == ["JL006"]
    assert _lint(src.replace("occupancyy", "occupancy")) == []


def test_jl006_if_chain_and_name_binding():
    src = (
        "def f(rec):\n"
        '    name = rec.get("name")\n'
        '    if name == "serve.speculate":\n'
        '        return rec.get("drafted"), rec.get("acceptedd")\n')
    assert _lint(src) == ["JL006"]


def test_jl007_emission_under_alias():
    src = ('def f(j): j.event("serve.request", rid=1, n_prompt=1, '
           'n_new=1, queue_s=0.0, total_s=0.1, tokens_per_s=1.0, '
           'preempted=0, ttft_s=0.1, itl_s=[])')
    assert _lint(src) == ["JL007"]


def test_jl007_consumer_hardcoded_alias_vs_names_for():
    hard = ('def f(events):\n'
            '    return [e for e in events if e.get("name") in '
            '("serve.request", "serve.request_done")]\n')
    assert _lint(hard) == ["JL007"]
    sanctioned = (
        'from torch_automatic_distributed_neural_network_tpu.obs.schema '
        'import names_for\n'
        'def f(events):\n'
        '    return [e for e in events if e.get("name") in '
        'names_for("serve.request_done")]\n')
    assert _lint(sanctioned) == []


def test_span_attachment_fields_are_resolved():
    src = ("def f(j):\n"
           '    with j.span("ckpt.wait") as rec:\n'
           '        rec["sharded"] = True\n')
    assert _lint(src) == []
    assert _lint(src.replace('"sharded"', '"shardedd"')) == ["JL004"]


def test_primitive_name_comparisons_are_not_name_tests():
    # jaxpr walkers compare `name` against primitive strings; none are
    # registry kinds, so no JL001 and no read attribution
    src = ("def f(eqn, name):\n"
           '    if name == "convert_element_type":\n'
           '        return eqn.get("params")\n')
    assert _lint(src) == []


def test_suppression_comment_with_reason():
    src = ('def f(j):\n'
           '    j.event("serve.bogus")  '
           '# tadnn: lint-ok(JL001) synthetic fixture kind\n')
    assert _lint(src) == []


# -- the mutation harness (self-validation) ----------------------------------

def test_mutation_harness_clean_and_planted_drifts():
    assert len(journal_lint.MUTATIONS) >= 8
    assert {m[2] for m in journal_lint.MUTATIONS} == {
        "JL001", "JL002", "JL003", "JL004", "JL005", "JL006", "JL007"}
    assert journal_lint.self_check() == []


# -- the repo-wide gate ------------------------------------------------------

def test_repo_journal_contract_is_clean():
    """The standing gate: zero findings over the package and 100%
    registry coverage of statically-discovered emission kinds (the
    ``tadnn check --journal --strict`` CI leg, as a tier-1 test)."""
    findings, stats = journal_lint.lint_paths()
    assert findings == [], "\n".join(f.format() for f in findings)
    assert stats["coverage"] == 1.0
    assert stats["kinds_emitted"] > 80
    assert stats["sites"] > 100


# -- runtime enforcement -----------------------------------------------------

def test_journal_validate_raises_on_contract_violation():
    j = Journal(validate=True)
    j.event("serve.preempt", rid=1, n_regenerate=2)  # clean
    with pytest.raises(schema.JournalContractError, match="JL002"):
        j.event("serve.preempt", rid=1)
    with pytest.raises(schema.JournalContractError, match="JL001"):
        j.event("serve.bogus")
    with pytest.raises(schema.JournalContractError, match="JL003"):
        j.event("serve.preempt", rid="x", n_regenerate=2)


def test_journal_validate_spans_checked_at_exit():
    j = Journal(validate=True)
    with j.span("ckpt.wait") as rec:
        rec["sharded"] = True
    with pytest.raises(schema.JournalContractError, match="JL004"):
        with j.span("ckpt.wait") as rec:
            rec["undeclared_field"] = 1


def test_journal_validate_env_gate(monkeypatch):
    monkeypatch.setenv("TADNN_JOURNAL_VALIDATE", "1")
    j = Journal()
    assert j.validate
    with pytest.raises(schema.JournalContractError):
        j.event("serve.bogus")
    monkeypatch.setenv("TADNN_JOURNAL_VALIDATE", "0")
    assert not Journal().validate
    # explicit argument beats the environment
    assert Journal(validate=True).validate


def test_journal_validate_off_by_default():
    j = Journal()
    assert not j.validate
    j.event("whatever.goes")  # un-validated journals accept anything


# -- journal-file audit ------------------------------------------------------

def test_audit_journal_flags_bad_records(tmp_path):
    p = tmp_path / "j.jsonl"
    good = _rec("serve.preempt", rid=1, n_regenerate=2)
    bad = _rec("serve.preempt", rid=1)  # missing n_regenerate
    unknown = _rec("serve.bogus")
    p.write_text(json.dumps(good) + "\n" + json.dumps(bad) + "\n"
                 + json.dumps(unknown) + "\n" + '{"torn...\n')
    findings, stats = journal_lint.audit_journal(str(p))
    assert stats == {"records": 3, "torn": 1}
    assert [f.code for f in findings] == ["JL002", "JL001"]
    assert findings[0].where.endswith(":2")
    assert findings[1].where.endswith(":3")


# -- consumer alias satellite ------------------------------------------------

def test_live_aggregator_accepts_pre_rename_records():
    from torch_automatic_distributed_neural_network_tpu.obs.live import (
        LiveAggregator,
    )

    agg = LiveAggregator(window_s=10.0, clock=None)
    # one record under the old name, one under the new: both must fold
    for t, name in ((1.0, "serve.request"), (2.0, "serve.request_done")):
        agg.add({"kind": "event", "name": name, "t": t, "wall": t,
                 "depth": 0, "rid": 1, "n_prompt": 4, "n_new": 8,
                 "queue_s": 0.0, "total_s": 0.5, "tokens_per_s": 16.0,
                 "preempted": 0, "ttft_s": 0.1, "itl_s": [0.05]})
    agg.flush()
    assert agg.totals["n_done"] == 2


# -- round trips over live smoke journals ------------------------------------

def test_gateway_chaos_round_trip_validates(tmp_path):
    """A live gateway chaos run's journal must audit clean against the
    registry — the in-process half of the CI smoke round trip."""
    from torch_automatic_distributed_neural_network_tpu.inference \
        .gateway.chaos import chaos_smoke

    path = str(tmp_path / "chaos.journal.jsonl")
    out = chaos_smoke(journal_path=path, scale="light", max_replicas=4)
    assert out["ok"]
    findings, stats = journal_lint.audit_journal(path)
    assert stats["records"] > 100
    assert findings == [], "\n".join(f.format() for f in findings)


@pytest.mark.slow
def test_serve_smoke_round_trip_validates(tmp_path, monkeypatch):
    from torch_automatic_distributed_neural_network_tpu import cli

    monkeypatch.setenv("TADNN_JOURNAL_VALIDATE", "1")
    path = str(tmp_path / "serve.journal.jsonl")
    rc = cli.main(["serve", "--smoke", "--journal", path])
    assert rc == 0
    findings, stats = journal_lint.audit_journal(path)
    assert stats["records"] > 10
    assert findings == [], "\n".join(f.format() for f in findings)


@pytest.mark.slow
def test_launch_smoke_round_trip_validates(tmp_path, monkeypatch):
    from torch_automatic_distributed_neural_network_tpu import cli

    monkeypatch.setenv("TADNN_JOURNAL_VALIDATE", "1")
    d = tmp_path / "launch-smoke"
    rc = cli.main(["launch", "--launch-dir", str(d), "--hosts", "2",
                   "--local-devices", "2", "--steps", "4",
                   "--ckpt-every", "2", "--smoke", "--json"])
    assert rc == 0
    merged = sorted(d.glob("*/journal.merged.jsonl"))
    assert merged
    for m in merged:
        findings, _ = journal_lint.audit_journal(str(m))
        assert findings == [], "\n".join(f.format() for f in findings)
