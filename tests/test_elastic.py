"""Failure-detection / elastic-recovery tests (SURVEY.md §5, §4
'fault injection = kill-and-resume harness on CPU sim')."""

import time

import jax
import numpy as np
import optax
import pytest

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticLM,
)
from torch_automatic_distributed_neural_network_tpu.models import GPT2
from torch_automatic_distributed_neural_network_tpu.training import (
    CheckpointManager,
    FaultInjector,
    Heartbeat,
    InjectedFault,
    StepWatchdog,
    Trainer,
    TrainerConfig,
    next_token_loss,
    run_with_recovery,
)


def test_watchdog_fires_on_stall():
    fired = []
    wd = StepWatchdog(0.2, on_stall=lambda age: fired.append(age))
    with wd:
        wd.beat()
        time.sleep(0.6)
    assert wd.stalled and fired and fired[0] >= 0.2


def test_watchdog_quiet_when_beating():
    wd = StepWatchdog(0.5)
    with wd:
        for _ in range(6):
            time.sleep(0.1)
            wd.beat()
    assert not wd.stalled


def test_heartbeat_staleness(tmp_path):
    d = str(tmp_path / "beats")
    hb = Heartbeat(d, interval_s=0.1, host_index=0)
    with hb:
        hb.set_step(7)
        time.sleep(0.25)
        assert Heartbeat.stale_hosts(d, max_age_s=5.0) == []
    beats = Heartbeat.read_all(d)
    assert beats[0]["step"] == 7
    # a host whose beat is old shows up stale
    time.sleep(0.3)
    assert Heartbeat.stale_hosts(d, max_age_s=0.2) == [0]


def _make_trainer(tmp_path, steps, callbacks=None, devices=None):
    ad = tad.AutoDistribute(
        GPT2("test", vocab_size=256, max_seq_len=32),
        optimizer=optax.adamw(1e-3),
        loss_fn=next_token_loss,
        strategy="dp",
        devices=devices,
    )
    ckpt = CheckpointManager(str(tmp_path / "ckpt"), save_interval_steps=0)
    return Trainer(
        ad,
        TrainerConfig(steps=steps, log_every=0, ckpt_every=2),
        ckpt=ckpt,
        callbacks=callbacks,
    )


@pytest.mark.parametrize("kill_at", [3, 4])
def test_preemption_drain_checkpoints_and_resumes(
    devices8, tmp_path, kill_at
):
    """SIGTERM mid-run (a TPU maintenance event / spot reclaim): the
    PreemptionGuard drains cooperatively — Trainer saves a checkpoint at
    the interrupted step and returns; a fresh fit resumes from there and
    matches the uninterrupted trajectory.  kill_at=4 lands on a
    ckpt_every=2 boundary where the periodic save already wrote the step
    (orbax refuses overwrites — the drain must not re-save)."""
    import os
    import signal

    data = SyntheticLM(vocab_size=256, seq_len=33, batch_size=8)
    steps = 8

    # uninterrupted oracle
    t0 = _make_trainer(tmp_path / "a", steps)
    final_a = t0.fit(data)
    t0.ckpt.close()

    # SIGTERM delivered during the kill step's callbacks; the handler
    # sets the flag and the loop drains at that step's post-callback
    # check
    def bomb(step, state, metrics):
        if step == kill_at:
            os.kill(os.getpid(), signal.SIGTERM)

    trainer = _make_trainer(tmp_path / "b", steps, callbacks=[bomb])
    drained = trainer.fit(data)
    assert int(drained.step) == kill_at
    assert trainer.ckpt.latest_step() == kill_at

    # resume to completion with a fresh trainer (no bomb)
    trainer2 = _make_trainer(tmp_path / "b", steps)
    final_b = trainer2.fit(data)
    trainer.ckpt.close()
    trainer2.ckpt.close()
    assert int(final_b.step) == steps
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(final_a.params)[0]),
        np.asarray(jax.tree.leaves(final_b.params)[0]),
        rtol=1e-6, atol=1e-6,
    )


def test_kill_and_resume_matches_uninterrupted(devices8, tmp_path):
    data = SyntheticLM(vocab_size=256, seq_len=33, batch_size=8)
    steps = 8

    # uninterrupted oracle
    t0 = _make_trainer(tmp_path / "a", steps)
    final_a = t0.fit(data)
    t0.ckpt.close()

    # killed at step 5, recovered; step-indexed data keeps batches aligned
    fault = FaultInjector(at_step=5)
    trainer = _make_trainer(tmp_path / "b", steps, callbacks=[fault])
    restarts = []
    final_b = run_with_recovery(
        lambda: trainer.fit(data),
        max_restarts=1,
        retriable=(InjectedFault,),
        on_restart=lambda n, e: restarts.append((n, str(e))),
    )
    trainer.ckpt.close()

    assert restarts, "fault did not fire"
    assert int(final_b.step) == steps
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(final_a.params)[0]),
        np.asarray(jax.tree.leaves(final_b.params)[0]),
        rtol=1e-6, atol=1e-6,
    )


def test_recovery_gives_up_after_max_restarts(devices8, tmp_path):
    data = SyntheticLM(vocab_size=256, seq_len=33, batch_size=8)

    def always_fail(step, state, metrics):
        raise InjectedFault("persistent failure")

    trainer = _make_trainer(tmp_path, 8, callbacks=[always_fail])
    with pytest.raises(InjectedFault):
        run_with_recovery(
            lambda: trainer.fit(data),
            max_restarts=2,
            retriable=(InjectedFault,),
            on_restart=lambda n, e: None,
        )
    trainer.ckpt.close()


def test_resume_on_different_mesh(devices8, tmp_path):
    """Elastic resume onto a different mesh shape: 8-way dp checkpoint
    restored into a 4-device dp run (resharding restore)."""
    data = SyntheticLM(vocab_size=256, seq_len=33, batch_size=8)
    fault = FaultInjector(at_step=5)
    t8 = _make_trainer(tmp_path, 8, callbacks=[fault])
    with pytest.raises(InjectedFault):
        t8.fit(data)
    t8.ckpt.wait()

    t4 = _make_trainer(tmp_path, 8, devices=jax.devices()[:4])
    final = t4.fit(data)
    t4.ckpt.close()
    assert int(final.step) == 8
    assert np.isfinite(
        float(np.asarray(jax.tree.leaves(final.params)[0]).sum())
    )
