"""ZeRO-1 cross-replica weight-update sharding (arxiv 2004.13336).

Covers the whole zero1 slice: the planner's opt_spec_tree, the RS+AG
collective-traffic profile that replaces the dp grad all-reduce, GL002
cleanliness, the mem_lint moment-shard accounting, tuner enumeration /
memory-tight ranking / cache-key separation, the choose_strategy
single-device degenerate, and dp-vs-dp+zero1 numeric parity on the
8-device CPU sim from conftest.py.
"""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu import (
    analysis,
    planner,
    topology,
    tune,
)
from torch_automatic_distributed_neural_network_tpu.analysis import mem_lint
from torch_automatic_distributed_neural_network_tpu.models import MLP
from torch_automatic_distributed_neural_network_tpu.obs import (
    journal as obs_journal,
)
from torch_automatic_distributed_neural_network_tpu.training import (
    softmax_xent_loss,
)


class Shape:
    def __init__(self, *shape, dtype=jnp.float32):
        self.shape = shape
        self.dtype = dtype


def divisible_params(d=64, ff=256):
    """Every dim divisible by 8 — zero1 shards every leaf."""
    return {
        "up": {"kernel": Shape(d, ff), "bias": Shape(ff)},
        "down": {"kernel": Shape(ff, d), "bias": Shape(d)},
    }


def codes(findings):
    return [f.code for f in findings]


def toy_batch(seed=0, batch=16, dim=8, classes=10):
    rng = np.random.RandomState(seed)
    return {
        "x": jnp.asarray(rng.randn(batch, dim), jnp.float32),
        "label": jnp.asarray(rng.randint(0, classes, size=(batch,))),
    }


def _mlp_ad(optimizer=None, *, zero1=True, strategy="dp", features=(64, 32)):
    return tad.AutoDistribute(
        MLP(features=features),
        optimizer=optimizer or optax.adam(1e-2),
        loss_fn=softmax_xent_loss,
        strategy=strategy,
        zero1=zero1,
    )


# ---------------------------------------------------------------------------
# planner: zero1_spec_tree + make_plan wiring
# ---------------------------------------------------------------------------


class TestZero1SpecTree:
    def test_largest_divisible_dim_shards_over_data(self):
        params = {"w": Shape(16, 64), "b": Shape(32)}
        specs = {"w": P(), "b": P()}
        out = planner.zero1_spec_tree(params, {"data": 8}, specs)
        assert out["w"] == P(None, "data")  # 64 > 16: second dim wins
        assert out["b"] == P("data")

    def test_indivisible_and_scalar_leaves_keep_param_spec(self):
        params = {"odd": Shape(3, 5), "s": Shape()}
        specs = {"odd": P(), "s": P()}
        out = planner.zero1_spec_tree(params, {"data": 8}, specs)
        assert out["odd"] == P() and out["s"] == P()

    def test_respects_existing_param_sharding(self):
        # a tp-sharded kernel: 'data' must land on a dim tensor doesn't own
        params = {"w": Shape(64, 64)}
        specs = {"w": P(None, "tensor")}
        out = planner.zero1_spec_tree(
            params, {"data": 4, "tensor": 2}, specs)
        assert out["w"] == P("data", "tensor")

    def test_noop_without_data_axis(self):
        params = {"w": Shape(16, 64)}
        specs = {"w": P("fsdp", None)}
        assert planner.zero1_spec_tree(
            params, {"fsdp": 8}, specs) is specs

    def test_leaf_count_mismatch_raises(self):
        with pytest.raises(ValueError, match="does not match"):
            planner.zero1_spec_tree(
                {"a": Shape(8), "b": Shape(8)}, {"data": 8}, {"a": P()})


class TestMakePlanZero1:
    def test_dp_plan_gains_distinct_opt_spec_tree(self, devices8):
        plan = planner.make_plan(divisible_params(), strategy="dp",
                                 zero1=True)
        assert plan.zero1
        assert plan.opt_spec_tree is not None
        opt = jax.tree.leaves(plan.opt_spec_tree,
                              is_leaf=lambda x: isinstance(x, P))
        par = jax.tree.leaves(plan.param_specs,
                              is_leaf=lambda x: isinstance(x, P))
        assert opt != par  # params untouched, moments sharded
        assert all(s == P() for s in par)
        assert all("data" in planner.spec_axes(s) for s in opt)
        assert "+zero1" in plan.describe()

    def test_default_is_off(self, devices8):
        plan = planner.make_plan(divisible_params(), strategy="dp")
        assert not plan.zero1 and plan.opt_spec_tree is None
        assert "+zero1" not in plan.describe()

    def test_downgrades_cleanly_without_data_axis(self, devices8):
        plan = planner.make_plan(divisible_params(), strategy="fsdp",
                                 zero1=True)
        assert not plan.zero1 and plan.opt_spec_tree is None


def test_choose_strategy_single_device_is_identity_dp():
    """Satellite fix: n==1 must short-circuit to dp[data1], never fall
    through to the fsdp catch-all (a {'fsdp': 1} mesh is a dead axis
    that trips PL004 downstream)."""
    topo = topology.Topology(num_devices=1, num_hosts=1,
                             platform="cpu", device_kind="cpu")
    big = {"big": {"kernel": Shape(32768, 32768)}}  # would want fsdp
    assert planner.choose_strategy(big, topo) == ("dp", {"data": 1})


# ---------------------------------------------------------------------------
# collective-traffic profile: RS+AG replaces the dp all-reduce
# ---------------------------------------------------------------------------


class TestZero1CollectiveBytes:
    def test_rs_ag_replace_dp_allreduce(self, devices8):
        params = divisible_params()
        plan = planner.make_plan(params, strategy="dp", zero1=True)
        est = planner.expected_collective_bytes(plan, params)
        per = est["per_device"]
        pbytes = sum(math.prod(s.shape) * 4
                     for s in jax.tree.leaves(params))
        rs, ag = (per["zero1_grad_reduce_scatter"],
                  per["zero1_param_allgather"])
        # every leaf is divisible: the whole grad payload moves as RS+AG
        assert rs["payload_bytes"] == pbytes
        assert ag["payload_bytes"] == pbytes
        assert rs["wire_bytes"] == int(7 / 8 * pbytes)
        assert ag["wire_bytes"] == int(7 / 8 * pbytes)
        # ...and the 2(n-1)/n all-reduce is GONE, not double-charged
        assert per["grad_allreduce"]["wire_bytes"] == 0
        # same total wire as dp's single all-reduce: zero1 trades no
        # bandwidth, only memory (the paper's headline property)
        dp_plan = planner.make_plan(params, strategy="dp")
        dp = planner.expected_collective_bytes(dp_plan, params)
        assert (rs["wire_bytes"] + ag["wire_bytes"]
                == dp["per_device"]["grad_allreduce"]["wire_bytes"])

    def test_non_zero1_plan_has_no_zero1_categories(self, devices8):
        plan = planner.make_plan(divisible_params(), strategy="dp")
        per = planner.expected_collective_bytes(
            plan, divisible_params())["per_device"]
        assert "zero1_grad_reduce_scatter" not in per
        assert "zero1_param_allgather" not in per

    def test_indivisible_leaf_keeps_residual_allreduce(self, devices8):
        params = {**divisible_params(), "odd": {"w": Shape(3, 5)}}
        plan = planner.make_plan(params, strategy="dp", zero1=True)
        per = planner.expected_collective_bytes(plan, params)["per_device"]
        # the (3,5) leaf can't shard on data=8: its grad still rides a
        # plain all-reduce (2(n-1)/n of its 60-byte payload)
        assert per["grad_allreduce"]["payload_bytes"] == 15 * 4
        assert per["grad_allreduce"]["wire_bytes"] == int(
            2 * 7 / 8 * 15 * 4)

    def test_param_allgather_does_not_scale_with_grad_accum(self, devices8):
        params = divisible_params()
        plan = planner.make_plan(params, strategy="dp", zero1=True)
        one = planner.expected_collective_bytes(
            plan, params, grad_accum=1)["per_device"]
        four = planner.expected_collective_bytes(
            plan, params, grad_accum=4)["per_device"]
        # grads reduce-scatter once per accumulation slice...
        assert (four["zero1_grad_reduce_scatter"]["wire_bytes"]
                == 4 * one["zero1_grad_reduce_scatter"]["wire_bytes"])
        # ...but the fresh params gather once per optimizer step
        assert (four["zero1_param_allgather"]["wire_bytes"]
                == one["zero1_param_allgather"]["wire_bytes"])


# ---------------------------------------------------------------------------
# graph lint: the zero1 RS/AG over 'data' must be GL002-clean
# ---------------------------------------------------------------------------


def test_preflight_is_gl002_clean_on_zero1_plan(devices8):
    ad = _mlp_ad()
    batch = toy_batch()
    ad.init(jax.random.key(0), batch)
    assert ad.plan.zero1
    findings = analysis.preflight(ad, batch, rng=jax.random.key(1),
                                  budget="16GiB")
    assert "GL002" not in codes(findings), [
        (f.code, f.msg) for f in findings]


# ---------------------------------------------------------------------------
# mem_lint: moments charged by the zero1 shard fraction
# ---------------------------------------------------------------------------


def _opt_bytes(ad):
    batch = toy_batch()
    ad.build_plan(jax.random.key(0), batch)
    state_abs = jax.eval_shape(ad._make_state_fn(batch),
                               jax.random.key(0))
    est = mem_lint.estimate_step_memory(
        None, ad.plan, state_abs.params, opt_state=state_abs.opt_state)
    return est


class TestMemLintZero1:
    def test_adam_two_moments_shard_dp_fold(self, devices8):
        repl = _opt_bytes(_mlp_ad(optax.adam(1e-2), zero1=False))
        z1 = _opt_bytes(_mlp_ad(optax.adam(1e-2), zero1=True))
        assert repl.params_bytes == z1.params_bytes  # params untouched
        # MLP(64,32) on d=8 input: every dim divides 8 -> both adam
        # moments shard exactly 8-fold (plus adam's scalar count)
        assert z1.optimizer_bytes <= 1.15 * repl.optimizer_bytes / 8
        # and the moments really are 2x param bytes when replicated
        assert repl.optimizer_bytes == pytest.approx(
            2 * repl.params_bytes, rel=0.01)

    def test_sgd_momentum_single_moment_shards(self, devices8):
        opt = optax.sgd(0.1, momentum=0.9)
        repl = _opt_bytes(_mlp_ad(opt, zero1=False))
        z1 = _opt_bytes(_mlp_ad(opt, zero1=True))
        assert repl.optimizer_bytes == pytest.approx(
            repl.params_bytes, rel=0.01)  # one momentum tree
        assert z1.optimizer_bytes <= 1.15 * repl.optimizer_bytes / 8

    def test_ml001_flips_clean_on_config_that_only_fits_with_zero1(
            self, devices8):
        repl = _opt_bytes(_mlp_ad(optax.adam(1e-2), zero1=False))
        z1 = _opt_bytes(_mlp_ad(optax.adam(1e-2), zero1=True))
        budget = (repl.peak_bytes + z1.peak_bytes) // 2
        over = mem_lint.lint_memory(repl, budget_bytes=budget)
        fits = mem_lint.lint_memory(z1, budget_bytes=budget, headroom=0.05)
        assert "ML001" in codes(over)  # replicated state predicts OOM
        assert "ML001" not in codes(fits)  # same model+budget, zero1 fits


# ---------------------------------------------------------------------------
# tune: enumeration, memory-tight ranking, cache-key separation
# ---------------------------------------------------------------------------


def transformer_like_params(d=256, ff=1024, vocab=1024):
    return {
        "embed": {"embedding": Shape(vocab, d)},
        "layers_0": {
            "mlp": {
                "up_proj": {"kernel": Shape(d, ff)},
                "down_proj": {"kernel": Shape(ff, d)},
            },
        },
        "lm_head": {"kernel": Shape(d, vocab)},
    }


def topo8(device_kind="v5p"):
    return topology.Topology(num_devices=8, num_hosts=1,
                             platform="tpu", device_kind=device_kind)


class TestTuneZero1:
    def test_space_enumerates_zero1_twins_of_data_meshes(self):
        kept, _ = tune.enumerate_candidates(
            transformer_like_params(), topo8("v5p"))
        by_label = {c.label(): c for c in kept}
        assert "dp[data8]" in by_label and "dp[data8]+z1" in by_label
        assert by_label["dp[data8]+z1"].zero1
        # fsdp has no data axis -> no twin to enumerate
        assert not any(c.zero1 for c in kept if c.strategy == "fsdp")

    def test_space_zero1_off_suppresses_twins(self):
        kept, _ = tune.enumerate_candidates(
            transformer_like_params(), topo8("v5p"), zero1=False)
        assert not any(c.zero1 for c in kept)

    def test_memory_tight_budget_ranks_zero1_above_plain_dp(self):
        """The acceptance scenario: fp32 adam state of a ~4 GiB kernel
        is ~17 GiB replicated — over v5e's 16 GiB — while the zero1
        variant's moments/8 fit.  Fits-first ordering must put dp+z1
        strictly above plain dp."""
        big = {"big": {"kernel": Shape(32768, 32768)}}
        cands = [tune.Candidate("dp", (("data", 8),)),
                 tune.Candidate("dp", (("data", 8),), zero1=True)]
        ranked = tune.rank(big, topo8("v5e"), cands)
        assert [e.candidate.zero1 for e in ranked] == [True, False]
        assert ranked[0].fits and not ranked[1].fits
        assert ranked[0].to_json()["zero1"] is True

    def test_zero1_state_bytes_are_moments_over_dp(self):
        cand = tune.Candidate("dp", (("data", 8),), zero1=True)
        mem = tune.space.candidate_memory(divisible_params(), cand)
        pb = sum(math.prod(s.shape) * 4
                 for s in jax.tree.leaves(divisible_params()))
        # params+grads replicated (2P) + 2 adam moments sharded (2P/8)
        assert mem["state_bytes"] == int(2 * pb + 2 * pb / 8)

    def test_policy_zero1_changes_cache_key(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TADNN_TUNE_CACHE", str(tmp_path / "c.jsonl"))
        on = tune.tune(transformer_like_params(), topo8("v5p"),
                       policy=tune.TunePolicy(zero1=True))
        off = tune.tune(transformer_like_params(), topo8("v5p"),
                        policy=tune.TunePolicy(zero1=False))
        assert on.key != off.key  # a cached plain-dp decision can never
        assert not off.zero1      # shadow a dp+zero1 search

    def test_zero1_winner_round_trips_through_cache(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("TADNN_TUNE_CACHE", str(tmp_path / "c.jsonl"))
        big = {"big": {"kernel": Shape(32768, 32768)}}
        first = tune.tune(big, topo8("v5e"))
        assert first.source == "cost_model"
        assert first.strategy == "dp" and first.zero1  # beats fsdp on comm
        again = tune.tune(big, topo8("v5e"))
        assert again.source == "cache"
        assert (again.strategy, again.zero1) == ("dp", True)


# ---------------------------------------------------------------------------
# end-to-end on the 8-device sim: parity, sharding, journal
# ---------------------------------------------------------------------------


def _run(ad, steps=6):
    state = ad.init(jax.random.key(0), toy_batch())
    losses = []
    for i in range(steps):
        state, metrics = ad.step(state, toy_batch(seed=i))
        losses.append(float(metrics["loss"]))
    return state, losses


class TestZero1Parity:
    def test_dp_vs_dp_zero1_numeric_parity(self, devices8):
        """Satellite acceptance: same model/data/seeds under dp and
        dp+zero1 — allclose loss trajectory, allclose params, and the
        zero1 run's gathered params bitwise identical on every replica
        (the all-gather at update time leaves no per-replica drift)."""
        s_dp, l_dp = _run(_mlp_ad(zero1=False))
        s_z1, l_z1 = _run(_mlp_ad(zero1=True))
        np.testing.assert_allclose(l_dp, l_z1, rtol=1e-4)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
            s_dp.params, s_z1.params)
        for leaf in jax.tree.leaves(s_z1.params):
            shards = leaf.addressable_shards
            assert all(s.data.shape == leaf.shape for s in shards)
            ref = np.asarray(shards[0].data)
            for s in shards[1:]:
                np.testing.assert_array_equal(ref, np.asarray(s.data))

    def test_opt_state_is_actually_sharded(self, devices8):
        ad = _mlp_ad(zero1=True)
        state = ad.init(jax.random.key(0), toy_batch())
        mu = state.opt_state[0].mu
        sharded = [leaf for leaf in jax.tree.leaves(mu)
                   if leaf.addressable_shards[0].data.shape != leaf.shape]
        assert sharded, "no adam moment leaf is sharded under zero1"
        for leaf in sharded:
            shard = leaf.addressable_shards[0].data
            assert math.prod(shard.shape) * 8 == math.prod(leaf.shape)


def test_plan_zero1_journal_event(devices8):
    j = obs_journal.set_default(obs_journal.Journal())
    try:
        ad = _mlp_ad(zero1=True)
        ad.build_plan(jax.random.key(0), toy_batch())
        recs = {r["name"]: r for r in j.records}
        assert recs["plan"]["zero1"] is True
        z1 = recs["plan.zero1"]
        assert z1["data_degree"] == 8
        assert z1["predicted_reduce_scatter_bytes"] > 0
        assert z1["predicted_allgather_bytes"] > 0
        assert z1["compiled_bytes"] is None  # filled by the crosscheck
        json.dumps(z1)  # journal rows must stay JSON-serializable
    finally:
        obs_journal.set_default(None)
