"""Profiling helpers (SURVEY.md §5 tracing row): cost analysis + memory
analysis wrappers used for MFU and HBM accounting."""

import jax
import jax.numpy as jnp

from torch_automatic_distributed_neural_network_tpu.utils.profiling import (
    compiled_flops,
    compiled_memory,
)


def test_compiled_flops_matmul(devices8):
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((64, 128))
    b = jnp.ones((128, 32))
    flops = compiled_flops(f, a, b)
    # 2*M*K*N = 2*64*128*32; cost analysis may add epsilon overhead
    assert flops is not None and flops >= 2 * 64 * 128 * 32


def test_compiled_memory_step(devices8):
    f = jax.jit(lambda x: (x @ x.T).sum())
    mem = compiled_memory(f, jnp.ones((256, 256)))
    assert mem is not None
    assert mem["argument_size"] == 256 * 256 * 4
    assert mem["temp_size"] > 0


def test_compile_report_abstract_only(devices8):
    """compile_report AOT-compiles the sharded step without materializing
    any state (the memfit path, bench.py mode=memfit / BASELINE.md row 4):
    per-device argument bytes must reflect the fsdp=8 shard, not the full
    model."""
    import numpy as np
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad
    from torch_automatic_distributed_neural_network_tpu.models import GPT2
    from torch_automatic_distributed_neural_network_tpu.training import (
        next_token_loss,
    )

    ad = tad.AutoDistribute(
        GPT2("test", vocab_size=512, max_seq_len=64),
        optimizer=optax.adamw(1e-4),
        loss_fn=next_token_loss,
        strategy="fsdp",
        precision="mixed",
    )
    sample = {"tokens": np.zeros((8, 65), np.int32)}
    report = ad.compile_report(jax.random.key(0), sample)
    assert report is not None
    assert report["per_device_peak_bytes"] > 0
    n_params = ad.model.cfg.num_params()
    # mixed precision state: fp32 master + bf16 moments = 8 B/param, all
    # fsdp-sharded 8 ways; argument_size is per-device and must sit well
    # under the unsharded total (padding/replicated odds allow 2x the
    # ideal shard but not the full tree)
    per_dev = report["memory"]["argument_size"]
    assert per_dev < (8 * n_params) / 8 * 2 + 2**20
    # the step must still run after the report (init path unaffected)
    state = ad.init(jax.random.key(0), sample)
    state, m = ad.step(state, sample)
    assert np.isfinite(float(m["loss"]))
