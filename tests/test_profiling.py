"""Profiling helpers (SURVEY.md §5 tracing row): cost analysis + memory
analysis wrappers used for MFU and HBM accounting."""

import jax
import jax.numpy as jnp

from torch_automatic_distributed_neural_network_tpu.utils.profiling import (
    compiled_flops,
    compiled_memory,
)


def test_compiled_flops_matmul(devices8):
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((64, 128))
    b = jnp.ones((128, 32))
    flops = compiled_flops(f, a, b)
    # 2*M*K*N = 2*64*128*32; cost analysis may add epsilon overhead
    assert flops is not None and flops >= 2 * 64 * 128 * 32


def test_compiled_memory_step(devices8):
    f = jax.jit(lambda x: (x @ x.T).sum())
    mem = compiled_memory(f, jnp.ones((256, 256)))
    assert mem is not None
    assert mem["argument_size"] == 256 * 256 * 4
    assert mem["temp_size"] > 0


def test_search_strategy_small_model_picks_dp(devices8):
    """strategy='search' on a model that trivially fits: the first ladder
    candidate (dp) must be accepted, with the measurement recorded."""
    import numpy as np
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad
    from torch_automatic_distributed_neural_network_tpu.models import GPT2
    from torch_automatic_distributed_neural_network_tpu.training import (
        next_token_loss,
    )

    ad = tad.AutoDistribute(
        GPT2("test", vocab_size=512, max_seq_len=64),
        optimizer=optax.adamw(1e-4),
        loss_fn=next_token_loss,
        strategy="search",
    )
    sample = {"tokens": np.zeros((8, 65), np.int32)}
    plan = ad.build_plan(jax.random.key(0), sample)
    assert plan.strategy == "dp"
    assert ad.search_report[0]["fits"] is True
    # and the searched plan trains
    state = ad.init(jax.random.key(0), sample)
    state, m = ad.step(state, sample)
    assert np.isfinite(float(m["loss"]))


def test_search_strategy_single_device_noop(devices8):
    """search on 1 device degrades to the no-op dp path and still leaves
    an (empty) search_report, per the documented contract."""
    import numpy as np
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad
    from torch_automatic_distributed_neural_network_tpu.models import GPT2
    from torch_automatic_distributed_neural_network_tpu.training import (
        next_token_loss,
    )

    ad = tad.AutoDistribute(
        GPT2("test", vocab_size=512, max_seq_len=64),
        optimizer=optax.adamw(1e-4),
        loss_fn=next_token_loss,
        strategy="search",
        devices=jax.devices()[:1],
    )
    sample = {"tokens": np.zeros((8, 65), np.int32)}
    plan = ad.build_plan(jax.random.key(0), sample)
    assert plan.strategy == "dp"
    assert ad.search_report == []


def test_search_strategy_escalates_on_memory(devices8):
    """strategy='search' must reject a candidate whose MEASURED peak
    exceeds the budget and escalate: GPT-2 large (774M) in fp32 is
    ~12.4 GiB of train state — over the 8 GiB cpu-sim budget for dp
    (replicated), under it for fsdp (ZeRO-3 over 8).  Abstract AOT
    compiles only; nothing is materialized."""
    import numpy as np
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad
    from torch_automatic_distributed_neural_network_tpu.models import GPT2
    from torch_automatic_distributed_neural_network_tpu.training import (
        next_token_loss,
    )

    ad = tad.AutoDistribute(
        GPT2("large", max_seq_len=64),
        optimizer=optax.adamw(1e-4),
        loss_fn=next_token_loss,
        strategy="search",
    )
    sample = {"tokens": np.zeros((8, 65), np.int32)}
    plan = ad.build_plan(jax.random.key(0), sample)
    assert plan.strategy != "dp"
    assert ad.search_report[0]["strategy"] == "dp"
    assert ad.search_report[0]["fits"] is False
    assert ad.search_report[-1]["fits"] is True


def test_search_strategy_moe_ladder(devices8):
    """MoE models search the expert ladder: the accepted entry is an
    ep-family strategy and error entries (if any) carry the same schema
    as measured ones (uniformly indexable report)."""
    import numpy as np
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad
    from torch_automatic_distributed_neural_network_tpu.models import MoE
    from torch_automatic_distributed_neural_network_tpu.training import (
        moe_next_token_loss,
    )

    ad = tad.AutoDistribute(
        MoE("test", vocab_size=256, max_seq_len=32),
        optimizer=optax.adamw(1e-4),
        loss_fn=moe_next_token_loss,
        strategy="search",
    )
    sample = {"tokens": np.zeros((8, 33), np.int32)}
    plan = ad.build_plan(jax.random.key(0), sample)
    assert plan.strategy.startswith("ep")
    for entry in ad.search_report:
        assert {"strategy", "remat", "peak_bytes", "budget_bytes",
                "fits", "flops"} <= set(entry)
    assert ad.search_report[-1]["fits"] is True


def test_compile_report_abstract_only(devices8):
    """compile_report AOT-compiles the sharded step without materializing
    any state (the memfit path, bench.py mode=memfit / BASELINE.md row 4):
    per-device argument bytes must reflect the fsdp=8 shard, not the full
    model."""
    import numpy as np
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad
    from torch_automatic_distributed_neural_network_tpu.models import GPT2
    from torch_automatic_distributed_neural_network_tpu.training import (
        next_token_loss,
    )

    ad = tad.AutoDistribute(
        GPT2("test", vocab_size=512, max_seq_len=64),
        optimizer=optax.adamw(1e-4),
        loss_fn=next_token_loss,
        strategy="fsdp",
        precision="mixed",
    )
    sample = {"tokens": np.zeros((8, 65), np.int32)}
    report = ad.compile_report(jax.random.key(0), sample)
    assert report is not None
    assert report["per_device_peak_bytes"] > 0
    n_params = ad.model.cfg.num_params()
    # mixed precision state: fp32 master + bf16 moments = 8 B/param, all
    # fsdp-sharded 8 ways; argument_size is per-device and must sit well
    # under the unsharded total (padding/replicated odds allow 2x the
    # ideal shard but not the full tree)
    per_dev = report["memory"]["argument_size"]
    assert per_dev < (8 * n_params) / 8 * 2 + 2**20
    # the step must still run after the report (init path unaffected)
    state = ad.init(jax.random.key(0), sample)
    state, m = ad.step(state, sample)
    assert np.isfinite(float(m["loss"]))
