"""Profiling helpers (SURVEY.md §5 tracing row): cost analysis + memory
analysis wrappers used for MFU and HBM accounting."""

import jax
import jax.numpy as jnp

from torch_automatic_distributed_neural_network_tpu.utils.profiling import (
    compiled_flops,
    compiled_memory,
)


def test_compiled_flops_matmul(devices8):
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((64, 128))
    b = jnp.ones((128, 32))
    flops = compiled_flops(f, a, b)
    # 2*M*K*N = 2*64*128*32; cost analysis may add epsilon overhead
    assert flops is not None and flops >= 2 * 64 * 128 * 32


def test_compiled_memory_step(devices8):
    f = jax.jit(lambda x: (x @ x.T).sum())
    mem = compiled_memory(f, jnp.ones((256, 256)))
    assert mem is not None
    assert mem["argument_size"] == 256 * 256 * 4
    assert mem["temp_size"] > 0
