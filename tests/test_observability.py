"""Observability layer tests (obs/): journal spans, recompile
accounting, analytic comm bytes, goodput bucketing, and the
`tadnn report` join over a real CPU-sim training run."""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu import cli
from torch_automatic_distributed_neural_network_tpu.models import MLP
from torch_automatic_distributed_neural_network_tpu.obs import (
    GoodputMeter,
    Journal,
)
from torch_automatic_distributed_neural_network_tpu.obs import (
    journal as obs_journal,
)
from torch_automatic_distributed_neural_network_tpu.obs import (
    report as obs_report,
)
from torch_automatic_distributed_neural_network_tpu.planner import (
    expected_collective_bytes,
)
from torch_automatic_distributed_neural_network_tpu.training import (
    MetricsLogger,
    softmax_xent_loss,
)


def toy_batch(seed=0, batch=16, dim=8, classes=10):
    rng = np.random.RandomState(seed)
    return {
        "x": jnp.asarray(rng.randn(batch, dim), jnp.float32),
        "label": jnp.asarray(rng.randint(0, classes, size=(batch,))),
    }


def make_ad(strategy="dp", **kw):
    return tad.AutoDistribute(
        MLP(features=(32, 16, 10)),
        optimizer=optax.sgd(0.1),
        loss_fn=softmax_xent_loss,
        strategy=strategy,
        **kw,
    )


# -- journal ----------------------------------------------------------------


def test_journal_span_nesting_and_timing(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path, meta={"run": "t"})
    j.event("hello", x=1)
    with j.span("outer"):
        with j.span("inner", tag="a"):
            pass
    j.close()
    recs = Journal.read(path)
    by_name = {r["name"]: r for r in recs}
    assert recs[0]["name"] == "journal.start" and recs[0]["run"] == "t"
    assert by_name["hello"]["x"] == 1
    # inner span closes (and writes) first; depth records the nesting
    assert [r["name"] for r in recs[-2:]] == ["inner", "outer"]
    assert by_name["outer"]["depth"] == 0
    assert by_name["inner"]["depth"] == 1
    assert 0 <= by_name["inner"]["dur_s"] <= by_name["outer"]["dur_s"]
    for r in recs:
        assert "t" in r and "wall" in r


def test_journal_span_records_error(tmp_path):
    j = Journal()  # in-memory
    with pytest.raises(ValueError):
        with j.span("boom"):
            raise ValueError("bad")
    assert j.records[-1]["name"] == "boom"
    assert "ValueError: bad" in j.records[-1]["error"]


def test_default_journal_is_noop_and_restorable():
    obs_journal.set_default(None)
    os.environ.pop("TADNN_JOURNAL", None)
    assert obs_journal.event("x") is None  # null sink: no crash, no record
    j = Journal()
    with obs_journal.as_default(j):
        obs_journal.event("inside")
    obs_journal.event("outside")
    assert [r["name"] for r in j.records] == ["journal.start", "inside"]


# -- recompile accounting ---------------------------------------------------


def test_recompile_counter_flat_then_trips_on_shape_change():
    ad = make_ad()
    j = Journal()
    with obs_journal.as_default(j):
        state = ad.init(jax.random.key(0), toy_batch())
        for i in range(4):  # steady state: same signature, no recompiles
            state, _ = ad.step(state, toy_batch(seed=i))
        assert ad.n_compiles == 1
        assert ad.recompile_count == 0
        state, _ = ad.step(state, toy_batch(batch=8))  # new shape
    assert ad.recompile_count == 1
    assert ad.n_compiles == 2
    events = [r["name"] for r in j.records]
    assert events.count("compile") == 1
    assert events.count("recompile") == 1
    recompile = next(r for r in j.records if r["name"] == "recompile")
    assert recompile["fn"] == "train_step"
    assert "[8" in recompile["signature"]  # the offending batch shape
    assert recompile["dur_s"] > 0


# -- comm accounting --------------------------------------------------------


def test_dp_allreduce_bytes_match_param_bytes():
    ad = make_ad("dp")
    batch = toy_batch()
    ad.build_plan(jax.random.key(0), batch)
    abstract = jax.eval_shape(
        lambda r: ad._split_variables(ad._init_variables(r, batch))[0],
        jax.random.key(0),
    )
    est = expected_collective_bytes(ad.plan, abstract)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(abstract))
    param_bytes = 4 * n_params  # fp32 grads
    ar = est["per_device"]["grad_allreduce"]
    assert ar["payload_bytes"] == param_bytes
    # ring allreduce wire cost: 2(n-1)/n of the payload
    n = 8
    assert ar["wire_bytes"] == pytest.approx(
        param_bytes * 2 * (n - 1) / n)
    assert est["per_device"]["param_allgather"]["payload_bytes"] == 0
    assert est["total_wire_bytes"] == ar["wire_bytes"]


def test_fsdp_gathers_params_and_scatters_grads():
    ad = make_ad("fsdp")
    batch = toy_batch()
    ad.build_plan(jax.random.key(0), batch)
    abstract = jax.eval_shape(
        lambda r: ad._split_variables(ad._init_variables(r, batch))[0],
        jax.random.key(0),
    )
    est = expected_collective_bytes(ad.plan, abstract)
    per = est["per_device"]
    # ZeRO-3: params gathered fwd+bwd, grads reduce-scattered; leaves the
    # planner leaves replicated (small biases) still allreduce
    assert per["param_allgather"]["payload_bytes"] > 0
    assert per["grad_reduce_scatter"]["payload_bytes"] > 0
    # fwd+bwd gather = 2x the scattered grad bytes for fp32-everywhere
    assert per["param_allgather"]["payload_bytes"] == pytest.approx(
        2 * per["grad_reduce_scatter"]["payload_bytes"])


def test_grad_accum_multiplies_grad_collectives():
    ad = make_ad("dp")
    batch = toy_batch()
    ad.build_plan(jax.random.key(0), batch)
    abstract = jax.eval_shape(
        lambda r: ad._split_variables(ad._init_variables(r, batch))[0],
        jax.random.key(0),
    )
    e1 = expected_collective_bytes(ad.plan, abstract, grad_accum=1)
    e4 = expected_collective_bytes(ad.plan, abstract, grad_accum=4)
    assert e4["per_device"]["grad_allreduce"]["payload_bytes"] == \
        4 * e1["per_device"]["grad_allreduce"]["payload_bytes"]


# -- goodput ----------------------------------------------------------------


def test_goodput_fractions_sum_to_one():
    m = GoodputMeter()
    m.add("compile", 1.0)
    m.add("step", 3.0)
    with m.measure("checkpoint"):
        pass
    s = m.summary(total_wall_s=5.0)
    assert s["seconds"]["compile"] == 1.0
    assert s["seconds"]["idle"] == pytest.approx(
        5.0 - sum(v for k, v in s["seconds"].items() if k != "idle"))
    assert sum(s["fractions"].values()) == pytest.approx(1.0)
    assert s["goodput"] == pytest.approx(3.0 / 5.0)


def test_goodput_idle_clamped_nonnegative():
    m = GoodputMeter()
    m.add("step", 2.0)
    s = m.summary(total_wall_s=1.0)  # buckets exceed claimed wall
    assert s["seconds"]["idle"] == 0.0


# -- metrics satellites -----------------------------------------------------


def test_metrics_close_idempotent_and_context_manager(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path, console=False) as m:
        m.start_step()
        m.log_step(0, {"loss": 1.0}, 16)
    m.close()  # second close: no crash
    m.log_step(1, {"loss": 0.5}, 16)  # post-close logs don't raise
    recs = [json.loads(l) for l in open(path)]
    assert len(recs) == 1 and recs[0]["loss"] == 1.0


def test_metrics_warns_once_per_dropped_key(tmp_path):
    m = MetricsLogger(str(tmp_path / "m.jsonl"), console=False)
    m.start_step()
    bad = {"loss": 1.0, "histogram": np.zeros((4, 4))}
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m.log_step(0, bad, 16)
        m.log_step(1, bad, 16)  # second drop of the same key: silent
        m.log_eval(2, {"other": "text"})
    msgs = [str(x.message) for x in w
            if "MetricsLogger" in str(x.message)]
    assert len(msgs) == 2
    assert any("'histogram'" in s for s in msgs)
    assert any("'other'" in s for s in msgs)
    m.close()


# -- compiled_cost error plumbing ------------------------------------------


def test_compiled_cost_attaches_failure_reason():
    from torch_automatic_distributed_neural_network_tpu.utils import (
        profiling,
    )

    def broken(x):
        raise TypeError("tracing exploded")

    j = Journal()
    with obs_journal.as_default(j):
        cost = profiling.compiled_cost(jax.jit(broken), jnp.zeros(3))
    assert cost["flops"] is None
    assert "TypeError: tracing exploded" in cost["error"]
    errs = [r for r in j.records if r["name"] == "cost_analysis.error"]
    assert len(errs) == 1 and "tracing exploded" in errs[0]["error"]
    assert profiling.compiled_flops(jax.jit(broken), jnp.zeros(3)) is None


# -- end-to-end: Trainer run -> artifacts -> report ------------------------


@pytest.fixture(scope="module")
def observed_run(tmp_path_factory):
    """One real CPU-sim Trainer run leaving journal + metrics behind."""
    from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
        SyntheticClassification,
    )
    from torch_automatic_distributed_neural_network_tpu.training import (
        Trainer,
        TrainerConfig,
    )

    out = tmp_path_factory.mktemp("obsrun")
    ad = make_ad("dp")
    journal = Journal(str(out / "journal.jsonl"))
    metrics = MetricsLogger(str(out / "metrics.jsonl"), console=False)
    trainer = Trainer(
        ad,
        TrainerConfig(steps=8, log_every=2),
        metrics=metrics,
        items_per_step=16,
        journal=journal,
    )
    trainer.fit(SyntheticClassification(batch_size=16))
    journal.close()
    return {"dir": str(out), "ad": ad, "trainer": trainer}


def test_run_emits_goodput_that_sums(observed_run):
    gp = observed_run["trainer"].goodput
    assert gp is not None
    assert sum(gp["fractions"].values()) == pytest.approx(1.0, abs=1e-6)
    assert gp["seconds"]["step"] > 0
    assert gp["seconds"]["compile"] > 0  # init trace+compile was bucketed


def test_report_joins_journal_and_metrics(observed_run):
    rep = obs_report.generate(observed_run["dir"])
    assert rep["compile"]["count"] >= 1
    assert rep["compile"]["recompile_count"] == 0  # fixed-shape pipeline
    assert sum(rep["goodput"]["fractions"].values()) == pytest.approx(
        1.0, abs=1e-6)
    # analytic dp comm bytes made it into the artifacts
    per = rep["comms"]["per_device"]
    expected = observed_run["ad"].comm_profile
    assert expected and "error" not in expected
    assert per["grad_allreduce"] == \
        expected["per_device"]["grad_allreduce"]["payload_bytes"]
    assert per["grad_allreduce"] > 0
    tr = rep["training"]
    assert tr["n_step_records"] >= 3
    assert tr["last_step"] == 7
    assert tr["final_loss"] is not None
    text = obs_report.format_report(rep)
    assert "recompiles: 0" in text
    assert "goodput:" in text
    assert "grad_allreduce" in text


def test_report_cli_human_and_json(observed_run, capsys):
    assert cli.main(["report", observed_run["dir"]]) == 0
    text = capsys.readouterr().out
    assert "compiles:" in text and "goodput:" in text
    assert cli.main(["report", observed_run["dir"], "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["compile"]["recompile_count"] == 0
    assert rep["comms"]["per_device"]["grad_allreduce"] > 0


def test_report_missing_journal_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        obs_report.generate(str(tmp_path))
