"""Resilience-layer tests (SURVEY.md §5): checkpoint integrity +
fallback chain, restart policy backoff/budget, anomaly rollback, and
the deterministic chaos harness (kill-and-resume on the CPU sim)."""

import os
import shutil
import signal
import threading
import time

import jax
import numpy as np
import optax
import pytest

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu import cli
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticClassification,
)
from torch_automatic_distributed_neural_network_tpu.models import MLP
from torch_automatic_distributed_neural_network_tpu.obs import Journal
from torch_automatic_distributed_neural_network_tpu.obs import (
    journal as obs_journal,
)
from torch_automatic_distributed_neural_network_tpu.training import (
    AnomalyConfig,
    ChaosData,
    ChaosInjector,
    ChaosPlan,
    CheckpointManager,
    FaultInjector,
    Heartbeat,
    InjectedFault,
    PreemptionGuard,
    RestartPolicy,
    StallError,
    Trainer,
    TrainerConfig,
    run_with_recovery,
    softmax_xent_loss,
    tear_checkpoint,
    verify_directory,
)
from torch_automatic_distributed_neural_network_tpu.training import (
    resilience,
)


def make_data(**kw):
    return SyntheticClassification(image_shape=(8,), num_classes=10,
                                   batch_size=16, **kw)


def make_trainer(ckpt_dir, steps, *, callbacks=None, journal=None,
                 anomaly=None, **cfg_kw):
    ad = tad.AutoDistribute(
        MLP(features=(32, 10)),
        optimizer=optax.adam(1e-2),
        loss_fn=softmax_xent_loss,
        strategy="dp",
    )
    ckpt = CheckpointManager(str(ckpt_dir), save_interval_steps=0)
    return Trainer(
        ad,
        TrainerConfig(steps=steps, log_every=0, ckpt_every=2,
                      anomaly=anomaly, **cfg_kw),
        ckpt=ckpt,
        callbacks=callbacks,
        journal=journal,
    )


def leaves(state):
    return [np.asarray(x) for x in jax.tree.leaves(state.params)]


def events(journal, name):
    return [r for r in journal.records if r.get("name") == name]


# -- integrity manifest -------------------------------------------------------


def test_manifest_roundtrip_and_bitflip_detection(tmp_path):
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": {"c": np.ones(4, dtype=np.int32)}}
    d = str(tmp_path)
    resilience.write_manifest(d, 7, tree)
    man = resilience.read_manifest(d, 7)
    assert man["step"] == 7
    assert resilience.verify_tree(tree, man) == []
    tree["a"][0, 0] += 1.0  # single bit-ish flip
    problems = resilience.verify_tree(tree, man)
    assert problems and "checksum mismatch at a" in problems[0]
    # structural drift is also caught
    del tree["b"]
    assert any("missing leaf" in p for p in
               resilience.verify_tree(tree, man))


def test_save_writes_manifest_and_restore_verifies(devices8, tmp_path):
    j = Journal()
    trainer = make_trainer(tmp_path / "ck", 4, journal=j)
    state = trainer.fit(make_data())
    trainer.ckpt.close()
    assert int(state.step) == 4
    assert os.path.exists(resilience.manifest_path(str(tmp_path / "ck"), 4))
    # fresh run: no restore happened; resume and check verification runs
    j2 = Journal()
    trainer2 = make_trainer(tmp_path / "ck", 4, journal=j2)
    state2 = trainer2.fit(make_data())
    trainer2.ckpt.close()
    restores = [r for r in j2.records if r.get("name") == "ckpt.restore"]
    assert restores and restores[0].get("verified") is True
    for a, b in zip(leaves(state), leaves(state2)):
        np.testing.assert_array_equal(a, b)


# -- fallback chain (acceptance: torn latest -> bitwise parity) ---------------


def test_corrupt_latest_falls_back_bitwise(devices8, tmp_path):
    """Torn checkpoint at the latest step: restore_or_init quarantines
    it, resumes from the newest intact step, and the resumed run's
    final params match an uninterrupted run BITWISE (step-indexed
    data)."""
    steps = 8
    data = make_data()

    # uninterrupted oracle
    t0 = make_trainer(tmp_path / "a", steps)
    final_a = t0.fit(data)
    t0.ckpt.close()

    # killed at step 5 (checkpoints at 2 and 4 committed)
    t1 = make_trainer(tmp_path / "b", steps,
                      callbacks=[FaultInjector(at_step=5)])
    with pytest.raises(InjectedFault):
        t1.fit(data)
    assert t1.ckpt.latest_step() == 4
    t1.ckpt.close()

    # tear the latest step — a partial write during preemption
    assert tear_checkpoint(str(tmp_path / "b"), 4) > 0

    j = Journal()
    t2 = make_trainer(tmp_path / "b", steps, journal=j)
    final_b = t2.fit(data)
    t2.ckpt.close()

    corrupt = events(j, "ckpt.corrupt")
    assert corrupt and corrupt[0]["step"] == 4
    assert os.path.isdir(str(tmp_path / "b" / "4.corrupt"))
    assert int(final_b.step) == steps
    for a, b in zip(leaves(final_a), leaves(final_b)):
        np.testing.assert_array_equal(a, b)


def test_all_corrupt_falls_back_to_fresh_init(devices8, tmp_path):
    t1 = make_trainer(tmp_path / "c", 4)
    t1.fit(make_data())
    t1.ckpt.close()
    for step in (2, 4):
        tear_checkpoint(str(tmp_path / "c"), step)
    j = Journal()
    t2 = make_trainer(tmp_path / "c", 4, journal=j)
    state = t2.fit(make_data())
    t2.ckpt.close()
    assert int(state.step) == 4
    assert len(events(j, "ckpt.corrupt")) == 2
    runs = events(j, "run_start")
    assert runs and runs[0]["resumed"] is False


# -- restart policy -----------------------------------------------------------


def test_restart_policy_backoff_deterministic_jitter():
    p1 = RestartPolicy(backoff_base_s=1.0, backoff_factor=2.0,
                       backoff_max_s=60.0, jitter=0.1, seed=7)
    p2 = RestartPolicy(backoff_base_s=1.0, backoff_factor=2.0,
                       backoff_max_s=60.0, jitter=0.1, seed=7)
    d1 = [p1.delay_s(n) for n in range(1, 6)]
    assert d1 == [p2.delay_s(n) for n in range(1, 6)]  # deterministic
    for n, d in enumerate(d1, start=1):
        base = min(1.0 * 2.0 ** (n - 1), 60.0)
        assert base * 0.9 <= d <= base * 1.1  # exponential envelope
    assert d1[1] > d1[0] and d1[2] > d1[1]
    p3 = RestartPolicy(backoff_base_s=1.0, jitter=0.1, seed=8)
    assert p3.delay_s(1) != p1.delay_s(1)  # seed moves the jitter
    # capped at backoff_max_s (+jitter)
    assert p1.delay_s(30) <= 60.0 * 1.1


def test_restart_policy_budget_and_journal(tmp_path):
    """Backoff schedule + rolling-window budget exhaustion, asserted via
    the journal's elastic.restart attempts/delays (acceptance)."""
    sleeps = []
    policy = RestartPolicy(max_restarts=3, window_s=1e9,
                           backoff_base_s=1.0, backoff_factor=2.0,
                           backoff_max_s=60.0, jitter=0.1, seed=5,
                           sleep=sleeps.append)

    def always_fail():
        raise RuntimeError("boom")

    j = Journal()
    with obs_journal.as_default(j):
        with pytest.raises(RuntimeError):
            run_with_recovery(always_fail, policy=policy,
                              on_restart=lambda n, e: None)
    recs = events(j, "elastic.restart")
    assert [r["attempt"] for r in recs] == [1, 2, 3, 4]
    assert [r["gave_up"] for r in recs] == [False, False, False, True]
    # the journaled delays are exactly the policy's deterministic schedule
    assert [r["delay_s"] for r in recs[:3]] == [policy.delay_s(n)
                                                for n in (1, 2, 3)]
    assert sleeps == [policy.delay_s(n) for n in (1, 2, 3)]
    assert sleeps[1] > sleeps[0] and sleeps[2] > sleeps[1]


def test_restart_policy_rolling_window_forgives_old_failures():
    now = [0.0]
    policy = RestartPolicy(max_restarts=2, window_s=100.0,
                           backoff_base_s=0.0, clock=lambda: now[0])
    calls = [0]

    def flaky():
        calls[0] += 1
        now[0] += 200.0  # each failure lands in a fresh window
        if calls[0] <= 5:
            raise RuntimeError("transient")
        return "done"

    # 5 failures but never >2 inside any 100s window: budget never trips
    assert run_with_recovery(flaky, policy=policy,
                             on_restart=lambda n, e: None) == "done"
    assert calls[0] == 6


def test_restart_policy_real_backoff_timestamps():
    """Journal wall-clock gaps actually observe the backoff sleeps."""
    policy = RestartPolicy(max_restarts=2, backoff_base_s=0.08,
                           backoff_factor=2.0, jitter=0.0)
    calls = [0]

    def fail_twice():
        calls[0] += 1
        if calls[0] <= 2:
            raise RuntimeError("boom")
        return calls[0]

    j = Journal()
    with obs_journal.as_default(j):
        assert run_with_recovery(fail_twice, policy=policy,
                                 on_restart=lambda n, e: None) == 3
    recs = events(j, "elastic.restart")
    assert len(recs) == 2
    gap = recs[1]["t"] - recs[0]["t"]
    assert gap >= 0.08 * 0.8  # first backoff sleep separates the attempts


# -- anomaly rollback ---------------------------------------------------------


def test_anomaly_guard_stats():
    g = resilience.AnomalyGuard(AnomalyConfig(min_history=4,
                                              spike_sigma=6.0))
    for i in range(8):
        assert g.check(1.0 + 0.01 * (i % 3)) is None
    assert g.check(float("nan")) == "non-finite"
    assert g.check(50.0) == "spike"
    assert g.check(1.01) is None  # anomalies were not admitted to stats


def test_anomaly_rollback_skips_bad_batch(devices8, tmp_path):
    """NaN batch at index 5: the guard rolls back to the last verified
    checkpoint (step 4) and skips the offending window; the run
    completes deterministically (two runs agree bitwise)."""
    plan = ChaosPlan(nan_at=(5,))

    def run(sub):
        j = Journal()
        trainer = make_trainer(tmp_path / sub, 8, journal=j,
                               anomaly=AnomalyConfig(min_history=2))
        state = trainer.fit(ChaosData(make_data(), plan))
        trainer.ckpt.close()
        return state, j

    state, j = run("a")
    assert int(state.step) == 8
    rb = events(j, "resilience.rollback")
    assert len(rb) == 1
    assert rb[0]["reason"] == "non-finite"
    assert rb[0]["at_step"] == 6 and rb[0]["to_step"] == 4
    assert rb[0]["skipped_batches"] == 2
    assert all(np.isfinite(x).all() for x in leaves(state))

    state2, _ = run("b")
    for a, b in zip(leaves(state), leaves(state2)):
        np.testing.assert_array_equal(a, b)


def test_anomaly_rollback_budget_exhausted(devices8, tmp_path):
    # every batch after step 4 is poisoned; one rollback is allowed,
    # the second anomaly must surface as the legacy crash
    plan = ChaosPlan(nan_at=tuple(range(5, 40)))
    trainer = make_trainer(tmp_path / "x", 8,
                           anomaly=AnomalyConfig(min_history=2,
                                                 max_rollbacks=1))
    with pytest.raises(FloatingPointError, match="budget exhausted"):
        trainer.fit(ChaosData(make_data(), plan))
    trainer.ckpt.close()


def test_anomaly_without_checkpoint_raises(devices8):
    plan = ChaosPlan(nan_at=(2,))
    ad = tad.AutoDistribute(MLP(features=(32, 10)),
                            optimizer=optax.adam(1e-2),
                            loss_fn=softmax_xent_loss, strategy="dp")
    trainer = Trainer(ad, TrainerConfig(steps=4, log_every=0,
                                        anomaly=AnomalyConfig(
                                            min_history=1)))
    with pytest.raises(FloatingPointError, match="no rollback path"):
        trainer.fit(ChaosData(make_data(), plan))


# -- chaos harness ------------------------------------------------------------


def test_chaos_plan_deterministic():
    p = ChaosPlan(seed=3, p_exception=0.3)
    fires = [p.fires("exception", s) for s in range(50)]
    assert fires == [ChaosPlan(seed=3, p_exception=0.3)
                     .fires("exception", s) for s in range(50)]
    assert any(fires) and not all(fires)
    assert fires != [ChaosPlan(seed=4, p_exception=0.3)
                     .fires("exception", s) for s in range(50)]
    assert ChaosPlan(stall_at=(7,)).fires("stall", 7)
    assert not ChaosPlan(stall_at=(7,)).fires("stall", 8)


@pytest.mark.slow
def test_chaos_kill_and_resume_end_to_end(devices8, tmp_path):
    """The long chaos loop: injected step exceptions AND a torn
    checkpoint in one run, recovered under a RestartPolicy — final
    params bitwise-match the uninterrupted oracle."""
    steps = 12
    data = make_data()

    t0 = make_trainer(tmp_path / "oracle", steps)
    final_a = t0.fit(data)
    t0.ckpt.close()

    j = Journal()
    trainer = make_trainer(tmp_path / "chaos", steps, journal=j)
    plan = ChaosPlan(seed=1, exception_at=(3, 7), torn_ckpt_at=(6,))
    injector = ChaosInjector(plan, ckpt=trainer.ckpt)
    trainer.callbacks.append(injector)
    policy = RestartPolicy(max_restarts=5, window_s=600.0,
                           backoff_base_s=0.01, backoff_max_s=0.05,
                           seed=2)
    with obs_journal.as_default(j):
        final_b = run_with_recovery(lambda: trainer.fit(data),
                                    policy=policy,
                                    on_restart=lambda n, e: None)
    trainer.ckpt.close()

    assert int(final_b.step) == steps
    assert len(events(j, "elastic.restart")) == 2  # the two exceptions
    assert len(events(j, "resilience.chaos")) == 3
    assert events(j, "ckpt.corrupt")  # torn step 6 was quarantined
    for a, b in zip(leaves(final_a), leaves(final_b)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_chaos_stall_escalates_to_restart(devices8, tmp_path):
    """A stalled step: the watchdog escalates StallError into the
    training thread; run_with_recovery restarts and the run completes."""
    data = make_data()
    j = Journal()
    trainer = make_trainer(tmp_path / "stall", 8, journal=j,
                           watchdog_timeout_s=0.3, watchdog_escalate=True)
    plan = ChaosPlan(stall_at=(4,), stall_s=1.5)
    trainer.callbacks.append(ChaosInjector(plan))
    with obs_journal.as_default(j):
        state = run_with_recovery(
            lambda: trainer.fit(data),
            policy=RestartPolicy(max_restarts=3, backoff_base_s=0.0),
            on_restart=lambda n, e: None,
        )
    trainer.ckpt.close()
    assert int(state.step) == 8
    assert events(j, "resilience.stall_escalation")
    restarts = events(j, "elastic.restart")
    assert restarts and "StallError" in restarts[0]["error"]


def test_stall_escalator_raises_in_training_thread():
    trainer = Trainer(None, TrainerConfig(watchdog_timeout_s=1.0))
    escalate = trainer._stall_escalator()  # bound to this thread
    threading.Timer(0.2, escalate, args=(9.9,)).start()
    with pytest.raises(StallError):
        for _ in range(200):  # async exc lands on a bytecode boundary
            time.sleep(0.05)


# -- doctor CLI ---------------------------------------------------------------


def test_doctor_healthy_prints_chain(devices8, tmp_path, capsys):
    trainer = make_trainer(tmp_path / "ok", 4)
    trainer.fit(make_data())
    trainer.ckpt.close()
    rc = cli.main(["doctor", str(tmp_path / "ok")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fallback chain" in out and "ok, verified" in out
    assert "resume from step 4" in out


def test_doctor_corrupt_only_exits_nonzero(devices8, tmp_path, capsys):
    trainer = make_trainer(tmp_path / "bad", 2)
    trainer.fit(make_data())
    trainer.ckpt.close()
    tear_checkpoint(str(tmp_path / "bad"), 2)
    rc = cli.main(["doctor", str(tmp_path / "bad")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "CORRUPT" in out and "NO restorable step" in out
    # empty directory is nonzero too
    os.makedirs(tmp_path / "empty")
    assert cli.main(["doctor", str(tmp_path / "empty")]) == 1


def test_verify_directory_mixed(devices8, tmp_path):
    trainer = make_trainer(tmp_path / "mix", 4)
    trainer.fit(make_data())
    trainer.ckpt.close()
    tear_checkpoint(str(tmp_path / "mix"), 4)
    rep = verify_directory(str(tmp_path / "mix"))
    assert rep["healthy"] and rep["best_step"] == 2
    verdicts = {v["step"]: v["ok"] for v in rep["steps"]}
    assert verdicts == {4: False, 2: True}


# -- satellites ---------------------------------------------------------------


def test_heartbeat_stop_survives_torn_down_dir(tmp_path):
    d = str(tmp_path / "beats")
    hb = Heartbeat(d, interval_s=5.0, host_index=0).start()
    shutil.rmtree(d)
    hb.stop()  # must not raise: final best-effort beat into a dead dir


def test_data_exhausted_mid_run_saves_and_returns(devices8, tmp_path):
    data = make_data()
    batches = [data.batch(i) for i in range(3)]
    j = Journal()
    trainer = make_trainer(tmp_path / "ex", 8, journal=j)
    state = trainer.fit(iter(batches))
    assert trainer.ckpt.latest_step() == 3
    trainer.ckpt.close()
    assert int(state.step) == 3
    ex = events(j, "data_exhausted")
    assert ex and ex[0]["step"] == 3 and ex[0]["saved"] is True


def test_empty_iterator_raises_value_error(devices8, tmp_path):
    trainer = make_trainer(tmp_path / "empty", 4)
    with pytest.raises(ValueError, match="data is empty"):
        trainer.fit(iter([]))
    trainer.ckpt.close()


def test_preemption_guard_chains_previous_handler():
    seen = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: seen.append(s))
    try:
        guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
        os.kill(os.getpid(), signal.SIGUSR1)
        # handler runs synchronously in the main thread on kill return
        assert guard.requested
        assert seen == [signal.SIGUSR1]  # outer supervisor still notified
        guard.uninstall()
    finally:
        signal.signal(signal.SIGUSR1, prev)


def test_report_renders_resilience_incidents(tmp_path, capsys):
    j = Journal(str(tmp_path / "journal.jsonl"))
    j.event("ckpt.corrupt", step=4, reason="ValueError: torn write")
    j.event("resilience.rollback", reason="non-finite", loss=float("inf"),
            at_step=6, to_step=4, skipped_batches=2)
    j.event("resilience.chaos", kind="exception", step=3)
    j.event("resilience.stall_escalation", age_s=12.0, timeout_s=5.0)
    j.event("data_exhausted", step=7, saved=True)
    j.event("elastic.restart", attempt=1, max_restarts=2,
            window_failures=1, delay_s=1.0,
            error="ChaosFault: chaos", gave_up=False)
    j.close()
    rc = cli.main(["report", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 corrupt checkpoints" in out
    assert "1 anomaly rollbacks" in out
    assert "1 chaos faults" in out
    assert "1 stall escalations" in out
    assert "1 data exhaustions" in out
    assert "1 elastic restarts" in out
    assert "ckpt.corrupt step 4" in out
    assert "rollback (non-finite): step 6 -> 4, skipped 2 batch(es)" in out


def test_restore_config_failure_is_journaled_not_fatal(devices8, tmp_path):
    ckpt_dir = tmp_path / "cfg"
    trainer = make_trainer(ckpt_dir, 2)
    trainer.fit(make_data())
    trainer.ckpt.close()
    # tear only the config item
    cfg_dir = ckpt_dir / "2" / "config"
    assert cfg_dir.is_dir()
    for dirpath, _, files in os.walk(cfg_dir):
        for name in files:
            with open(os.path.join(dirpath, name), "r+b") as f:
                f.truncate(1)
    j = Journal()
    ckpt = CheckpointManager(str(ckpt_dir))
    with obs_journal.as_default(j):
        assert ckpt.restore_config() is None
    ckpt.close()
    fails = events(j, "ckpt.restore_config_failed")
    assert fails and fails[0]["step"] == 2 and "Error" in fails[0]["error"]
