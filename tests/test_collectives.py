

def test_overlap_bench_runs(devices8):
    """C4 overlap microbench: fields are consistent; no overlap claim is
    made on the CPU sim (shared host cores), only that the measurement
    machinery works."""
    from torch_automatic_distributed_neural_network_tpu.parallel.collectives import (
        bench_overlap,
    )

    r = bench_overlap(d=128, layers=3, bucket_bytes=2**16, iters=2, warmup=1)
    assert r.n_devices == 8
    assert r.t_compute_s > 0 and r.t_comm_s > 0 and r.t_both_s > 0
    assert -1.0 <= r.overlap_frac <= 1.0

def test_broadcast_delivers_root_shard(devices8):
    """broadcast: every shard receives the root shard's value (all_gather
    + root-slice formulation, half the wire cost of a masked psum)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial
    from torch_automatic_distributed_neural_network_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    import torch_automatic_distributed_neural_network_tpu as tad
    from torch_automatic_distributed_neural_network_tpu.parallel.collectives import (
        broadcast,
    )

    mesh = tad.build_mesh(data=8)
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)

    @partial(shard_map, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
             check_vma=False)
    def run(shard):
        return broadcast(shard, "data", root=3)

    out = np.asarray(run(x))
    # every device's output row equals root device 3's input row
    for i in range(8):
        np.testing.assert_array_equal(out[i], np.asarray(x)[3])
