"""Hang-proofing contract for the driver entrypoints (VERDICT r3 item #1).

The round-3 MULTICHIP artifact went red (rc=124) because a process on the
driver path initialized the unreachable axon TPU backend and wedged, even
though the dryrun itself passes on the CPU sim.  These tests pin the two
properties that prevent a recurrence:

1. importing ``__graft_entry__`` and running its parent-side dryrun
   machinery touches nothing heavier than the stdlib (no ``jax`` import,
   so no backend init can ever happen before the CPU-sim re-exec);
2. ``entry()`` probes the backend out-of-process and falls back to
   XLA:CPU instead of hanging when the probe fails.
"""

import os
import subprocess
import sys

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, _REPO_ROOT)
import __graft_entry__  # noqa: E402


def test_parent_path_imports_no_jax():
    """The dryrun parent must not import jax (backend-init hang vector).

    Run in a pristine subprocess (this test process already has jax
    loaded): import the module, build the re-exec env, and assert jax
    never entered sys.modules.  The axon sitecustomize imports jax at
    interpreter start in EVERY child process, so it must be dropped from
    PYTHONPATH here to observe what __graft_entry__ itself pulls in.
    """
    code = (
        "import sys; sys.path.insert(0, {root!r});\n"
        "import __graft_entry__\n"
        "env = __graft_entry__._cpu_sim_env(4)\n"
        "assert 'jax' not in sys.modules, 'parent path imported jax'\n"
        "assert 'torch_automatic_distributed_neural_network_tpu' not in "
        "sys.modules, 'parent path imported the package'\n"
        "print('clean')"
    ).format(root=_REPO_ROOT)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "clean" in proc.stdout


def test_cpu_sim_env_strips_axon_and_forces_cpu():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        ["/root/.axon_site", "/keep/me"])
    env["JAX_PLATFORMS"] = "axon"
    env["XLA_FLAGS"] = "--foo --xla_force_host_platform_device_count=2"
    old = os.environ.copy()
    os.environ.clear()
    os.environ.update(env)
    try:
        child = __graft_entry__._cpu_sim_env(8)
    finally:
        os.environ.clear()
        os.environ.update(old)
    assert "axon" not in child.get("PYTHONPATH", "")
    assert "/keep/me" in child["PYTHONPATH"]
    assert child["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=8" in child["XLA_FLAGS"]
    assert "--foo" in child["XLA_FLAGS"]
    assert child["XLA_FLAGS"].count("device_count") == 1


def test_entry_probe_failure_falls_back_to_cpu(monkeypatch):
    """With the tunnel 'down', entry() must return promptly on XLA:CPU."""
    monkeypatch.setattr(
        __graft_entry__, "_probe_backend",
        lambda timeout_s=120: "backend init hung > 120s (simulated)",
    )
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    fn, args = __graft_entry__.entry()
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    out = fn(*args)
    assert out.shape[0] == 2  # [batch, seq, vocab] logits


def test_probe_backend_short_circuits_on_cpu(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert __graft_entry__._probe_backend(timeout_s=1) is None


def test_dryrun_backend_unreachable_degrades_to_smoke(monkeypatch, capsys):
    """Satellite r05 fix: an unreachable backend must not go rc-124 dark.

    The parent probes out-of-process; on failure it (1) emits an explicit
    ``status=backend_unreachable`` JSON record (the bench never-replay
    contract applied to the multichip trajectory) and (2) re-execs the
    CPU sim with the SMOKE subset so the run fits the remaining budget.
    """
    import json

    calls = []
    monkeypatch.delenv(__graft_entry__._CHILD_FLAG, raising=False)
    # conftest forces count=8; ask for 4 so the parent branch runs
    monkeypatch.setattr(
        __graft_entry__, "_probe_backend",
        lambda timeout_s=120: "backend init hung > 120s (simulated)",
    )
    monkeypatch.setattr(
        __graft_entry__, "_reexec_cpu_sim",
        lambda n, smoke=False: calls.append((n, smoke)),
    )
    monkeypatch.setattr(
        __graft_entry__, "_launch_smoke",
        lambda n: {"ok": True, "parity": True, "restarts_used": 1,
                   "final_loss": 1.0, "world": 1, "rc": 0},
    )
    __graft_entry__.dryrun_multichip(4)
    assert calls == [(4, True)]
    recs = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")]
    assert len(recs) == 2
    rec = recs[0]
    assert rec["status"] == "backend_unreachable"
    assert rec["fallback"] == "cpu_sim_smoke"
    assert rec["n_devices"] == 4
    assert "error" in rec and rec["configs"]
    # the trajectory also routes through the elastic launcher and says
    # so explicitly — the record is simulated, never silent
    sim = recs[1]
    assert sim["status"] == "simulated"
    assert sim["launch"]["ok"] and sim["launch"]["parity"]
    assert "backend_error" in sim


def test_dryrun_healthy_backend_keeps_full_matrix(monkeypatch, capsys):
    import json

    calls = []
    monkeypatch.delenv(__graft_entry__._CHILD_FLAG, raising=False)
    monkeypatch.setattr(__graft_entry__, "_probe_backend",
                        lambda timeout_s=120: None)
    monkeypatch.setattr(
        __graft_entry__, "_reexec_cpu_sim",
        lambda n, smoke=False: calls.append((n, smoke)),
    )
    monkeypatch.setattr(
        __graft_entry__, "_launch_smoke",
        lambda n: {"ok": True, "parity": True, "restarts_used": 0,
                   "final_loss": 1.0, "world": 1, "rc": 0},
    )
    __graft_entry__.dryrun_multichip(4)
    assert calls == [(4, False)]
    recs = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")]
    # healthy backend: no unreachable record, but the launch-smoke leg
    # still reports (sim mesh -> status=simulated, no backend_error)
    assert [r["status"] for r in recs] == ["simulated"]
    assert "backend_error" not in recs[0]


def test_dryrun_budget_exhausted_emits_record_and_exits_clean(
        monkeypatch, capsys):
    """When the child's wall-clock budget runs out mid-matrix it must say
    so explicitly (completed/skipped split) and return rc 0 — a partial
    pass on record beats a full pass killed dark at rc 124."""
    import json

    monkeypatch.setenv(__graft_entry__._CHILD_FLAG, "1")
    monkeypatch.setenv(__graft_entry__._BUDGET_ENV, "1e-9")
    monkeypatch.setattr(__graft_entry__, "_run_config",
                        lambda *a, **k: None)
    __graft_entry__.dryrun_multichip(8)
    recs = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["status"] == "budget_exhausted"
    assert rec["completed"] == ["tp_fsdp"]  # first config always runs
    assert rec["skipped"]  # the rest are named, not silently dropped
    assert set(rec) >= {"budget_s", "elapsed_s"}


def test_dryrun_smoke_flag_filters_to_smoke_subset(monkeypatch, capsys):
    monkeypatch.setenv(__graft_entry__._CHILD_FLAG, "1")
    monkeypatch.setenv(__graft_entry__._SMOKE_FLAG, "1")
    ran = []
    monkeypatch.setattr(
        __graft_entry__, "_run_config",
        lambda label, *a, **k: ran.append(label),
    )
    __graft_entry__.dryrun_multichip(8)
    out = capsys.readouterr().out
    assert ran == list(__graft_entry__._SMOKE_CONFIGS)
    assert f"ALL {len(ran)}/{len(ran)} configs ok" in out


@pytest.mark.slow
def test_dryrun_multichip_end_to_end_with_poisoned_parent(tmp_path):
    """Full dryrun(2) through the re-exec machinery, with a tripwire.

    A fake ``jax`` module is planted on PYTHONPATH in a directory whose
    name contains 'axon': if the PARENT imports jax it explodes
    immediately (proving the parent is backend-free), while the CHILD's
    env builder strips the path (name contains 'axon') so the real jax
    loads in the re-exec'd CPU-sim process.
    """
    poison = tmp_path / "axon_poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise RuntimeError('parent imported jax — hang vector!')\n"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # default (axon-like) driver env
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = str(poison)
    code = (
        "import sys; sys.path.insert(0, {root!r}); "
        "import __graft_entry__; __graft_entry__.dryrun_multichip(2)"
    ).format(root=_REPO_ROOT)
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "tp_fsdp ok" in proc.stdout, proc.stdout[-2000:]


def test_dryrun_reexec_streams_progress_and_finishes_in_budget():
    """The r04 artifact failure mode: the re-exec child's output was
    buffered (capture_output=True), so a driver-side timeout kill left
    nothing in the artifact tail.  Pin the fix's two properties:

    1. per-config progress lines appear on the PARENT's stdout while the
       parent is still running (streamed, not buffered-at-exit);
    2. the single-config re-exec path completes under a hard wall-clock
       budget (the full 7-config dryrun is sized to fit the driver's
       budget warm; this pins the machinery's overhead, and the
       compile-cache env vars keep repeat runs warm).
    """
    import time

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # default (axon-like) driver env
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    )
    env["_TADNN_DRYRUN_ONLY"] = "tp_fsdp"
    code = (
        "import sys; sys.path.insert(0, {root!r}); "
        "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"
    ).format(root=_REPO_ROOT)
    import threading

    t0 = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-c", code], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    # watchdog: a hang regression (the very thing this test pins) must
    # FAIL the test, not wedge the reader loop below waiting for EOF
    watchdog = threading.Timer(600, proc.kill)
    watchdog.start()
    streamed_while_running = False
    lines = []
    try:
        for line in proc.stdout:
            lines.append(line)
            if "starting..." in line and proc.poll() is None:
                streamed_while_running = True
        rc = proc.wait(timeout=30)
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
    out = "".join(lines)
    assert rc == 0, out[-3000:]
    assert streamed_while_running, (
        "no per-config marker arrived while the parent was running — "
        "child output is being buffered again:\n" + out[-2000:]
    )
    assert "ALL 1/1 configs ok" in out, out[-2000:]
    elapsed = time.perf_counter() - t0
    assert elapsed < 600, f"single-config re-exec took {elapsed:.0f}s"
