"""LoRA adapters (training/lora.py): merge math, frozen-base contract,
optimizer-state footprint, and the 1-vs-8-device oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import torch_automatic_distributed_neural_network_tpu as tad
from torch_automatic_distributed_neural_network_tpu.data.synthetic import (
    SyntheticLM,
)
from torch_automatic_distributed_neural_network_tpu.models import GPT2
from torch_automatic_distributed_neural_network_tpu.training import (
    LoraSpec,
    LoraTarget,
    init_lora_params,
    lora_init_fn,
    lora_loss,
    lora_optimizer,
    merge_lora,
    next_token_loss,
)

VOCAB = 512


def tiny():
    return GPT2("test", vocab_size=VOCAB, max_seq_len=64,
                dtype=jnp.float32)


@pytest.fixture(scope="module")
def base_params():
    model = tiny()
    return model, model.init(
        jax.random.key(1), jnp.zeros((2, 16), jnp.int32))["params"]


def test_merge_math(base_params):
    # W + (alpha/r) * a @ b in the MATRIX view: the 4-D DenseGeneral
    # q_proj kernel [L, d, H, hd] factors as [L, d, r] x [L, r, H*hd]
    _, base = base_params
    spec = LoraSpec(rank=4, alpha=8.0)
    lora = init_lora_params(jax.random.key(0), base, spec)
    a = lora["layers"]["attn"]["q_proj"]["kernel"]["a"]
    b = jnp.ones_like(lora["layers"]["attn"]["q_proj"]["kernel"]["b"])
    lora["layers"]["attn"]["q_proj"]["kernel"]["b"] = b
    merged = merge_lora(base, lora, spec)
    w0 = base["layers"]["attn"]["q_proj"]["kernel"]
    L, d, H, hd = w0.shape
    assert a.shape == (L, d, 4) and b.shape == (L, 4, H * hd)
    got = merged["layers"]["attn"]["q_proj"]["kernel"]
    want = w0 + 2.0 * jnp.einsum(
        "...ir,...ro->...io", a, b).reshape(w0.shape)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    # untouched leaves pass through by identity
    assert merged["embed"]["embedding"] is base["embed"]["embedding"]


def test_adapters_are_parameter_efficient(base_params):
    # the whole point: rank-r factors are a small fraction of the frozen
    # kernels they adapt (the naive last-two-dims factorization of 4-D
    # attention kernels was 2x LARGER than the base — round-5 review)
    _, base = base_params
    spec = LoraSpec(rank=4)
    lora = init_lora_params(jax.random.key(0), base, spec)
    n_lora = sum(x.size for x in jax.tree.leaves(lora))
    w = base["layers"]["attn"]["q_proj"]["kernel"]
    n_adapted_base = 2 * w.size  # q_proj + v_proj
    assert n_lora < 0.15 * n_adapted_base, (n_lora, n_adapted_base)


def test_step0_is_exactly_the_base_model(base_params):
    # b initializes to zero, so before any update the adapted model IS
    # the base model bit-for-bit
    model, base = base_params
    spec = LoraSpec(rank=4)
    lora = init_lora_params(jax.random.key(0), base, spec)
    merged = merge_lora(base, lora, spec)
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, (2, 16)), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(model.apply({"params": merged}, toks)),
        np.asarray(model.apply({"params": base}, toks)))


def test_no_match_raises(base_params):
    _, base = base_params
    with pytest.raises(ValueError, match="matched no"):
        init_lora_params(jax.random.key(0), base,
                         LoraSpec(targets=(r"nonexistent_proj",)))


def _pretrained_base():
    """A briefly FULL-trained base: lora-on-random-init barely moves the
    loss (uniform logits through the frozen tied head), so the learning
    assertion needs a base with real structure to adapt."""
    model = tiny()
    data = SyntheticLM(vocab_size=VOCAB, seq_len=65, batch_size=16)
    ad = tad.AutoDistribute(model, optimizer=optax.adamw(3e-3),
                            loss_fn=next_token_loss, strategy="dp")
    state = ad.init(jax.random.key(0), data.batch(0))
    for i in range(30):
        state, _ = ad.step(state, data.batch(i))
    return jax.device_get(state.params), data


_SPEC = LoraSpec(rank=16, alpha=32.0,
                 targets=(LoraTarget(r"q_proj/kernel", 1, 2),
                          LoraTarget(r"v_proj/kernel", 1, 2),
                          LoraTarget(r"up_proj/kernel", 1, 1)))


def _finetune(base, data, devices, strategy, steps=3, start=30):
    ad = tad.AutoDistribute(
        tiny(),
        optimizer=lora_optimizer(optax.adamw(3e-3)),
        loss_fn=lora_loss(next_token_loss, _SPEC),
        init_fn=lora_init_fn(base, _SPEC),
        strategy=strategy,
        devices=devices,
    )
    state = ad.init(jax.random.key(2), data.batch(start))
    losses = []
    for i in range(start, start + steps):
        state, m = ad.step(state, data.batch(i))
        losses.append(float(m["loss"]))
    return state, losses


@pytest.fixture(scope="module")
def pretrained():
    return _pretrained_base()


def test_base_frozen_and_adapters_train(pretrained):
    base, data = pretrained
    state, losses = _finetune(base, data, jax.devices(), "fsdp", steps=25)
    # frozen bit-exact through 25 fsdp-sharded, donated steps
    for (_, l0), (_, l1) in zip(
            jax.tree_util.tree_flatten_with_path(base)[0],
            jax.tree_util.tree_flatten_with_path(state.params["base"])[0]):
        np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
    # the adapters moved and the loss dropped
    b_norm = float(jnp.linalg.norm(
        state.params["lora"]["layers"]["attn"]["q_proj"]["kernel"]["b"]))
    assert b_norm > 0
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.03, losses


def test_opt_state_covers_adapters_only(pretrained):
    base, data = pretrained
    state, _ = _finetune(base, data, jax.devices(), "dp", steps=1)
    n_opt = sum(x.size for x in jax.tree.leaves(state.opt_state)
                if hasattr(x, "size"))
    n_lora = sum(x.size for x in jax.tree.leaves(state.params["lora"]))
    # adam: m + v per adapter leaf (+ scalar counters); nothing for base
    assert n_opt < 2 * n_lora + 16, (n_opt, n_lora)


@pytest.mark.parametrize("strategy", ["dp", "fsdp", "tp_fsdp"])
def test_lora_1_vs_8_device_parity(strategy, pretrained):
    base, data = pretrained
    _, ref = _finetune(base, data, jax.devices()[:1], "dp")
    _, got = _finetune(base, data, jax.devices(), strategy)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_lora_checkpoint_resume_parity(pretrained, tmp_path):
    # the combined {"base", "lora"} state checkpoints and resumes like
    # any TrainState: 4 straight lora steps == 2 + save/restore + 2
    from torch_automatic_distributed_neural_network_tpu.training import (
        CheckpointManager,
        abstract_state_for,
    )

    base, data = pretrained

    def make_ad():
        return tad.AutoDistribute(
            tiny(),
            optimizer=lora_optimizer(optax.adamw(3e-3)),
            loss_fn=lora_loss(next_token_loss, _SPEC),
            init_fn=lora_init_fn(base, _SPEC),
            strategy="fsdp",
        )

    ad = make_ad()
    s = ad.init(jax.random.key(2), data.batch(30))
    for i in range(30, 34):
        s, _ = ad.step(s, data.batch(i))
    straight = jax.tree.leaves(s.params["lora"])

    ad1 = make_ad()
    s1 = ad1.init(jax.random.key(2), data.batch(30))
    for i in range(30, 32):
        s1, _ = ad1.step(s1, data.batch(i))
    ckpt = CheckpointManager(str(tmp_path / "lora_ckpt"))
    ckpt.save(2, s1)
    ckpt.close()

    ad2 = make_ad()
    ckpt2 = CheckpointManager(str(tmp_path / "lora_ckpt"))
    abstract = abstract_state_for(ad2, jax.random.key(2), data.batch(30))
    s2 = ckpt2.restore(abstract)
    ad2._compile_step(abstract, ad2.state_shardings(abstract))
    for i in range(32, 34):
        s2, _ = ad2.step(s2, data.batch(i))
    ckpt2.close()
    for a, b in zip(straight, jax.tree.leaves(s2.params["lora"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
