"""Blockwise / vocab-sharded cross-entropy (VERDICT r3 #5).

Parity is pinned against the materializing ``next_token_loss`` on the
same params: loss values and grads must agree for tied and untied heads,
with and without padding masks, and for MoE (aux-loss path).  The
sharded test runs the loss under a tp mesh where lm_head is
vocab-sharded (planner rule ``lm_head/kernel -> (None, 'tensor')``) and
checks 1-dev parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torch_automatic_distributed_neural_network_tpu.models import (  # noqa: E402
    DecoderLM,
    MoE,
)
from torch_automatic_distributed_neural_network_tpu.models.transformer_core import (  # noqa: E402
    TransformerConfig,
)
from torch_automatic_distributed_neural_network_tpu.training import (  # noqa: E402

    blockwise_next_token_loss,
    moe_next_token_loss,
    next_token_loss,
)


# Minutes-scale on the 8-device CPU sim (every case is a fresh
# multi-device XLA compile): excluded from the quick tier-1 pass,
# run with -m slow (or no marker filter) for full coverage.
pytestmark = pytest.mark.slow

def _apply_fn(model):
    return lambda p, *a, **k: model.apply({"params": p}, *a, **k)


def _setup(tied, vocab=97, seq=33):
    cfg = TransformerConfig(
        vocab_size=vocab, d_model=64, n_layers=2, n_heads=4,
        max_seq_len=seq + 8, tie_embeddings=tied,
    )
    model = DecoderLM(cfg)
    toks = np.random.RandomState(0).randint(0, vocab, (3, seq))
    batch = {"tokens": jnp.asarray(toks)}
    params = model.init(jax.random.key(0), batch["tokens"][:, :-1])["params"]
    return model, params, batch


@pytest.mark.parametrize("tied", [True, False])
@pytest.mark.parametrize("block", [8, 16, 64])  # 64 > S: single block
def test_loss_and_grad_parity(tied, block):
    model, params, batch = _setup(tied)
    fn = _apply_fn(model)
    ref, _ = next_token_loss(params, batch, None, fn)
    got, _ = blockwise_next_token_loss(block)(params, batch, None, fn)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    g_ref = jax.grad(lambda p: next_token_loss(p, batch, None, fn)[0])(params)
    g_got = jax.grad(
        lambda p: blockwise_next_token_loss(block)(p, batch, None, fn)[0]
    )(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3),
        g_ref, g_got)


def test_masked_parity():
    model, params, batch = _setup(tied=False)
    fn = _apply_fn(model)
    mask = np.ones_like(np.asarray(batch["tokens"]), np.float32)
    mask[:, 20:] = 0.0  # padding tail
    mask[1, 5:] = 0.0
    batch = dict(batch, mask=jnp.asarray(mask))
    ref, _ = next_token_loss(params, batch, None, fn)
    got, _ = blockwise_next_token_loss(8)(params, batch, None, fn)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)


def test_moe_aux_loss_parity():
    model = MoE("test", vocab_size=64, max_seq_len=40)
    toks = np.random.RandomState(1).randint(0, 64, (4, 33))
    batch = {"tokens": jnp.asarray(toks)}
    params = model.init(jax.random.key(0), batch["tokens"][:, :-1])["params"]
    fn = _apply_fn(model)
    ref, ref_aux = moe_next_token_loss(params, batch, None, fn)
    got, got_aux = blockwise_next_token_loss(8)(params, batch, None, fn)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    np.testing.assert_allclose(float(got_aux["router_loss"]),
                               float(ref_aux["router_loss"]), rtol=1e-5)


def test_autodistribute_tp_vocab_sharded(devices8):
    """Full AutoDistribute tp_fsdp step with the blockwise loss: lm_head
    is vocab-sharded over 'tensor', and the 8-device trajectory matches
    the 1-device oracle."""
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad

    def make(devices, strategy):
        cfg = TransformerConfig(
            vocab_size=128, d_model=64, n_layers=2, n_heads=4,
            max_seq_len=48, tie_embeddings=False,
        )
        return tad.AutoDistribute(
            DecoderLM(cfg),
            optimizer=optax.sgd(0.1),
            loss_fn=blockwise_next_token_loss(16),
            strategy=strategy,
            devices=devices,
        )

    toks = np.random.RandomState(2).randint(0, 128, (8, 41))
    batch = {"tokens": jnp.asarray(toks)}

    losses = {}
    for name, devs, strat in (
        ("1dev", jax.devices()[:1], "dp"),
        ("8dev", jax.devices(), "tp_fsdp"),
    ):
        ad = make(devs, strat)
        state = ad.init(jax.random.key(0), batch)
        run = []
        for _ in range(3):
            state, metrics = ad.step(state, batch)
            run.append(float(metrics["loss"]))
        losses[name] = run
    np.testing.assert_allclose(losses["8dev"], losses["1dev"],
                               rtol=2e-4, atol=2e-4)


def test_peak_temp_smaller_than_full_loss(devices8):
    """The point of the exercise: AOT memory analysis shows materially
    smaller temps than the materializing loss on a long-seq, big-vocab
    config (per-device, fsdp over 8 sim devices)."""
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad

    def peak(loss_fn):
        cfg = TransformerConfig(
            vocab_size=32768, d_model=128, n_layers=2, n_heads=4,
            max_seq_len=1024, tie_embeddings=False, scan_layers=True,
        )
        ad = tad.AutoDistribute(
            DecoderLM(cfg),
            optimizer=optax.adamw(1e-3),
            loss_fn=loss_fn,
            strategy="fsdp",
            devices=jax.devices(),
        )
        sample = {"tokens": np.zeros((8, 1025), np.int32)}
        report = ad.compile_report(jax.random.key(0), sample)
        assert report and report.get("per_device_peak_bytes")
        return report["memory"]["temp_size"]

    full = peak(next_token_loss)
    blockwise = peak(blockwise_next_token_loss(128))
    # full loss holds the fp32 [8,1024,32768] logits + its grad twin
    # (~2 GiB over 8 devices); blockwise holds one [8,128,32768] block
    assert blockwise < 0.6 * full, (blockwise, full)


def test_blockwise_with_grad_accum(devices8):
    """blockwise CE composes with gradient accumulation (the lax.scan
    slice loop folds through the features path like any loss)."""
    import optax

    import torch_automatic_distributed_neural_network_tpu as tad

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, max_seq_len=48,
        tie_embeddings=False,
    )
    toks = np.random.RandomState(3).randint(0, 128, (16, 33))
    batch = {"tokens": jnp.asarray(toks)}

    def run(accum):
        ad = tad.AutoDistribute(
            DecoderLM(cfg), optimizer=optax.sgd(0.1),
            loss_fn=blockwise_next_token_loss(16), strategy="dp",
            grad_accum=accum,
        )
        state = ad.init(jax.random.key(0), batch)
        out = []
        for _ in range(3):
            state, m = ad.step(state, batch)
            out.append(float(m["loss"]))
        return out

    np.testing.assert_allclose(run(2), run(1), rtol=2e-4, atol=2e-4)


def test_adamw_cosine_decay_mask():
    """adamw_cosine decays matrices only (norm scales/biases untouched
    by weight decay — the GPT no_decay param-group analog)."""
    from torch_automatic_distributed_neural_network_tpu.training.optim import (
        adamw_cosine,
    )

    params = {"w": jnp.ones((4, 4)), "scale": jnp.ones((4,))}
    grads = jax.tree.map(jnp.zeros_like, params)
    # zero grads -> the adam term is exactly 0, so ANY nonzero update
    # is weight decay; the mask must keep it off the 1-D param
    tx = adamw_cosine(peak_lr=1.0, total_steps=10, warmup_steps=0,
                      weight_decay=0.5, grad_clip=0.0)
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    assert float(jnp.abs(updates["w"]).max()) > 0.0      # decayed
    assert float(jnp.abs(updates["scale"]).max()) == 0.0  # masked


def test_adamw_cosine_decay_mask_scanned_layers():
    """The mask is path-based, not ndim-based: nn.scan-stacked layer
    params carry a leading [L] axis, so stacked norm scales/biases are
    rank 2 and an ndim>=2 mask would decay them (round-4 advisor)."""
    from torch_automatic_distributed_neural_network_tpu.training.optim import (
        adamw_cosine, decay_mask,
    )

    params = {
        "layers": {
            "mlp": {"kernel": jnp.ones((3, 4, 4)),   # [L, d, d]
                    "bias": jnp.ones((3, 4))},        # [L, d] — rank 2!
            "norm": {"scale": jnp.ones((3, 4))},      # [L, d] — rank 2!
        },
        "embedding": jnp.ones((8, 4)),
    }
    mask = decay_mask(params)
    assert mask["layers"]["mlp"]["kernel"] is True
    assert mask["layers"]["mlp"]["bias"] is False
    assert mask["layers"]["norm"]["scale"] is False
    assert mask["embedding"] is True

    grads = jax.tree.map(jnp.zeros_like, params)
    tx = adamw_cosine(peak_lr=1.0, total_steps=10, warmup_steps=0,
                      weight_decay=0.5, grad_clip=0.0)
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    assert float(jnp.abs(updates["layers"]["mlp"]["kernel"]).max()) > 0.0
    assert float(jnp.abs(updates["layers"]["mlp"]["bias"]).max()) == 0.0
    assert float(jnp.abs(updates["layers"]["norm"]["scale"]).max()) == 0.0
